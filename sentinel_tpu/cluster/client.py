"""Sync token client with xid-correlated responses, timeout and reconnect.

Analog of ``DefaultClusterTokenClient.java:45`` over
``NettyTransportClient.java:61``: an atomic xid generator, a pending-promise
map (``TokenClientPromiseHolder.java:30-50``), a hard request timeout
defaulting to the reference's 20ms (``ClusterConstants.java:44``), and
lazy reconnect with linear backoff (``NettyTransportClient.java:67``).

The client is sync because its caller is the (sync) flow-checker hot path; a
background thread owns the socket read side.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
from typing import Dict, Optional

from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.token_service import TokenResult, TokenService
from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine import TokenStatus

RECONNECT_DELAY_S = 2.0  # NettyTransportClient.RECONNECT_DELAY_MS analog


class _Pending:
    __slots__ = ("event", "response")

    def __init__(self):
        self.event = threading.Event()
        self.response: Optional[P.FlowResponse] = None


class TokenClient(TokenService):
    def __init__(self, host: str, port: int, timeout_ms: int = 20,
                 namespace: str = "default"):
        self.host = host
        self.port = port
        self.timeout_ms = timeout_ms
        # declared to the server in the PING handshake; the server scopes
        # its connection counts (AVG_LOCAL scaling) by this group
        self.namespace = namespace
        self._xid = itertools.count(1)
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self._reader: Optional[threading.Thread] = None
        self._last_connect_attempt = 0.0

    # -- connection management ---------------------------------------------
    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            return True
        with self._state_lock:
            if self._sock is not None:
                return True
            now = time.monotonic()
            if now - self._last_connect_attempt < RECONNECT_DELAY_S:
                return False
            self._last_connect_attempt = now
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=1.0
                )
                # create_connection leaves its connect timeout on the socket;
                # the reader must block indefinitely or idle periods kill the
                # connection with socket.timeout (an OSError)
                sock.settimeout(None)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock = sock
            except OSError as e:
                record_log.warning("token server unreachable: %s", e)
                return False
            self._reader = threading.Thread(
                target=self._read_loop, args=(sock,), daemon=True,
                name="sentinel-token-client-reader",
            )
            self._reader.start()
            handshake = True
        if handshake:
            # outside _state_lock (ping → _send → _ensure_connected would
            # re-enter it); best-effort — a lost handshake only delays the
            # server's connected-count update to the next keepalive
            self.ping()
        return True

    def _drop_connection(self, sock: socket.socket) -> None:
        with self._state_lock:
            was_active = self._sock is sock
            if was_active:
                self._sock = None
        try:
            sock.close()
        except OSError:
            pass
        # Fail waiters so they fall back immediately instead of timing out —
        # but only when the active connection died; a stale reader thread's
        # exit must not abort in-flight requests on a newer connection.
        if was_active:
            for pending in list(self._pending.values()):
                pending.event.set()

    def close(self) -> None:
        sock = self._sock
        if sock is not None:
            self._drop_connection(sock)

    def _read_loop(self, sock: socket.socket) -> None:
        frames = P.FrameReader()
        try:
            while True:
                data = sock.recv(4096)
                if not data:
                    break
                for payload in frames.feed(data):
                    rsp = P.decode_response(payload)
                    pending = self._pending.get(rsp.xid)
                    if pending is not None:
                        pending.response = rsp
                        pending.event.set()
        except OSError:
            pass
        finally:
            self._drop_connection(sock)

    # -- TokenService -------------------------------------------------------
    def request_token(self, flow_id, acquire=1, prioritized=False) -> TokenResult:
        rsp = self._roundtrip(
            P.FlowRequest(next(self._xid), flow_id, acquire, prioritized)
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return TokenResult(TokenStatus(rsp.status), rsp.remaining, rsp.wait_ms)

    def request_params_token(self, flow_id, acquire, param_hashes) -> TokenResult:
        rsp = self._roundtrip(
            P.FlowRequest(
                next(self._xid), flow_id, acquire, False,
                P.MsgType.PARAM_FLOW, tuple(param_hashes),
            )
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return TokenResult(TokenStatus(rsp.status), rsp.remaining, rsp.wait_ms)

    def request_concurrent_token(self, flow_id, acquire=1, prioritized=False):
        rsp = self._roundtrip(
            P.FlowRequest(
                next(self._xid), flow_id, acquire, prioritized,
                P.MsgType.CONCURRENT_ACQUIRE,
            )
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return TokenResult(
            TokenStatus(rsp.status), rsp.remaining, rsp.wait_ms, rsp.token_id
        )

    def release_concurrent_token(self, token_id):
        # the flow_id slot carries the token id on the wire (protocol docstring)
        rsp = self._roundtrip(
            P.FlowRequest(
                next(self._xid), token_id, 0, False, P.MsgType.CONCURRENT_RELEASE
            )
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return TokenResult(TokenStatus(rsp.status))

    def ping(self, namespace: Optional[str] = None) -> bool:
        """Handshake/keepalive; declares a namespace this client serves
        (``TokenServerHandler.handlePingRequest``). One connection may
        declare several namespaces — each ping adds one group membership."""
        return (
            self._roundtrip(P.Ping(next(self._xid), namespace or self.namespace))
            is not None
        )

    def _roundtrip(self, req) -> Optional[P.FlowResponse]:
        """Correlated request/response: register pending, send, wait, pop."""
        pending = _Pending()
        self._pending[req.xid] = pending
        try:
            if not self._send(P.encode_request(req)):
                return None
            if not pending.event.wait(self.timeout_ms / 1000.0):
                return None  # timeout → caller falls back (20ms budget blown)
            return pending.response
        finally:
            self._pending.pop(req.xid, None)

    def _send(self, data: bytes) -> bool:
        if not self._ensure_connected():
            return False
        sock = self._sock
        if sock is None:
            return False
        try:
            with self._send_lock:
                sock.sendall(data)
            return True
        except OSError:
            self._drop_connection(sock)
            return False
