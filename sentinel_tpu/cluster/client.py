"""Sync token client with xid-correlated responses, timeout and reconnect.

Analog of ``DefaultClusterTokenClient.java:45`` over
``NettyTransportClient.java:61``: an atomic xid generator, a pending-promise
map (``TokenClientPromiseHolder.java:30-50``), a hard request timeout
defaulting to the reference's 20ms (``ClusterConstants.java:44``), and
lazy reconnect with bounded exponential backoff + jitter (the reference's
fixed ``RECONNECT_DELAY_MS``, ``NettyTransportClient.java:67``, retried in
lockstep from every caller — the reconnect storm this ladder avoids).

The client is sync because its caller is the (sync) flow-checker hot path; a
background thread owns the socket read side.
"""

from __future__ import annotations

import itertools
import random
import socket
import struct
import threading
import time
from typing import Dict, Optional

from sentinel_tpu import chaos
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.token_service import TokenResult, TokenService
from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine import TokenStatus

RECONNECT_DELAY_S = 2.0  # legacy cap alias; see the backoff ladder below

# reconnect backoff: first retry comes fast (a restarted server should be
# picked up quickly), repeated failures back off exponentially with jitter
# so a dead server isn't hammered by every request of every client in sync
# (NettyTransportClient's fixed RECONNECT_DELAY_MS caused exactly that storm)
RECONNECT_BASE_S = 0.1
RECONNECT_MAX_S = 30.0
RECONNECT_JITTER = 0.2

# process-wide client receive accounting (all TokenClient readers): bytes
# received off token-server sockets and growable-buffer expansions — the
# exporter renders these as sentinel_client_recv_bytes_total /
# sentinel_client_recv_buf_grows_total
_recv_lock = threading.Lock()
_recv_bytes = 0
_recv_buf_grows = 0


def _count_recv(n: int, grows: int = 0) -> None:
    global _recv_bytes, _recv_buf_grows
    with _recv_lock:
        _recv_bytes += n
        _recv_buf_grows += grows


def client_recv_bytes_total() -> int:
    with _recv_lock:
        return _recv_bytes


def client_recv_buf_grows_total() -> int:
    with _recv_lock:
        return _recv_buf_grows


def reset_client_metrics_for_tests() -> None:
    global _recv_bytes, _recv_buf_grows
    with _recv_lock:
        _recv_bytes = 0
        _recv_buf_grows = 0


class _Pending:
    __slots__ = ("event", "response")

    def __init__(self):
        self.event = threading.Event()
        self.response: Optional[P.FlowResponse] = None


class TokenClient(TokenService):
    def __init__(self, host: str, port: int, timeout_ms: int = 20,
                 namespace: str = "default"):
        self.host = host
        self.port = port
        self.timeout_ms = timeout_ms
        # declared to the server in the PING handshake; the server scopes
        # its connection counts (AVG_LOCAL scaling) by this group
        self.namespace = namespace
        self._xid = itertools.count(1)
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self._reader: Optional[threading.Thread] = None
        self._last_connect_attempt = 0.0
        # consecutive failed connect attempts since the last success; drives
        # the reconnect backoff and is surfaced for HA health introspection
        self._consecutive_failures = 0
        self._reconnect_delay_s = 0.0
        self._reconnect_base_s = SentinelConfig.get_float(
            "sentinel.tpu.client.reconnect.base.s", RECONNECT_BASE_S
        )
        self._reconnect_max_s = SentinelConfig.get_float(
            "sentinel.tpu.client.reconnect.max.s", RECONNECT_MAX_S
        )

    @property
    def consecutive_failures(self) -> int:
        return self._consecutive_failures

    # -- connection management ---------------------------------------------
    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            return True
        with self._state_lock:
            if self._sock is not None:
                return True
            now = time.monotonic()
            if now - self._last_connect_attempt < self._reconnect_delay_s:
                return False
            self._last_connect_attempt = now
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=1.0
                )
                # create_connection leaves its connect timeout on the socket;
                # the reader must block indefinitely or idle periods kill the
                # connection with socket.timeout (an OSError)
                sock.settimeout(None)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock = sock
                self._consecutive_failures = 0
                self._reconnect_delay_s = 0.0
            except OSError as e:
                self._consecutive_failures += 1
                # bounded exponential backoff with jitter: without it, every
                # request-carrying thread retries the dead address in
                # lockstep (connect timeout × request rate = a reconnect
                # storm). Only the first few failures log — the storm used
                # to flood the record log too.
                k = min(self._consecutive_failures, 16)
                self._reconnect_delay_s = min(
                    self._reconnect_base_s * (2 ** (k - 1)),
                    self._reconnect_max_s,
                ) * (1.0 + RECONNECT_JITTER * random.random())
                if self._consecutive_failures <= 3:
                    record_log.warning(
                        "token server unreachable (%d consecutive): %s",
                        self._consecutive_failures, e,
                    )
                return False
            self._reader = threading.Thread(
                target=self._read_loop, args=(sock,), daemon=True,
                name="sentinel-token-client-reader",
            )
            self._reader.start()
            handshake = True
        if handshake:
            # outside _state_lock (ping → _send → _ensure_connected would
            # re-enter it); best-effort — a lost handshake only delays the
            # server's connected-count update to the next keepalive
            self.ping()
        return True

    def _drop_connection(self, sock: socket.socket) -> None:
        with self._state_lock:
            was_active = self._sock is sock
            if was_active:
                self._sock = None
        try:
            # shutdown BEFORE close: the reader thread is blocked in recv on
            # this socket, and CPython defers the real fd close until that
            # call returns — without the shutdown no FIN ever reaches the
            # server and the connection lingers until the idle sweep
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        # Fail waiters so they fall back immediately instead of timing out —
        # but only when the active connection died; a stale reader thread's
        # exit must not abort in-flight requests on a newer connection.
        if was_active:
            for pending in list(self._pending.values()):
                pending.event.set()

    def close(self) -> None:
        sock = self._sock
        if sock is not None:
            self._drop_connection(sock)

    def _read_loop(self, sock: socket.socket) -> None:
        # growable receive buffer, parsed in place: recv_into lands bytes
        # directly in the bytearray (no per-chunk bytes object), frames are
        # split by offset arithmetic (no per-feed copy/compact), and only
        # payloads that still have a waiter get copied out for the handoff.
        # The buffer doubles when a partial frame fills it (one max frame is
        # 2+65535 bytes, just over the initial 64KiB) and never shrinks —
        # its high-water mark is the deepest response burst seen.
        buf = bytearray(65536)
        view = memoryview(buf)
        r = w = 0  # parse offset / write offset into buf
        head = P._HEAD.size
        try:
            while True:
                if w == len(buf):
                    if r > 0:
                        # reclaim the consumed prefix before growing
                        view[: w - r] = view[r:w]
                        w -= r
                        r = 0
                    else:
                        grown = bytearray(2 * len(buf))
                        grown[:w] = buf
                        buf = grown
                        view = memoryview(buf)
                        _count_recv(0, grows=1)
                n = sock.recv_into(view[w:])
                if n == 0:
                    break
                if chaos.ARMED:  # inbound bit-rot injection (frame_corrupt)
                    data = chaos.mangle(
                        "frame_corrupt", bytes(view[w : w + n])
                    )
                    view[w : w + n] = data
                _count_recv(n)
                w += n
                while w - r >= 2:
                    ln = (buf[r] << 8) | buf[r + 1]
                    # a 2-byte length cannot exceed MAX_FRAME, but a frame
                    # too short for even a header is garbage — drop the
                    # connection (same contract as protocol.FrameReader)
                    if ln < head:
                        raise ValueError("runt frame")
                    if w - r < 2 + ln:
                        break
                    payload = view[r + 2 : r + 2 + ln]
                    r += 2 + ln
                    if P.peek_type(payload) == P.MsgType.BATCH_FLOW:
                        # copy + store the raw payload; the waiting thread
                        # decodes (spreads the vectorized decode across
                        # callers). Frames whose waiter already gave up
                        # skip even this copy.
                        xid = int.from_bytes(
                            payload[:4], "big", signed=True
                        )
                        pending = self._pending.get(xid)
                        if pending is not None:
                            pending.response = bytes(payload)
                            pending.event.set()
                        continue
                    rsp = P.decode_response(bytes(payload))
                    pending = self._pending.get(rsp.xid)
                    if pending is not None:
                        pending.response = rsp
                        pending.event.set()
                if r == w:
                    r = w = 0  # fully drained: rewind without compaction
        except OSError:
            pass
        except (ValueError, struct.error):
            # corrupt/truncated server bytes (runt frame, short response):
            # drop the connection gracefully — in-flight requests resolve
            # via _drop_connection below, and the reader thread must never
            # die with a traceback on hostile input
            record_log.warning("malformed frame from server; dropping connection")
        finally:
            self._drop_connection(sock)

    # -- TokenService -------------------------------------------------------
    def request_token(self, flow_id, acquire=1, prioritized=False) -> TokenResult:
        rsp = self._roundtrip(
            P.FlowRequest(next(self._xid), flow_id, acquire, prioritized)
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return TokenResult(
            TokenStatus(rsp.status), rsp.remaining, rsp.wait_ms,
            endpoint=rsp.endpoint,
        )

    def request_params_token(self, flow_id, acquire, param_hashes) -> TokenResult:
        rsp = self._roundtrip(
            P.FlowRequest(
                next(self._xid), flow_id, acquire, False,
                P.MsgType.PARAM_FLOW, tuple(param_hashes),
            )
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return TokenResult(
            TokenStatus(rsp.status), rsp.remaining, rsp.wait_ms,
            endpoint=rsp.endpoint,
        )

    def request_concurrent_token(self, flow_id, acquire=1, prioritized=False):
        rsp = self._roundtrip(
            P.FlowRequest(
                next(self._xid), flow_id, acquire, prioritized,
                P.MsgType.CONCURRENT_ACQUIRE,
            )
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return TokenResult(
            TokenStatus(rsp.status), rsp.remaining, rsp.wait_ms, rsp.token_id
        )

    def release_concurrent_token(self, token_id):
        # the flow_id slot carries the token id on the wire (protocol docstring)
        rsp = self._roundtrip(
            P.FlowRequest(
                next(self._xid), token_id, 0, False, P.MsgType.CONCURRENT_RELEASE
            )
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return TokenResult(TokenStatus(rsp.status))

    def request_batch_arrays(self, flow_ids, counts=None, prios=None,
                             timeout_ms: Optional[int] = None):
        """Array-in/array-out batched verdicts over BATCH_FLOW frames:
        (status int8[N], remaining int32[N], wait_ms int32[N]) in request
        order, or None on send failure/timeout.

        Batches larger than one frame are **pipelined**: every chunk frame
        is sent before the first response is awaited, so the server's
        micro-batcher sees them back-to-back and a chunked batch costs one
        round trip, not one per chunk.
        """
        import numpy as np

        flow_ids = np.asarray(flow_ids, dtype=np.int64)
        n = flow_ids.shape[0]
        if n == 0:
            e = np.empty(0, np.int32)
            return np.empty(0, np.int8), e, e
        budget = (timeout_ms or self.timeout_ms) / 1000.0
        chunk = P.MAX_BATCH_PER_FRAME
        spans = [(i, min(i + chunk, n)) for i in range(0, n, chunk)]
        pendings = []
        try:
            for lo, hi in spans:
                xid = next(self._xid)
                pending = _Pending()
                self._pending[xid] = pending
                pendings.append((xid, pending, lo, hi))
                frame = P.encode_batch_request(
                    xid, flow_ids[lo:hi],
                    None if counts is None else counts[lo:hi],
                    None if prios is None else prios[lo:hi],
                    # declare the whole budget as the frame's deadline: a
                    # deadline-aware server sheds the frame instead of
                    # serving a verdict this client stopped waiting for
                    deadline_ms=max(1, int(budget * 1000)),
                )
                if not self._send(frame):
                    return None
            status = np.empty(n, np.int8)
            remaining = np.empty(n, np.int32)
            wait = np.empty(n, np.int32)
            deadline = time.monotonic() + budget
            for xid, pending, lo, hi in pendings:
                if not pending.event.wait(max(deadline - time.monotonic(), 0)):
                    return None
                payload = pending.response
                if not isinstance(payload, (bytes, bytearray)):
                    return None  # connection died mid-batch
                try:
                    _, st, rem, wt = P.decode_batch_response(payload)
                except Exception:
                    # truncated/malformed server frame degrades to the
                    # documented None contract, never an exception out of
                    # the caller (the local-fallback path handles None)
                    return None
                if st.shape[0] != hi - lo:
                    return None
                status[lo:hi] = st
                remaining[lo:hi] = rem
                wait[lo:hi] = wt
            return status, remaining, wait
        finally:
            for xid, _, _, _ in pendings:
                self._pending.pop(xid, None)

    def request_batch(self, requests) -> list:
        """List-of-(flow_id, acquire, prioritized) → List[TokenResult]
        (TokenService.request_batch over the wire)."""
        import numpy as np

        if not requests:
            return []
        n = len(requests)
        out = self.request_batch_arrays(
            np.fromiter((f for f, _, _ in requests), np.int64, n),
            np.fromiter((a for _, a, _ in requests), np.int32, n),
            np.fromiter((p for _, _, p in requests), bool, n),
        )
        if out is None:
            return [TokenResult(TokenStatus.FAIL)] * n
        status, remaining, wait = out
        return [
            TokenResult(TokenStatus(int(status[i])), int(remaining[i]),
                        int(wait[i]))
            for i in range(n)
        ]

    def ping(self, namespace: Optional[str] = None) -> bool:
        """Handshake/keepalive; declares a namespace this client serves
        (``TokenServerHandler.handlePingRequest``). One connection may
        declare several namespaces — each ping adds one group membership."""
        return self.ping_ex(namespace) is True

    def ping_ex(self, namespace: Optional[str] = None) -> Optional[bool]:
        """Ping that separates transport failure from the server's answer:
        ``None`` when no response arrived (dead host, timeout, send
        failure), else the server's verdict — status 0 means the namespace
        group accepted this connection. Failover health accounting charges
        an endpoint's breaker only for the ``None`` case."""
        rsp = self._roundtrip(
            P.Ping(next(self._xid), namespace or self.namespace)
        )
        if rsp is None:
            return None
        return rsp.status == 0

    def _roundtrip(self, req) -> Optional[P.FlowResponse]:
        """Correlated request/response: register pending, send, wait, pop."""
        pending = _Pending()
        self._pending[req.xid] = pending
        try:
            if not self._send(P.encode_request(req)):
                return None
            if not pending.event.wait(self.timeout_ms / 1000.0):
                return None  # timeout → caller falls back (20ms budget blown)
            return pending.response
        finally:
            self._pending.pop(req.xid, None)

    def _send(self, data: bytes) -> bool:
        if not self._ensure_connected():
            return False
        sock = self._sock
        if sock is None:
            return False
        if chaos.ARMED:
            if chaos.should("conn_reset"):  # RST mid-request
                self._drop_connection(sock)
                return False
            data = chaos.mangle("frame_corrupt", data)  # outbound bit rot
        try:
            with self._send_lock:
                sock.sendall(data)
            return True
        except OSError:
            self._drop_connection(sock)
            return False
