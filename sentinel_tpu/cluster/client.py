"""Sync token client with xid-correlated responses, timeout and reconnect.

Analog of ``DefaultClusterTokenClient.java:45`` over
``NettyTransportClient.java:61``: an atomic xid generator, a pending-promise
map (``TokenClientPromiseHolder.java:30-50``), a hard request timeout
defaulting to the reference's 20ms (``ClusterConstants.java:44``), and
lazy reconnect with bounded exponential backoff + jitter (the reference's
fixed ``RECONNECT_DELAY_MS``, ``NettyTransportClient.java:67``, retried in
lockstep from every caller — the reconnect storm this ladder avoids).

The client is sync because its caller is the (sync) flow-checker hot path; a
background thread owns the socket read side.
"""

from __future__ import annotations

import itertools
import math
import random
import socket
import struct
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from sentinel_tpu import chaos
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.token_service import TokenResult, TokenService
from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine import TokenStatus
from sentinel_tpu.trace import ring as _TR

RECONNECT_DELAY_S = 2.0  # legacy cap alias; see the backoff ladder below

# reconnect backoff: first retry comes fast (a restarted server should be
# picked up quickly), repeated failures back off exponentially with jitter
# so a dead server isn't hammered by every request of every client in sync
# (NettyTransportClient's fixed RECONNECT_DELAY_MS caused exactly that storm)
RECONNECT_BASE_S = 0.1
RECONNECT_MAX_S = 30.0
RECONNECT_JITTER = 0.2

# process-wide client receive accounting (all TokenClient readers): bytes
# received off token-server sockets and growable-buffer expansions — the
# exporter renders these as sentinel_client_recv_bytes_total /
# sentinel_client_recv_buf_grows_total. unknown_frames counts frames whose
# type byte this build doesn't speak (a newer server's rev): rev-7 readers
# SKIP those instead of dropping the connection, and the count is the
# rollout canary (sentinel_client_unknown_frames_total).
_recv_lock = threading.Lock()
_recv_bytes = 0
_recv_buf_grows = 0
_unknown_frames = 0


def _count_recv(n: int, grows: int = 0) -> None:
    global _recv_bytes, _recv_buf_grows
    with _recv_lock:
        _recv_bytes += n
        _recv_buf_grows += grows


def _count_unknown_frame(n: int = 1) -> None:
    global _unknown_frames
    with _recv_lock:
        _unknown_frames += n


def client_recv_bytes_total() -> int:
    with _recv_lock:
        return _recv_bytes


def client_recv_buf_grows_total() -> int:
    with _recv_lock:
        return _recv_buf_grows


def client_unknown_frames_total() -> int:
    with _recv_lock:
        return _unknown_frames


def reset_client_metrics_for_tests() -> None:
    global _recv_bytes, _recv_buf_grows, _unknown_frames
    with _recv_lock:
        _recv_bytes = 0
        _recv_buf_grows = 0
        _unknown_frames = 0


class _Pending:
    __slots__ = ("event", "response")

    def __init__(self):
        self.event = threading.Event()
        self.response: Optional[P.FlowResponse] = None


# client-side lease safety margin: stop admitting from a lease at 90% of
# its TTL so a verdict granted locally is never newer than the server's
# idea of the lease's life (clock-rate skew over a 500ms TTL is noise,
# but the margin also absorbs the renew RPC's latency)
_LEASE_EXPIRY_SAFETY = 0.9
# renew-ahead point: refresh at ~45% of TTL (or half the tokens spent,
# whichever comes first) so the replacement slice lands before exhaustion
_LEASE_RENEW_AT = 0.45

# wire rev 6: locally-recorded completion outcomes awaiting coalescence
# onto the next outbound frame. Bounded so a client that never sends again
# (idle, or stuck behind a dead server) holds a fixed amount of memory —
# the deque evicts the OLDEST outcome, keeping the freshest window of
# observations, and evictions are counted (dropped_overflow).
_OUTCOME_BUF_CAP = 8192


class _FlowLease:
    """One cached wire-rev-5 lease: the client-local admission budget for
    a flow. ``used`` only grows under the client's lease lock; the renew
    path retires the object from the cache *first* and reports that final
    ``used``, so tokens are never spent from a slice after its unused part
    was credited back (conservation, client side)."""

    __slots__ = ("lease_id", "tokens", "used", "expiry", "renew_at",
                 "renewing")

    def __init__(self, lease_id: int, tokens: int, used: int,
                 now: float, ttl_ms: int):
        self.lease_id = int(lease_id)
        self.tokens = int(tokens)
        self.used = int(used)
        self.expiry = now + ttl_ms * _LEASE_EXPIRY_SAFETY / 1000.0
        self.renew_at = now + ttl_ms * _LEASE_RENEW_AT / 1000.0
        self.renewing = False


class TokenClient(TokenService):
    def __init__(self, host: str, port: int, timeout_ms: int = 20,
                 namespace: str = "default", lease: bool = False,
                 lease_want: int = 256, lease_backoff_s: float = 0.1,
                 wait_and_admit: bool = False):
        self.host = host
        self.port = port
        self.timeout_ms = timeout_ms
        # declared to the server in the PING handshake; the server scopes
        # its connection counts (AVG_LOCAL scaling) by this group
        self.namespace = namespace
        self._xid = itertools.count(1)
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self._reader: Optional[threading.Thread] = None
        self._last_connect_attempt = 0.0
        # consecutive failed connect attempts since the last success; drives
        # the reconnect backoff and is surfaced for HA health introspection
        self._consecutive_failures = 0
        self._reconnect_delay_s = 0.0
        self._reconnect_base_s = SentinelConfig.get_float(
            "sentinel.tpu.client.reconnect.base.s", RECONNECT_BASE_S
        )
        self._reconnect_max_s = SentinelConfig.get_float(
            "sentinel.tpu.client.reconnect.max.s", RECONNECT_MAX_S
        )
        # wire rev 5 client-local admission: when enabled, hot flows admit
        # from a cached short-TTL lease instead of one RPC per decision.
        # The first miss grants synchronously (that RPC replaces the
        # decision RPC 1:1); renew-ahead refreshes in the background; any
        # refusal (NOT_LEASABLE, NO_RULE, MOVED, transport failure) backs
        # the flow off and the caller falls back to the per-request path —
        # leasing can only remove RPCs, never verdicts.
        self.lease_enabled = bool(lease)
        self.lease_want = max(1, int(lease_want))
        self._lease_backoff_s = float(lease_backoff_s)
        self._lease_lock = threading.Lock()
        self._leases: Dict[int, _FlowLease] = {}
        self._lease_backoff: Dict[int, float] = {}  # flow → retry-after mono
        self._lease_inflight: set = set()  # flows with a grant/renew RPC out
        self._lease_counts = {
            "granted": 0, "renewed": 0, "returned": 0, "refused": 0,
            "expired": 0, "local_admits": 0, "wire_rows": 0,
        }
        self._rpcs = 0  # wire round trips (request/lease/ping/batch chunks)
        # wire rev 6 outcome feedback: completions recorded locally and
        # coalesced into OUTCOME_REPORT frames prepended to the next
        # outbound request frame (zero extra round trips — the report is
        # fire-and-forget, the server never answers it)
        self._outcome_lock = threading.Lock()
        self._outcome_buf: deque = deque(maxlen=_OUTCOME_BUF_CAP)
        self._outcome_counts = {
            "recorded": 0,   # record_outcome calls accepted into the buffer
            "sent": 0,       # rows shipped inside OUTCOME_REPORT frames
            "frames": 0,     # OUTCOME_REPORT frames shipped
            "dropped_overflow": 0,  # oldest rows evicted by the buffer cap
        }
        # opt-in pacing cooperation: a SHOULD_WAIT verdict with a wait hint
        # means the server already reserved the token at now+wait (paced
        # admission / priority occupy) — sleeping out the hint and reporting
        # OK needs no second RPC. Off by default: most callers want the
        # hint, not the blocking.
        self.wait_and_admit = bool(wait_and_admit)
        # wire rev 7 push state (all under _lease_lock): a pushed breaker
        # OPEN parks the flow behind a local DEGRADED clock — admits answer
        # DEGRADED with the pushed retry-after until it expires, so a
        # leased fast path stops within one RTT of the server-side flip
        # instead of at lease TTL. _push_counts tracks applies by kind;
        # _rule_epoch fences RULE_EPOCH_INVALIDATE replays.
        self._breaker_until: Dict[int, float] = {}  # flow → mono deadline
        self._rule_epoch = 0
        self._push_counts = {
            "lease_revoke": 0, "breaker_flip": 0, "rule_epoch_invalidate": 0,
            "shard_map_push": 0, "brownout_advisory": 0, "malformed": 0,
        }
        # out-of-band push listeners: routing subscribes shard-map docs,
        # failover subscribes brownout advisories. Callbacks run on the
        # reader thread — keep them cheap and never let them raise.
        self.on_shard_map: Optional[callable] = None
        self.on_brownout: Optional[callable] = None

    @property
    def consecutive_failures(self) -> int:
        return self._consecutive_failures

    # -- connection management ---------------------------------------------
    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            return True
        with self._state_lock:
            if self._sock is not None:
                return True
            now = time.monotonic()
            if now - self._last_connect_attempt < self._reconnect_delay_s:
                return False
            self._last_connect_attempt = now
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=1.0
                )
                # create_connection leaves its connect timeout on the socket;
                # the reader must block indefinitely or idle periods kill the
                # connection with socket.timeout (an OSError)
                sock.settimeout(None)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock = sock
                self._consecutive_failures = 0
                self._reconnect_delay_s = 0.0
            except OSError as e:
                self._consecutive_failures += 1
                # bounded exponential backoff with jitter: without it, every
                # request-carrying thread retries the dead address in
                # lockstep (connect timeout × request rate = a reconnect
                # storm). Only the first few failures log — the storm used
                # to flood the record log too.
                k = min(self._consecutive_failures, 16)
                self._reconnect_delay_s = min(
                    self._reconnect_base_s * (2 ** (k - 1)),
                    self._reconnect_max_s,
                ) * (1.0 + RECONNECT_JITTER * random.random())
                if self._consecutive_failures <= 3:
                    record_log.warning(
                        "token server unreachable (%d consecutive): %s",
                        self._consecutive_failures, e,
                    )
                return False
            self._reader = threading.Thread(
                target=self._read_loop, args=(sock,), daemon=True,
                name="sentinel-token-client-reader",
            )
            self._reader.start()
            handshake = True
        if handshake:
            # outside _state_lock (ping → _send → _ensure_connected would
            # re-enter it); best-effort — a lost handshake only delays the
            # server's connected-count update to the next keepalive
            self.ping()
        return True

    def _drop_connection(self, sock: socket.socket) -> None:
        with self._state_lock:
            was_active = self._sock is sock
            if was_active:
                self._sock = None
        try:
            # shutdown BEFORE close: the reader thread is blocked in recv on
            # this socket, and CPython defers the real fd close until that
            # call returns — without the shutdown no FIN ever reaches the
            # server and the connection lingers until the idle sweep
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        # Fail waiters so they fall back immediately instead of timing out —
        # but only when the active connection died; a stale reader thread's
        # exit must not abort in-flight requests on a newer connection.
        if was_active:
            for pending in list(self._pending.values()):
                pending.event.set()

    def close(self) -> None:
        try:
            self.flush_outcomes()  # best-effort: don't strand observations
        except Exception:
            pass
        self._return_leases()  # best-effort: unused tokens go back early
        sock = self._sock
        if sock is not None:
            self._drop_connection(sock)

    def _read_loop(self, sock: socket.socket) -> None:
        # growable receive buffer, parsed in place: recv_into lands bytes
        # directly in the bytearray (no per-chunk bytes object), frames are
        # split by offset arithmetic (no per-feed copy/compact), and only
        # payloads that still have a waiter get copied out for the handoff.
        # The buffer doubles when a partial frame fills it (one max frame is
        # 2+65535 bytes, just over the initial 64KiB) and never shrinks —
        # its high-water mark is the deepest response burst seen.
        buf = bytearray(65536)
        view = memoryview(buf)
        r = w = 0  # parse offset / write offset into buf
        head = P._HEAD.size
        try:
            while True:
                if w == len(buf):
                    if r > 0:
                        # reclaim the consumed prefix before growing
                        view[: w - r] = view[r:w]
                        w -= r
                        r = 0
                    else:
                        grown = bytearray(2 * len(buf))
                        grown[:w] = buf
                        buf = grown
                        view = memoryview(buf)
                        _count_recv(0, grows=1)
                n = sock.recv_into(view[w:])
                if n == 0:
                    break
                if chaos.ARMED:  # inbound bit-rot injection (frame_corrupt)
                    data = chaos.mangle(
                        "frame_corrupt", bytes(view[w : w + n])
                    )
                    view[w : w + n] = data
                _count_recv(n)
                w += n
                while w - r >= 2:
                    ln = (buf[r] << 8) | buf[r + 1]
                    # a 2-byte length cannot exceed MAX_FRAME, but a frame
                    # too short for even a header is garbage — drop the
                    # connection (same contract as protocol.FrameReader)
                    if ln < head:
                        raise ValueError("runt frame")
                    if w - r < 2 + ln:
                        break
                    payload = view[r + 2 : r + 2 + ln]
                    r += 2 + ln
                    mtype = P.peek_type(payload)
                    if mtype in P.PUSH_TYPES:
                        # rev-7 push: dispatched out-of-band, never resolves
                        # a pending xid. A malformed push is skipped and
                        # counted — it can't strand a waiter, so it never
                        # justifies killing the connection.
                        self._handle_push(bytes(payload))
                        continue
                    if mtype not in P.KNOWN_TYPES:
                        # a newer server's frame type: skip + count instead
                        # of dropping the connection (mixed-rev fleets)
                        _count_unknown_frame()
                        continue
                    if mtype in P.LEASE_TYPES or mtype in P.HIER_TYPES:
                        rsp = P.decode_lease_response(bytes(payload))
                        pending = self._pending.get(rsp.xid)
                        if pending is not None:
                            pending.response = rsp
                            pending.event.set()
                        continue
                    if mtype == P.MsgType.BATCH_FLOW:
                        # copy + store the raw payload; the waiting thread
                        # decodes (spreads the vectorized decode across
                        # callers). Frames whose waiter already gave up
                        # skip even this copy.
                        xid = int.from_bytes(
                            payload[:4], "big", signed=True
                        )
                        pending = self._pending.get(xid)
                        if pending is not None:
                            pending.response = bytes(payload)
                            pending.event.set()
                        continue
                    rsp = P.decode_response(bytes(payload))
                    pending = self._pending.get(rsp.xid)
                    if pending is not None:
                        pending.response = rsp
                        pending.event.set()
                if r == w:
                    r = w = 0  # fully drained: rewind without compaction
        except OSError:
            pass
        except (ValueError, struct.error):
            # corrupt/truncated server bytes (runt frame, short response):
            # drop the connection gracefully — in-flight requests resolve
            # via _drop_connection below, and the reader thread must never
            # die with a traceback on hostile input
            record_log.warning("malformed frame from server; dropping connection")
        finally:
            self._drop_connection(sock)

    # -- wire rev 7: push dispatch ------------------------------------------
    def _handle_push(self, payload: bytes) -> None:
        """Apply one server push out-of-band (reader thread). Malformed
        pushes are counted and skipped — a push gates no pending request,
        so it never justifies dropping the connection."""
        try:
            push = P.decode_push(payload)
        except (ValueError, struct.error):
            with self._lease_lock:
                self._push_counts["malformed"] += 1
            return
        now = time.monotonic()
        if push.msg_type == P.MsgType.LEASE_REVOKE:
            with self._lease_lock:
                self._push_counts["lease_revoke"] += 1
                lease = self._leases.get(push.flow_id)
                if lease is not None and (
                    push.lease_id == 0 or lease.lease_id == push.lease_id
                ):
                    # stop local admits NOW (the server already reclaimed
                    # the unused slice — charge-at-grant) and hold off the
                    # regrant one backoff so a reload settles first
                    del self._leases[push.flow_id]
                    self._lease_counts["revoked"] = (
                        self._lease_counts.get("revoked", 0) + 1
                    )
                    self._lease_backoff[push.flow_id] = (
                        now + self._lease_backoff_s
                    )
        elif push.msg_type == P.MsgType.BREAKER_FLIP:
            with self._lease_lock:
                self._push_counts["breaker_flip"] += 1
                if push.state == 1:  # OPEN (DEGRADE.md state code)
                    # an OPEN without a pushed clock still parks the flow a
                    # bounded moment; the server's wire-path DEGRADED
                    # answers carry the authoritative retry-after
                    retry_ms = push.retry_after_ms if push.retry_after_ms > 0 else 1000
                    self._breaker_until[push.flow_id] = now + retry_ms / 1000.0
                    lease = self._leases.pop(push.flow_id, None)
                    if lease is not None:
                        self._lease_counts["revoked"] = (
                            self._lease_counts.get("revoked", 0) + 1
                        )
                    backoff = now + retry_ms / 1000.0
                    if backoff > self._lease_backoff.get(push.flow_id, 0.0):
                        self._lease_backoff[push.flow_id] = backoff
                else:
                    # CLOSED or HALF_OPEN: lift the local clock so requests
                    # reach the server again (HALF_OPEN needs wire traffic
                    # for its probe election)
                    self._breaker_until.pop(push.flow_id, None)
        elif push.msg_type == P.MsgType.RULE_EPOCH_INVALIDATE:
            with self._lease_lock:
                self._push_counts["rule_epoch_invalidate"] += 1
                if push.epoch > self._rule_epoch:
                    # every cached lease predates the new rule state:
                    # drop them (and stale backoffs) and re-fetch fresh
                    self._rule_epoch = push.epoch
                    self._leases.clear()
                    self._lease_backoff.clear()
        elif push.msg_type == P.MsgType.SHARD_MAP_PUSH:
            with self._lease_lock:
                self._push_counts["shard_map_push"] += 1
            cb = self.on_shard_map
            if cb is not None:
                try:
                    cb(push.doc)
                except Exception:
                    pass  # a listener bug must not kill the reader
        elif push.msg_type == P.MsgType.BROWNOUT_ADVISORY:
            with self._lease_lock:
                self._push_counts["brownout_advisory"] += 1
            cb = self.on_brownout
            if cb is not None:
                try:
                    cb(push.level, push.retry_after_ms)
                except Exception:
                    pass
        if push.stamp_ms > 0:
            # server-emit → client-apply staleness, off the frame's wall
            # stamp (clock skew makes cross-host samples advisory; the
            # drill's gates run co-located where the stamp is exact)
            try:
                from sentinel_tpu.metrics.server import server_metrics

                server_metrics().record_push_staleness(
                    time.time() * 1000.0 - push.stamp_ms
                )
            except Exception:
                pass

    def _breaker_refusal(self, flow_id: int) -> Optional[TokenResult]:
        """A pushed breaker-OPEN clock still running answers DEGRADED
        locally (remaining carries the retry-after left, the wire
        convention) — the leased fast path stops admitting within one RTT
        of the server-side flip instead of at lease TTL."""
        with self._lease_lock:
            deadline = self._breaker_until.get(flow_id)
            if deadline is None:
                return None
            left_ms = int((deadline - time.monotonic()) * 1000.0)
            if left_ms <= 0:
                del self._breaker_until[flow_id]
                return None
        return TokenResult(TokenStatus.DEGRADED, left_ms, left_ms)

    def push_stats(self) -> Dict[str, int]:
        """Client-side push-apply counters (drill + test surface)."""
        with self._lease_lock:
            out = dict(self._push_counts)
            out["breaker_clocks"] = len(self._breaker_until)
            out["rule_epoch"] = self._rule_epoch
            return out

    # -- TokenService -------------------------------------------------------
    def request_token(self, flow_id, acquire=1, prioritized=False) -> TokenResult:
        if self._breaker_until:
            refusal = self._breaker_refusal(int(flow_id))
            if refusal is not None:
                return refusal
        if self.lease_enabled:
            local = self._lease_admit(int(flow_id), int(acquire))
            if local is not None:
                return local
        with self._lease_lock:
            self._lease_counts["wire_rows"] += 1
        rsp = self._roundtrip(
            P.FlowRequest(next(self._xid), flow_id, acquire, prioritized)
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return self._maybe_wait(TokenResult(
            TokenStatus(rsp.status), rsp.remaining, rsp.wait_ms,
            endpoint=rsp.endpoint,
        ))

    def _maybe_wait(self, res: TokenResult) -> TokenResult:
        """``wait_and_admit`` resolution of a SHOULD_WAIT verdict: the
        server's charge already covers this request at ``now + wait_ms``,
        so sleeping out the hint IS the admission."""
        if (
            self.wait_and_admit
            and res.status == TokenStatus.SHOULD_WAIT
            and res.wait_ms > 0
        ):
            time.sleep(res.wait_ms / 1000.0)
            return TokenResult(
                TokenStatus.OK, res.remaining, res.wait_ms,
                endpoint=res.endpoint,
            )
        return res

    # -- wire rev 5: client-local admission ---------------------------------
    def _lease_admit(self, flow_id: int, acquire: int) -> Optional[TokenResult]:
        """Admit ``acquire`` tokens from the flow's cached lease, or try to
        obtain one (the grant/renew RPC replaces this decision's RPC 1:1).
        ``None`` means no usable lease — the caller takes the per-request
        wire path, so leasing never loses a verdict."""
        if acquire <= 0:
            return None
        now = time.monotonic()
        stale = None
        with self._lease_lock:
            lease = self._leases.get(flow_id)
            if lease is not None:
                if now >= lease.expiry:
                    del self._leases[flow_id]
                    self._lease_counts["expired"] += 1
                elif lease.used + acquire <= lease.tokens:
                    lease.used += acquire
                    self._lease_counts["local_admits"] += 1
                    kick = (
                        not lease.renewing
                        and (now >= lease.renew_at
                             or 2 * lease.used >= lease.tokens)
                    )
                    if kick:
                        lease.renewing = True
                    remaining = lease.tokens - lease.used
                    if kick:
                        self._spawn_renew(flow_id)
                    if _TR.ARMED:  # flight recorder: admitted wire-free
                        _TR.record(
                            _TR.LEASE_LOCAL, xid=flow_id, aux=acquire
                        )
                    return TokenResult(TokenStatus.OK, remaining)
                elif not lease.renewing:
                    # exhausted before the renew-ahead fired: retire it and
                    # renew inline below (credit + regrant, one RPC)
                    del self._leases[flow_id]
                    stale = lease
            if stale is None:
                if now < self._lease_backoff.get(flow_id, 0.0):
                    return None
                if flow_id in self._lease_inflight:
                    return None  # another thread is granting; go to wire
            self._lease_inflight.add(flow_id)
        try:
            if stale is not None:
                rsp = self._lease_roundtrip(
                    P.MsgType.LEASE_RENEW, flow_id,
                    want=max(acquire, self.lease_want),
                    lease_id=stale.lease_id, used=stale.used,
                )
                return self._install_lease(flow_id, rsp, acquire, "renewed")
            rsp = self._lease_roundtrip(
                P.MsgType.LEASE_GRANT, flow_id,
                want=max(acquire, self.lease_want),
            )
            return self._install_lease(flow_id, rsp, acquire, "granted")
        finally:
            with self._lease_lock:
                self._lease_inflight.discard(flow_id)

    def _install_lease(
        self, flow_id: int, rsp, acquire: int, stat: str
    ) -> Optional[TokenResult]:
        """Install a grant/renew response into the cache and admit
        ``acquire`` from it; ``None`` (fall back to wire) on refusal,
        transport failure, or a slice too small for this acquire."""
        now = time.monotonic()
        with self._lease_lock:
            if rsp is None or rsp.status != 0 or rsp.tokens <= 0:
                if rsp is not None:
                    self._lease_counts["refused"] += 1
                self._lease_backoff[flow_id] = now + self._lease_backoff_s
                return None
            self._lease_backoff.pop(flow_id, None)
            self._lease_counts[stat] += 1
            if acquire <= 0:
                # background renew: install the fresh slice, nothing to admit
                self._leases[flow_id] = _FlowLease(
                    rsp.lease_id, rsp.tokens, 0, now, rsp.ttl_ms
                )
                return None
            if rsp.tokens < acquire:
                # slice smaller than this acquire: keep it for smaller
                # acquires, decide this one over the wire
                self._leases[flow_id] = _FlowLease(
                    rsp.lease_id, rsp.tokens, 0, now, rsp.ttl_ms
                )
                return None
            self._leases[flow_id] = _FlowLease(
                rsp.lease_id, rsp.tokens, acquire, now, rsp.ttl_ms
            )
            self._lease_counts["local_admits"] += 1
            return TokenResult(TokenStatus.OK, rsp.tokens - acquire)

    def _spawn_renew(self, flow_id: int) -> None:
        threading.Thread(
            target=self._renew_flow, args=(flow_id,), daemon=True,
            name="sentinel-lease-renew",
        ).start()

    def _renew_flow(self, flow_id: int) -> None:
        """Background renew-ahead: retire the cached lease FIRST (so no
        token is spent from it after its unused part is reported), then
        credit + regrant in one RPC. While the RPC is in flight, admits
        for the flow fall back to the wire — a bounded, tiny window."""
        with self._lease_lock:
            lease = self._leases.pop(flow_id, None)
            if lease is None:
                return
            self._lease_inflight.add(flow_id)
        try:
            rsp = self._lease_roundtrip(
                P.MsgType.LEASE_RENEW, flow_id, want=self.lease_want,
                lease_id=lease.lease_id, used=lease.used,
            )
            self._install_lease(flow_id, rsp, 0, "renewed")
        finally:
            with self._lease_lock:
                self._lease_inflight.discard(flow_id)

    def _lease_roundtrip(
        self, msg_type, flow_id: int, want: int = 0,
        lease_id: int = 0, used: int = 0,
    ):
        """Correlated lease RPC; returns ``P.LeaseResponse`` or None."""
        xid = next(self._xid)
        pending = _Pending()
        self._pending[xid] = pending
        try:
            frame = P.encode_lease_request(
                xid, msg_type, flow_id, want, lease_id=lease_id, used=used
            )
            if not self._send(frame):
                return None
            self._count_rpc()
            if not pending.event.wait(self.timeout_ms / 1000.0):
                return None
            rsp = pending.response
            return rsp if isinstance(rsp, P.LeaseResponse) else None
        finally:
            self._pending.pop(xid, None)

    def _return_leases(self) -> None:
        """Best-effort LEASE_RETURN of every cached lease (close path):
        unused tokens go back instead of expiring with the window."""
        if not self.lease_enabled:
            return
        with self._lease_lock:
            leases = list(self._leases.items())
            self._leases.clear()
        for flow_id, lease in leases:
            rsp = self._lease_roundtrip(
                P.MsgType.LEASE_RETURN, flow_id,
                lease_id=lease.lease_id, used=lease.used,
            )
            if rsp is not None and rsp.status == 0:
                with self._lease_lock:
                    self._lease_counts["returned"] += 1

    def _count_rpc(self) -> None:
        with self._lease_lock:
            self._rpcs += 1

    def lease_stats(self) -> Dict[str, int]:
        """Client-side lease counters for the bench artifact: cumulative
        grant/renew/return/refusal counts, rows admitted locally vs sent
        over the wire, cached leases, and total wire round trips (the
        numerator of rpcs_per_decision)."""
        with self._lease_lock:
            out = dict(self._lease_counts)
            out["cached"] = len(self._leases)
            out["rpcs"] = self._rpcs
            return out

    # -- wire rev 6: completion outcome reporting ----------------------------
    def record_outcome(
        self, flow_id: int, rt_ms, exception: bool = False
    ) -> None:
        """Record one entry completion (response time in ms, exception
        flag) locally. Nothing goes on the wire here — buffered outcomes
        coalesce into one OUTCOME_REPORT frame prepended to the NEXT
        outbound request frame (or shipped by :meth:`flush_outcomes`), so
        the serve path never pays an extra round trip for telemetry."""
        try:
            r = float(rt_ms)
        except (TypeError, ValueError):
            r = float("nan")
        # NaN/inf can't ride an int32 wire row: park at -1 so the server's
        # wire-boundary validation drops + counts it rather than silently
        # wrapping; finite values clamp into int32 (the server enforces
        # the real OUTCOME_MAX_RT_MS ceiling and counts the overage)
        rt = int(min(r, float(2**31 - 1))) if math.isfinite(r) else -1
        with self._outcome_lock:
            if len(self._outcome_buf) == self._outcome_buf.maxlen:
                self._outcome_counts["dropped_overflow"] += 1
            self._outcome_buf.append(
                (int(flow_id), rt, bool(exception))
            )
            self._outcome_counts["recorded"] += 1

    def _drain_outcome_frames(self) -> List[bytes]:
        """Pull every buffered outcome and encode the coalesced
        OUTCOME_REPORT frame(s) — normally one; more only when a burst
        outgrew MAX_OUTCOME_PER_FRAME. Counters update on drain (the
        frames WILL be sent by the caller or the rows are lost with the
        connection, same contract as any fire-and-forget write)."""
        with self._outcome_lock:
            if not self._outcome_buf:
                return []
            rows = list(self._outcome_buf)
            self._outcome_buf.clear()
            self._outcome_counts["sent"] += len(rows)
        frames: List[bytes] = []
        step = P.MAX_OUTCOME_PER_FRAME
        for lo in range(0, len(rows), step):
            chunk = rows[lo:lo + step]
            frames.append(P.encode_outcome_report(
                next(self._xid),
                [c[0] for c in chunk],
                [c[1] for c in chunk],
                [c[2] for c in chunk],
            ))
        with self._outcome_lock:
            self._outcome_counts["frames"] += len(frames)
        return frames

    def _send_outcome_frames(self, frames: List[bytes]) -> bool:
        """Ship already-encoded outcome frames standalone. TCP coalesces
        them into one write; the shm subclass overrides (one ring slot
        carries exactly one frame)."""
        if not frames:
            return True
        return self._send(b"".join(frames), piggyback=False)

    def flush_outcomes(self) -> bool:
        """Force buffered outcomes onto the wire without waiting for the
        next request (idle clients, shutdown). True when nothing was
        pending or the write succeeded."""
        return self._send_outcome_frames(self._drain_outcome_frames())

    def outcome_stats(self) -> Dict[str, int]:
        """Client-side outcome counters: the reconciliation gate checks
        ``sent`` against the server's accepted totals."""
        with self._outcome_lock:
            out = dict(self._outcome_counts)
            out["buffered"] = len(self._outcome_buf)
            return out

    # -- hierarchy tier (pod share agent ↔ global budget coordinator) --------
    def share_op(
        self, msg_type, flow_id: int, want: int = 0,
        share_id: int = 0, used: int = 0,
    ):
        """SHARE_GRANT / SHARE_RENEW / SHARE_RETURN round trip; returns
        ``P.LeaseResponse`` or None. Shares ride the lease frame layout
        (``lease_id`` is the share id), so this is the lease roundtrip
        with a hierarchy type byte."""
        if msg_type not in P.SHARE_TYPES:
            raise ValueError(f"not a share type: {msg_type}")
        return self._lease_roundtrip(
            msg_type, flow_id, want, lease_id=share_id, used=used
        )

    def demand_report(self, pod_id: str, entries):
        """Ship one DEMAND_REPORT (``entries`` = ``[(flow_id, share_id,
        rate_milli), ...]``) and wait for the coordinator's ack; returns
        ``P.LeaseResponse`` (``tokens`` = entries accepted) or None."""
        xid = next(self._xid)
        pending = _Pending()
        self._pending[xid] = pending
        try:
            frame = P.encode_demand_report(xid, pod_id, entries)
            if not self._send(frame):
                return None
            self._count_rpc()
            if not pending.event.wait(self.timeout_ms / 1000.0):
                return None
            rsp = pending.response
            return rsp if isinstance(rsp, P.LeaseResponse) else None
        finally:
            self._pending.pop(xid, None)

    def request_params_token(self, flow_id, acquire, param_hashes) -> TokenResult:
        rsp = self._roundtrip(
            P.FlowRequest(
                next(self._xid), flow_id, acquire, False,
                P.MsgType.PARAM_FLOW, tuple(param_hashes),
            )
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return TokenResult(
            TokenStatus(rsp.status), rsp.remaining, rsp.wait_ms,
            endpoint=rsp.endpoint,
        )

    def request_concurrent_token(self, flow_id, acquire=1, prioritized=False):
        rsp = self._roundtrip(
            P.FlowRequest(
                next(self._xid), flow_id, acquire, prioritized,
                P.MsgType.CONCURRENT_ACQUIRE,
            )
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return TokenResult(
            TokenStatus(rsp.status), rsp.remaining, rsp.wait_ms, rsp.token_id
        )

    def release_concurrent_token(self, token_id):
        # the flow_id slot carries the token id on the wire (protocol docstring)
        rsp = self._roundtrip(
            P.FlowRequest(
                next(self._xid), token_id, 0, False, P.MsgType.CONCURRENT_RELEASE
            )
        )
        if rsp is None:
            return TokenResult(TokenStatus.FAIL)
        return TokenResult(TokenStatus(rsp.status))

    def request_batch_arrays(self, flow_ids, counts=None, prios=None,
                             timeout_ms: Optional[int] = None):
        """Array-in/array-out batched verdicts: (status int8[N], remaining
        int32[N], wait_ms int32[N]) in request order, or None on send
        failure/timeout.

        With leasing enabled, rows of a flow whose cached lease covers the
        flow's ENTIRE in-batch demand are admitted locally (zero wire
        bytes); only the rest ride BATCH_FLOW frames. Lease consumption is
        rolled back if the wire leg fails, so the None contract still means
        "nothing was admitted"."""
        import numpy as np

        if not self.lease_enabled:
            return self._wire_batch_arrays(flow_ids, counts, prios,
                                           timeout_ms)
        flow_ids = np.asarray(flow_ids, dtype=np.int64)
        n = flow_ids.shape[0]
        if n == 0:
            e = np.empty(0, np.int32)
            return np.empty(0, np.int8), e, e
        acq = (np.ones(n, np.int64) if counts is None
               else np.asarray(counts, np.int64))
        local = np.zeros(n, bool)
        remaining = np.zeros(n, np.int32)
        now = time.monotonic()
        taken = []  # (flow_id, amount, lease) for rollback
        kicks = []
        with self._lease_lock:
            for fid in np.unique(flow_ids):
                f = int(fid)
                lease = self._leases.get(f)
                if lease is None:
                    continue
                if now >= lease.expiry:
                    del self._leases[f]
                    self._lease_counts["expired"] += 1
                    continue
                rows = flow_ids == fid
                demand = int(acq[rows].sum())
                # all-or-nothing per flow: a partial cover would need
                # per-row splits; those rows just ride the wire this time
                if demand <= 0 or lease.used + demand > lease.tokens:
                    continue
                lease.used += demand
                taken.append((f, demand, lease))
                local[rows] = True
                remaining[rows] = lease.tokens - lease.used
                if not lease.renewing and (
                    now >= lease.renew_at or 2 * lease.used >= lease.tokens
                ):
                    lease.renewing = True
                    kicks.append(f)
            n_local = int(local.sum())
            self._lease_counts["local_admits"] += n_local
        for f in kicks:
            self._spawn_renew(f)
        if n_local == n:
            return (np.zeros(n, np.int8), remaining, np.zeros(n, np.int32))
        widx = np.nonzero(~local)[0]
        out = self._wire_batch_arrays(
            flow_ids[widx],
            None if counts is None else np.asarray(counts)[widx],
            None if prios is None else np.asarray(prios)[widx],
            timeout_ms,
        )
        if out is None:
            if taken:
                # un-admit the local rows: the caller retries the whole
                # batch elsewhere, so nothing may stay spent here
                with self._lease_lock:
                    for f, amount, lease in taken:
                        if self._leases.get(f) is lease:
                            lease.used -= amount
                    self._lease_counts["local_admits"] -= n_local
            return None
        if n_local == 0:
            return out
        status = np.zeros(n, np.int8)
        wait = np.zeros(n, np.int32)
        status[widx], remaining[widx], wait[widx] = out
        return status, remaining, wait

    def _wire_batch_arrays(self, flow_ids, counts=None, prios=None,
                           timeout_ms: Optional[int] = None):
        """The BATCH_FLOW wire path. Batches larger than one frame are
        **pipelined**: every chunk frame is sent before the first response
        is awaited, so the server's micro-batcher sees them back-to-back
        and a chunked batch costs one round trip, not one per chunk.
        """
        import numpy as np

        flow_ids = np.asarray(flow_ids, dtype=np.int64)
        n = flow_ids.shape[0]
        if n == 0:
            e = np.empty(0, np.int32)
            return np.empty(0, np.int8), e, e
        budget = (timeout_ms or self.timeout_ms) / 1000.0
        chunk = P.MAX_BATCH_PER_FRAME
        spans = [(i, min(i + chunk, n)) for i in range(0, n, chunk)]
        pendings = []
        try:
            for lo, hi in spans:
                xid = next(self._xid)
                pending = _Pending()
                self._pending[xid] = pending
                pendings.append((xid, pending, lo, hi))
                frame = P.encode_batch_request(
                    xid, flow_ids[lo:hi],
                    None if counts is None else counts[lo:hi],
                    None if prios is None else prios[lo:hi],
                    # declare the whole budget as the frame's deadline: a
                    # deadline-aware server sheds the frame instead of
                    # serving a verdict this client stopped waiting for
                    deadline_ms=max(1, int(budget * 1000)),
                )
                if not self._send(frame):
                    return None
                self._count_rpc()
            with self._lease_lock:
                self._lease_counts["wire_rows"] += n
            status = np.empty(n, np.int8)
            remaining = np.empty(n, np.int32)
            wait = np.empty(n, np.int32)
            deadline = time.monotonic() + budget
            for xid, pending, lo, hi in pendings:
                if not pending.event.wait(max(deadline - time.monotonic(), 0)):
                    return None
                payload = pending.response
                if not isinstance(payload, (bytes, bytearray)):
                    return None  # connection died mid-batch
                try:
                    _, st, rem, wt = P.decode_batch_response(payload)
                except Exception:
                    # truncated/malformed server frame degrades to the
                    # documented None contract, never an exception out of
                    # the caller (the local-fallback path handles None)
                    return None
                if st.shape[0] != hi - lo:
                    return None
                status[lo:hi] = st
                remaining[lo:hi] = rem
                wait[lo:hi] = wt
            return status, remaining, wait
        finally:
            for xid, _, _, _ in pendings:
                self._pending.pop(xid, None)

    def request_batch(self, requests) -> list:
        """List-of-(flow_id, acquire, prioritized) → List[TokenResult]
        (TokenService.request_batch over the wire)."""
        import numpy as np

        if not requests:
            return []
        n = len(requests)
        out = self.request_batch_arrays(
            np.fromiter((f for f, _, _ in requests), np.int64, n),
            np.fromiter((a for _, a, _ in requests), np.int32, n),
            np.fromiter((p for _, _, p in requests), bool, n),
        )
        if out is None:
            return [TokenResult(TokenStatus.FAIL)] * n
        status, remaining, wait = out
        return [
            TokenResult(TokenStatus(int(status[i])), int(remaining[i]),
                        int(wait[i]))
            for i in range(n)
        ]

    def ping(self, namespace: Optional[str] = None) -> bool:
        """Handshake/keepalive; declares a namespace this client serves
        (``TokenServerHandler.handlePingRequest``). One connection may
        declare several namespaces — each ping adds one group membership."""
        return self.ping_ex(namespace) is True

    def ping_ex(self, namespace: Optional[str] = None) -> Optional[bool]:
        """Ping that separates transport failure from the server's answer:
        ``None`` when no response arrived (dead host, timeout, send
        failure), else the server's verdict — status 0 means the namespace
        group accepted this connection. Failover health accounting charges
        an endpoint's breaker only for the ``None`` case."""
        rsp = self._roundtrip(
            P.Ping(next(self._xid), namespace or self.namespace)
        )
        if rsp is None:
            return None
        return rsp.status == 0

    def _roundtrip(self, req) -> Optional[P.FlowResponse]:
        """Correlated request/response: register pending, send, wait, pop."""
        pending = _Pending()
        self._pending[req.xid] = pending
        try:
            if not self._send(P.encode_request(req)):
                return None
            self._count_rpc()
            if not pending.event.wait(self.timeout_ms / 1000.0):
                return None  # timeout → caller falls back (20ms budget blown)
            return pending.response
        finally:
            self._pending.pop(req.xid, None)

    def _send(self, data: bytes, piggyback: bool = True) -> bool:
        if piggyback and self._outcome_buf:
            # rev-6 piggyback: buffered completion outcomes ride ahead of
            # this frame in the SAME sendall — one syscall, zero extra
            # round trips (the server never answers an OUTCOME_REPORT)
            frames = self._drain_outcome_frames()
            if frames:
                data = b"".join(frames) + data
        if not self._ensure_connected():
            return False
        sock = self._sock
        if sock is None:
            return False
        if chaos.ARMED:
            if chaos.should("conn_reset"):  # RST mid-request
                self._drop_connection(sock)
                return False
            data = chaos.mangle("frame_corrupt", data)  # outbound bit rot
        try:
            with self._send_lock:
                sock.sendall(data)
            return True
        except OSError:
            self._drop_connection(sock)
            return False
