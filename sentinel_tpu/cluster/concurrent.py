"""Cluster-wide concurrency (semaphore) flow control.

Analog of the reference's concurrent token mode
(``sentinel-cluster-server-default``):

- ``CurrentConcurrencyManager.java:37-95`` — per-flowId ``nowCalls`` counter;
- ``ConcurrentClusterFlowChecker.java:48-74`` — synchronized check+add with
  ``concurrencyLevel = count × (GLOBAL ? 1 : connectedCount)``;
- ``TokenCacheNodeManager.java:28-71`` — issued token-id cache
  (ConcurrentLinkedHashMap in the reference; an insertion-ordered dict here,
  which is the same structure — tokens expire in issue order because every
  token of one rule shares a TTL);
- ``RegularExpireStrategy`` — background/amortized sweep of expired tokens so
  a crashed client cannot leak permits forever.

This path is host-side by design: acquire/release is a keyed mutable cache
with TTLs and sub-microsecond critical sections — there are no FLOPs to ship
to the TPU, and a device round-trip per release would only add latency. The
single host lock replaces the reference's per-structure synchronization.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.engine import TokenStatus
from sentinel_tpu.engine.rules import ThresholdMode

DEFAULT_RESOURCE_TIMEOUT_MS = 2_000  # ClusterFlowConfig#resourceTimeout default
_SWEEP_PER_ACQUIRE = 64  # amortized RegularExpireStrategy budget per acquire


@dataclass(frozen=True)
class ConcurrentFlowRule:
    """Concurrency-mode cluster rule: at most ``concurrency_level`` permits
    held at once across the cluster (× connected clients when AVG_LOCAL)."""

    flow_id: int
    concurrency_level: int
    mode: ThresholdMode = ThresholdMode.GLOBAL
    resource_timeout_ms: int = DEFAULT_RESOURCE_TIMEOUT_MS
    namespace: str = "default"  # AVG_LOCAL scales by this namespace's clients


@dataclass
class TokenCacheNode:
    """``TokenCacheNode.java`` — one issued permit."""

    token_id: int
    flow_id: int
    acquire: int
    expire_at_ms: int


@dataclass(frozen=True)
class AcquireResult:
    status: TokenStatus
    token_id: int = 0
    remaining: int = 0


class ConcurrencyManager:
    """Owns ``nowCalls`` per flow + the issued-token cache.

    Single-writer under one lock (the reference stripes this across an
    AtomicInteger per flow, a synchronized checker, and a concurrent map —
    the TPU build keeps host mutation single-writer per SURVEY.md §5)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: Dict[int, ConcurrentFlowRule] = {}
        self._now_calls: Dict[int, int] = {}
        self._tokens: Dict[int, TokenCacheNode] = {}  # insertion-ordered
        self._ids = itertools.count(1)
        self._connected: Dict[str, int] = {}  # namespace → client count

    # -- config -------------------------------------------------------------
    def load_rules(self, rules: List[ConcurrentFlowRule]) -> None:
        with self._lock:
            self._rules = {r.flow_id: r for r in rules}
            # permits for deleted rules drain naturally via release/expiry

    def has_rules(self) -> bool:
        with self._lock:
            return bool(self._rules)

    def set_connected_count(self, n: int, namespace: str = "default") -> None:
        """ConnectionManager callback, scoped per namespace
        (``ConnectionManager.java:30-58``)."""
        with self._lock:
            self._connected[namespace] = max(1, int(n))

    # -- introspection --------------------------------------------------------
    def now_calls(self, flow_id: int) -> int:
        with self._lock:
            return self._now_calls.get(int(flow_id), 0)

    def token_count(self) -> int:
        with self._lock:
            return len(self._tokens)

    # -- hot path -------------------------------------------------------------
    def acquire(
        self,
        flow_id: int,
        acquire: int = 1,
        prioritized: bool = False,
        now_ms: Optional[int] = None,
    ) -> AcquireResult:
        """``ConcurrentClusterFlowChecker.acquireConcurrentToken``: admit iff
        ``nowCalls + acquire ≤ level``; on pass, issue a cached token id."""
        flow_id = int(flow_id)
        now = _clock.now_ms() if now_ms is None else int(now_ms)
        with self._lock:
            self._sweep_locked(now, _SWEEP_PER_ACQUIRE)
            rule = self._rules.get(flow_id)
            if rule is None:
                return AcquireResult(TokenStatus.NO_RULE_EXISTS)
            if acquire <= 0:
                return AcquireResult(TokenStatus.FAIL)
            level = rule.concurrency_level * (
                1
                if rule.mode == ThresholdMode.GLOBAL
                else self._connected.get(rule.namespace, 1)
            )
            held = self._now_calls.get(flow_id, 0)
            if held + acquire > level:
                return AcquireResult(
                    TokenStatus.BLOCKED, remaining=max(0, level - held)
                )
            self._now_calls[flow_id] = held + acquire
            token_id = next(self._ids)
            self._tokens[token_id] = TokenCacheNode(
                token_id, flow_id, acquire, now + rule.resource_timeout_ms
            )
            return AcquireResult(
                TokenStatus.OK, token_id, max(0, level - held - acquire)
            )

    def release(self, token_id: int) -> TokenStatus:
        """``ConcurrentClusterFlowChecker.releaseConcurrentToken``: idempotent —
        a token already released (or expired by the sweeper) reports
        ALREADY_RELEASE rather than double-decrementing."""
        with self._lock:
            node = self._tokens.pop(int(token_id), None)
            if node is None:
                return TokenStatus.ALREADY_RELEASE
            self._dec_locked(node)
            return TokenStatus.RELEASE_OK

    # -- expiry (RegularExpireStrategy analog) --------------------------------
    def expire(self, now_ms: Optional[int] = None,
               limit: Optional[int] = None) -> int:
        """Sweep expired tokens; returns the number reclaimed. ``limit``
        bounds entries *inspected* (hot-path callers); the background task
        passes None for a full scan — issue order only clusters expired
        tokens at the front per rule, so short-TTL tokens stuck behind a
        long-TTL rule's live permits need the unbounded sweep."""
        now = _clock.now_ms() if now_ms is None else int(now_ms)
        with self._lock:
            return self._sweep_locked(
                now, len(self._tokens) if limit is None else limit
            )

    def _sweep_locked(self, now: int, limit: int) -> int:
        # `limit` bounds entries *inspected*, not reclaimed, so an acquire-path
        # sweep is O(limit) even when nothing is expired (50k live permits must
        # not put a full-dict scan inside the hot-path critical section)
        expired = []
        for inspected, (token_id, node) in enumerate(self._tokens.items()):
            if inspected >= limit:
                break
            if node.expire_at_ms <= now:
                expired.append(token_id)
        for token_id in expired:
            self._dec_locked(self._tokens.pop(token_id))
        return len(expired)

    def _dec_locked(self, node: TokenCacheNode) -> None:
        held = self._now_calls.get(node.flow_id, 0) - node.acquire
        if held > 0:
            self._now_calls[node.flow_id] = held
        else:
            self._now_calls.pop(node.flow_id, None)


class ExpiryTask:
    """Background sweep thread (``RegularExpireStrategy`` analog)."""

    def __init__(self, manager: ConcurrencyManager, interval_s: float = 0.5):
        self._manager = manager
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="sentinel-concurrent-expiry", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            if thread.is_alive():
                # still draining a long sweep: leave the stop event set so it
                # exits at its next wait; a re-start would duplicate sweepers
                return
            self._thread = None
        self._stop.clear()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._manager.expire()
