"""Token server transport: asyncio TCP front door(s) + micro-batcher.

Analog of ``NettyTransportServer.java:51`` + ``TokenServerHandler.java:39``,
re-shaped for the TPU data plane: instead of one decision per channelRead, the
handler enqueues requests and an **adaptive** batcher drains everything queued
into one device step the moment the device is free — batches grow naturally
with load (arrivals pile up behind the in-flight step) and a lone request
pays no batching delay. This is what turns the reference's 20ms RPC budget
(``ClusterConstants.java:44``) into sub-ms micro-batches with room to spare.

Two throughput mechanisms layered on top (round-3):

- **BATCH_FLOW frames**: one frame carries N requests (protocol.py), decoded
  to numpy arrays in one shot and answered with one vectorized response
  frame — per-request Python cost drops ~100×. Mirrors how the reference
  amortizes netty channel reads with its batched ``FlowRequestData`` writer,
  taken further because the device wants big batches anyway.
- **Multi-loop IO** (``n_loops > 1``): N acceptor/reader event loops share
  the listening port via SO_REUSEPORT, each with its own micro-batcher, all
  feeding one ``TokenService`` (whose lock covers only device dispatch).
  The asyncio analog of ``NettyTransportServer.java:73-101``'s boss/worker
  pools (workers = 2×cores).

The asyncio loops run on dedicated threads (``start()``/``stop()`` are
host-thread-safe); large device steps run in a worker thread so the IO loop
keeps pumping frames while XLA executes.
"""

from __future__ import annotations

import asyncio
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sentinel_tpu import chaos
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.connection import ConnectionManager
from sentinel_tpu.cluster.token_service import TokenService
from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine import TokenStatus
from sentinel_tpu.metrics.profiler import ProfilerHook
from sentinel_tpu.metrics.server import server_metrics
from sentinel_tpu.overload import AdmissionController, BrownoutLevel
from sentinel_tpu.trace import ring as _TR
from sentinel_tpu.trace.slo import slo_plane as _slo_plane

_SM = server_metrics()
_OVERLOAD = int(TokenStatus.OVERLOAD)
_STANDBY = int(TokenStatus.STANDBY)


class _BatchFrame:
    """A decoded BATCH_FLOW request frame awaiting its verdict slice."""

    __slots__ = ("xid", "flow_ids", "counts", "prios", "deadline_ms")

    def __init__(self, payload: bytes):
        self.xid, self.flow_ids, self.counts, self.prios = (
            P.decode_batch_request(payload)
        )
        # rev-2 relative deadline trailer (0 = none declared)
        self.deadline_ms = P.decode_batch_deadline(payload)


class _LoopWorker:
    """One event loop: acceptor + per-connection readers + micro-batcher."""

    def __init__(self, server: "TokenServer", index: int):
        self.server = server
        self.index = index
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.queue: Optional[asyncio.Queue] = None
        self.inflight = 0  # _process tasks alive (loop-thread only)
        self.thread: Optional[threading.Thread] = None
        self.aserver: Optional[asyncio.AbstractServer] = None
        self.started = threading.Event()
        self.start_error: Optional[BaseException] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self.thread = threading.Thread(
            target=self._run, name=f"sentinel-token-server-{self.index}",
            daemon=True,
        )
        self.thread.start()

    def stop(self) -> None:
        loop = self.loop
        self.loop = None
        if loop is not None:
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass  # loop already stopped itself (failed bind) or closed
        if self.thread is not None:
            self.thread.join(timeout=5)
            self.thread = None
        self.started.clear()

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self.loop = loop
        self.queue = asyncio.Queue()
        loop.create_task(self._serve())
        loop.create_task(self._batcher())
        try:
            loop.run_forever()
        finally:
            if self.aserver is not None:
                self.aserver.close()
            tasks = asyncio.all_tasks(loop)
            for task in tasks:
                task.cancel()
            if tasks:
                try:
                    loop.run_until_complete(
                        asyncio.gather(*tasks, return_exceptions=True)
                    )
                except RuntimeError:
                    pass  # a concurrent stop() interrupted the drain
            loop.close()

    async def _serve(self) -> None:
        srv = self.server
        try:
            # SO_REUSEPORT spreads incoming connections across the workers'
            # listening sockets in the kernel — no user-space handoff
            self.aserver = await asyncio.start_server(
                self._on_connection, srv.host, srv.port,
                reuse_port=(srv.n_loops > 1),
            )
        except OSError as e:
            self.start_error = e
            self.started.set()
            asyncio.get_event_loop().stop()
            return
        addr = self.aserver.sockets[0].getsockname()
        srv.port = addr[1]  # resolve port 0 → actual (worker 0 binds first)
        if self.index == 0:
            record_log.info(
                "token server listening on %s:%d (%d loops)",
                addr[0], addr[1], srv.n_loops,
            )
        self.started.set()

    # -- per-connection reader ---------------------------------------------
    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        srv = self.server
        frames = P.FrameReader()
        peer = writer.get_extra_info("peername")
        address = f"{peer[0]}:{peer[1]}" if peer else repr(writer)
        repl_session = None  # per-connection rev-3 chunk reassembly, lazy
        move_session = None  # per-connection rev-4 move channel, lazy
        loop = asyncio.get_running_loop()
        srv.connections.attach_closer(
            address, lambda: loop.call_soon_threadsafe(writer.close)
        )
        # rev-7 push sink: emitters run on arbitrary threads (lease sweep,
        # breaker scan, brownout eval), so frames hop onto this loop and
        # ride the connection's reply lane via the same non-blocking
        # writer.write the verdict flushes use — a push never waits and
        # never blocks a verdict
        srv.push_hub.attach(
            address,
            lambda frame: loop.call_soon_threadsafe(writer.write, frame),
        )
        try:
            while True:
                data = await reader.read(65536)
                if not data:
                    break
                try:
                    payloads = frames.feed(data)
                except ValueError:
                    record_log.warning("oversized frame from client; closing")
                    return
                for payload in payloads:
                    if chaos.ARMED and chaos.should("frame_drop"):
                        # the frame vanishes pre-decode; only the client's
                        # timeout resolves it (the invariant under test)
                        _SM.count_shed("chaos_drop", 1)
                        continue
                    mtype = P.peek_type(payload)
                    if mtype in P.REPL_TYPES:
                        # wire rev 3 (replication control plane): the
                        # primary's sender speaks to this door directly.
                        # Non-standby servers close — a repl frame here
                        # means a misconfigured sender.
                        if srv.applier is None:
                            record_log.warning(
                                "repl frame on non-standby server; closing"
                            )
                            return
                        if repl_session is None:
                            repl_session = srv.applier.connection()
                        try:
                            repl_session.handle(payload, writer.write)
                        except ValueError:
                            record_log.warning("torn repl stream; closing")
                            return
                        await writer.drain()
                        continue
                    if mtype in P.MOVE_TYPES:
                        # wire rev 4 (live-move control plane): a source
                        # server's MoveCoordinator drains a namespace into
                        # this one. Routed like the repl channel; the
                        # session discards staged state on disconnect.
                        if move_session is None:
                            move_session = srv.move_target.connection()
                        try:
                            move_session.handle(payload, writer.write)
                        except ValueError:
                            record_log.warning("torn move stream; closing")
                            return
                        await writer.drain()
                        continue
                    if mtype in P.LEASE_TYPES:
                        # wire rev 5 (client-local admission): lease ops are
                        # control-plane-rare (one per TTL per hot flow), so
                        # they skip the micro-batch queue and run the
                        # service's host-side grant/renew/return directly —
                        # to_thread keeps the device fold off the event loop
                        try:
                            (xid, lmt, lease_id, lflow, used, want) = (
                                P.decode_lease_request(payload)
                            )
                        except Exception:
                            record_log.warning(
                                "bad lease frame from client; closing"
                            )
                            return
                        srv.connections.touch(address)
                        if _TR.ARMED:  # flight recorder: lease control hop
                            _TR.record(
                                _TR.LEASE, xid=xid, shard=self.index,
                                aux=want,
                            )
                        if srv.is_standby:
                            # proof-of-life refusal, same contract as the
                            # decision path: the client falls back to
                            # per-request RPCs and the failover layer never
                            # evicts this endpoint
                            writer.write(P.encode_lease_response(
                                xid, lmt, _STANDBY
                            ))
                            await writer.drain()
                            continue
                        lease_fn = getattr(srv.service, "lease_grant", None)
                        if lease_fn is None:
                            # SPI impl without leases: refuse, don't die
                            writer.write(P.encode_lease_response(
                                xid, lmt, P.NOT_LEASABLE_STATUS
                            ))
                            await writer.drain()
                            continue
                        if lmt == P.MsgType.LEASE_GRANT:
                            res = await asyncio.to_thread(
                                srv.service.lease_grant, lflow, want
                            )
                        elif lmt == P.MsgType.LEASE_RENEW:
                            res = await asyncio.to_thread(
                                srv.service.lease_renew,
                                lease_id, lflow, used, want,
                            )
                        else:
                            res = await asyncio.to_thread(
                                srv.service.lease_return, lease_id, used
                            )
                        writer.write(P.encode_lease_response(
                            xid, lmt, int(res.status),
                            lease_id=res.lease_id, tokens=res.tokens,
                            ttl_ms=res.ttl_ms, endpoint=res.endpoint,
                        ))
                        await writer.drain()
                        continue
                    if mtype in P.HIER_TYPES:
                        # hierarchy tier: pod share agents leasing from the
                        # co-located global budget coordinator. Control-
                        # plane-rare (one frame per agent tick); the
                        # coordinator is a host-side ledger, so to_thread
                        # keeps its lock wait off the event loop.
                        hier = getattr(srv.service, "hierarchy", None)
                        try:
                            if mtype == P.MsgType.DEMAND_REPORT:
                                xid, pod_id, entries = (
                                    P.decode_demand_report(payload)
                                )
                                hmt = P.MsgType.DEMAND_REPORT
                                args = (pod_id, entries)
                            else:
                                (xid, hmt, share_id, hflow, used, want) = (
                                    P.decode_lease_request(payload)
                                )
                                args = (share_id, hflow, used, want)
                        except Exception:
                            record_log.warning(
                                "bad hier frame from agent; closing"
                            )
                            return
                        srv.connections.touch(address)
                        if _TR.ARMED:  # flight recorder: hierarchy hop
                            _TR.record(_TR.HIER, xid=xid, shard=self.index)
                        if srv.is_standby:
                            writer.write(P.encode_lease_response(
                                xid, hmt, _STANDBY
                            ))
                            await writer.drain()
                            continue
                        if hier is None:
                            # no coordinator co-located here: refuse, the
                            # agent's failover walk tries the next endpoint
                            writer.write(P.encode_lease_response(
                                xid, hmt, P.NOT_LEASABLE_STATUS
                            ))
                            await writer.drain()
                            continue
                        if hmt == P.MsgType.DEMAND_REPORT:
                            res = await asyncio.to_thread(
                                hier.handle_demand_report, *args
                            )
                        elif hmt == P.MsgType.SHARE_GRANT:
                            res = await asyncio.to_thread(
                                hier.share_grant, args[1], args[3]
                            )
                        elif hmt == P.MsgType.SHARE_RENEW:
                            res = await asyncio.to_thread(
                                hier.share_renew,
                                args[0], args[1], args[2], args[3],
                            )
                        else:
                            res = await asyncio.to_thread(
                                hier.share_return, args[0], args[2]
                            )
                        writer.write(P.encode_lease_response(
                            xid, hmt, int(res.status),
                            lease_id=res.lease_id, tokens=res.tokens,
                            ttl_ms=res.ttl_ms, endpoint=res.endpoint,
                        ))
                        await writer.drain()
                        continue
                    if mtype in P.OUTCOME_TYPES:
                        # wire rev 6 (outcome feedback): a client's coalesced
                        # completion report, piggy-backed ahead of its next
                        # request frame. Fire-and-forget — NO response frame,
                        # so the lease/request fast path never waits on it.
                        try:
                            oxid, ofids, orts, oexcs = (
                                P.decode_outcome_report(payload)
                            )
                        except Exception:
                            record_log.warning("bad outcome frame; closing")
                            return
                        srv.connections.touch(address)
                        if srv.is_standby:
                            # outcome columns replicate from the primary;
                            # counting here would double on promotion
                            continue
                        await asyncio.to_thread(
                            srv.service.report_outcomes,
                            ofids, orts, oexcs, oxid,
                        )
                        continue
                    if mtype == P.MsgType.BATCH_FLOW:
                        # vectorized decode; no per-request Python objects
                        try:
                            item = _BatchFrame(payload)
                        except Exception:
                            record_log.warning("bad batch frame; closing")
                            return
                        srv.connections.touch(address)
                        k = len(item.flow_ids)
                        if _TR.ARMED:  # flight recorder: frame decoded
                            _TR.record(
                                _TR.CLIENT_IN, xid=item.xid,
                                shard=self.index, aux=k,
                            )
                        if srv.is_standby:
                            # redirect-style refusal: this node replicates
                            # from a live primary and must not double-count
                            # — the failover client walks on (STANDBY is
                            # proof of life, not failure)
                            writer.write(
                                P.encode_batch_response(
                                    item.xid,
                                    np.full(k, _STANDBY, np.int8),
                                    np.zeros(k, np.int32),
                                    np.zeros(k, np.int32),
                                )
                            )
                            await writer.drain()
                            continue
                        if (
                            srv.max_queue
                            and self.queue.qsize() >= srv.max_queue
                        ):
                            # queue full: an explicit OVERLOAD answer NOW
                            # beats silently queueing past the client's
                            # budget (the old failure mode: timeout + a
                            # mis-charged failover breaker)
                            _SM.count_shed("queue_full", k)
                            if _TR.ARMED:
                                _TR.record(
                                    _TR.SHED, xid=item.xid,
                                    shard=self.index, aux=k,
                                )
                            ns_fn = getattr(
                                srv.service, "namespace_index", None
                            )
                            if ns_fn is not None:
                                _slo_plane().record_shed_indexed(
                                    *ns_fn(item.flow_ids),
                                    reason="queue_full",
                                )
                            writer.write(
                                P.encode_batch_response(
                                    item.xid,
                                    np.full(k, _OVERLOAD, np.int8),
                                    np.zeros(k, np.int32),
                                    np.full(
                                        k, srv.overload.retry_hint_ms,
                                        np.int32,
                                    ),
                                )
                            )
                            await writer.drain()
                            continue
                        deadline = (
                            loop.time() + item.deadline_ms / 1000.0
                            if item.deadline_ms
                            else None
                        )
                        srv.overload.note_enqueued(k)
                        if _TR.ARMED:  # flight recorder: queued for batch
                            _TR.record(
                                _TR.ENQUEUE, xid=item.xid,
                                shard=self.index, aux=self.queue.qsize(),
                            )
                        await self.queue.put(
                            (item, writer, loop.time(), deadline)
                        )
                        continue
                    try:
                        req = P.decode_request(payload)
                    except Exception:
                        record_log.warning("bad frame from client; closing")
                        return
                    if isinstance(req, P.Ping):
                        # handshake: bind this connection to its namespace
                        # group; answer with the group's connected count
                        # (TokenServerHandler.handlePingRequest). Also
                        # refreshes the connection's liveness for the idle
                        # sweep (ScanIdleConnectionTask analog).
                        count = srv.connections.add(req.namespace, address)
                        writer.write(
                            P.encode_response(
                                P.FlowResponse(
                                    req.xid, P.MsgType.PING, 0,
                                    remaining=count,
                                )
                            )
                        )
                        await writer.drain()
                    else:
                        srv.connections.touch(address)
                        if srv.is_standby:
                            writer.write(
                                P.encode_response(
                                    P.FlowResponse(
                                        req.xid, req.msg_type, _STANDBY,
                                        0, 0,
                                    )
                                )
                            )
                            await writer.drain()
                            continue
                        if (
                            srv.max_queue
                            and self.queue.qsize() >= srv.max_queue
                        ):
                            _SM.count_shed("queue_full", 1)
                            writer.write(
                                P.encode_response(
                                    P.FlowResponse(
                                        req.xid, req.msg_type, _OVERLOAD,
                                        0, srv.overload.retry_hint_ms,
                                    )
                                )
                            )
                            await writer.drain()
                            continue
                        srv.overload.note_enqueued(1)
                        await self.queue.put(
                            (req, writer, loop.time(), None)
                        )
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            if move_session is not None:
                # a source that died mid-move must not leave a staged
                # claim behind (crash matrix: dest discards, source owns)
                move_session.closed()
            srv.push_hub.detach(address)
            srv.connections.remove_address(address)
            try:
                writer.close()
            except Exception:
                pass

    # -- micro-batcher ------------------------------------------------------
    async def _batcher(self) -> None:
        """Adaptive micro-batching with bounded in-flight steps.

        While a device step is in flight, new arrivals pile up in the queue
        and the next iteration drains them all in one go — so batches grow
        naturally with load and a lone request under light load pays ZERO
        batching delay. A fixed collect window (``batch_window_ms > 0``) is
        still honored for callers that prefer bigger batches over tail
        latency.

        Up to ``srv.max_inflight`` batches are processed CONCURRENTLY
        (``_process`` runs as a task gated by a semaphore): with JAX's async
        dispatch, batch k+1's host prep and dispatch overlap batch k's
        device execution and response encode — the device never waits for
        Python between steps. Responses are xid-correlated, so cross-batch
        completion order is free to vary.
        """
        srv = self.server
        sem = asyncio.Semaphore(max(1, srv.max_inflight))
        loop = asyncio.get_running_loop()
        while True:
            first = await self.queue.get()
            if chaos.ARMED:  # lane_delay: a descheduled batcher
                d = chaos.delay_s("lane_delay")
                if d:
                    await asyncio.sleep(d)
            # item = (request, writer, t_enqueued, abs_deadline | None)
            batch: List[Tuple[object, asyncio.StreamWriter, float, object]] = [
                first
            ]
            total = self._n_requests(first[0])
            while total < srv.max_batch:
                try:
                    item = self.queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                batch.append(item)
                total += self._n_requests(item[0])
            if srv.batch_window_ms > 0:
                deadline = (
                    asyncio.get_event_loop().time()
                    + srv.batch_window_ms / 1000.0
                )
                while total < srv.max_batch:
                    timeout = deadline - asyncio.get_event_loop().time()
                    if timeout <= 0:
                        break
                    try:
                        item = await asyncio.wait_for(
                            self.queue.get(), timeout=timeout
                        )
                    except asyncio.TimeoutError:
                        break
                    batch.append(item)
                    total += self._n_requests(item[0])
            # stage metrics: enqueue→drain wait per queue item (one frame =
            # one item, so this stays O(items), not O(requests)) + the batch
            # size distribution the adaptive batcher actually produced
            t_drain = loop.time()
            for queued_item in batch:
                _SM.queue_wait_ms.record(
                    (t_drain - queued_item[2]) * 1e3,
                    self._n_requests(queued_item[0]),
                )
            _SM.batch_size.record(total)
            await sem.acquire()
            self.inflight += 1
            task = loop.create_task(self._process(batch, total))

            def _done(_t):
                self.inflight -= 1
                sem.release()

            task.add_done_callback(_done)

    @staticmethod
    def _n_requests(item) -> int:
        if isinstance(item, _BatchFrame):
            return len(item.flow_ids)
        return 1

    async def _process(self, batch, total: int) -> None:
        try:
            await self._process_inner(batch)
        finally:
            # inflight accounting covers enqueue → answered/shed; the BBR
            # gate reads it as the pipeline's concurrency
            self.server.overload.note_done(total)

    async def _process_inner(self, batch) -> None:
        srv = self.server
        service = srv.service
        # deadline shed: a frame whose client budget is already blown gets
        # DROPPED, not served — the client stopped waiting, so a verdict
        # would only burn a device slot (and an OVERLOAD answer would race
        # a closed socket). Counted so the drop is never invisible.
        now = asyncio.get_running_loop().time()
        live = []
        for entry in batch:
            deadline = entry[3]
            if deadline is not None and now > deadline:
                _SM.count_shed("deadline", self._n_requests(entry[0]))
                continue
            live.append(entry)
        batch = live
        if not batch:
            return
        # split by kind: FLOW singles + BATCH_FLOW frames share one device
        # step; param requests go to the param sketch path; concurrent
        # acquire/release to the host-side semaphore path
        flow_singles: List[Tuple[int, P.FlowRequest]] = []
        batch_frames: List[Tuple[int, _BatchFrame]] = []
        for i, (item, _w, _t, _dl) in enumerate(batch):
            if isinstance(item, _BatchFrame):
                batch_frames.append((i, item))
            elif item.msg_type == P.MsgType.FLOW:
                flow_singles.append((i, item))

        results: Dict[int, Tuple[int, int, int, int]] = {}
        frame_slices: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
        n_flow = len(flow_singles) + sum(
            len(f.flow_ids) for _, f in batch_frames
        )
        if n_flow:
            ids_parts, cnt_parts, prio_parts = [], [], []
            for _, f in batch_frames:
                ids_parts.append(f.flow_ids)
                cnt_parts.append(f.counts)
                prio_parts.append(f.prios)
            if flow_singles:
                ids_parts.append(
                    np.fromiter(
                        (r.flow_id for _, r in flow_singles), np.int64,
                        len(flow_singles),
                    )
                )
                cnt_parts.append(
                    np.fromiter(
                        (r.count for _, r in flow_singles), np.int32,
                        len(flow_singles),
                    )
                )
                prio_parts.append(
                    np.fromiter(
                        (r.prioritized for _, r in flow_singles), bool,
                        len(flow_singles),
                    )
                )
            flow_ids = ids_parts[0] if len(ids_parts) == 1 else np.concatenate(ids_parts)
            counts = cnt_parts[0] if len(cnt_parts) == 1 else np.concatenate(cnt_parts)
            prios = prio_parts[0] if len(prio_parts) == 1 else np.concatenate(prio_parts)
            # brownout gate (BBR admission, overload/admission.py): SHED_LOW
            # refuses the non-prioritized rows with OVERLOAD and serves the
            # rest; DEGRADE skips the device entirely and answers locally
            # (probabilistic pass / OVERLOAD). Shed rows are still ANSWERED
            # — one response frame per request frame, always.
            level = srv.overload.level()
            ns_fn = getattr(service, "namespace_index", None)
            if level >= BrownoutLevel.DEGRADE:
                shed = srv.overload.shed_mask(prios, level)
                status, remaining, wait = srv.overload.degrade_verdicts(shed)
                _SM.count_shed("degrade", int(shed.sum()))
                # per-tenant attribution: degrade answers locally, so the
                # verdict counters (and the SLO shed plane underneath)
                # resolve namespaces here instead of on the device path
                ns_idx, ns_names = (
                    ns_fn(flow_ids) if ns_fn is not None else (None, ())
                )
                _SM.record_verdict_batch(status, ns_idx, ns_names)
                keep = None
            else:
                keep = None
                if level >= BrownoutLevel.SHED_LOW:
                    # tenant attribution up front so the shed is
                    # share-weighted when shares are configured
                    ns_pair = (
                        ns_fn(flow_ids) if ns_fn is not None else (None, ())
                    )
                    m = srv.overload.shed_mask(
                        prios, level, ns_idx=ns_pair[0], ns_names=ns_pair[1]
                    )
                    if m.any():
                        keep = np.nonzero(~m)[0]
                        _SM.count_shed("brownout", n_flow - keep.size)
                        if ns_pair[0] is not None:
                            _slo_plane().record_shed_indexed(
                                ns_pair[0][m], ns_pair[1], reason="brownout"
                            )
                d_ids, d_cnts, d_prios = (
                    (flow_ids, counts, prios)
                    if keep is None
                    else (flow_ids[keep], counts[keep], prios[keep])
                )
                d_n = len(d_ids)
                if _TR.ARMED and batch_frames:
                    _TR.record_many(
                        _TR.DISPATCH, [f.xid for _i, f in batch_frames],
                        shard=self.index, aux=d_n,
                    )
                t_decide = time.perf_counter()
                try:
                    dispatch = getattr(service, "dispatch_batch_arrays", None)
                    if d_n == 0:
                        status = np.empty(0, np.int8)
                        remaining = np.empty(0, np.int32)
                        wait = np.empty(0, np.int32)
                    elif dispatch is not None:
                        # dispatch INLINE on the loop thread: host prep + async
                        # enqueue only (sub-100µs), so device steps start in
                        # batch order even when several _process tasks are in
                        # flight. Materialization (blocks on the device) hops to
                        # a worker thread for large steps so the loop keeps
                        # pumping frames and the next batch's dispatch overlaps
                        # this step's execution.
                        materialize = dispatch(d_ids, d_cnts, d_prios)
                        if d_n <= srv.inline_below and self.inflight == 1:
                            # small LONE step: the two executor hops of
                            # to_thread cost more than the step blocks the loop
                            # for. Only when nothing else is in flight — device
                            # state chains serially, so an inline materialize
                            # behind another task's large step would block the
                            # loop for the predecessor's duration too.
                            status, remaining, wait = materialize()
                        else:
                            status, remaining, wait = await asyncio.to_thread(
                                materialize
                            )
                    elif d_n <= srv.inline_below:
                        status, remaining, wait = service.request_batch_arrays(
                            d_ids, d_cnts, d_prios
                        )
                    else:
                        status, remaining, wait = await asyncio.to_thread(
                            service.request_batch_arrays, d_ids, d_cnts, d_prios
                        )
                except Exception:
                    record_log.exception("device step failed; failing batch")
                    status = np.full(d_n, int(TokenStatus.FAIL), np.int8)
                    remaining = np.zeros(d_n, np.int32)
                    wait = np.zeros(d_n, np.int32)
                _SM.decide_ms.record((time.perf_counter() - t_decide) * 1e3)
                if keep is not None:
                    # scatter the served subset back; shed rows answer
                    # OVERLOAD with the retry hint
                    st = np.full(n_flow, _OVERLOAD, np.int8)
                    rm = np.zeros(n_flow, np.int32)
                    wt = np.full(
                        n_flow, srv.overload.retry_hint_ms, np.int32
                    )
                    st[keep] = status
                    rm[keep] = remaining
                    wt[keep] = wait
                    status, remaining, wait = st, rm, wt
            off = 0
            for i, f in batch_frames:
                k = len(f.flow_ids)
                frame_slices[i] = (
                    status[off : off + k],
                    remaining[off : off + k],
                    wait[off : off + k],
                )
                off += k
            for j, (i, _) in enumerate(flow_singles):
                results[i] = (
                    int(status[off + j]), int(remaining[off + j]),
                    int(wait[off + j]), 0,
                )

        async def run_one(i: int, req) -> None:
            # overlapped thread hops: the service locks still serialize the
            # critical sections, but responses aren't head-of-line blocked
            try:
                if req.msg_type == P.MsgType.PARAM_FLOW:
                    r = await asyncio.to_thread(
                        service.request_params_token,
                        req.flow_id, req.count, req.param_hashes,
                    )
                    results[i] = (int(r.status), r.remaining, r.wait_ms, 0)
                elif req.msg_type == P.MsgType.CONCURRENT_ACQUIRE:
                    r = await asyncio.to_thread(
                        service.request_concurrent_token,
                        req.flow_id, req.count, req.prioritized,
                    )
                    results[i] = (int(r.status), r.remaining, r.wait_ms, r.token_id)
                elif req.msg_type == P.MsgType.CONCURRENT_RELEASE:
                    # flow_id slot carries the token id (protocol docstring)
                    r = await asyncio.to_thread(
                        service.release_concurrent_token, req.flow_id
                    )
                    results[i] = (int(r.status), 0, 0, 0)
            except Exception:
                record_log.exception("%s request failed", req.msg_type.name)
                results[i] = (int(TokenStatus.FAIL), 0, 0, 0)

        host_side = [
            (i, req)
            for i, (req, _w, _t, _dl) in enumerate(batch)
            if not isinstance(req, _BatchFrame)
            and req.msg_type != P.MsgType.FLOW
        ]
        is_host_side = {i for i, _ in host_side}

        async def write_out(indices) -> None:
            t_write = time.perf_counter()
            writers_to_drain = set()
            # batch frames group per writer: ONE vectorized multi-frame
            # encode (encode_batch_responses) and one socket write per
            # client instead of one of each per frame
            grouped: dict = {}  # writer → (xids, counts, verdict slices)
            for i in indices:
                item, writer, _t_enq, _dl = batch[i]
                try:
                    if isinstance(item, _BatchFrame):
                        sliced = frame_slices.get(i)
                        if sliced is None:  # only when the frame was empty
                            k = len(item.flow_ids)
                            sliced = (
                                np.full(k, int(TokenStatus.FAIL), np.int8),
                                np.zeros(k, np.int32),
                                np.zeros(k, np.int32),
                            )
                        g = grouped.setdefault(writer, ([], [], []))
                        g[0].append(item.xid)
                        g[1].append(len(sliced[0]))
                        g[2].append(sliced)
                    else:
                        st, remaining, wait, token_id = results.get(
                            i, (int(TokenStatus.FAIL), 0, 0, 0)
                        )
                        endpoint = ""
                        if st == int(TokenStatus.MOVED):
                            # rev 4: single responses carry the new owner
                            # as a UTF-8 trailer so a redirected client
                            # needs no shard-map fetch to follow
                            lookup = getattr(
                                service, "moved_redirect", None
                            )
                            red = lookup(item.flow_id) if lookup else None
                            endpoint = red[0] if red else ""
                        writer.write(
                            P.encode_response(
                                P.FlowResponse(
                                    item.xid, item.msg_type, st, remaining,
                                    wait, token_id, endpoint,
                                )
                            )
                        )
                        writers_to_drain.add(writer)
                        if _TR.ARMED:
                            _TR.record(
                                _TR.REPLY_OUT, xid=item.xid,
                                shard=self.index,
                            )
                except Exception:
                    pass
            for writer, (xids, counts, slices) in grouped.items():
                try:
                    # scatter encode into the connection's reused buffer
                    # (out=): the transport copies what it can't send
                    # synchronously before write() returns, so recycling
                    # the bytearray on the next flush is safe
                    buf = srv._writer_bufs.get(writer)
                    if buf is None:
                        buf = bytearray()
                        srv._writer_bufs[writer] = buf
                    writer.write(
                        P.encode_batch_responses(
                            xids, counts,
                            np.concatenate([s[0] for s in slices]),
                            np.concatenate([s[1] for s in slices]),
                            np.concatenate([s[2] for s in slices]),
                            out=buf,
                        )
                    )
                    writers_to_drain.add(writer)
                except Exception:
                    pass
            for writer in writers_to_drain:
                try:
                    await writer.drain()
                except Exception:
                    pass
            if _TR.ARMED and grouped:  # flight recorder: replies flushed
                for _w, (xids, counts, _s) in grouped.items():
                    _TR.record_many(
                        _TR.REPLY_OUT, xids, shard=self.index,
                    )
            _SM.write_ms.record((time.perf_counter() - t_write) * 1e3)

        # flow verdicts go out the moment they're materialized, CONCURRENT
        # with the host-side (param/concurrent) work — neither plane may
        # queue behind the other (a stalled flow client's drain must not
        # delay another client's CONCURRENT_RELEASE, and vice versa;
        # responses are xid-correlated, order-free)
        async def host_side_then_write() -> None:
            await asyncio.gather(*(run_one(i, req) for i, req in host_side))
            await write_out(is_host_side)

        flow_write = write_out(
            i for i in range(len(batch)) if i not in is_host_side
        )
        if host_side:
            await asyncio.gather(flow_write, host_side_then_write())
        else:
            await flow_write


class TokenServer:
    def __init__(
        self,
        service: TokenService,
        host: str = "127.0.0.1",
        port: int = 18730,
        batch_window_ms: float = 0.0,
        max_batch: int = 1024,
        inline_below: int = 64,
        n_loops: int = 1,
        max_inflight: int = 2,
        idle_ttl_s: Optional[float] = 600.0,
        profile_dir: Optional[str] = None,
        metrics_port: Optional[int] = None,
        snapshot_dir: Optional[str] = None,
        snapshot_period_s: Optional[float] = None,
        max_queue: int = 8192,
        overload: Optional[AdmissionController] = None,
        standby_of: Optional[str] = None,
        promote_after_ms: Optional[float] = None,
        replicate_to: Optional[Sequence] = None,
        repl_interval_ms: Optional[float] = None,
        push: bool = True,
    ):
        self.service = service
        self.host = host
        self.port = port
        self.batch_window_ms = batch_window_ms
        self.max_batch = max_batch
        # per-loop bound on queued frames: at capacity the front door
        # answers OVERLOAD immediately instead of queueing past every
        # client's budget (0 disables the bound)
        self.max_queue = max(0, int(max_queue))
        # BBR-style admission gate + brownout ladder (overload/admission.py);
        # pass a configured controller to tune headroom, or one with
        # enabled=False to opt out
        self.overload = (
            overload if overload is not None else AdmissionController()
        )
        # flow batches at or under this size dispatch inline on the loop
        # thread (sub-ms step; executor hops would dominate); larger ones go
        # through to_thread so the IO loop keeps pumping during the step
        self.inline_below = inline_below
        self.n_loops = max(1, int(n_loops))
        # batches processed concurrently per loop (device pipelining depth);
        # 2 keeps one step executing while the next preps/dispatches
        self.max_inflight = max(1, int(max_inflight))
        self.idle_ttl_s = idle_ttl_s
        self._workers: List[_LoopWorker] = []
        # namespace-scoped connection groups (ConnectionManager.java:35);
        # counts feed the service's AVG_LOCAL threshold scaling
        notify = getattr(self.service, "connected_count_changed", None)
        self.connections = ConnectionManager(on_count_changed=notify)
        self._idle_task = None
        # optional serving-loop profiling (SURVEY §5 tracing row): a
        # jax.profiler trace spanning start()→stop() captures every device
        # step the micro-batchers dispatch, viewable in TensorBoard/XProf.
        # Also honored from the env so an operator can profile a live
        # deployment without code changes.
        self.profile_dir = profile_dir or os.environ.get(
            "SENTINEL_PROFILE_DIR"
        ) or None
        # on-demand trace control for the cluster/server/profiler command;
        # start() opens an always-on trace through it when profile_dir is set
        self.profiler = ProfilerHook(default_dir=self.profile_dir)
        # optional standalone Prometheus endpoint (GET /metrics): the command
        # center already serves the same body at /metric/prometheus, but a
        # token server often runs without one — 0 picks a free port
        self.metrics_port = metrics_port
        self._metrics_exporter = None
        self._gauge_fns: Dict[str, object] = {}
        # HA state snapshots (sentinel_tpu.ha.snapshot): with a directory
        # set, start() restores the newest artifact into a COLD service and
        # arms the periodic writer; stop() takes a final save. Honored from
        # the env too so an operator can arm it without code changes.
        self.snapshot_dir = snapshot_dir or os.environ.get(
            "SENTINEL_SNAPSHOT_DIR"
        ) or None
        self.snapshot_period_s = snapshot_period_s
        self._snapshots = None
        # warm-standby replication roles (ha.replication). standby_of= makes
        # this a STANDBY: the front door answers data-plane traffic with
        # TokenStatus.STANDBY until promoted, while rev-3 frames from the
        # primary named here (informational label) stream state in through
        # a StandbyApplier. replicate_to= makes this a PRIMARY shipping
        # deltas to the listed standby addresses. The roles compose — a
        # promoted standby can itself replicate onward — but a server is
        # normally one or the other.
        self.standby_of = standby_of
        self.promote_after_ms = promote_after_ms
        self.replicate_to = list(replicate_to) if replicate_to else None
        self.repl_interval_ms = repl_interval_ms
        self.applier = None  # StandbyApplier while in standby mode
        self.replicator = None  # ReplicationSender while primary
        # live-move destination side (cluster.rebalance): every server can
        # receive a namespace over the rev-4 move channel; staging only,
        # nothing mutates until MOVE_COMMIT
        from sentinel_tpu.cluster.rebalance import MoveTarget

        self.move_target = MoveTarget(service)
        # per-connection scatter-encode buffers: encode_batch_responses
        # lays each writer's grouped verdict frames into its reused
        # bytearray (out=) instead of allocating a bytes blob per flush;
        # weak keys let a closed connection's buffer fall away with it
        import weakref

        self._writer_bufs = weakref.WeakKeyDictionary()
        # rev-7 push plane (cluster.push): per-connection sinks feed
        # unsolicited server→client frames down the same reply lanes the
        # verdict writes use. The hub attaches to the service so lease
        # revocations / breaker flips / rule-epoch bumps go out the moment
        # they happen, and to the admission gate so brownout transitions
        # ride along as advisories. push=False disarms every emit (the
        # drills' push-dark phases).
        from sentinel_tpu.cluster.push import PushHub

        self.push_hub = PushHub(enabled=push)
        attach = getattr(self.service, "attach_push_hub", None)
        if attach is not None:
            attach(self.push_hub)
        self.overload.on_level_change = (
            lambda level, retry_ms: self.push_hub.push_brownout(
                level, retry_ms
            )
        )

    def tuning_kwargs(self) -> dict:
        """Operator-tunable constructor kwargs, for rebuilding this server on
        a port move (command or datasource driven) without silently resetting
        live tuning to defaults."""
        return dict(
            batch_window_ms=self.batch_window_ms,
            max_batch=self.max_batch,
            inline_below=self.inline_below,
            n_loops=self.n_loops,
            max_inflight=self.max_inflight,
            idle_ttl_s=self.idle_ttl_s,
            profile_dir=self.profile_dir,
            metrics_port=self.metrics_port,
            snapshot_dir=self.snapshot_dir,
            snapshot_period_s=self.snapshot_period_s,
            max_queue=self.max_queue,
            overload=self.overload,
            standby_of=self.standby_of,
            promote_after_ms=self.promote_after_ms,
            replicate_to=self.replicate_to,
            repl_interval_ms=self.repl_interval_ms,
            push=self.push_hub.enabled,
        )

    # -- warm-standby role ---------------------------------------------------
    @property
    def is_standby(self) -> bool:
        """True while the front door refuses data-plane traffic (standby
        mode, not yet promoted)."""
        applier = self.applier
        return applier is not None and not applier.promoted

    def promote(self, reason: str = "manual") -> bool:
        """Open the front door of a standby. Returns False when this server
        is not a standby or is already promoted."""
        if self.applier is None:
            return False
        return self.applier.promote(reason)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._workers:
            return
        # trigger the native library's lazy autobuild (fresh checkouts) at
        # STARTUP, alongside kernel warmup — never inside the first
        # request's frame decode
        from sentinel_tpu.native import lib as _native_lib

        _native_lib.load()
        warmup = getattr(self.service, "warmup", None)
        if warmup is not None:
            warmup()  # compile the decision kernels before accepting traffic
        if self.snapshot_dir and hasattr(self.service, "import_state"):
            from sentinel_tpu.ha.snapshot import restore_latest

            # only a COLD service restores (no rules loaded yet): a port
            # move reuses a live service whose in-memory state is newer
            # than any artifact on disk
            if not self.service.current_rules():
                restore_latest(self.service, self.snapshot_dir)
        reopen = getattr(self.service, "reopen", None)
        if reopen is not None:
            reopen()  # re-arm background sweeps a prior stop() released
        if self.standby_of is not None and self.applier is None:
            from sentinel_tpu.ha.replication import StandbyApplier

            # armed BEFORE the listener: the first frame a standby sees may
            # be the primary's REPL_HELLO
            self.applier = StandbyApplier(
                self.service, promote_after_ms=self.promote_after_ms,
            ).start()
        if self.profile_dir:
            try:
                self.profiler.start(self.profile_dir)
            except Exception:
                record_log.exception("profiler start failed; serving anyway")
        if self.n_loops > 1 and not hasattr(socket, "SO_REUSEPORT"):
            record_log.warning("SO_REUSEPORT unavailable; forcing n_loops=1")
            self.n_loops = 1
        # workers start sequentially: worker 0 resolves port 0 → a real port
        # the rest bind with reuse_port
        for i in range(self.n_loops):
            worker = _LoopWorker(self, i)
            self._workers.append(worker)
            worker.start()
            ok = worker.started.wait(timeout=5)
            if worker.start_error is not None or not ok:
                err = worker.start_error
                # unwind ONLY what this failed start created — the caller's
                # service stays usable (its close() is for a started server)
                workers, self._workers = self._workers, []
                for w in workers:
                    w.stop()
                raise RuntimeError(f"token server failed to start: {err}") from err
        if self.idle_ttl_s:
            from sentinel_tpu.cluster.connection import IdleConnectionSweeper

            self._idle_task = IdleConnectionSweeper(
                self.connections, ttl_s=self.idle_ttl_s
            )
            self._idle_task.start()
        # live gauges: scrape-time reads off the running workers (queue.qsize
        # is loop-thread-unsafe only for mutation; a racy read is fine for a
        # gauge). Registered per start() and torn down matched in stop() so
        # a replacement server's readers survive the old one's teardown.
        self._gauge_fns = {
            "queue_depth": lambda: sum(
                w.queue.qsize() for w in self._workers if w.queue is not None
            ),
            "inflight_batches": lambda: sum(
                w.inflight for w in self._workers
            ),
            "connections": lambda: sum(
                len(addrs) for addrs in self.connections.snapshot().values()
            ),
        }
        for name, fn in self._gauge_fns.items():
            _SM.register_gauge(name, fn)
        # hub half of the clusterServerStats `push` block (most recently
        # started door wins — same single-slot contract as the other
        # providers)
        _SM.register_push_provider(self.push_hub.stats)
        if self.metrics_port is not None:
            from sentinel_tpu.metrics.exporter import PrometheusExporter

            self._metrics_exporter = PrometheusExporter(
                host="0.0.0.0", port=self.metrics_port
            ).start()
            self.metrics_port = self._metrics_exporter.port  # resolve port 0
        if self.snapshot_dir and hasattr(self.service, "export_state"):
            from sentinel_tpu.ha.snapshot import SnapshotManager

            self._snapshots = SnapshotManager(
                self.service, self.snapshot_dir,
                period_s=self.snapshot_period_s,
            ).start()
        if self.replicate_to and hasattr(self.service, "export_delta"):
            from sentinel_tpu.ha.replication import ReplicationSender

            self.replicator = ReplicationSender(
                self.service, self.replicate_to,
                interval_ms=self.repl_interval_ms,
                sender_id=f"{self.host}:{self.port}",
            ).start()

    def stop(self) -> None:
        # replication teardown first: the sender must not race the service
        # close, and a standby's watchdog must not promote mid-shutdown
        if self.replicator is not None:
            self.replicator.stop()
            self.replicator = None
        if self.applier is not None:
            self.applier.stop()
            self.applier = None
        if self._snapshots is not None:
            # final save: the artifact a restarted primary (or a standby
            # picking up this node's directory) restores from
            self._snapshots.stop(final_save=True)
            self._snapshots = None
        if self.profiler.active:
            self.profiler.stop()
        if self._metrics_exporter is not None:
            self._metrics_exporter.stop()
            self._metrics_exporter = None
        for name, fn in getattr(self, "_gauge_fns", {}).items():
            _SM.unregister_gauge(name, fn)
        self._gauge_fns = {}
        if self._idle_task is not None:
            self._idle_task.stop()
            self._idle_task = None
        workers, self._workers = self._workers, []
        for worker in workers:
            worker.stop()
        # symmetric with the warmup hook in start(): release the service's
        # background resources (concurrent-mode expiry sweeper). Embedded
        # users who keep the service alive re-arm it on the next rule load.
        close = getattr(self.service, "close", None)
        if close is not None:
            close()
