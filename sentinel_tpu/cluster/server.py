"""Token server transport: asyncio TCP front door + micro-batcher.

Analog of ``NettyTransportServer.java:51`` + ``TokenServerHandler.java:39``,
re-shaped for the TPU data plane: instead of one decision per channelRead, the
handler enqueues requests and an **adaptive** batcher drains everything queued
into one device step the moment the device is free — batches grow naturally
with load (arrivals pile up behind the in-flight step) and a lone request
pays no batching delay. This is what turns the reference's 20ms RPC budget
(``ClusterConstants.java:44``) into sub-ms micro-batches with room to spare.

The asyncio loop runs on a dedicated thread (``start()``/``stop()`` are
host-thread-safe); the device step runs in a worker thread so the IO loop
keeps pumping frames while XLA executes.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Dict, List, Optional, Tuple

from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.connection import ConnectionManager
from sentinel_tpu.cluster.token_service import TokenService
from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine import TokenStatus


class TokenServer:
    def __init__(
        self,
        service: TokenService,
        host: str = "127.0.0.1",
        port: int = 18730,
        batch_window_ms: float = 0.0,
        max_batch: int = 1024,
        inline_below: int = 64,
    ):
        self.service = service
        self.host = host
        self.port = port
        self.batch_window_ms = batch_window_ms
        self.max_batch = max_batch
        # flow batches at or under this size dispatch inline on the loop
        # thread (sub-ms step; executor hops would dominate); larger ones go
        # through to_thread so the IO loop keeps pumping during the step
        self.inline_below = inline_below
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._queue: Optional[asyncio.Queue] = None
        self._started = threading.Event()
        # namespace-scoped connection groups (ConnectionManager.java:35);
        # counts feed the service's AVG_LOCAL threshold scaling
        notify = getattr(self.service, "connected_count_changed", None)
        self.connections = ConnectionManager(on_count_changed=notify)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        warmup = getattr(self.service, "warmup", None)
        if warmup is not None:
            warmup()  # compile the decision kernels before accepting traffic
        self._start_error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run_loop, name="sentinel-token-server", daemon=True
        )
        self._thread.start()
        ok = self._started.wait(timeout=5)
        if self._start_error is not None or not ok:
            err = self._start_error
            self._thread.join(timeout=5)
            self._thread = None
            self._started.clear()
            raise RuntimeError(f"token server failed to start: {err}") from err

    def stop(self) -> None:
        loop, self._loop = self._loop, None
        if loop is not None:
            loop.call_soon_threadsafe(loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._started.clear()
        # symmetric with the warmup hook in start(): release the service's
        # background resources (concurrent-mode expiry sweeper). Embedded
        # users who keep the service alive re-arm it on the next rule load.
        close = getattr(self.service, "close", None)
        if close is not None:
            close()

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        self._queue = asyncio.Queue()
        loop.create_task(self._serve())
        loop.create_task(self._batcher())
        try:
            loop.run_forever()
        finally:
            if self._server is not None:
                self._server.close()
            # drain cancelled tasks so nothing outlives the loop
            tasks = asyncio.all_tasks(loop)
            for task in tasks:
                task.cancel()
            if tasks:
                loop.run_until_complete(
                    asyncio.gather(*tasks, return_exceptions=True)
                )
            loop.close()

    async def _serve(self) -> None:
        try:
            self._server = await asyncio.start_server(
                self._on_connection, self.host, self.port
            )
        except OSError as e:
            self._start_error = e
            self._started.set()  # wake start() so it can fail with the cause
            asyncio.get_event_loop().stop()
            return
        addr = self._server.sockets[0].getsockname()
        self.port = addr[1]  # resolve port 0 → actual
        record_log.info("token server listening on %s:%d", *addr[:2])
        self._started.set()

    # -- per-connection reader ---------------------------------------------
    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        frames = P.FrameReader()
        peer = writer.get_extra_info("peername")
        address = f"{peer[0]}:{peer[1]}" if peer else repr(writer)
        try:
            while True:
                data = await reader.read(4096)
                if not data:
                    break
                try:
                    payloads = frames.feed(data)
                except ValueError:
                    record_log.warning("oversized frame from client; closing")
                    return
                for payload in payloads:
                    try:
                        req = P.decode_request(payload)
                    except Exception:
                        record_log.warning("bad frame from client; closing")
                        return
                    if isinstance(req, P.Ping):
                        # handshake: bind this connection to its namespace
                        # group; answer with the group's connected count
                        # (TokenServerHandler.handlePingRequest)
                        count = self.connections.add(req.namespace, address)
                        writer.write(
                            P.encode_response(
                                P.FlowResponse(
                                    req.xid, P.MsgType.PING, 0,
                                    remaining=count,
                                )
                            )
                        )
                        await writer.drain()
                    else:
                        await self._queue.put((req, writer))
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            self.connections.remove_address(address)
            try:
                writer.close()
            except Exception:
                pass

    # -- micro-batcher ------------------------------------------------------
    async def _batcher(self) -> None:
        """Adaptive micro-batching: dispatch as soon as the device is free.

        While a device step is in flight (``_process`` awaits it), new
        arrivals pile up in the queue and the next iteration drains them all
        in one go — so batches grow naturally with load and a lone request
        under light load pays ZERO batching delay. A fixed collect window
        (``batch_window_ms > 0``) is still honored for callers that prefer
        bigger batches over tail latency.
        """
        while True:
            first = await self._queue.get()
            batch: List[Tuple[P.FlowRequest, asyncio.StreamWriter]] = [first]
            while len(batch) < self.max_batch:
                try:
                    batch.append(self._queue.get_nowait())
                except asyncio.QueueEmpty:
                    break
            if self.batch_window_ms > 0:
                deadline = (
                    asyncio.get_event_loop().time()
                    + self.batch_window_ms / 1000.0
                )
                while len(batch) < self.max_batch:
                    timeout = deadline - asyncio.get_event_loop().time()
                    if timeout <= 0:
                        break
                    try:
                        batch.append(
                            await asyncio.wait_for(
                                self._queue.get(), timeout=timeout
                            )
                        )
                    except asyncio.TimeoutError:
                        break
            await self._process(batch)

    async def _process(self, batch) -> None:
        # route by message type: FLOW verdicts batch onto the device; param
        # requests go to the param sketch path; concurrent acquire/release to
        # the host-side semaphore path
        flow_items = [
            (i, r) for i, (r, _) in enumerate(batch) if r.msg_type == P.MsgType.FLOW
        ]
        results: Dict[int, Tuple[int, int, int, int]] = {}  # status, remaining, wait_ms, token_id
        if flow_items:
            flow_reqs = [(r.flow_id, r.count, r.prioritized) for _, r in flow_items]
            try:
                if len(flow_reqs) <= self.inline_below:
                    # small step: run it right here on the loop thread. The
                    # two executor hops of to_thread cost more than the step
                    # blocks the loop for, and a blocked loop just means
                    # arrivals pile up into the next batch — which is the
                    # batching policy anyway.
                    flow_results = self.service.request_batch(flow_reqs)
                else:
                    flow_results = await asyncio.to_thread(
                        self.service.request_batch, flow_reqs
                    )
            except Exception:
                record_log.exception("device step failed; failing batch")
                flow_results = None
            for k, (i, _) in enumerate(flow_items):
                if flow_results is None:
                    results[i] = (int(TokenStatus.FAIL), 0, 0, 0)
                else:
                    r = flow_results[k]
                    results[i] = (int(r.status), r.remaining, r.wait_ms, 0)
        async def run_one(i: int, req) -> None:
            # overlapped thread hops: the service locks still serialize the
            # critical sections, but responses aren't head-of-line blocked
            try:
                if req.msg_type == P.MsgType.PARAM_FLOW:
                    r = await asyncio.to_thread(
                        self.service.request_params_token,
                        req.flow_id, req.count, req.param_hashes,
                    )
                    results[i] = (int(r.status), r.remaining, r.wait_ms, 0)
                elif req.msg_type == P.MsgType.CONCURRENT_ACQUIRE:
                    r = await asyncio.to_thread(
                        self.service.request_concurrent_token,
                        req.flow_id, req.count, req.prioritized,
                    )
                    results[i] = (int(r.status), r.remaining, r.wait_ms, r.token_id)
                elif req.msg_type == P.MsgType.CONCURRENT_RELEASE:
                    # flow_id slot carries the token id (protocol docstring)
                    r = await asyncio.to_thread(
                        self.service.release_concurrent_token, req.flow_id
                    )
                    results[i] = (int(r.status), 0, 0, 0)
            except Exception:
                record_log.exception("%s request failed", req.msg_type.name)
                results[i] = (int(TokenStatus.FAIL), 0, 0, 0)

        host_side = [
            run_one(i, req)
            for i, (req, _) in enumerate(batch)
            if req.msg_type != P.MsgType.FLOW
        ]
        if host_side:
            await asyncio.gather(*host_side)

        writers_to_drain = set()
        for i, (req, writer) in enumerate(batch):
            status, remaining, wait, token_id = results.get(
                i, (int(TokenStatus.FAIL), 0, 0, 0)
            )
            try:
                writer.write(
                    P.encode_response(
                        P.FlowResponse(
                            req.xid, req.msg_type, status, remaining, wait, token_id
                        )
                    )
                )
                writers_to_drain.add(writer)
            except Exception:
                pass
        for writer in writers_to_drain:
            try:
                await writer.drain()
            except Exception:
                pass
