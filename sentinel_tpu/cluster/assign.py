"""Datasource-driven cluster assignment.

The reference's cluster client/server configuration is property-driven:
``ClusterClientConfigManager`` registers ``SentinelProperty`` instances for
the client assignment and config (``sentinel-cluster-client-default/.../
config/ClusterClientConfigManager.java``), and ``ClusterStateManager``
applies mode switches from properties too — the HTTP commands are just one
writer of those properties. Round 2 only had the command path; this module
adds the property path with the SAME payloads the commands accept, so a
fleet re-points itself from any datasource (file, nacos, etcd, …) without a
dashboard in the loop.

Usage::

    ds = FileRefreshableDataSource(path, converter=json.loads).start()
    register_client_assign_property(ds.property)
    # file contents: {"serverHost": "10.0.0.5", "serverPort": 18730,
    #                 "requestTimeout": 20, "namespace": "ns1"}

    register_cluster_mode_property(mode_ds.property)
    # contents: 0 | 1 | -1, or {"mode": 1, "tokenPort": 18730}
"""

from __future__ import annotations

import threading
from typing import Optional

from sentinel_tpu.core.log import record_log
from sentinel_tpu.core.property import DynamicProperty

_lock = threading.Lock()
_assign_property: Optional[DynamicProperty] = None
_assign_listener = None
_mode_property: Optional[DynamicProperty] = None
_mode_listener = None


def _on_assignment(value) -> None:
    if value is None:
        return
    from sentinel_tpu.transport.handlers import apply_client_assignment

    try:
        error = apply_client_assignment(dict(value))
        if error:
            record_log.warning("cluster assignment rejected: %s", error)
    except Exception:
        record_log.exception("cluster assignment failed")


def _on_mode(value) -> None:
    if value is None:
        return
    from sentinel_tpu.transport.handlers import apply_cluster_mode

    try:
        if isinstance(value, dict):
            mode = int(value.get("mode", -1))
            port = int(value.get("tokenPort", 18730))
        else:
            mode, port = int(value), 18730
        apply_cluster_mode(mode, port)
    except Exception:
        record_log.exception("cluster mode switch failed")


def register_client_assign_property(prop: DynamicProperty) -> None:
    """Subscribe the token-client assignment to a property
    (``ClusterClientConfigManager.registerServerAssignProperty`` analog).
    The property's value is the modifyConfig payload:
    ``{serverHost, serverPort[, requestTimeout][, namespace]}``."""
    global _assign_property, _assign_listener
    with _lock:
        if _assign_property is not None and _assign_listener is not None:
            _assign_property.remove_listener(_assign_listener)
        _assign_property = prop
        _assign_listener = prop.listen(_on_assignment)


def register_cluster_mode_property(prop: DynamicProperty) -> None:
    """Subscribe this agent's cluster mode to a property
    (``ClusterStateManager.registerProperty`` analog). The value is the
    setClusterMode payload: an int mode, or ``{mode, tokenPort}``."""
    global _mode_property, _mode_listener
    with _lock:
        if _mode_property is not None and _mode_listener is not None:
            _mode_property.remove_listener(_mode_listener)
        _mode_property = prop
        _mode_listener = prop.listen(_on_mode)


def reset_for_tests() -> None:
    global _assign_property, _assign_listener, _mode_property, _mode_listener
    with _lock:
        if _assign_property is not None and _assign_listener is not None:
            _assign_property.remove_listener(_assign_listener)
        if _mode_property is not None and _mode_listener is not None:
            _mode_property.remove_listener(_mode_listener)
        _assign_property = _assign_listener = None
        _mode_property = _mode_listener = None
