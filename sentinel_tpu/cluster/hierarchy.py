"""Hierarchical global limits: pods lease provisioned shares of a global
flow budget, so a fleet-wide limit holds with ZERO per-decision cross-pod
traffic (ROADMAP item 3, SURVEY §7 step 5).

The trick is the wire-rev-5 lease machinery applied one level up::

    clients ──(LEASE_*)──▶ pod ──(SHARE_*/DEMAND_REPORT)──▶ coordinator
             local admit        slow DCN tier, ~100ms ticks

- :class:`GlobalBudgetCoordinator` runs co-located with any pod (attached
  via ``service.attach_hierarchy``; both front doors route ``HIER_TYPES``
  frames to it). It owns one ledger entry per global flow — the budget,
  every pod's live share, reported demand — and a reconciliation loop that
  water-fills share targets over reported arrival rates with hysteresis
  against share thrash. Targets ship as renew-time regrants: the
  coordinator never pushes, pods pull on their own tick.
- :class:`PodShareAgent` runs inside every pod. The pod loads the global
  rule at its FULL budget ``G``; each tick the agent reports observed
  demand (PASS + BLOCK rates — blocked tokens count, so a squeezed pod
  still registers demand), renews its share ``S``, and pins
  ``G − S`` tokens as a LEASED-column hold
  (``service.set_share_hold``) — local headroom becomes exactly the share
  and the decision hot path is UNTOUCHED (the device kernel already reads
  LEASED; psum'd limits, snapshots, deltas, and MOVE carry the hold like
  any lease charge).

Failure containment, by construction:

- Coordinator unreachable → the agent keeps re-topping its LAST-granted
  share ("degrade to last share"). Worst-case fleet over-admission is
  Σ outstanding pod shares — the same invariant the lease drill gates,
  one level up — and only until shares next converge.
- Pod dies → its share expires with the share TTL and reconciliation
  hands the tokens to the surviving pods' demand.
- Coordinator pod fails over → the ledger piggybacks on the replication
  stream (``export_delta["hier"]``), so the promoted standby's attached
  coordinator resumes with every share intact; agents walk their endpoint
  list (``FailoverTokenClient.share_op``) to find it.
- MOVE of a globally-limited namespace → the hold's LEASED charge rides
  the window-sum export (lossless), the registries drop, and the
  destination's own agent re-tops from ITS share on the next tick.

This module is importable without jax: the coordinator is a plain
host-side ledger (dict + lock — shares are control-plane state at agent
tick rate, not decision rate) and the agent only needs the socket
clients. Shares are CAPACITY provisioning, not consumables: a share is
never "used up", it is re-leased every tick at whatever the water-fill
says, so ``used`` rides as 0 on the share frames.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.cluster import protocol as P

log = logging.getLogger(__name__)

# TokenStatus mirrors (this module stays importable without jax)
_OK = 0
_FAIL = 5
_NOT_LEASABLE = int(P.NOT_LEASABLE_STATUS)


@dataclass(frozen=True)
class GlobalFlowBudget:
    """One globally-limited flow: ``count`` tokens/s fleet-wide, enforced
    over a ``window_s`` sliding window (match the pods' engine window).
    ``budget_tokens`` — the water-filled pool — is ``count × window_s``."""

    flow_id: int
    count: float
    window_s: float = 1.0
    namespace: str = "default"

    @property
    def budget_tokens(self) -> int:
        return max(0, int(self.count * self.window_s))


def water_fill(budget: int, demands: Dict[str, float], floor: int = 0):
    """Classic water-filling of ``budget`` tokens over per-pod ``demands``
    (token units), with a per-pod ``floor`` (min-share: a pod whose demand
    collapses keeps a toehold, so a demand flip doesn't need a grant round
    trip before ANY traffic passes). Conserves the budget exactly:
    returned shares are integers summing to ``budget`` (when any pod
    exists). Under-demanded slack is split equally — idle headroom parks
    with every pod, absorbing spikes one tick sooner.
    """
    pods = sorted(demands)
    n = len(pods)
    out: Dict[str, int] = {}
    if n == 0 or budget <= 0:
        return {p: 0 for p in pods}
    floor = max(0, int(floor))
    if floor * n >= budget:
        # budget can't cover the floors: degenerate equal split
        share = budget // n
        out = {p: share for p in pods}
        for p in pods[: budget - share * n]:
            out[p] += 1
        return out
    free = float(budget - floor * n)
    want = {p: max(0.0, float(demands[p]) - floor) for p in pods}
    total = sum(want.values())
    if total <= free:
        slack = (free - total) / n
        level_of = {p: floor + want[p] + slack for p in pods}
    else:
        # raise the fill level until the free pool is spent
        vals = sorted(want.values())
        prev = spent = 0.0
        level = vals[-1]
        for i, v in enumerate(vals):
            width = n - i
            need = (v - prev) * width
            if spent + need >= free:
                level = prev + (free - spent) / width
                break
            spent += need
            prev = v
        level_of = {p: floor + min(want[p], level) for p in pods}
    # integerize conserving the total: floors first, largest remainders win
    ints = {p: int(level_of[p]) for p in pods}
    rem = budget - sum(ints.values())
    for p in sorted(pods, key=lambda q: (level_of[q] - ints[q], q),
                    reverse=True):
        if rem <= 0:
            break
        ints[p] += 1
        rem -= 1
    return ints


@dataclass
class ShareResult:
    """Outcome of a share op — duck-compatible with the lease-result shape
    the doors encode (status / lease_id / tokens / ttl_ms / endpoint)."""

    status: int
    lease_id: int = 0  # the share id (lease frame field name)
    tokens: int = 0
    ttl_ms: int = 0
    endpoint: str = ""


class _Share:
    __slots__ = ("share_id", "flow_id", "pod_id", "tokens", "granted_ms",
                 "expiry_ms")

    def __init__(self, share_id, flow_id, pod_id, tokens, granted_ms,
                 expiry_ms):
        self.share_id = share_id
        self.flow_id = flow_id
        self.pod_id = pod_id  # None until a demand report labels it
        self.tokens = tokens
        self.granted_ms = granted_ms
        self.expiry_ms = expiry_ms


class _FlowLedger:
    __slots__ = ("budget", "shares", "targets", "demand")

    def __init__(self, budget: GlobalFlowBudget):
        self.budget = budget
        self.shares: Dict[int, _Share] = {}
        self.targets: Dict[str, int] = {}
        # pod_id → (rate tokens/s, reported_at_ms)
        self.demand: Dict[str, Tuple[float, int]] = {}


class GlobalBudgetCoordinator:
    """The global budget ledger + reconciliation loop.

    Invariant (enforced arithmetically, never trusted to timing):
    for every flow, Σ live share tokens ≤ ``budget_tokens``. Grants and
    renews draw from ``budget − Σ live``; a renew drops the old share
    FIRST, so a pod's regrant can always reclaim at least its own tokens.

    Pod identity is learned, not declared: grants are anonymous until the
    pod's next demand report carries the share id, which labels the share
    with the pod — keeping the grant path stateless for the agent (crash
    → new share, old one expires with its TTL).
    """

    def __init__(
        self,
        budgets,
        share_ttl_ms: int = 5000,
        reconcile_ms: int = 100,
        hysteresis: float = 0.10,
        min_share_frac: float = 0.05,
    ):
        self._flows: Dict[int, _FlowLedger] = {
            int(b.flow_id): _FlowLedger(b) for b in budgets
        }
        self.share_ttl_ms = max(1, int(share_ttl_ms))
        self.reconcile_ms = max(1, int(reconcile_ms))
        self.hysteresis = max(0.0, float(hysteresis))
        self.min_share_frac = max(0.0, float(min_share_frac))
        self._lock = threading.Lock()
        self._seq = 1
        self._stats = {
            "share_grants": 0, "share_renews": 0, "share_returns": 0,
            "reconciles": 0, "demand_reports": 0, "share_expired": 0,
        }
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- ledger primitives (caller holds self._lock) -------------------------
    def _sweep_locked(self, led: _FlowLedger, now: int) -> None:
        dead = [sid for sid, s in led.shares.items() if now >= s.expiry_ms]
        for sid in dead:
            del led.shares[sid]
        self._stats["share_expired"] += len(dead)

    @staticmethod
    def _live_locked(led: _FlowLedger) -> int:
        return sum(s.tokens for s in led.shares.values())

    def _grant_locked(
        self, led: _FlowLedger, pod_id: Optional[str], want: int, now: int,
        stat: str,
    ) -> ShareResult:
        free = led.budget.budget_tokens - self._live_locked(led)
        target = led.targets.get(pod_id) if pod_id is not None else None
        grant = min(int(want), free)
        if target is not None:
            grant = min(grant, target)
        grant = max(0, grant)
        self._stats[stat] += 1
        if grant <= 0:
            # an authoritative zero: the pod holds no share right now (all
            # budget is out on other pods' shares, or its target is 0).
            # OK-with-zero-tokens, not NOT_LEASABLE — the agent must pin
            # the full budget as hold, not degrade to its last share.
            return ShareResult(_OK, lease_id=0, tokens=0,
                               ttl_ms=self.share_ttl_ms)
        sid = self._seq
        self._seq += 1
        led.shares[sid] = _Share(
            sid, led.budget.flow_id, pod_id, grant, now,
            now + self.share_ttl_ms,
        )
        return ShareResult(_OK, lease_id=sid, tokens=grant,
                           ttl_ms=self.share_ttl_ms)

    # -- wire-facing ops (doors dispatch HIER_TYPES here) --------------------
    def share_grant(self, flow_id: int, want: int) -> ShareResult:
        with self._lock:
            led = self._flows.get(int(flow_id))
            if led is None:
                return ShareResult(_NOT_LEASABLE)
            now = _clock.now_ms()
            self._sweep_locked(led, now)
            return self._grant_locked(led, None, want, now, "share_grants")

    def share_renew(
        self, share_id: int, flow_id: int, used: int, want: int
    ) -> ShareResult:
        """Drop the old share (tokens return to the pool), regrant at
        ``min(want, target, free)``. An unknown share id (expired, or a
        promoted coordinator that never saw it) degrades to a plain grant
        — no handshake after failover. ``used`` is ignored: shares are
        capacity, not consumables."""
        with self._lock:
            led = self._flows.get(int(flow_id))
            if led is None:
                return ShareResult(_NOT_LEASABLE)
            now = _clock.now_ms()
            self._sweep_locked(led, now)
            old = led.shares.pop(int(share_id), None)
            pod_id = old.pod_id if old is not None else None
            return self._grant_locked(led, pod_id, want, now, "share_renews")

    def share_return(self, share_id: int, used: int) -> ShareResult:
        """Give a share back early (pod drain/shutdown). Idempotent."""
        with self._lock:
            for led in self._flows.values():
                if led.shares.pop(int(share_id), None) is not None:
                    self._stats["share_returns"] += 1
                    break
            return ShareResult(_OK)

    def handle_demand_report(self, pod_id: str, entries) -> ShareResult:
        """Record per-pod observed demand and label shares with their pod.
        Returns an ack whose ``tokens`` is the number of entries accepted
        (entries naming unknown flows are skipped, not errors — rules roll
        out pod by pod)."""
        accepted = 0
        with self._lock:
            now = _clock.now_ms()
            for flow_id, share_id, rate_milli in entries:
                led = self._flows.get(int(flow_id))
                if led is None:
                    continue
                led.demand[str(pod_id)] = (
                    max(0.0, float(rate_milli) / 1000.0), now
                )
                share = led.shares.get(int(share_id))
                if share is not None and share.pod_id is None:
                    share.pod_id = str(pod_id)
                accepted += 1
            self._stats["demand_reports"] += 1
        return ShareResult(_OK, tokens=accepted)

    # -- reconciliation ------------------------------------------------------
    def reconcile_once(self) -> Dict[int, Dict[str, int]]:
        """One water-fill pass: demand rates → share targets per pod, with
        hysteresis (a target moves only when the change exceeds
        ``hysteresis × budget`` — share thrash costs a regrant round trip
        and a hold rewrite on every pod, so small demand noise shouldn't).
        Demand entries older than 2× the share TTL age out (a dead pod
        stops attracting budget). Returns the new target map per flow."""
        out: Dict[int, Dict[str, int]] = {}
        with self._lock:
            now = _clock.now_ms()
            stale_ms = 2 * self.share_ttl_ms
            for fid, led in self._flows.items():
                led.demand = {
                    p: (r, t) for p, (r, t) in led.demand.items()
                    if now - t < stale_ms
                }
                budget = led.budget.budget_tokens
                if not led.demand:
                    led.targets = {}
                    out[fid] = {}
                    continue
                window_s = max(1e-9, led.budget.window_s)
                demand_tokens = {
                    p: r * window_s for p, (r, t) in led.demand.items()
                }
                floor = int(self.min_share_frac * budget)
                fresh = water_fill(budget, demand_tokens, floor)
                hyst = self.hysteresis * budget
                targets = {}
                for p, t in fresh.items():
                    old = led.targets.get(p)
                    targets[p] = (
                        old if old is not None and abs(t - old) <= hyst
                        else t
                    )
                # hysteresis keeps old targets; never let the kept sum
                # exceed the budget (scale down proportionally if it would)
                total = sum(targets.values())
                if total > budget and total > 0:
                    scale = budget / total
                    targets = {p: int(t * scale) for p, t in targets.items()}
                led.targets = targets
                out[fid] = dict(targets)
            self._stats["reconciles"] += 1
        return out

    def start(self) -> "GlobalBudgetCoordinator":
        """Run :meth:`reconcile_once` every ``reconcile_ms`` on a daemon
        thread (the DCN-tier loop — deliberately slow; see docs/PERF.md
        for the sizing rule)."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def _run():
            while not self._stop.wait(self.reconcile_ms / 1000.0):
                try:
                    self.reconcile_once()
                except Exception:  # pragma: no cover - loop must survive
                    log.exception("hierarchy reconcile failed")

        self._thread = threading.Thread(
            target=_run, name="hier-reconcile", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    # -- introspection -------------------------------------------------------
    def outstanding_shares(self) -> int:
        """Σ live share tokens across every flow — the fleet's worst-case
        over-admission while the coordinator is dark (each pod keeps
        admitting at its last-granted share). The hier drill gates against
        exactly this number at SIGKILL time."""
        with self._lock:
            now = _clock.now_ms()
            total = 0
            for led in self._flows.values():
                self._sweep_locked(led, now)
                total += self._live_locked(led)
            return total

    def budget_of(self, flow_id: int) -> int:
        with self._lock:
            led = self._flows.get(int(flow_id))
            return led.budget.budget_tokens if led is not None else 0

    def budgets(self) -> Dict[int, int]:
        with self._lock:
            return {
                fid: led.budget.budget_tokens
                for fid, led in self._flows.items()
            }

    def stats(self) -> Dict[str, object]:
        with self._lock:
            now = _clock.now_ms()
            share_tokens: Dict[int, int] = {}
            n_shares = 0
            for fid, led in self._flows.items():
                self._sweep_locked(led, now)
                share_tokens[fid] = self._live_locked(led)
                n_shares += len(led.shares)
            out: Dict[str, object] = dict(self._stats)
            out["outstanding_shares"] = n_shares
            out["outstanding_share_tokens"] = sum(share_tokens.values())
            out["share_tokens"] = share_tokens
            out["budget_tokens"] = {
                fid: led.budget.budget_tokens
                for fid, led in self._flows.items()
            }
            out["targets"] = {
                fid: dict(led.targets) for fid, led in self._flows.items()
            }
            return out

    # -- standby piggyback (rides the replication stream as JSON) ------------
    def export_doc(self) -> Dict[str, object]:
        with self._lock:
            return {
                "seq": self._seq,
                "flows": {
                    str(fid): {
                        "targets": dict(led.targets),
                        "demand": {
                            p: [r, t] for p, (r, t) in led.demand.items()
                        },
                        "shares": {
                            str(s.share_id): {
                                "pod": s.pod_id,
                                "tokens": int(s.tokens),
                                "granted_ms": int(s.granted_ms),
                                "expiry_ms": int(s.expiry_ms),
                            }
                            for s in led.shares.values()
                        },
                    }
                    for fid, led in self._flows.items()
                },
            }

    def import_doc(self, doc: Dict[str, object]) -> None:
        """Land a primary's ledger into THIS (standby) coordinator. Flows
        this coordinator wasn't configured with are ignored (budget config
        is deployment state, not replicated state)."""
        with self._lock:
            self._seq = max(self._seq, int(doc.get("seq", 1)))
            for fid_s, fdoc in (doc.get("flows") or {}).items():
                led = self._flows.get(int(fid_s))
                if led is None:
                    continue
                led.targets = {
                    str(p): int(t)
                    for p, t in (fdoc.get("targets") or {}).items()
                }
                led.demand = {
                    str(p): (float(v[0]), int(v[1]))
                    for p, v in (fdoc.get("demand") or {}).items()
                }
                led.shares = {}
                for sid_s, sdoc in (fdoc.get("shares") or {}).items():
                    sid = int(sid_s)
                    led.shares[sid] = _Share(
                        sid, int(fid_s),
                        sdoc.get("pod"), int(sdoc["tokens"]),
                        int(sdoc["granted_ms"]), int(sdoc["expiry_ms"]),
                    )


class PodShareAgent:
    """The pod-side half: one control-plane tick loop that (1) reports the
    pod's observed demand, (2) renews its share of every global flow, and
    (3) pins ``window_budget − share`` as the LEASED hold so local
    headroom equals the share. Decision-path cost: ZERO — nothing here
    runs per request, and the tick's wire work is a handful of frames
    every ``tick_ms``.

    ``endpoints`` is the coordinator endpoint list (primary + standbys);
    the agent walks it via :class:`~sentinel_tpu.ha.failover.
    FailoverTokenClient` share ops, so coordinator failover needs no agent
    config change. ``update_endpoints`` follows the shard map's
    ``global_flows`` section, epoch-fenced like every other route."""

    def __init__(
        self,
        service,
        endpoints: List[str],
        pod_id: str,
        flows,
        tick_ms: int = 100,
        timeout_ms: int = 50,
        deadline_ms: int = 200,
        client_cls=None,
    ):
        if client_cls is None:
            from sentinel_tpu.ha.failover import FailoverTokenClient
            client_cls = FailoverTokenClient
        self._client_cls = client_cls
        self.service = service
        self.pod_id = str(pod_id)
        self.tick_ms = max(1, int(tick_ms))
        self.timeout_ms = max(1, int(timeout_ms))
        self.deadline_ms = max(self.timeout_ms, int(deadline_ms))
        self._flow_ids = [int(getattr(b, "flow_id", b)) for b in flows]
        self._lock = threading.Lock()
        self._endpoints = list(endpoints)
        self._epoch = -1
        self._client = self._make_client(self._endpoints)
        # per-flow: last-granted (share_id, tokens); tokens survives
        # coordinator silence — degrade-to-last-share
        self._shares: Dict[int, Tuple[int, int]] = {
            fid: (0, 0) for fid in self._flow_ids
        }
        self._stats = {
            "agent_ticks": 0, "agent_rpcs": 0, "agent_report_fail": 0,
            "agent_renew_fail": 0, "agent_degraded": 0,
        }
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        attach = getattr(service, "attach_share_agent", None)
        if attach is not None:
            attach(self)

    @staticmethod
    def _parse_endpoint(ep):
        if isinstance(ep, (tuple, list)):
            return (str(ep[0]), int(ep[1]))
        host, _, port = str(ep).rpartition(":")
        return (host, int(port))

    def _make_client(self, endpoints: List[str]):
        return self._client_cls(
            [self._parse_endpoint(e) for e in endpoints],
            timeout_ms=self.timeout_ms, deadline_ms=self.deadline_ms,
        )

    def update_endpoints(self, endpoints: List[str], epoch: int) -> bool:
        """Follow a shard-map ``global_flows`` update. Epoch-fenced: a
        stale map (epoch ≤ last applied) is a no-op, same contract as
        routing. Returns True when the client was rebuilt."""
        with self._lock:
            if int(epoch) <= self._epoch:
                return False
            self._epoch = int(epoch)
            if list(endpoints) == self._endpoints:
                return True
            old, self._client = self._client, self._make_client(
                list(endpoints)
            )
            self._endpoints = list(endpoints)
        try:
            old.close()
        except Exception:  # pragma: no cover - retired client teardown
            pass
        return True

    def apply_shard_map(self, shard_map) -> None:
        """Convenience hook for ``ShardMapPublisher.listen``: pull this
        agent's coordinator endpoints out of the map's ``global_flows``
        section (all this agent's flows share one coordinator; the first
        mapped flow wins)."""
        gf = getattr(shard_map, "global_flows", None) or {}
        for fid in self._flow_ids:
            ep = gf.get(str(fid)) or gf.get(fid)
            if ep:
                self.update_endpoints([ep], int(shard_map.epoch))
                return

    # -- the tick ------------------------------------------------------------
    def tick(self) -> None:
        """One control-plane pass: demand report → share renew → hold
        re-top. Each step tolerates coordinator silence independently; the
        hold re-top ALWAYS runs (it is what keeps a rotated-out hold
        pinned, whether or not the coordinator answered)."""
        with self._lock:
            client = self._client
        rates = self.service.demand_rates(self._flow_ids)
        entries = [
            (fid, self._shares.get(fid, (0, 0))[0],
             int(rates.get(fid, 0.0) * 1000))
            for fid in self._flow_ids
        ]
        self._stats["agent_rpcs"] += 1
        ack = client.demand_report(self.pod_id, entries)
        if ack is None:
            self._stats["agent_report_fail"] += 1
        degraded = 0
        for fid in self._flow_ids:
            share_id, last = self._shares.get(fid, (0, 0))
            budget = int(self.service.window_budget(fid))
            self._stats["agent_rpcs"] += 1
            rsp = client.share_op(
                P.MsgType.SHARE_RENEW if share_id
                else P.MsgType.SHARE_GRANT,
                fid, want=budget, share_id=share_id,
            )
            if rsp is not None and int(rsp.status) == _OK:
                self._shares[fid] = (int(rsp.lease_id), int(rsp.tokens))
            elif rsp is not None and int(rsp.status) == _NOT_LEASABLE:
                # authoritative refusal (flow not budgeted there): keep the
                # last share — config may be mid-rollout
                self._stats["agent_renew_fail"] += 1
                degraded = 1
            else:
                # coordinator dark: DEGRADE TO LAST SHARE. The old share id
                # is kept so the next successful renew reclaims it (and the
                # coordinator's ledger still counts it until TTL — which is
                # exactly what bounds fleet over-admission while dark).
                self._stats["agent_renew_fail"] += 1
                degraded = 1
            _, share = self._shares.get(fid, (0, 0))
            self.service.set_share_hold(fid, max(0, budget - share))
        self._stats["agent_degraded"] = degraded
        self._stats["agent_ticks"] += 1

    def start(self) -> "PodShareAgent":
        if self._thread is not None:
            return self
        self._stop.clear()

        def _run():
            while not self._stop.wait(self.tick_ms / 1000.0):
                try:
                    self.tick()
                except Exception:  # pragma: no cover - loop must survive
                    log.exception("share agent tick failed")

        self._thread = threading.Thread(
            target=_run, name="hier-share-agent", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, return_shares: bool = True) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
        if return_shares:
            with self._lock:
                client = self._client
            for fid, (share_id, _) in list(self._shares.items()):
                if share_id:
                    try:
                        client.share_op(
                            P.MsgType.SHARE_RETURN, fid, share_id=share_id
                        )
                    except Exception:  # pragma: no cover - best effort
                        pass
                self._shares[fid] = (0, 0)

    def close(self) -> None:
        self.stop()
        with self._lock:
            try:
                self._client.close()
            except Exception:  # pragma: no cover
                pass

    def shares(self) -> Dict[int, int]:
        """flow_id → last-granted share tokens."""
        return {fid: s for fid, (_, s) in self._shares.items()}

    def stats(self) -> Dict[str, object]:
        out: Dict[str, object] = dict(self._stats)
        out["share_tokens"] = self.shares()
        return out


# -- coordinator auto-election (rev-7: no configured single point) -----------
# The leader lock lives in the shard map's ``global_flows`` section under a
# key no flow id can collide with (flow keys are ``str(int(...))``;
# ``coordinator_of`` lookups therefore never read it). The lock value names
# the holder, its endpoint, and a wall-clock deadline — a lease, renewed by
# the leader and claimable by anyone after expiry. Claims are arbitrated by
# the SAME epoch fence MOVE uses: every claim is a next-epoch map through
# ``ShardMapPublisher.publish``, which admits exactly one map per epoch, so
# two pods racing for an expired lock can't both win — the loser's publish
# returns False and it stays a follower.
COORD_LOCK_KEY = "coordinator_lock"


def encode_coord_lock(pod_id: str, endpoint: str, deadline_ms: int) -> str:
    return f"{pod_id}|{endpoint}|{int(deadline_ms)}"


def decode_coord_lock(text) -> Optional[Tuple[str, str, int]]:
    """``(pod_id, endpoint, deadline_ms)`` or None for absent/malformed."""
    try:
        pod_id, endpoint, deadline = str(text).split("|")
        return pod_id, endpoint, int(deadline)
    except (ValueError, AttributeError):
        return None


class CoordinatorElection:
    """Auto-elects which pod hosts the :class:`GlobalBudgetCoordinator`.

    One instance per pod, ticking against a shared
    :class:`~sentinel_tpu.cluster.rebalance.ShardMapPublisher`. The winner
    constructs and attaches a coordinator (``service.attach_hierarchy``),
    publishes a map whose ``global_flows`` points every budgeted flow at
    its own endpoint, and broadcasts that map as a ``SHARD_MAP_PUSH`` on
    every attached hub so agents and routing clients cut over within one
    RTT instead of a poll interval. A deposed or expired leader detaches.

    Failover needs no handshake: a freshly-elected coordinator starts with
    an empty ledger, agents' renews carry unknown share ids and degrade to
    plain grants (:meth:`GlobalBudgetCoordinator.share_renew`), and until
    then each pod admits at its last-granted share — the same
    Σ-outstanding-shares bound that holds while a coordinator is dark.
    """

    def __init__(
        self,
        service,
        publisher,
        pod_id: str,
        endpoint: str,
        budgets,
        lock_ttl_ms: int = 3000,
        tick_ms: int = 500,
        share_ttl_ms: int = 5000,
        reconcile_ms: int = 100,
        coordinator_factory=None,
        push_hubs=(),
    ):
        self.service = service
        self.publisher = publisher
        self.pod_id = str(pod_id)
        self.endpoint = str(endpoint)
        self.budgets = list(budgets)
        self.lock_ttl_ms = max(1, int(lock_ttl_ms))
        self.tick_ms = max(1, int(tick_ms))
        self._factory = coordinator_factory or (
            lambda: GlobalBudgetCoordinator(
                self.budgets, share_ttl_ms=share_ttl_ms,
                reconcile_ms=reconcile_ms,
            )
        )
        self.push_hubs = list(push_hubs)
        self.coordinator: Optional[GlobalBudgetCoordinator] = None
        self.is_leader = False
        self._lock = threading.Lock()
        self._stats = {
            "elections_won": 0, "lock_renewals": 0, "depositions": 0,
            "claim_lost": 0,
        }
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lock plumbing -------------------------------------------------------
    def _current_lock(self, shard_map, now: int):
        """The LIVE lock holder tuple, or None (absent/expired/torn)."""
        lock = decode_coord_lock(
            (shard_map.global_flows or {}).get(COORD_LOCK_KEY)
        )
        if lock is None or now >= lock[2]:
            return None
        return lock

    def _publish_claim(self, shard_map, now: int) -> bool:
        """Next-epoch map: our lock + every budgeted flow pointed at our
        endpoint. The publisher's epoch fence arbitrates racing claims."""
        g = dict(shard_map.global_flows or {})
        g[COORD_LOCK_KEY] = encode_coord_lock(
            self.pod_id, self.endpoint, now + self.lock_ttl_ms
        )
        for b in self.budgets:
            g[str(int(b.flow_id))] = self.endpoint
        nxt = type(shard_map)(
            int(shard_map.epoch) + 1, dict(shard_map.endpoint_of), g
        )
        return bool(self.publisher.publish(nxt))

    def _push_map(self) -> None:
        """Broadcast the published map on every hub (SHARD_MAP_PUSH) so
        live clients learn the election outcome within one RTT. Best
        effort — the publisher's listener plane is the polling fallback."""
        if not self.push_hubs:
            return
        from sentinel_tpu.cluster.rebalance import encode_shard_map_doc

        try:
            doc = encode_shard_map_doc(self.publisher.current())
        except Exception:  # pragma: no cover - doc encode must not kill tick
            return
        for hub in self.push_hubs:
            try:
                hub.push_shard_map(doc)
            except Exception:
                pass

    # -- leadership transitions ---------------------------------------------
    def _ensure_leader(self) -> None:
        with self._lock:
            if self.is_leader:
                return
            self.coordinator = self._factory()
            self.is_leader = True
            self._stats["elections_won"] += 1
        attach = getattr(self.service, "attach_hierarchy", None)
        if attach is not None:
            attach(self.coordinator)
        log.info("pod %s won coordinator election (%s)",
                 self.pod_id, self.endpoint)
        self._push_map()

    def _ensure_follower(self) -> None:
        with self._lock:
            if not self.is_leader:
                return
            coord, self.coordinator = self.coordinator, None
            self.is_leader = False
            self._stats["depositions"] += 1
        if getattr(self.service, "hierarchy", None) is coord:
            self.service.hierarchy = None
        if coord is not None:
            coord.stop()
        log.info("pod %s deposed as coordinator", self.pod_id)

    # -- the tick ------------------------------------------------------------
    def tick(self) -> bool:
        """One election pass; returns True while this pod leads. A live
        foreign lock → follow. Our lock → renew when less than half the
        TTL remains (each renewal is a next-epoch publish). Absent or
        expired lock → claim; the epoch fence picks exactly one winner."""
        now = _clock.now_ms()
        shard_map = self.publisher.current()
        lock = self._current_lock(shard_map, now)
        if lock is not None and lock[0] != self.pod_id:
            self._ensure_follower()
            return False
        if lock is not None:
            # ours and live: renew before it can lapse mid-tick-period
            if lock[2] - now < self.lock_ttl_ms / 2:
                if self._publish_claim(shard_map, now):
                    self._stats["lock_renewals"] += 1
            self._ensure_leader()
            return True
        if self._publish_claim(shard_map, now):
            self._ensure_leader()
            return True
        # lost the race to a concurrent claimant; learn the winner next tick
        self._stats["claim_lost"] += 1
        self._ensure_follower()
        return False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "CoordinatorElection":
        if self._thread is not None:
            return self
        self._stop.clear()

        def _run():
            while not self._stop.wait(self.tick_ms / 1000.0):
                try:
                    self.tick()
                except Exception:  # pragma: no cover - loop must survive
                    log.exception("coordinator election tick failed")

        self._thread = threading.Thread(
            target=_run, name="hier-election", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, release: bool = True) -> None:
        """Graceful exit: stop ticking, step down, and (by default) publish
        a lock release so the next claimant needn't wait out the TTL."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
        was_leader = self.is_leader
        self._ensure_follower()
        if release and was_leader:
            shard_map = self.publisher.current()
            lock = decode_coord_lock(
                (shard_map.global_flows or {}).get(COORD_LOCK_KEY)
            )
            if lock is not None and lock[0] == self.pod_id:
                g = dict(shard_map.global_flows)
                g.pop(COORD_LOCK_KEY, None)
                self.publisher.publish(type(shard_map)(
                    int(shard_map.epoch) + 1,
                    dict(shard_map.endpoint_of), g,
                ))
                self._push_map()

    def hard_stop(self) -> None:
        """Drill stand-in for SIGKILL: the pod vanishes WITHOUT releasing
        the lock or detaching anything cleanly — survivors must wait out
        the lock TTL, exactly like a real crash."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
        coord = self.coordinator
        if coord is not None:
            coord.stop()

    def stats(self) -> Dict[str, object]:
        out: Dict[str, object] = dict(self._stats)
        out["is_leader"] = self.is_leader
        out["pod_id"] = self.pod_id
        return out
