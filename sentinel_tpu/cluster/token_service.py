"""TokenService SPI and its engine-backed default implementation.

Analogs: ``sentinel-core/.../cluster/TokenService.java`` (the SPI seam),
``TokenResult``/``TokenResultStatus``, and the server-side
``DefaultTokenService.java:36-97`` whose per-request logic is replaced by the
jitted batch kernel ``sentinel_tpu.engine.decide``.

Both deployment shapes of the reference exist here:
- **standalone** (``SentinelDefaultTokenServer``): ``server.TokenServer``
  wraps a ``DefaultTokenService`` behind the TCP front door;
- **embedded** (``DefaultEmbeddedTokenServer``): the same object serves
  in-process calls from the local flow checker *and* remote clients.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from sentinel_tpu import chaos as _chaos
from sentinel_tpu.core import clock as _clock
from sentinel_tpu.engine import (
    ClusterFlowRule,
    DegradeRule,
    EngineConfig,
    EngineState,
    TokenStatus,
    build_rule_table,
    decide,
    drain_pending_clear,
    make_batch,
    make_state,
)
from sentinel_tpu.engine.param import (
    ParamConfig,
    hash_indices,
    make_param_state,
    param_decide,
)
from sentinel_tpu.engine.rules import RuleIndex
from sentinel_tpu.metrics.server import server_metrics
from sentinel_tpu.metrics.stat_logger import log_cluster
from sentinel_tpu.trace import ring as _TR

_SM = server_metrics()


class _PrepCache:
    """Bounded LRU memo of the host-side batch prep — the ``lookup_slots``
    resolution plus the grouping argsort and padded ``RequestBatch`` — keyed
    by the exact (flow_ids, acquires, prios) byte content and the lookup
    snapshot identity. Closed-loop clients (and real sidecar fleets) resend
    the same hot flow-id vectors frame after frame, so the hit path replaces
    an O(n log n) sort + four array passes with one memcmp verification.

    A rule reload swaps the lookup snapshot, which changes the key and
    naturally invalidates every entry (dead entries age out of the LRU).
    Entries hold numpy arrays the device step only reads, so sharing one
    prepped batch across dispatches is safe (batches are never donated).
    """

    def __init__(self, capacity: int = 64):
        from collections import OrderedDict

        self.capacity = int(capacity)
        self._map: "OrderedDict" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, snap_keys, cap: int, flow_ids, acq, pr):
        key = (
            id(snap_keys), cap, hash(flow_ids.tobytes()),
            hash(acq.tobytes()), hash(pr.tobytes()),
        )
        with self._lock:
            hit = self._map.get(key)
            if hit is not None:
                self._map.move_to_end(key)
        if hit is not None:
            c_ids, c_acq, c_pr, slots, order, batch = hit
            # content verification: `hash` collisions must never hand a
            # different request vector someone else's slot assignment
            if (
                np.array_equal(c_ids, flow_ids)
                and np.array_equal(c_acq, acq)
                and np.array_equal(c_pr, pr)
            ):
                self.hits += 1
                return key, (slots, order, batch)
        self.misses += 1
        return key, None

    def put(self, key, flow_ids, acq, pr, slots, order, batch) -> None:
        # copies: callers may hand views into reused front-door buffers
        entry = (
            np.array(flow_ids), np.array(acq), np.array(pr),
            slots, order, batch,
        )
        with self._lock:
            self._map[key] = entry
            self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)


@dataclass(frozen=True)
class ClusterParamFlowRule:
    """Cluster hot-param rule (``ParamFlowRule`` + ``ClusterFlowConfig``):
    per-value QPS threshold, with per-item overrides keyed by the value's
    stable hash (``ParamFlowItem`` analog — compute with
    ``sentinel_tpu.core.hashing.stable_param_hash``)."""

    flow_id: int
    count: float
    item_thresholds: Optional[Tuple[Tuple[int, float], ...]] = None
    namespace: str = "default"


@dataclass(frozen=True)
class TokenResult:
    """``TokenResult.java`` — status + remaining + wait hint (+ token id in
    concurrent mode)."""

    status: TokenStatus
    remaining: int = 0
    wait_ms: int = 0
    token_id: int = 0
    # MOVED only: the new owner's "host:port" (``remaining`` then carries the
    # shard-map epoch). Empty for every other status.
    endpoint: str = ""

    @property
    def ok(self) -> bool:
        # RELEASE_OK is the success status of a concurrent release — the one
        # natural success predicate must cover both acquire and release paths
        return self.status in (TokenStatus.OK, TokenStatus.RELEASE_OK)

    @property
    def retry_after_ms(self) -> int:
        """DEGRADED only: how long until the flow's breaker admits a
        recovery probe (``remaining`` carries it on the wire, like the
        MOVED epoch). 0 for every other status."""
        return (
            int(self.remaining)
            if self.status == TokenStatus.DEGRADED else 0
        )


class TokenService:
    """The SPI: local flow checkers and the transport both speak this."""

    def request_token(
        self, flow_id: int, acquire: int = 1, prioritized: bool = False
    ) -> TokenResult:
        raise NotImplementedError

    def request_params_token(
        self, flow_id: int, acquire: int, param_hashes: Sequence[int]
    ) -> TokenResult:
        raise NotImplementedError

    def request_batch(
        self, requests: Sequence[Tuple[int, int, bool]]
    ) -> List[TokenResult]:
        """Vectorized form: list of (flow_id, acquire, prioritized)."""
        return [self.request_token(f, a, p) for f, a, p in requests]

    def request_batch_arrays(self, flow_ids, acquires=None, prios=None):
        """Array form: (status int8[N], remaining int32[N], wait_ms int32[N])
        in request order. The transport speaks this; the default delegates to
        ``request_batch`` so any SPI implementation serves batch frames."""
        n = len(flow_ids)
        results = self.request_batch(
            [
                (
                    int(flow_ids[i]),
                    1 if acquires is None else int(acquires[i]),
                    False if prios is None else bool(prios[i]),
                )
                for i in range(n)
            ]
        )
        status = np.fromiter((int(r.status) for r in results), np.int8, n)
        remaining = np.fromiter((r.remaining for r in results), np.int32, n)
        wait = np.fromiter((r.wait_ms for r in results), np.int32, n)
        return status, remaining, wait

    def request_concurrent_token(
        self, flow_id: int, acquire: int = 1, prioritized: bool = False
    ) -> TokenResult:
        """Cluster-semaphore acquire (``ConcurrentClusterFlowChecker``)."""
        raise NotImplementedError

    def release_concurrent_token(self, token_id: int) -> TokenResult:
        raise NotImplementedError


@dataclass(frozen=True)
class LeaseResult:
    """Outcome of a wire-rev-5 lease operation (grant/renew/return).

    ``status`` is a TokenStatus code: OK carries a live lease
    (``lease_id``/``tokens``/``ttl_ms``), NOT_LEASABLE means admit
    per-request instead (no headroom, revoked, or leasing disabled),
    NO_RULE_EXISTS / MOVED / STANDBY mean what they mean on the decision
    path — MOVED fills ``endpoint`` with the new owner."""

    status: int
    lease_id: int = 0
    tokens: int = 0
    ttl_ms: int = 0
    endpoint: str = ""

    @property
    def ok(self) -> bool:
        return int(self.status) == int(TokenStatus.OK)


class _Lease:
    """One outstanding lease: host registry entry only. The token charge
    itself lives in the LEASED column of the flow window — the registry is
    what lets renew/return credit unused tokens back and lets the drill
    bound crash over-admission by ``outstanding_leases()``. Deliberately
    NOT part of snapshots/deltas: a promoted standby starts with an empty
    registry, renews become credit-less re-grants, and the charge (which
    IS replicated) keeps the limit conservative."""

    __slots__ = ("lease_id", "flow_id", "slot", "tokens", "granted_ms",
                 "expiry_ms")

    def __init__(self, lease_id, flow_id, slot, tokens, granted_ms,
                 expiry_ms):
        self.lease_id = int(lease_id)
        self.flow_id = int(flow_id)
        self.slot = int(slot)
        self.tokens = int(tokens)
        self.granted_ms = int(granted_ms)
        self.expiry_ms = int(expiry_ms)


class DefaultTokenService(TokenService):
    """Engine-backed token service.

    The reference hot loop (rule lookup → LeapArray read-sum → LongAdder adds,
    ``ClusterFlowChecker.java:55-120``) runs as one device step per
    micro-batch; this class owns the device state and the host-side
    flow_id → slot index, and serializes steps with a lock (single-writer —
    the race-free analog of the JVM's CAS storm).
    """

    def __init__(
        self,
        config: Optional[EngineConfig] = None,
        param_config: Optional[ParamConfig] = None,
        mesh=None,
        serve_buckets: Optional[Sequence[int]] = None,
        fuse_depths: Optional[Sequence[int]] = (8, 4, 2),
        lease_ttl_ms: int = 500,
        lease_fraction: float = 0.5,
    ):
        self.config = config or EngineConfig()
        # serving shape buckets: a lightly-loaded step pads to the smallest
        # bucket that fits instead of the full batch size (the decide cost is
        # shape-proportional — ~4× cheaper at 64 than 1024 — and state
        # tensors are batch-agnostic, so each bucket is just one more
        # compiled variant of the same kernel). Default: geometric ×4 ladder
        # 64, 256, 1024, … up to batch_size, so no batch pays more than ~4×
        # its size. Warmup compiles 2 variants per bucket; trim the set if
        # compile time matters more than tail latency.
        if serve_buckets is None:
            buckets = set()
            b = 64
            while b < self.config.batch_size:
                buckets.add(b)
                b *= 4
            buckets.add(self.config.batch_size)
        else:
            buckets = {
                min(int(b), self.config.batch_size) for b in serve_buckets
            }
            buckets.add(self.config.batch_size)
        self._serve_buckets = sorted(buckets)
        # Optional jax.sharding.Mesh: the flow axis of the engine state and
        # rule table shards across the mesh's devices and the decision step
        # runs under shard_map with psums over ICI — one pod's chips serve
        # one namespace partition together (SURVEY §7.5 tier 1; tier 2 —
        # namespaces across pods — is sentinel_tpu.cluster.namespaces).
        self.mesh = mesh
        self._sharded_steps: Dict[Tuple[int, bool], object] = {}
        # fused multi-frame dispatch ladder: an oversized pull splits into
        # full-batch_size frames and each run of F consecutive frames folds
        # into ONE chained device step (lax.scan over the donated-state
        # step) — the per-dispatch overhead (20–50ms through the TPU
        # tunnel, BENCH_r05 per_bucket_dispatch_overhead_ms) is paid once
        # per F frames instead of once per frame. Ladder entries are the
        # compiled scan depths (greedy largest-fit split, e.g. 7 frames →
        # scan(4) + scan(2) + single); empty disables fusion (per-frame
        # dispatch, the pre-fusion behavior). Mesh-sharded services skip
        # fusion — the shard_map step has its own dispatch discipline.
        self._fuse_depths = tuple(sorted(
            {int(d) for d in (fuse_depths or ()) if int(d) >= 2},
            reverse=True,
        ))
        self._fused_steps: Dict[Tuple[int, bool], object] = {}
        # fused staging freelists: per scan depth, recycled [depth, batch]
        # RequestBatch leaf blocks the fused dispatch writes prepped frames
        # into — replaces the per-dispatch np.stack (4 fresh [depth, batch]
        # allocations per fused group) with copies into pinned, reused
        # memory. Blocks are released after verdict materialization (the
        # device has definitely consumed the host buffers by then).
        self._fused_staging: Dict[int, object] = {}
        self._prep_cache = _PrepCache()
        self._lock = threading.Lock()
        # outer mutex for rule read-modify-write sequences: a namespace
        # replacement (merge current rules + load) must be atomic against a
        # concurrent replacement of ANOTHER namespace, or the later load
        # silently drops the earlier one's rules. Reentrant so
        # load_namespace_rules → load_rules nests.
        self._rules_mutex = threading.RLock()
        self._state = self._place_state(make_state(self.config))
        table, self._index = build_rule_table(self.config, [])
        self._table = self._place_rules(table)
        # vectorized flow_id → slot lookup: one (sorted keys, slots) tuple,
        # swapped atomically on rule load, read lock-free on the hot path
        self._lookup = (np.empty(0, np.int64), np.empty(0, np.int32))
        # slot → namespace row snapshot for per-namespace verdict counters,
        # same atomic-swap discipline: (names tuple, int32[max_flows] of
        # namespace indices, -1 where the slot holds no rule)
        self._ns_snapshot: Tuple[Tuple[str, ...], np.ndarray] = (
            (), np.full(self.config.max_flows, -1, np.int32),
        )
        self._epoch_ms: Optional[int] = None
        self._connected: Dict[str, int] = {}  # namespace → client count
        self._ns_max_qps = 30_000.0
        # namespace-scoped rule bookkeeping (ClusterFlowRuleManager keeps
        # namespace → flowId sets; the command surface edits one namespace
        # at a time while the device table always holds the union)
        self._rules_by_ns: Dict[str, Dict[int, ClusterFlowRule]] = {}
        # flat flow_id → rule view of _rules_by_ns (same lifecycle): the
        # lease grant path needs the rule's count/mode/namespace per call
        # without walking namespaces
        self._rule_of: Dict[int, ClusterFlowRule] = {}
        self._param_rules_src: Dict[int, "ClusterParamFlowRule"] = {}
        # device-resident circuit breakers (engine/degrade.py): the source
        # DegradeRule objects keyed by flow_id (compiled into the br_*
        # rule-table columns on every load_rules), the slots that carry a
        # breaker (dirty-set and lease-refusal gating), and the host-side
        # state mirror the transition scanner diffs against (int8[F] copy
        # of the last breaker.state this host observed — the device is the
        # authority; the mirror only exists to emit
        # sentinel_breaker_transitions_total edges and the CLOSED→OPEN
        # blackbox dump without a device round-trip per transition).
        self._degrade_rules_src: Dict[int, "DegradeRule"] = {}
        self._has_breakers = False
        self._breaker_slots: set = set()
        self._breaker_prev: Optional[np.ndarray] = None
        self._breaker_scan_ts = 0.0
        # namespaces this server explicitly serves (modifyNamespaceSet);
        # unioned with namespaces of loaded rules for info/fetchConfig
        self.namespace_set: set = set()
        # hot-param sketch path (ClusterParamFlowChecker analog)
        self.param_config = param_config or ParamConfig()
        self._param_state = make_param_state(self.param_config)
        self._param_rules: Dict[int, Tuple[int, float, Dict[int, float]]] = {}
        self._param_free = list(range(self.param_config.max_param_rules - 1, -1, -1))
        # sketch observability (sentinel_sketch_* series + the `sketch`
        # block of clusterServerStats): the process-wide ServerMetrics pulls
        # through a weakref so a dead service never pins memory; the most
        # recently constructed service is the one scraped
        import weakref

        _self = weakref.ref(self)
        _SM.register_sketch_provider(
            lambda: (lambda s: s.sketch_stats() if s is not None else {})(
                _self()
            )
        )
        # concurrent (semaphore) mode — host-side by design, see
        # sentinel_tpu.cluster.concurrent
        from sentinel_tpu.cluster.concurrent import ConcurrencyManager

        self.concurrency = ConcurrencyManager()
        self._expiry = None  # background sweep; started on first rule load
        # warm-standby replication hooks (ha.replication): dirty-slot sets
        # collected by the dispatch paths since the last export_delta().
        # None until replication_enable() — the serving hot path pays one
        # `is not None` check when no standby is attached. _state_gen bumps
        # on every rule/param-rule reload: slot assignments (the delta's
        # row keys) are only stable within a generation, so a bump tells
        # the sender to re-bootstrap standbys with a full snapshot.
        self._state_gen = 0
        self._dirty: Optional[Dict[str, set]] = None
        # live-rebalance MOVING set (cluster.rebalance): namespace →
        # (destination "host:port", shard-map epoch). While a namespace is
        # here its flows are masked OUT of every device batch (their rows
        # never count a token — the zero-over-admission invariant) and the
        # materializers overlay TokenStatus.MOVED. _moving_snap is the
        # dispatch-path view: an immutable (mask bool[max_namespaces],
        # epoch int32[max_namespaces]) pair rebuilt under self._lock on
        # every begin/abort/end and rule reload, or None when nothing is
        # moving — the idle hot path pays one `is not None` check.
        self._moving: Dict[str, Tuple[str, int]] = {}
        self._moving_snap: Optional[Tuple[np.ndarray, np.ndarray]] = None
        # wire rev 5 token leases: short-TTL client-local admission slices.
        # A grant charges the whole slice into the LEASED event column of
        # the flow window at grant time (pre-paid — see ClusterEvent.LEASED)
        # and records it here so renew/return can credit unused tokens back.
        # lease_fraction caps each grant at that share of the flow's CURRENT
        # headroom, so k clients racing for leases geometrically share the
        # window instead of the first one draining it; lease_ttl_ms bounds
        # how long a crashed client's slice stays admitted-but-unobserved
        # (the over-admission window). lease_fraction <= 0 disables leasing
        # (every grant answers NOT_LEASABLE).
        self.lease_ttl_ms = max(1, int(lease_ttl_ms))
        self.lease_fraction = float(lease_fraction)
        self._leases: Dict[int, _Lease] = {}
        self._lease_seq = itertools.count(1)
        self._lease_stats = {
            "granted": 0, "renewed": 0, "returned": 0, "revoked": 0,
        }
        _SM.register_lease_provider(
            lambda: (lambda s: s.lease_stats() if s is not None else {})(
                _self()
            )
        )
        # hierarchy tier (cluster/hierarchy.py): when this pod participates
        # in a global flow budget, its share agent pins the UNPROVISIONED
        # remainder of the budget as a LEASED-column hold — local headroom
        # == the pod's share with zero hot-path changes. Entries are
        # (granted_ms, tokens) charges with the same exact-bucket lifecycle
        # as leases; the agent re-tops them every tick because bucket
        # rotation expires them (conservative: a stale hold only
        # under-admits). `hierarchy` is the co-located coordinator, if any;
        # both doors route HIER_TYPES frames to it.
        self._share_holds: Dict[int, List[Tuple[int, int]]] = {}
        self.hierarchy = None
        self.share_agent = None
        _SM.register_hier_provider(
            lambda: (lambda s: s.hier_stats() if s is not None else {})(
                _self()
            )
        )
        # rev-6 outcome plane: the donated completion-scatter step compiles
        # lazily (reports arrive on the clients' cadence, not the serve
        # path's — the first report pays the compile; row counts pad to a
        # geometric shape ladder so retraces stay bounded); host counters
        # back sentinel_outcome_reported_total /
        # sentinel_outcome_dropped_total{reason} and the reconciliation
        # gate. All mutated under self._lock.
        self._outcome_step = None
        self._outcome_counts: Dict[str, object] = {
            "reported": 0,  # rows accepted and scattered
            "exceptions": 0,  # subset of reported with exc=1
            "rt_sum_ms": 0,  # host-side mirror of the accepted RT mass
            "batches": 0,  # OUTCOME_REPORT frames ingested
            # reason -> count; reasons: negative, too_large, unknown_flow
            "dropped": {},
        }
        _SM.register_outcome_provider(
            lambda: (lambda s: s.outcome_stats() if s is not None else {})(
                _self()
            )
        )
        _SM.register_breaker_provider(
            lambda: (lambda s: s.breaker_stats() if s is not None else {})(
                _self()
            )
        )
        # rev-7 push plane: front doors attach their PushHub here so the
        # service can emit unsolicited server→client frames at the moment
        # server truth changes (lease revoked, breaker flipped, rules
        # reloaded) instead of waiting for clients to poll into it. Emits
        # are fire-and-forget through non-blocking sinks — safe to call
        # under self._lock (see _emit_push).
        self._push_hubs: List[object] = []

    @staticmethod
    def _prep_batch(cfg, slots, acq, pr):
        """Build the device batch; returns ``(order, batch)`` where order is
        None when slots arrived ascending-SORTED (stable argsort would be
        the identity) — skipping an O(n log n) sort and three fancy-index
        passes each way. Grouped-but-unsorted input still sorts.
        Shared by the hot prep and the rare rules-reloaded re-prep so the
        two can't diverge."""
        sorted_already = bool((slots[:-1] <= slots[1:]).all())
        if sorted_already:
            return None, make_batch(cfg, slots, acq, pr)
        order = np.argsort(slots, kind="stable")
        return order, make_batch(cfg, slots[order], acq[order], pr[order])

    # -- mesh placement -----------------------------------------------------
    def _place_state(self, state):
        if self.mesh is None:
            return state
        from sentinel_tpu.parallel.sharding import shard_state

        return shard_state(state, self.mesh)

    def _place_rules(self, table):
        if self.mesh is None:
            return table
        from sentinel_tpu.parallel.sharding import shard_rules

        return shard_rules(table, self.mesh)

    def _step_fn(self, bucket: int, uniform: bool):
        """The device step for one (shape bucket, uniform) variant —
        single-shard ``decide`` or the mesh-sharded shard_map step.

        Cached per variant for BOTH paths: a fresh closure + fresh config
        object per call would route every dispatch through pjit's slow
        Python cache-miss path (~1ms/call on CPU — measured; the C++
        fast path keys on the callable identity), which at serving rates
        costs more than the kernel itself.

        BOTH steps DONATE the state buffers: every serving step
        scatter-updates the full [max_flows, buckets, events] window
        tensors, and without donation XLA must copy them first (measured
        22% of the 64-bucket step at 100k flows on CPU; on TPU it is HBM
        traffic and allocator churn — and under a mesh the copy is paid
        per shard, every dispatch). Safe because the service lock makes
        `self._state, verdicts = step(self._state, …)` the only reader of
        the old buffer, and warmup feeds throwaway states. If a dispatch
        ever raises AFTER consuming its donated input, later steps fail
        loudly with a donated-buffer error (visible, not silent)."""
        key = (bucket, uniform)
        step = self._sharded_steps.get(key)
        if step is not None:
            return step
        cfg = self.config._replace(batch_size=bucket)
        if self.mesh is None:
            from sentinel_tpu.engine.decide import decide_donating

            step = decide_donating(cfg, grouped=True, uniform=uniform)
        else:
            from sentinel_tpu.parallel.sharding import make_sharded_decide

            step = make_sharded_decide(
                cfg, self.mesh, grouped=True, uniform=uniform, donate=True
            )
        self._sharded_steps[key] = step
        return step

    def _fused_step_fn(self, depth: int, uniform: bool):
        """The chained multi-frame device step for one (scan depth, uniform)
        variant — ``lax.scan`` of the donated-state step over ``depth``
        stacked full-``batch_size`` frames. Cached per variant for the same
        reason as :meth:`_step_fn` (fresh closures would route every fused
        dispatch through pjit's slow path). Under a mesh the scan runs
        inside one ``shard_map`` entry and psum-stitches each frame's
        verdicts before the next frame decides — same per-frame semantics,
        one dispatch."""
        key = (depth, uniform)
        step = self._fused_steps.get(key)
        if step is not None:
            return step
        if self.mesh is None:
            from sentinel_tpu.engine.decide import decide_fused_donating

            step = decide_fused_donating(
                self.config, depth, grouped=True, uniform=uniform
            )
        else:
            from sentinel_tpu.parallel.sharding import make_sharded_decide

            step = make_sharded_decide(
                self.config, self.mesh, grouped=True, uniform=uniform,
                donate=True, depth=depth,
            )
        self._fused_steps[key] = step
        return step

    def _fused_block_pool(self, depth: int):
        """The staging freelist for one scan depth (lazily built)."""
        pool = self._fused_staging.get(depth)
        if pool is None:
            from sentinel_tpu.cluster.protocol import StagingPool
            from sentinel_tpu.engine.decide import alloc_fused_batch

            pool = self._fused_staging.setdefault(
                depth,
                StagingPool(
                    partial(alloc_fused_batch, self.config, depth),
                    capacity=8,
                ),
            )
        return pool

    def _prep_cached(self, lookup_snap, cfg, bucket, flow_ids, acq, pr):
        """Host prep with the hot-vector memo: ``(slots, order, batch)`` for
        one engine frame, served from :class:`_PrepCache` when the same
        (flow_ids, acquires, prios) vector was prepped against the same
        lookup snapshot before."""
        key, hit = self._prep_cache.get(
            lookup_snap[0], bucket, flow_ids, acq, pr
        )
        if hit is not None:
            return hit
        slots = self._lookup_from(lookup_snap, flow_ids)
        order, batch = self._prep_batch(cfg, slots, acq, pr)
        self._prep_cache.put(key, flow_ids, acq, pr, slots, order, batch)
        return slots, order, batch

    # -- rule management (ClusterFlowRuleManager analog) --------------------
    def load_rules(
        self,
        rules: List[ClusterFlowRule],
        ns_max_qps: Optional[float] = None,
        connected: Optional[Dict[str, int]] = None,
    ) -> None:
        with self._rules_mutex, self._lock:
            if ns_max_qps is not None:
                self._ns_max_qps = ns_max_qps
            if connected is not None:
                self._connected.update(connected)
            by_ns: Dict[str, Dict[int, ClusterFlowRule]] = {}
            for r in rules:
                by_ns.setdefault(r.namespace, {})[r.flow_id] = r
            self._rules_by_ns = by_ns
            self._rule_of = {r.flow_id: r for r in rules}
            degrade = list(self._degrade_rules_src.values())
            table, self._index = build_rule_table(
                self.config, rules, index=self._index,
                ns_max_qps=self._ns_max_qps, connected=self._connected,
                degrade_rules=degrade,
            )
            self._table = self._place_rules(table)
            # breaker bookkeeping: which slots carry a breaker (dirty-set
            # and lease gating) and a fresh transition-scan mirror — slot
            # assignments may have moved, so the old mirror is meaningless
            self._has_breakers = bool(degrade)
            self._breaker_slots = {
                self._index.slot_of[d.flow_id] for d in degrade
                if d.flow_id in self._index.slot_of
            }
            self._breaker_prev = None
            # re-place after the drain scatter: eager sharding propagation
            # through .at[].set isn't guaranteed to keep the flow layout
            self._state = self._place_state(
                drain_pending_clear(self._index, self._state)
            )
            items = sorted(self._index.slot_of.items())
            self._lookup = (
                np.fromiter((k for k, _ in items), np.int64, len(items)),
                np.fromiter((v for _, v in items), np.int32, len(items)),
            )
            # rebuild the slot → namespace snapshot for the verdict counters
            # (ns_of rows persist across reloads, so removed namespaces keep
            # their index; only live rules point at them)
            n_ns = max(self._index.ns_of.values(), default=-1) + 1
            ns_names = [""] * n_ns
            for ns_name, row in self._index.ns_of.items():
                ns_names[row] = ns_name
            slot_ns = np.full(self.config.max_flows, -1, np.int32)
            for r in rules:
                slot_ns[self._index.slot_of[r.flow_id]] = (
                    self._index.ns_of[r.namespace]
                )
            self._ns_snapshot = (tuple(ns_names), slot_ns)
            # a reload can introduce rules (hence slots) for a namespace
            # that is mid-move; refresh the dispatch-path MOVING view so
            # those new slots are masked too
            self._rebuild_moving_snap()
            # slot assignments may have moved: deltas collected against the
            # old generation are meaningless, so drop them and force the
            # replication sender into a full-snapshot resync
            self._state_gen += 1
            if self._dirty is not None:
                self._dirty = {
                    "flow": set(), "param": set(), "param_fat": set(),
                    "outcome": set(), "breaker": set(),
                }
            # leases pin flow_id → slot; a reload may have reassigned the
            # slot or dropped the rule, so re-resolve every outstanding
            # lease and revoke those whose rule vanished (their LEASED
            # charge simply expires with the window — conservative)
            dead = []
            if self._leases:
                for lid, lease in self._leases.items():
                    slot = self._index.slot_of.get(lease.flow_id)
                    if slot is None:
                        dead.append(lease)
                    else:
                        lease.slot = int(slot)
                for lease in dead:
                    del self._leases[lease.lease_id]
                self._lease_stats["revoked"] += len(dead)
            gen = self._state_gen
        # rev-7 push, emitted after the rule locks drop: recall the leases
        # the reload killed and invalidate client-cached rule-derived state
        # (backoffs, cached NO_RULE answers) within one RTT instead of a
        # TTL — the generation bump above is the epoch clients fence on
        for lease in dead:
            self._emit_push(
                "push_lease_revoke", lease.lease_id, lease.flow_id,
                lease.tokens,
            )
        self._emit_push("push_rule_epoch", gen)

    def load_namespace_rules(
        self, namespace: str, rules: List[ClusterFlowRule]
    ) -> None:
        """Replace ONE namespace's flow rules, keeping every other
        namespace's intact (``ClusterFlowRuleManager.loadRules(namespace,
        rules)`` — the shape the cluster/server/modifyFlowRules command
        edits)."""
        import dataclasses as _dc

        # replace() keeps every field (including the shaping knobs) — a
        # positional rebuild here would silently strip control_behavior
        fixed = [
            r if r.namespace == namespace
            else _dc.replace(r, namespace=namespace)
            for r in rules
        ]
        with self._rules_mutex:
            with self._lock:
                merged = {
                    ns: dict(m) for ns, m in self._rules_by_ns.items()
                    if ns != namespace
                }
                if fixed:
                    merged[namespace] = {r.flow_id: r for r in fixed}
                flat = [r for m in merged.values() for r in m.values()]
            self.load_rules(flat)

    def current_rules(
        self, namespace: Optional[str] = None
    ) -> List[ClusterFlowRule]:
        with self._lock:
            if namespace is not None:
                return list(self._rules_by_ns.get(namespace, {}).values())
            return [
                r for m in self._rules_by_ns.values() for r in m.values()
            ]

    # -- degrade (circuit-breaker) rules (DegradeRuleManager analog) --------
    def load_degrade_rules(self, rules: List[DegradeRule]) -> None:
        """Replace the full degrade-rule set. Rules compile into the
        ``br_*`` rule-table columns next to the flow rules (one table, one
        gather on the hot path); a flow may carry a breaker with or without
        a flow rule — breaker-only flows get an effectively-unlimited slot
        so the gate still sees them. Breaker STATE survives the reload for
        flows whose rule persists (the state columns are keyed by slot and
        slots are sticky across reloads); a removed rule's slot resets to
        CLOSED via ``drain_pending_clear``."""
        with self._rules_mutex:
            with self._lock:
                self._degrade_rules_src = {r.flow_id: r for r in rules}
            self.load_rules(self.current_rules())

    def load_namespace_degrade_rules(
        self, namespace: str, rules: List[DegradeRule]
    ) -> None:
        """Replace ONE namespace's degrade rules, keeping the others (the
        same shape as :meth:`load_namespace_rules`; the MOVE import path
        uses this to land a namespace's breakers on the destination)."""
        import dataclasses as _dc

        fixed = [
            r if r.namespace == namespace
            else _dc.replace(r, namespace=namespace)
            for r in rules
        ]
        with self._rules_mutex:
            with self._lock:
                keep = [
                    r for r in self._degrade_rules_src.values()
                    if r.namespace != namespace
                ]
            self.load_degrade_rules(keep + fixed)

    def current_degrade_rules(
        self, namespace: Optional[str] = None
    ) -> List[DegradeRule]:
        with self._lock:
            rules = list(self._degrade_rules_src.values())
        if namespace is not None:
            rules = [r for r in rules if r.namespace == namespace]
        return rules

    def served_namespaces(self) -> List[str]:
        """Explicit namespace set ∪ namespaces with loaded rules."""
        with self._lock:
            return sorted(self.namespace_set | set(self._rules_by_ns))

    def set_max_allowed_qps(self, qps: float) -> None:
        """Dynamic ``ServerFlowConfig.maxAllowedQps`` update — rebuilds the
        namespace-guard row of the rule table without retracing."""
        with self._rules_mutex:
            self.load_rules(self.current_rules(), ns_max_qps=float(qps))

    def config_snapshot(self) -> Dict[str, object]:
        """Flow-config view (cluster/server/fetchConfig shape)."""
        from sentinel_tpu.engine.state import flow_spec

        spec = flow_spec(self.config)
        return {
            "exceedCount": self.config.exceed_count,
            "maxOccupyRatio": self.config.max_occupy_ratio,
            "intervalMs": spec.interval_ms,
            "sampleCount": self.config.n_buckets,
            "maxAllowedQps": self._ns_max_qps,
            "maxFlows": self.config.max_flows,
            "batchSize": self.config.batch_size,
            "namespaceSet": self.served_namespaces(),
        }

    def connected_count_changed(self, namespace: str, n: int) -> None:
        """``ConnectionManager`` callback: AVG_LOCAL thresholds scale with it.
        Counts persist across rule reloads. Namespaces no rule uses are
        remembered host-side but allocate no device slot."""
        self.concurrency.set_connected_count(max(1, int(n)), namespace)
        with self._lock:
            self._connected[namespace] = max(1, int(n))
            ns = self._index.ns_of.get(namespace)
            if ns is None:
                return  # no rule in this namespace yet; applied on next load
            conn = np.array(self._table.ns_connected)  # writable copy
            conn[ns] = max(1, int(n))
            self._table = self._place_rules(
                self._table._replace(ns_connected=jnp.asarray(conn))
            )

    # -- time ---------------------------------------------------------------
    # int32 engine-ms wraps after ~24.8 days; re-base well before that.
    # Callers hold self._lock.
    _REBASE_AFTER_MS = 2**30  # ~12.4 days

    def _engine_now(self) -> int:
        """Engine-relative int32 ms; automatically re-bases the epoch (and
        shifts all window starts) long before int32 wraparound."""
        wall = _clock.now_ms()
        if self._epoch_ms is None:
            self._epoch_ms = wall - 1  # keep engine time strictly positive
        now = wall - self._epoch_ms
        if now > self._REBASE_AFTER_MS:
            from sentinel_tpu.engine.param import NEVER as _PNEVER
            from sentinel_tpu.stats.window import rebase

            from sentinel_tpu.stats.window import NEVER as _WNEVER

            delta = now - 60_000  # keep the last minute of history addressable
            shp = self._state.shaping
            brk = self._state.breaker
            d32 = jnp.int32(delta)
            self._state = EngineState(
                flow=rebase(self._state.flow, delta),
                occupy=rebase(self._state.occupy, delta),
                ns=rebase(self._state.ns, delta),
                # the shaper clocks are engine-ms too; NEVER stays NEVER
                shaping=shp._replace(
                    lpt=jnp.where(shp.lpt == _WNEVER, shp.lpt, shp.lpt - d32),
                    warm_filled=jnp.where(
                        shp.warm_filled == _WNEVER,
                        shp.warm_filled,
                        shp.warm_filled - d32,
                    ),
                ),
                outcome=rebase(self._state.outcome, delta),
                # breaker fence/ticket clocks share the engine epoch; the
                # state column is epoch-free and passes through untouched
                breaker=brk._replace(
                    opened_ms=jnp.where(
                        brk.opened_ms == _WNEVER,
                        brk.opened_ms,
                        brk.opened_ms - d32,
                    ),
                    probe_ms=jnp.where(
                        brk.probe_ms == _WNEVER,
                        brk.probe_ms,
                        brk.probe_ms - d32,
                    ),
                ),
            )
            # the param sketch's starts are engine-ms too
            pstarts = self._param_state.starts
            self._param_state = self._param_state._replace(
                starts=jnp.where(
                    pstarts == _PNEVER, pstarts, pstarts - jnp.int32(delta)
                )
            )
            self._epoch_ms += delta
            now -= delta
        return now

    # -- decision path ------------------------------------------------------
    def warmup(self) -> None:
        """Trigger XLA compilation of the decision kernels before serving.

        First-compile latency (~1s on CPU, tens of seconds on TPU) must not be
        paid by the first real request — it would blow the 20ms client budget
        *and* let early traffic slip through an expired window."""
        with self._lock:
            now = self._engine_now()
            # compile both serving variants (uniform acquire and mixed) for
            # every shape bucket the serving path can pick (mesh-sharded
            # variants when this service runs over a pod mesh). ONE
            # throwaway state threads through every variant: the
            # single-shard step donates its state argument (passing the
            # live self._state would invalidate it), and since each step
            # returns a same-shaped state, chaining keeps warmup at a
            # single extra state allocation instead of one per variant.
            ws = self._place_state(make_state(self.config))
            compiles = 0
            for bucket in self._serve_buckets:
                cfg = self.config._replace(batch_size=bucket)
                batch = make_batch(cfg, [-1])
                for uniform in (True, False):
                    step = self._step_fn(bucket, uniform)
                    ws, _ = step(ws, self._table, batch, jnp.int32(now))
                    compiles += 1
            # fused multi-frame variants (full batch_size frames only):
            # compile the ladder's scan depths so the first oversized pull
            # doesn't pay scan compilation while holding the service lock.
            # Single-shard warms the uniform-acquire common case only
            # (mixed-acquire fused spans are rare and compile lazily);
            # under a mesh, warm EVERY (depth, uniform) sharded-fused
            # bucket — mesh compiles are far slower, and a cold bucket in
            # the serving window would stall the whole pod's device lane.
            fused_uniforms = (True,) if self.mesh is None else (True, False)
            base = make_batch(self.config, [-1])
            for fdepth in self._fuse_depths:
                stacked = type(base)(
                    *(np.stack([leaf] * fdepth) for leaf in base)
                )
                for uniform in fused_uniforms:
                    step = self._fused_step_fn(fdepth, uniform)
                    ws, _ = step(ws, self._table, stacked, jnp.int32(now))
                    compiles += 1
            # compile counts on the cluster stat log: a serving window
            # that shows more compiles than warmup recorded hit a cold
            # bucket (shape drift, ladder change) — visible, not silent.
            log_cluster("warmup_step_compiles", count=compiles)
            idx = hash_indices(
                np.zeros(1, np.int64),
                self.param_config.depth,
                self.param_config.cell_width,
            )
            idx_slim = None
            if self.param_config.slim_enabled:
                from sentinel_tpu.sketch.slim import slim_indices

                si = slim_indices(self.param_config, np.zeros(1, np.int64))
                idx_slim = jnp.asarray(
                    np.broadcast_to(si, (8, si.shape[1]))
                )
            n_pad = 8  # matches request_params_token's minimum padded shape
            param_decide(
                self.param_config,
                self._param_state,
                jnp.zeros(n_pad, jnp.int32),
                jnp.asarray(np.broadcast_to(idx, (n_pad, idx.shape[1]))),
                jnp.zeros(n_pad, jnp.int32),
                jnp.zeros(n_pad, jnp.float32),
                jnp.zeros(n_pad, bool),  # nothing valid → state unchanged
                jnp.int32(now),
                idx_slim=idx_slim,
            )

    def request_token(self, flow_id, acquire=1, prioritized=False) -> TokenResult:
        return self.request_batch([(flow_id, acquire, prioritized)])[0]

    def lookup_slots(self, flow_ids: np.ndarray) -> np.ndarray:
        """Vectorized flow_id → slot (-1 when no rule). Lock-free: reads one
        immutable (keys, slots) snapshot."""
        return self._lookup_from(self._lookup, flow_ids)

    @staticmethod
    def _lookup_from(snapshot, flow_ids: np.ndarray) -> np.ndarray:
        keys, slots = snapshot
        if keys.size == 0:
            return np.full(flow_ids.shape, -1, np.int32)
        pos = np.searchsorted(keys, flow_ids)
        pos = np.minimum(pos, keys.size - 1)
        return np.where(keys[pos] == flow_ids, slots[pos], -1).astype(np.int32)

    def request_batch_arrays(
        self,
        flow_ids: np.ndarray,
        acquires: Optional[np.ndarray] = None,
        prios: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Array-in/array-out decision path: (status int8[N], remaining
        int32[N], wait_ms int32[N]) in request order.

        Dispatch + materialize in one call; pipelining callers use
        :meth:`dispatch_batch_arrays` directly.
        """
        return self.dispatch_batch_arrays(flow_ids, acquires, prios)()

    def dispatch_batch_arrays(
        self,
        flow_ids: np.ndarray,
        acquires: Optional[np.ndarray] = None,
        prios: Optional[np.ndarray] = None,
    ):
        """Serving hot path, phase 1: host prep + device dispatch. Returns a
        zero-arg **materializer** that blocks on the async dispatch and
        yields ``(status, remaining, wait)`` in request order.

        The service lock covers ONLY the device dispatch + state swap — host
        prep (slot lookup, grouping sort, batch padding) runs before it and
        verdict materialization after it (the lock-free analog of the
        reference's unsynchronized ``ClusterFlowChecker.java:55-120`` hot
        loop). Because JAX dispatch is asynchronous and consecutive steps
        chain on-device through the state future, a caller that dispatches
        batch k+1 before materializing batch k keeps the device busy end to
        end — the serving-path analog of the netty pipeline that amortizes
        the reference's per-RPC cost (``NettyTransportServer.java:73-101``).
        Oversized bursts are split into per-bucket chunks whose dispatches
        are ALL issued before any chunk materializes, so one big pull
        pipelines internally too; runs of full-``batch_size`` chunks are
        additionally FUSED into single chained device steps (see
        :meth:`_dispatch_oversized`) so the fixed per-dispatch overhead is
        paid once per fused group instead of once per frame.
        """
        if _chaos.ARMED:  # device_stall injection: a slow/preempted step
            _chaos.maybe_sleep("device_stall")
        t_dispatch = time.monotonic()
        flow_ids = np.asarray(flow_ids, np.int64)
        n = flow_ids.shape[0]
        if n == 0:
            def _empty():
                empty32 = np.empty(0, np.int32)
                return np.empty(0, np.int8), empty32, empty32

            return _empty
        acq = (
            np.ones(n, np.int32) if acquires is None
            else np.asarray(acquires, np.int32)
        )
        pr = (
            np.zeros(n, bool) if prios is None
            else np.asarray(prios, bool)
        )
        cap = self.config.batch_size
        if n > cap:  # split oversized bursts; dispatch all chunks first
            return self._dispatch_oversized(flow_ids, acq, pr, n, cap)
        # -- host prep, outside the lock --
        lookup_snap = self._lookup
        # serving fast path: group same-flow requests contiguously (stable,
        # so greedy admission order within a flow is arrival order) and
        # detect the uniform-acquire common case — together they skip the
        # device argsort and the iterative admission refinement (see
        # decide()'s grouped/uniform flags)
        uniform = bool(acq.min() == acq.max())
        # smallest compiled shape bucket that fits this batch
        bucket = next(b for b in self._serve_buckets if n <= b)
        cfg = self.config._replace(batch_size=bucket)
        slots, order, batch = self._prep_cached(
            lookup_snap, cfg, bucket, flow_ids, acq, pr
        )
        step = self._step_fn(bucket, uniform)
        slots_ns = slots  # pre-mask slots: verdict→namespace attribution
        moved_mask = moved_epochs = None
        # -- device step: the only serialized section --
        with self._lock:
            if self._lookup is not lookup_snap:
                # rules reloaded between prep and step: slot assignments may
                # have moved, so redo the slot-dependent prep against the
                # live table (rare, and still under the lock — the same
                # atomicity load_rules callers had before the narrowing)
                slots = self._lookup_from(self._lookup, flow_ids)
                slots_ns = slots
                order, batch = self._prep_batch(cfg, slots, acq, pr)
            mv = self._moving_snap
            if mv is not None:
                # live rebalance: rows of a MOVING namespace are masked out
                # of the device batch — their counters never move (the
                # zero-over-admission half of the lossless move) — and the
                # materializer overlays MOVED. Checked under the lock so a
                # begin_move strictly orders against every dispatch.
                moved_mask, moved_epochs = self._moving_mask_for(slots, mv)
                if moved_mask is not None:
                    slots = np.where(
                        moved_mask, np.int32(-1), slots
                    ).astype(np.int32)
                    order, batch = self._prep_batch(cfg, slots, acq, pr)
            now = self._engine_now()
            self._state, verdicts = step(
                self._state, self._table, batch, np.int32(now)
            )
            if self._dirty is not None:
                touched = np.unique(slots[slots >= 0]).tolist()
                self._dirty["flow"].update(touched)
                if self._has_breakers:
                    # breaker transitions only happen for batched rows, so
                    # touched ∩ breaker-slots is exactly the dirty set
                    self._dirty.setdefault("breaker", set()).update(
                        s for s in touched if s in self._breaker_slots
                    )
        if _TR.ARMED:  # flight recorder: device step submitted
            _TR.record(_TR.DEVICE_IN, aux=n)

        def _materialize():
            # blocks on the async dispatch; runs outside the lock
            status_sorted = np.asarray(verdicts.status)[:n]
            remaining_sorted = np.asarray(verdicts.remaining)[:n]
            wait_sorted = np.asarray(verdicts.wait_ms)[:n]
            if order is None:
                # copy: callers own writable results (the sorted path builds
                # fresh arrays), and a [:n] view would pin the whole padded
                # bucket buffer alive
                status = np.array(status_sorted)
                remaining = np.array(remaining_sorted, np.int32)
                wait = np.array(wait_sorted, np.int32)
            else:
                status = np.empty(n, status_sorted.dtype)
                remaining = np.empty(n, np.int32)
                wait = np.empty(n, np.int32)
                status[order] = status_sorted
                remaining[order] = remaining_sorted
                wait[order] = wait_sorted
            if moved_mask is not None:
                # MOVED overlay: the device saw these rows as no-rule; the
                # client sees a redirect carrying the shard-map epoch
                status[moved_mask] = np.int8(int(TokenStatus.MOVED))
                remaining[moved_mask] = moved_epochs[moved_mask]
                wait[moved_mask] = 0
                from sentinel_tpu.metrics.ha import ha_metrics
                ha_metrics().count_rebalance_redirects(
                    int(moved_mask.sum())
                )
            # per-namespace verdict counters (sentinel_server_verdicts_total):
            # attribute each request's verdict to its rule's namespace via
            # the lock-free slot→namespace snapshot. `slots_ns` is request-
            # order and PRE-mask, so MOVED verdicts land on their namespace.
            ns_names, slot_ns = self._ns_snapshot
            ns_idx = np.where(
                slots_ns >= 0, slot_ns[np.maximum(slots_ns, 0)], np.int32(-1)
            )
            _SM.record_verdict_batch(
                status, ns_idx, ns_names,
                latency_ms=(time.monotonic() - t_dispatch) * 1e3,
                wait_ms=wait,
            )
            if _TR.ARMED:  # flight recorder: device step materialized
                _TR.record(_TR.DEVICE_OUT, aux=n)
            # cluster server stat log (ClusterServerStatLogUtil analog): one
            # aggregated counter per verdict class per window
            n_degraded = 0
            for event, code in (
                ("pass", int(TokenStatus.OK)),
                ("block", int(TokenStatus.BLOCKED)),
                ("occupied", int(TokenStatus.SHOULD_WAIT)),
                ("tooManyRequest", int(TokenStatus.TOO_MANY_REQUEST)),
                ("degraded", int(TokenStatus.DEGRADED)),
            ):
                hits = int((status == code).sum())
                if hits:
                    log_cluster(event, count=hits)
                    if event == "degraded":
                        n_degraded = hits
            if n_degraded:
                # breaker activity observed: fold the device transitions
                # into the host transition counters / blackbox plane
                self._breaker_scan()
            return status, remaining, wait

        return _materialize

    def _dispatch_oversized(self, flow_ids, acq, pr, n, cap):
        """Split an oversized burst into ``cap``-sized frames and fold runs
        of FULL frames into fused chained device steps — greedy largest-fit
        over the fusion ladder (``fuse_depths``), so e.g. 7 full frames with
        ladder (8, 4, 2) dispatch as scan(4) + scan(2) + 1 plain step. The
        fixed per-dispatch overhead (the 20–50ms/bucket measured in
        BENCH_r05) is then paid once per fused group instead of once per
        frame. Leftovers and sub-``cap`` tails take the ordinary per-chunk
        path. As before, ALL dispatches are issued before any chunk
        materializes, so one big pull pipelines internally. The ladder runs
        identically over a mesh — the fused step is then one ``shard_map``
        entry scanning the sharded step (psum stitch per frame), and the
        staging/prep machinery below is mesh-oblivious by construction.
        """
        mats = []
        pos = 0
        ladder = self._fuse_depths
        while ladder and (n - pos) // cap >= ladder[-1]:
            depth = next(
                (d for d in ladder if d <= (n - pos) // cap), None
            )
            if depth is None:
                break
            end = pos + depth * cap
            mats.append(
                self._dispatch_fused(
                    flow_ids[pos:end], acq[pos:end], pr[pos:end], depth, cap
                )
            )
            pos = end
        for i in range(pos, n, cap):
            mats.append(
                self.dispatch_batch_arrays(
                    flow_ids[i : i + cap], acq[i : i + cap], pr[i : i + cap]
                )
            )

        def _concat():
            parts = [m() for m in mats]
            return tuple(np.concatenate(ps) for ps in zip(*parts))

        return _concat

    def _dispatch_fused(self, flow_ids, acq, pr, depth, cap):
        """Phase-1 dispatch of ``depth`` consecutive full-``cap`` frames as
        ONE chained device step (``lax.scan`` of the donated-state step —
        see :func:`decide_fused_donating`). Returns a materializer yielding
        request-order ``(status, remaining, wait)`` for the whole span.

        Each frame is prepped independently (slot lookup + grouping sort,
        through the prep cache) and the padded batches stacked into
        ``[depth, cap]`` leaves; the single device call then replaces
        ``depth`` dispatches. The fused group shares one ``now`` — frames in
        one pull arrived together, so this only collapses sub-millisecond
        clock skew a per-frame loop would have read anyway.
        """
        t_dispatch = time.monotonic()
        lookup_snap = self._lookup
        # a fused span is uniform only if acquire is constant across ALL its
        # frames; mixed spans scan the general (refining) body for every
        # frame, which is still correct for the uniform ones among them
        uniform = bool(acq.min() == acq.max())
        cfg = self.config  # fused frames are exactly batch_size-shaped

        def _prep_all(snapshot):
            preps = []
            for f in range(depth):
                sl = slice(f * cap, (f + 1) * cap)
                preps.append(
                    self._prep_cached(
                        snapshot, cfg, cap, flow_ids[sl], acq[sl], pr[sl]
                    )
                )
            return preps

        pool = self._fused_block_pool(depth)
        block = pool.acquire()

        def _fill(preps):
            # lay each frame's prepped leaves into its staging row — the
            # zero-alloc replacement for per-leaf np.stack (cache hits make
            # this the only per-frame host copy left on the fused path)
            for f, p in enumerate(preps):
                b = p[2]
                block.flow_slot[f] = b.flow_slot
                block.acquire[f] = b.acquire
                block.prioritized[f] = b.prioritized
                block.valid[f] = b.valid

        preps = _prep_all(lookup_snap)
        _fill(preps)
        step = self._fused_step_fn(depth, uniform)
        moved_span = moved_epochs_span = span_ns = None
        # -- device step: the only serialized section --
        with self._lock:
            if self._lookup is not lookup_snap:
                # rules reloaded between prep and step (see
                # dispatch_batch_arrays): redo slot-dependent prep against
                # the live table, bypassing the cache (its entries are keyed
                # by snapshot identity, so stale hits are impossible, but
                # re-prepping directly keeps the rare path simple). Writes
                # land straight in the staging rows (make_batch_into).
                from sentinel_tpu.engine.decide import make_batch_into

                preps = []
                for f in range(depth):
                    sl = slice(f * cap, (f + 1) * cap)
                    slots_f = self._lookup_from(self._lookup, flow_ids[sl])
                    if bool((slots_f[:-1] <= slots_f[1:]).all()):
                        order_f = None
                        make_batch_into(block, f, slots_f, acq[sl], pr[sl])
                    else:
                        order_f = np.argsort(slots_f, kind="stable")
                        make_batch_into(
                            block, f, slots_f[order_f], acq[sl][order_f],
                            pr[sl][order_f],
                        )
                    preps.append((slots_f, order_f, None))
            mv = self._moving_snap
            if mv is not None:
                # live rebalance (see dispatch_batch_arrays): mask MOVING-
                # namespace rows out of every staged frame so the fused
                # step never counts their tokens, and remember the span
                # mask for the MOVED overlay
                span0 = np.concatenate([p[0] for p in preps])
                m, eps = self._moving_mask_for(span0, mv)
                if m is not None:
                    from sentinel_tpu.engine.decide import make_batch_into

                    moved_span, moved_epochs_span, span_ns = m, eps, span0
                    preps = []
                    for f in range(depth):
                        sl = slice(f * cap, (f + 1) * cap)
                        slots_f = np.where(
                            m[sl], np.int32(-1), span0[sl]
                        ).astype(np.int32)
                        if bool((slots_f[:-1] <= slots_f[1:]).all()):
                            order_f = None
                            make_batch_into(
                                block, f, slots_f, acq[sl], pr[sl]
                            )
                        else:
                            order_f = np.argsort(slots_f, kind="stable")
                            make_batch_into(
                                block, f, slots_f[order_f],
                                acq[sl][order_f], pr[sl][order_f],
                            )
                        preps.append((slots_f, order_f, None))
            now = self._engine_now()
            self._state, verdicts = step(
                self._state, self._table, block, np.int32(now)
            )
            if self._dirty is not None:
                span = np.concatenate([p[0] for p in preps])
                touched = np.unique(span[span >= 0]).tolist()
                self._dirty["flow"].update(touched)
                if self._has_breakers:
                    self._dirty.setdefault("breaker", set()).update(
                        s for s in touched if s in self._breaker_slots
                    )
        _SM.record_fused(depth)
        if _TR.ARMED:  # flight recorder: fused group submitted
            _TR.record(_TR.FUSE, aux=depth)
            _TR.record(_TR.DEVICE_IN, aux=depth * cap)

        def _materialize():
            # blocks on the async dispatch; runs outside the lock. Verdict
            # leaves are [depth, cap]; unsort each frame back to request
            # order and lay the frames out contiguously.
            status_all = np.asarray(verdicts.status)
            remaining_all = np.asarray(verdicts.remaining)
            wait_all = np.asarray(verdicts.wait_ms)
            # verdicts are ready → the device has consumed the staging
            # block's host buffers; recycle it for the next fused group
            pool.release(block)
            total = depth * cap
            status = np.empty(total, status_all.dtype)
            remaining = np.empty(total, np.int32)
            wait = np.empty(total, np.int32)
            for f, (_slots_f, order_f, _b) in enumerate(preps):
                dst = slice(f * cap, (f + 1) * cap)
                if order_f is None:
                    status[dst] = status_all[f]
                    remaining[dst] = remaining_all[f]
                    wait[dst] = wait_all[f]
                else:
                    status[dst.start : dst.stop][order_f] = status_all[f]
                    remaining[dst.start : dst.stop][order_f] = remaining_all[f]
                    wait[dst.start : dst.stop][order_f] = wait_all[f]
            if moved_span is not None:
                status[moved_span] = np.int8(int(TokenStatus.MOVED))
                remaining[moved_span] = moved_epochs_span[moved_span]
                wait[moved_span] = 0
                from sentinel_tpu.metrics.ha import ha_metrics
                ha_metrics().count_rebalance_redirects(
                    int(moved_span.sum())
                )
            # per-namespace verdict counters + cluster stat log, once for
            # the whole span (mirrors dispatch_batch_arrays._materialize);
            # span_ns is the PRE-mask slot span when a move masked rows
            slots_span = (
                span_ns if span_ns is not None
                else np.concatenate([p[0] for p in preps])
            )
            ns_names, slot_ns = self._ns_snapshot
            ns_idx = np.where(
                slots_span >= 0,
                slot_ns[np.maximum(slots_span, 0)],
                np.int32(-1),
            )
            _SM.record_verdict_batch(
                status, ns_idx, ns_names,
                latency_ms=(time.monotonic() - t_dispatch) * 1e3,
                wait_ms=wait,
            )
            if _TR.ARMED:  # flight recorder: fused group materialized
                _TR.record(_TR.DEVICE_OUT, aux=depth * cap)
            n_degraded = 0
            for event, code in (
                ("pass", int(TokenStatus.OK)),
                ("block", int(TokenStatus.BLOCKED)),
                ("occupied", int(TokenStatus.SHOULD_WAIT)),
                ("tooManyRequest", int(TokenStatus.TOO_MANY_REQUEST)),
                ("degraded", int(TokenStatus.DEGRADED)),
            ):
                hits = int((status == code).sum())
                if hits:
                    log_cluster(event, count=hits)
                    if event == "degraded":
                        n_degraded = hits
            if n_degraded:
                self._breaker_scan()
            return status, remaining, wait

        return _materialize

    def request_batch(self, requests) -> List[TokenResult]:
        if not requests:
            return []
        n = len(requests)
        flow_ids = np.fromiter((f for f, _, _ in requests), np.int64, n)
        acquires = np.fromiter((a for _, a, _ in requests), np.int32, n)
        prios = np.fromiter((p for _, _, p in requests), bool, n)
        status, remaining, wait = self.request_batch_arrays(
            flow_ids, acquires, prios
        )
        moved = int(TokenStatus.MOVED)
        out = []
        for i in range(n):
            st = int(status[i])
            if st == moved:
                # enrich the redirect with the destination endpoint so
                # in-process callers (and the single-request wire path)
                # can follow it without a second lookup
                red = self.moved_redirect(int(flow_ids[i]))
                out.append(TokenResult(
                    TokenStatus(st), int(remaining[i]), int(wait[i]),
                    endpoint=red[0] if red else "",
                ))
            else:
                out.append(TokenResult(
                    TokenStatus(st), int(remaining[i]), int(wait[i])
                ))
        return out

    def load_param_rules(self, rules: List[ClusterParamFlowRule]) -> None:
        """``ClusterParamFlowRuleManager`` analog; slots stable across
        reloads, freed slots cleared."""
        with self._rules_mutex, self._lock:
            live = {r.flow_id for r in rules}
            # validate capacity BEFORE mutating so a failed load cannot leave
            # a half-applied rule set
            n_new = len({r.flow_id for r in rules if r.flow_id not in self._param_rules})
            n_freed = sum(1 for fid in self._param_rules if fid not in live)
            if n_new > len(self._param_free) + n_freed:
                raise ValueError(
                    f"param rule capacity exceeded: need {n_new} new slots, "
                    f"have {len(self._param_free) + n_freed}"
                )
            for fid in list(self._param_rules):
                if fid not in live:
                    slot, _, _ = self._param_rules.pop(fid)
                    self._param_free.append(slot)
                    # clear the whole sketch row: fat cells (for SALSA the
                    # zeroed int16 cells are unmerged zeros, so the merge
                    # state clears with them), the slim twin row, and the
                    # slot's merge counter
                    self._param_state = self._param_state._replace(
                        counts=self._param_state.counts.at[slot].set(0),
                        slim=self._param_state.slim.at[slot].set(0),
                        merges=self._param_state.merges.at[slot].set(0),
                    )
            for rule in rules:
                existing = self._param_rules.get(rule.flow_id)
                slot = existing[0] if existing else None
                if slot is None:
                    if not self._param_free:
                        raise ValueError("param rule capacity exceeded")
                    slot = self._param_free.pop()
                items = dict(rule.item_thresholds or ())
                self._param_rules[rule.flow_id] = (slot, rule.count, items)
            self._param_rules_src = {r.flow_id: r for r in rules}
            # same resync discipline as load_rules: param slot moves/frees
            # invalidate any delta collected against the old generation
            self._state_gen += 1
            if self._dirty is not None:
                self._dirty = {
                    "flow": set(), "param": set(), "param_fat": set(),
                    "outcome": set(), "breaker": set(),
                }

    def load_namespace_param_rules(
        self, namespace: str, rules: List[ClusterParamFlowRule]
    ) -> None:
        """Replace one namespace's param rules, keeping the others
        (``ClusterParamFlowRuleManager`` namespace scope — the
        cluster/server/modifyParamRules command edits one namespace)."""
        fixed = [
            r if r.namespace == namespace
            else ClusterParamFlowRule(r.flow_id, r.count, r.item_thresholds,
                                      namespace)
            for r in rules
        ]
        with self._rules_mutex:
            with self._lock:
                keep = [
                    r for r in self._param_rules_src.values()
                    if r.namespace != namespace
                ]
            self.load_param_rules(keep + fixed)

    def current_param_rules(
        self, namespace: Optional[str] = None
    ) -> List[ClusterParamFlowRule]:
        with self._lock:
            rules = list(self._param_rules_src.values())
        if namespace is not None:
            rules = [r for r in rules if r.namespace == namespace]
        return rules

    def request_params_token(self, flow_id, acquire, param_hashes) -> TokenResult:
        """CMS-windowed per-value admission. All values of the request are
        judged together; any blocked value blocks the request (reference
        ``ClusterParamFlowChecker``: every param value must have headroom).
        Admitted values are counted; on a mixed verdict the passed values'
        counts stand (conservative overcount, same direction as CMS error).
        """
        if not param_hashes:
            return TokenResult(TokenStatus.OK)
        with self._lock:
            entry = self._param_rules.get(int(flow_id))
            if entry is None:
                return TokenResult(TokenStatus.NO_RULE_EXISTS)
            slot, count, items = entry
            hashes = np.asarray(list(param_hashes), dtype=np.int64)
            idx = hash_indices(
                hashes, self.param_config.depth, self.param_config.cell_width
            )
            n = hashes.shape[0]
            # pad to a power of two: param_decide's shapes are baked into its
            # jit cache, and a client cycling value counts must not force a
            # recompilation per count while holding the service lock
            n_pad = max(8, 1 << (n - 1).bit_length())
            pad = n_pad - n
            idx = np.pad(idx, ((0, pad), (0, 0)))
            idx_slim = None
            if self.param_config.slim_enabled:
                from sentinel_tpu.sketch.slim import slim_indices

                idx_slim = jnp.asarray(np.pad(
                    slim_indices(self.param_config, hashes),
                    ((0, pad), (0, 0)),
                ))
            thresholds = np.array(
                [items.get(int(h), count) for h in hashes], dtype=np.float32
            )
            thresholds = np.pad(thresholds, (0, pad))
            valid = np.zeros(n_pad, dtype=bool)
            valid[:n] = True
            now = self._engine_now()
            self._param_state, admit, _est = param_decide(
                self.param_config,
                self._param_state,
                jnp.full((n_pad,), slot, jnp.int32),
                jnp.asarray(idx),
                jnp.full((n_pad,), int(acquire), jnp.int32),
                jnp.asarray(thresholds),
                jnp.asarray(valid),
                jnp.int32(now),
                idx_slim=idx_slim,
            )
            if self._dirty is not None:
                self._dirty["param"].add(int(slot))
        if bool(np.asarray(admit)[:n].all()):
            return TokenResult(TokenStatus.OK)
        return TokenResult(TokenStatus.BLOCKED)

    # -- concurrent (semaphore) mode ----------------------------------------
    def load_concurrent_rules(self, rules) -> None:
        self.concurrency.load_rules(rules)
        # the acquire-path sweep is bounded (64 entries), so a crashed client
        # holding permits behind long-TTL live tokens needs the background
        # sweep (RegularExpireStrategy analog) to reclaim them
        if rules and self._expiry is None:
            from sentinel_tpu.cluster.concurrent import ExpiryTask

            self._expiry = ExpiryTask(self.concurrency)
            self._expiry.start()

    def close(self) -> None:
        if self._expiry is not None:
            self._expiry.stop()
            self._expiry = None

    def reopen(self) -> None:
        """Re-arm background resources after a close() when the service is
        put back behind a transport (e.g. a token-server port move reuses
        the service): without this, concurrent-mode tokens held by crashed
        clients would only be reclaimed by the bounded acquire-path sweep."""
        if self._expiry is None and self.concurrency.has_rules():
            from sentinel_tpu.cluster.concurrent import ExpiryTask

            self._expiry = ExpiryTask(self.concurrency)
            self._expiry.start()

    def request_concurrent_token(self, flow_id, acquire=1, prioritized=False):
        r = self.concurrency.acquire(flow_id, acquire, prioritized)
        return TokenResult(r.status, r.remaining, 0, r.token_id)

    def release_concurrent_token(self, token_id):
        return TokenResult(self.concurrency.release(token_id))

    # -- live rebalance (cluster.rebalance backing) --------------------------
    def _rebuild_moving_snap(self) -> None:
        """Rebuild the dispatch-path MOVING view from ``self._moving``.
        Caller holds ``self._lock`` (the lock is the linearization point:
        a dispatch that entered the lock before a ``begin_move`` decides
        pre-move and its tokens are included in the exported sums)."""
        if not self._moving:
            self._moving_snap = None
            return
        n = self.config.max_namespaces
        mask = np.zeros(n, bool)
        epochs = np.zeros(n, np.int32)
        for ns_name, (_dest, epoch) in self._moving.items():
            row = self._index.ns_of.get(ns_name)
            if row is not None and row < n:
                mask[row] = True
                epochs[row] = np.int32(epoch)
        self._moving_snap = (mask, epochs) if mask.any() else None

    def _moving_mask_for(self, slots: np.ndarray, mv):
        """Request-order bool mask of rows whose rule's namespace is MOVING
        (plus the per-row shard-map epoch vector), or ``(None, None)`` when
        this batch touches no moving namespace. Caller holds ``self._lock``
        (reads the live ``_ns_snapshot``)."""
        mask_arr, epoch_arr = mv
        _names, slot_ns = self._ns_snapshot
        ns_idx = np.where(
            slots >= 0, slot_ns[np.maximum(slots, 0)], np.int32(-1)
        )
        m = (ns_idx >= 0) & mask_arr[np.maximum(ns_idx, 0)]
        if not m.any():
            return None, None
        return m, epoch_arr[np.maximum(ns_idx, 0)]

    def begin_move(self, namespace: str, endpoint: str, epoch: int) -> None:
        """Mark ``namespace`` MOVING to ``endpoint`` under shard-map
        ``epoch``: from the next device step its flows stop counting tokens
        and answer ``TokenStatus.MOVED`` instead. Idempotent re-begin to the
        same destination is allowed (coordinator retry); a different
        destination while moving raises."""
        with self._lock:
            cur = self._moving.get(namespace)
            if cur is not None and cur[0] != endpoint:
                raise ValueError(
                    f"namespace {namespace!r} already moving to {cur[0]}"
                )
            self._moving[namespace] = (str(endpoint), int(epoch))
            self._rebuild_moving_snap()
            # recall the namespace's outstanding leases: registry entries
            # drop here (renews now answer MOVED → clients fall back and
            # re-grant at the destination) while the LEASED charge stays in
            # the flow window, so the MOVE's window-sum export carries it to
            # the new owner — "transfer the charge, recall the lease"
            flows = set(self._rules_by_ns.get(namespace, ()))
            dead = []
            if flows and self._leases:
                dead = [
                    l for l in self._leases.values() if l.flow_id in flows
                ]
                for l in dead:
                    del self._leases[l.lease_id]
                self._lease_stats["revoked"] += len(dead)
            # same contract for hierarchy share holds: the LEASED hold
            # charge rides the window-sum export to the new owner (so the
            # global budget stays pinned through the handoff) while the
            # registry drops — the destination's own share agent re-tops
            # its hold from ITS share on its next tick
            for fid in flows:
                self._share_holds.pop(int(fid), None)
        # rev-7 push: recalled leases cut over within one RTT — without
        # this the leased fast path keeps admitting against the recalled
        # slice until its next renew answers MOVED
        for l in dead:
            self._emit_push(
                "push_lease_revoke", l.lease_id, l.flow_id, l.tokens
            )
        if _TR.ARMED:  # flight recorder: MOVE begin (phase 0)
            _TR.record(_TR.MOVE, aux=0)

    def abort_move(self, namespace: str) -> None:
        """Restore normal serving for ``namespace``. Lossless by
        construction: MOVED-masked requests never touched the counters, so
        un-masking resumes from exactly the pre-move state."""
        with self._lock:
            self._moving.pop(namespace, None)
            self._rebuild_moving_snap()
        if _TR.ARMED:  # flight recorder: MOVE abort (phase 2)
            _TR.record(_TR.MOVE, aux=2)
        from sentinel_tpu.trace import blackbox as _blackbox

        _blackbox.maybe_dump(f"move_abort:{namespace}")

    def end_redirect(self, namespace: str) -> None:
        """Drop the post-commit redirect tombstone AND the namespace's rules
        (the destination owns them now). Until this is called a committed
        move keeps answering MOVED so stale clients learn the new owner."""
        with self._lock:
            self._moving.pop(namespace, None)
            self._rebuild_moving_snap()
        # degrade rules leave with the namespace too (the MOVE blob carried
        # them; keeping them here would pin dead breaker slots)
        if any(
            d.namespace == namespace
            for d in self._degrade_rules_src.values()
        ):
            self.load_namespace_degrade_rules(namespace, [])
        self.load_namespace_rules(namespace, [])

    def moving_namespaces(self) -> Dict[str, Tuple[str, int]]:
        """namespace → (destination endpoint, shard-map epoch)."""
        with self._lock:
            return dict(self._moving)

    def moved_redirect(self, flow_id: int) -> Optional[Tuple[str, int]]:
        """``(destination endpoint, shard-map epoch)`` when ``flow_id``'s
        namespace is MOVING (or committed-away), else None. The single-
        request wire path uses this to fill the MOVED endpoint trailer."""
        if not self._moving:
            return None
        with self._lock:
            slot = int(self._lookup_from(
                self._lookup, np.asarray([flow_id], np.int64)
            )[0])
            if slot < 0:
                return None
            names, slot_ns = self._ns_snapshot
            row = int(slot_ns[slot])
            if row < 0 or row >= len(names):
                return None
            return self._moving.get(names[row])

    def namespace_index(
        self, flow_ids
    ) -> Tuple[np.ndarray, Tuple[str, ...]]:
        """``(ns_idx int32[N], ns_names)`` for a batch of flow ids — the
        front doors' per-tenant attribution of rows that never reach the
        device (queue full, brownout, degrade), shaped for
        ``ServerMetrics.record_verdict_batch``. Lock-free (snapshot
        reads); shed paths only, never the serving hot path."""
        slots = self._lookup_from(
            self._lookup, np.asarray(flow_ids, np.int64)
        )
        names, slot_ns = self._ns_snapshot
        idx = np.where(
            slots >= 0, slot_ns[np.maximum(slots, 0)], np.int32(-1)
        )
        return idx, names

    # -- rev-7 push plane (server→client control frames) ---------------------
    def attach_push_hub(self, hub) -> None:
        """Register a front door's :class:`~sentinel_tpu.cluster.push.PushHub`.
        Every service-side truth change that clients may be caching (lease
        registry, breaker states, rule generation) is mirrored onto every
        attached hub; both doors of a server attach the same hub."""
        if hub not in self._push_hubs:
            self._push_hubs.append(hub)

    def _emit_push(self, method: str, *args) -> None:
        """Fan one emit across every attached hub. Never raises and never
        blocks — hub sinks are the same non-blocking enqueues the reply
        lanes use — so call sites inside ``self._lock`` are safe (the hub's
        own lock never calls back into the service)."""
        for hub in self._push_hubs:
            try:
                getattr(hub, method)(*args)
            except Exception:
                pass

    # -- wire rev 5: token leases (client-local admission) -------------------
    def _sweep_leases_locked(self, now: int) -> None:
        """Drop leases past their TTL. Their LEASED charge stays in the flow
        window and expires with it — a crashed client therefore causes
        *under*-admission for up to one window, never over-admission.
        Caller holds ``self._lock``."""
        if not self._leases:
            return
        dead = [
            l for l in list(self._leases.values()) if now >= l.expiry_ms
        ]
        if dead:
            for l in dead:
                del self._leases[l.lease_id]
            self._lease_stats["revoked"] += len(dead)
            # push the revocations so a live-but-slow client drops its
            # cached slice now instead of admitting against a lease the
            # server already wrote off
            for l in dead:
                self._emit_push(
                    "push_lease_revoke", l.lease_id, l.flow_id, l.tokens
                )

    def _credit_lease_locked(self, lease: _Lease, used: int) -> None:
        """Credit a lease's unused tokens back into the EXACT ring bucket
        its grant charged — but only when the start stamp proves that
        bucket is still the grant's epoch. Charge and credit then rotate
        out *together*, so a flow's LEASED window sum can never go net
        negative (crediting into a *different* bucket could outlive the
        charge and briefly over-admit). When the bucket has rotated (or
        an engine-time rebase shifted the stamps) the credit is dropped
        and the unused tokens expire with the window — the conservative
        direction. Caller holds ``self._lock``."""
        from sentinel_tpu.engine.state import ClusterEvent, flow_spec

        unused = lease.tokens - max(0, int(used))
        if unused <= 0:
            return
        spec = flow_spec(self.config)
        idx = int((lease.granted_ms // spec.bucket_ms) % spec.n_buckets)
        aligned = int(lease.granted_ms - lease.granted_ms % spec.bucket_ms)
        ws = self._state.flow
        if int(np.asarray(ws.starts)[idx]) != aligned:
            return
        counts = ws.counts.at[
            lease.slot, idx, int(ClusterEvent.LEASED)
        ].add(jnp.asarray(-unused, ws.counts.dtype))
        self._state = self._state._replace(
            flow=ws._replace(counts=counts)
        )
        if self._dirty is not None:
            self._dirty["flow"].add(int(lease.slot))

    def _lease_admit_locked(
        self, flow_id: int, want: int, now: int, stat: str
    ) -> LeaseResult:
        """Grant core: prorate a slice of the flow's CURRENT headroom
        (threshold − PASS − LEASED − matured borrows, the same occupancy
        the device kernel reads), charge it into the LEASED column, and
        register the lease. Caller holds ``self._lock`` and has swept."""
        from sentinel_tpu.engine.rules import ThresholdMode
        from sentinel_tpu.engine.state import (
            N_CLUSTER_EVENTS, ClusterEvent, flow_spec,
        )
        from sentinel_tpu.stats import window as W

        flow_id = int(flow_id)
        rule = self._rule_of.get(flow_id)
        if rule is None:
            return LeaseResult(int(TokenStatus.NO_RULE_EXISTS))
        mv = self._moving.get(rule.namespace)
        if mv is not None:
            # namespace mid-move or committed away: same redirect contract
            # as the decision path — tokens carries the shard-map epoch
            return LeaseResult(
                int(TokenStatus.MOVED), tokens=int(mv[1]), endpoint=mv[0]
            )
        want = int(want)
        if want <= 0 or self.lease_fraction <= 0.0:
            return LeaseResult(int(TokenStatus.NOT_LEASABLE))
        if int(getattr(rule, "control_behavior", 0)) != 0:
            # a shaped rule's admission curve lives in the device shaper
            # state — client-local lease admission would bypass warmup and
            # pacing entirely, so shaped flows are simply not leasable
            return LeaseResult(int(TokenStatus.NOT_LEASABLE))
        if self._has_breakers and flow_id in self._degrade_rules_src:
            # a breaker-guarded flow must answer per-request: a client-local
            # slice would keep admitting for a full TTL after the breaker
            # OPENs, and its traffic would never produce the DEGRADED
            # verdicts that tell the client to back off. Refusing the lease
            # bounds breaker over-admission to in-flight requests only.
            return LeaseResult(int(TokenStatus.NOT_LEASABLE))
        slot = self._index.slot_of.get(flow_id)
        if slot is None:
            return LeaseResult(int(TokenStatus.NO_RULE_EXISTS))
        spec = flow_spec(self.config)
        now32 = jnp.int32(now)
        ids = jnp.asarray(np.asarray([slot], np.int32))
        occupied = float(np.asarray(
            W.window_sum_at(spec, self._state.flow, now32,
                            int(ClusterEvent.PASS), ids)
            + W.window_sum_at(spec, self._state.flow, now32,
                              int(ClusterEvent.LEASED), ids)
            + W.window_sum_at(spec, self._state.occupy, now32, 0, ids)
        )[0])
        # same per-window budget the device kernel enforces: rule count is
        # per-second, scaled by connected clients under AVG_LOCAL
        factor = (
            max(1, int(self._connected.get(rule.namespace, 1)))
            if rule.mode == ThresholdMode.AVG_LOCAL else 1
        )
        threshold = (
            float(rule.count) * factor * self.config.exceed_count
            * (spec.interval_ms / 1000.0)
        )
        grant = min(want, int((threshold - occupied) * self.lease_fraction))
        if grant < 1:
            return LeaseResult(int(TokenStatus.NOT_LEASABLE))
        row = [0] * int(N_CLUSTER_EVENTS)
        row[int(ClusterEvent.LEASED)] = grant
        self._state = self._state._replace(
            flow=self._fold_into_current(
                self._state.flow, spec, now, [slot], [row]
            )
        )
        if self._dirty is not None:
            self._dirty["flow"].add(int(slot))
        lease_id = next(self._lease_seq)
        self._leases[lease_id] = _Lease(
            lease_id, flow_id, slot, grant, now, now + self.lease_ttl_ms
        )
        self._lease_stats[stat] += 1
        return LeaseResult(
            int(TokenStatus.OK), lease_id=lease_id, tokens=grant,
            ttl_ms=self.lease_ttl_ms,
        )

    def lease_grant(self, flow_id: int, want: int) -> LeaseResult:
        """Grant a short-TTL local-admission slice of ``flow_id``'s window:
        up to ``want`` tokens, capped at ``lease_fraction`` of the flow's
        current headroom. The slice is pre-paid (charged to the LEASED
        column now), so the client's local admissions never touch the
        server and every replica's psum'd limit already accounts them."""
        with self._lock:
            now = self._engine_now()
            self._sweep_leases_locked(now)
            res = self._lease_admit_locked(flow_id, want, now, "granted")
        if _TR.ARMED:  # flight recorder: lease grant
            _TR.record(_TR.LEASE, aux=getattr(res, "tokens", 0) or 0)
        return res

    def lease_renew(
        self, lease_id: int, flow_id: int, used: int, want: int
    ) -> LeaseResult:
        """Atomically credit the old lease's unused tokens and grant a
        fresh slice. An unknown ``lease_id`` (expired, revoked, or a
        promoted standby that never saw the grant) degrades to a
        credit-less grant — no handshake needed after failover; the old
        charge, wherever it lives, expires with its window."""
        with self._lock:
            now = self._engine_now()
            self._sweep_leases_locked(now)
            lease = self._leases.get(int(lease_id))
            if lease is not None and lease.flow_id == int(flow_id):
                del self._leases[int(lease_id)]
                self._credit_lease_locked(lease, used)
            res = self._lease_admit_locked(flow_id, want, now, "renewed")
        if _TR.ARMED:  # flight recorder: lease renew
            _TR.record(_TR.LEASE, aux=getattr(res, "tokens", 0) or 0)
        return res

    def lease_return(self, lease_id: int, used: int) -> LeaseResult:
        """Give a lease back early, crediting its unused tokens. Idempotent:
        returning an expired/revoked/unknown lease is OK (the charge simply
        expires with the window)."""
        with self._lock:
            now = self._engine_now()
            self._sweep_leases_locked(now)
            lease = self._leases.pop(int(lease_id), None)
            if lease is None:
                return LeaseResult(int(TokenStatus.OK))
            self._credit_lease_locked(lease, used)
            self._lease_stats["returned"] += 1
        if _TR.ARMED:  # flight recorder: lease returned early
            _TR.record(_TR.LEASE, aux=int(used))
        return LeaseResult(int(TokenStatus.OK))

    def outstanding_leases(self) -> int:
        """Sum of tokens currently delegated on live leases — the bound on
        crash over-admission (a dead client can have locally admitted at
        most what it was granted and never reported back). The ha drill
        gates against exactly this number at SIGKILL time."""
        with self._lock:
            self._sweep_leases_locked(self._engine_now())
            return sum(l.tokens for l in self._leases.values())

    def lease_stats(self) -> Dict[str, int]:
        """Counter block behind the ``sentinel_lease_*`` series and the
        bench artifact: cumulative granted/renewed/returned/revoked plus
        the live outstanding gauge (leases and delegated tokens).
        ``revoked`` covers every server-side end of life: TTL expiry,
        rule-reload drop, and MOVE recall."""
        with self._lock:
            if self._leases:
                self._sweep_leases_locked(self._engine_now())
            out = dict(self._lease_stats)
            out["outstanding"] = len(self._leases)
            out["outstanding_tokens"] = sum(
                l.tokens for l in self._leases.values()
            )
            return out

    # -- hierarchy tier: global-budget share holds ---------------------------
    # A globally-limited flow is loaded locally at its FULL global budget;
    # the pod's share agent then pins (window_budget − share) tokens as a
    # LEASED-column "hold", leaving exactly the pod's share as local
    # headroom. The decision hot path is untouched — the device kernel
    # already reads LEASED — and psum'd limits, snapshots, deltas, and MOVE
    # all carry the hold automatically, like any lease charge.

    def _live_hold_locked(self, spec, entries, now):
        """Filter hold entries to those whose grant bucket still counts
        toward the window sum: start-stamp equality (the bucket was never
        reused — same proof as lease credit) AND in-window age (the same
        ``(now − interval, now]`` test as ``stats.window.valid_mask``).
        Stamp equality alone is not enough: a rotated-out bucket keeps its
        stale stamp until some writer reuses it, so an age-expired hold
        would look live here while the admission read already dropped it —
        and the re-top would never fire. Expired entries are simply gone:
        their charge aged out with the bucket, so the hold decayed and the
        agent must re-top it (the conservative direction: a decayed hold
        admits MORE locally, only up to the full budget, and only until
        the next agent tick)."""
        starts = np.asarray(self._state.flow.starts)
        live = []
        for granted_ms, tokens in entries:
            idx = int((granted_ms // spec.bucket_ms) % spec.n_buckets)
            aligned = int(granted_ms - granted_ms % spec.bucket_ms)
            age = int(now) - aligned
            if int(starts[idx]) == aligned and 0 <= age < spec.interval_ms:
                live.append((granted_ms, tokens))
        return live

    def set_share_hold(self, flow_id: int, hold_tokens: int) -> int:
        """Pin exactly ``hold_tokens`` of ``flow_id``'s window as a
        LEASED-column hold. A hold is a STANDING reservation, not traffic:
        left where it was charged it would age out of the sliding window
        one interval later and dump its whole worth of headroom at once
        (a flat-out client eats that before the next tick — measured, not
        hypothetical). So every call *migrates* the hold forward: live
        entries are credited back into their exact grant buckets
        (start-stamp guarded, same invariant as lease credit) and the full
        target re-charges into the CURRENT bucket — the window sum is
        unchanged within the call, and as long as the agent ticks more
        often than one window the hold never decays. If ticks stop
        entirely (agent dead), the hold expires one window later and the
        flow reverts to its full local budget — the documented degrade.
        Returns the live hold after the call."""
        from sentinel_tpu.engine.state import (
            N_CLUSTER_EVENTS, ClusterEvent, flow_spec,
        )

        flow_id = int(flow_id)
        hold_tokens = max(0, int(hold_tokens))
        with self._lock:
            slot = self._index.slot_of.get(flow_id)
            if slot is None:
                self._share_holds.pop(flow_id, None)
                return 0
            spec = flow_spec(self.config)
            now = self._engine_now()
            entries = self._live_hold_locked(
                spec, self._share_holds.get(flow_id, []), now
            )
            ws = self._state.flow
            counts = ws.counts
            for granted_ms, tokens in entries:
                idx = int((granted_ms // spec.bucket_ms) % spec.n_buckets)
                counts = counts.at[
                    slot, idx, int(ClusterEvent.LEASED)
                ].add(jnp.asarray(-tokens, counts.dtype))
            ws = ws._replace(counts=counts)
            if hold_tokens > 0:
                row = [0] * int(N_CLUSTER_EVENTS)
                row[int(ClusterEvent.LEASED)] = hold_tokens
                ws = self._fold_into_current(ws, spec, now, [slot], [row])
                self._share_holds[flow_id] = [(now, hold_tokens)]
            else:
                self._share_holds.pop(flow_id, None)
            self._state = self._state._replace(flow=ws)
            if self._dirty is not None:
                self._dirty["flow"].add(int(slot))
            return hold_tokens

    def share_holds(self) -> Dict[int, int]:
        """Live hold tokens per flow (rotation-decayed entries excluded)."""
        from sentinel_tpu.engine.state import flow_spec

        with self._lock:
            spec = flow_spec(self.config)
            now = self._engine_now()
            out = {
                fid: sum(
                    t for _, t in self._live_hold_locked(spec, ents, now)
                )
                for fid, ents in self._share_holds.items()
            }
            # a fully-decayed hold is indistinguishable from no hold — the
            # registry entry is just garbage awaiting the next set
            return {fid: t for fid, t in out.items() if t > 0}

    def window_budget(self, flow_id: int) -> int:
        """The flow's full per-window token budget — the same threshold
        the device kernel enforces (count × connected-factor ×
        exceed_count × window). The share agent holds
        ``window_budget − share`` so local headroom equals the share."""
        from sentinel_tpu.engine.rules import ThresholdMode
        from sentinel_tpu.engine.state import flow_spec

        with self._lock:
            rule = self._rule_of.get(int(flow_id))
            if rule is None:
                return 0
            spec = flow_spec(self.config)
            factor = (
                max(1, int(self._connected.get(rule.namespace, 1)))
                if rule.mode == ThresholdMode.AVG_LOCAL else 1
            )
            return int(
                float(rule.count) * factor * self.config.exceed_count
                * (spec.interval_ms / 1000.0)
            )

    def demand_rates(self, flow_ids) -> Dict[int, float]:
        """Observed arrival rate per flow in tokens/s: (PASS + BLOCK)
        window sums over the window interval. BLOCK counts *blocked*
        tokens, so a pod squeezed to a tiny share still reports its true
        demand — which is exactly what lets the coordinator's
        water-filling move share back toward it."""
        from sentinel_tpu.engine.state import ClusterEvent, flow_spec
        from sentinel_tpu.stats import window as W

        out: Dict[int, float] = {}
        known = []
        with self._lock:
            spec = flow_spec(self.config)
            now32 = jnp.int32(self._engine_now())
            for fid in flow_ids:
                slot = self._index.slot_of.get(int(fid))
                if slot is None:
                    out[int(fid)] = 0.0
                else:
                    known.append((int(fid), int(slot)))
            if known:
                ids = jnp.asarray(
                    np.asarray([s for _, s in known], np.int32)
                )
                sums = np.asarray(
                    W.window_sum_at(spec, self._state.flow, now32,
                                    int(ClusterEvent.PASS), ids)
                    + W.window_sum_at(spec, self._state.flow, now32,
                                      int(ClusterEvent.BLOCK), ids)
                )
                interval_s = spec.interval_ms / 1000.0
                for (fid, _), v in zip(known, sums):
                    out[fid] = float(v) / interval_s
        return out

    def attach_hierarchy(self, coordinator) -> None:
        """Co-locate the global budget coordinator with this pod: both
        doors route HIER_TYPES frames to it, its ledger piggybacks on
        this service's replication stream, and its counters join
        ``hier_stats``."""
        self.hierarchy = coordinator

    def attach_share_agent(self, agent) -> None:
        """Register this pod's share agent so its counters join
        ``hier_stats`` (the agent itself talks to the coordinator over
        the wire, not through the service)."""
        self.share_agent = agent

    def hier_stats(self) -> Dict[str, object]:
        """Counter block behind the ``sentinel_hier_*`` series: agent-side
        share/tick counters overlaid (coordinator wins) with the
        coordinator ledger, when either is attached."""
        out: Dict[str, object] = {}
        agent = self.share_agent
        if agent is not None:
            try:
                out.update(agent.stats())
            except Exception:  # pragma: no cover - stats never raise
                pass
        coord = self.hierarchy
        if coord is not None:
            try:
                out.update(coord.stats())
            except Exception:  # pragma: no cover
                pass
        if out:
            out["hold_tokens"] = sum(self.share_holds().values())
        return out

    @staticmethod
    def _fold_into_current(ws, spec, now: int, rows, sums):
        """Add per-row event sums into the CURRENT ring bucket of ``ws``,
        host-side pre-rotating that column when its recorded start is stale
        (zero it across ALL rows and stamp the aligned start — exactly what
        :func:`stats.window.roll` would do on the next write) so the fold
        cannot resurrect a dead bucket's counts. Conservative direction:
        imported counts are all attributed to *now*, so they expire at most
        one window later than they would have at the source — never
        earlier, which is what zero-over-admission needs."""
        idx = int((now // spec.bucket_ms) % spec.n_buckets)
        aligned = int(now - now % spec.bucket_ms)
        starts = np.asarray(ws.starts)
        counts = ws.counts
        if int(starts[idx]) != aligned:
            counts = counts.at[:, idx].set(0)
            starts = np.array(starts)
            starts[idx] = aligned
        if rows is not None and len(rows):
            counts = counts.at[np.asarray(rows, np.int32), idx].add(
                jnp.asarray(np.asarray(sums), counts.dtype)
            )
        return ws._replace(starts=jnp.asarray(starts), counts=counts)

    def export_namespace_state(self, namespace: str) -> Dict[str, object]:
        """The *slim* representation of one namespace for a live move: its
        rules plus per-row **live-window sums** (flow/occupy event sums, the
        namespace guard row, and the param CMS cells), not the raw ring
        buckets. Sums are ring- and epoch-free, so the destination can fold
        them into its OWN current bucket regardless of clock skew or ring
        phase — the fat-update/slim-query split of SF-sketch applied to the
        handoff (ISSUE 8). Rules come back as rule objects; the rebalance
        codec serializes them."""
        from sentinel_tpu.engine.state import flow_spec
        from sentinel_tpu.stats import window as W

        with self._rules_mutex, self._lock:
            rules = list(self._rules_by_ns.get(namespace, {}).values())
            param_rules = [
                r for r in self._param_rules_src.values()
                if r.namespace == namespace
            ]
            now = self._engine_now()
            spec = flow_spec(self.config)
            fsum = np.asarray(
                W.window_sum_all(spec, self._state.flow, jnp.int32(now))
            )
            osum = np.asarray(
                W.window_sum_all(spec, self._state.occupy, jnp.int32(now))
            )
            nsum = np.asarray(
                W.window_sum_all(spec, self._state.ns, jnp.int32(now))
            )
            # completion-outcome columns move with the flow like the shaper
            # clocks from PR 15: live-window sums fold into the destination's
            # current bucket, so RT/exception telemetry (and the breakers it
            # will feed) survives a MOVE without a ring-phase contract
            outsum = np.asarray(
                W.window_sum_all(spec, self._state.outcome, jnp.int32(now))
            )
            from sentinel_tpu.stats.window import NEVER as _WNEVER

            lpt_h = np.asarray(self._state.shaping.lpt)
            wtok_h = np.asarray(self._state.shaping.warm_tokens)
            wfill_h = np.asarray(self._state.shaping.warm_filled)
            # breaker columns move with the flow like the shaper clocks: an
            # OPEN breaker must stay OPEN at the destination, its recovery
            # clock re-anchored to the destination's epoch
            br_st_h = np.asarray(self._state.breaker.state)
            br_op_h = np.asarray(self._state.breaker.opened_ms)
            br_pr_h = np.asarray(self._state.breaker.probe_ms)
            degrade_rules = [
                d for d in self._degrade_rules_src.values()
                if d.namespace == namespace
            ]
            flow_ids: List[int] = []
            frows: List[np.ndarray] = []
            orows: List[np.ndarray] = []
            outrows: List[np.ndarray] = []
            lpt_rel: List[int] = []
            wtok_rows: List[float] = []
            wfill_rel: List[int] = []
            br_state_rows: List[int] = []
            br_opened_rel: List[int] = []
            br_probe_rel: List[int] = []

            def _rel(v: int) -> int:
                return int(_WNEVER) if v == int(_WNEVER) else int(v) - now

            # breaker-only flows (a DegradeRule with no flow rule) still own
            # a slot and breaker state; walk the union so they move too
            exported = {r.flow_id for r in rules}
            movers = list(rules) + [
                d for d in degrade_rules if d.flow_id not in exported
            ]
            for r in movers:
                slot = self._index.slot_of.get(r.flow_id)
                if slot is None:
                    continue
                flow_ids.append(int(r.flow_id))
                frows.append(fsum[slot])
                orows.append(osum[slot])
                outrows.append(outsum[slot])
                # shaper clocks ship RELATIVE to now — the destination's
                # engine epoch is its own; NEVER stays NEVER
                lpt_rel.append(_rel(int(lpt_h[slot])))
                wtok_rows.append(float(wtok_h[slot]))
                wfill_rel.append(_rel(int(wfill_h[slot])))
                br_state_rows.append(int(br_st_h[slot]))
                br_opened_rel.append(_rel(int(br_op_h[slot])))
                br_probe_rel.append(_rel(int(br_pr_h[slot])))
            row = self._index.ns_of.get(namespace)
            doc: Dict[str, object] = {
                "namespace": namespace,
                "wall_ms": int(_clock.now_ms()),
                "interval_ms": int(spec.interval_ms),
                "rules": rules,
                "param_rules": param_rules,
                "flow_ids": flow_ids,
                "flow_sums": (
                    np.stack(frows) if frows
                    else np.zeros((0, fsum.shape[1]), fsum.dtype)
                ),
                "occupy_sums": (
                    np.stack(orows) if orows
                    else np.zeros((0, osum.shape[1]), osum.dtype)
                ),
                "outcome_sums": (
                    np.stack(outrows) if outrows
                    else np.zeros((0, outsum.shape[1]), outsum.dtype)
                ),
                "ns_sum": (
                    np.array(nsum[row]) if row is not None
                    else np.zeros(nsum.shape[1], nsum.dtype)
                ),
                "shaping_lpt_rel": np.asarray(lpt_rel, np.int64),
                "shaping_warm_tokens": np.asarray(wtok_rows, np.float32),
                "shaping_warm_filled_rel": np.asarray(wfill_rel, np.int64),
                "degrade_rules": degrade_rules,
                "breaker_state": np.asarray(br_state_rows, np.int8),
                "breaker_opened_rel": np.asarray(br_opened_rel, np.int64),
                "breaker_probe_rel": np.asarray(br_probe_rel, np.int64),
            }
            # param sketch: per-slot live-window cell sums [depth, cells] —
            # summed over DECODED cells (sketch.decoded_counts_np), so the
            # wire document is plain int sums whatever the in-memory
            # encoding (int32 cms or int16 SALSA pairs). The sketch is
            # linear over decoded values, so summing live buckets preserves
            # every estimate the destination will read.
            from sentinel_tpu.sketch import decoded_counts_np

            pfids: List[int] = []
            prows: List[np.ndarray] = []
            if param_rules:
                pstarts = np.asarray(self._param_state.starts)
                pcounts = decoded_counts_np(
                    self.param_config, self._param_state.counts
                )
                age = now - pstarts
                live = (age >= 0) & (age < self.param_config.interval_ms)
                for r in param_rules:
                    entry = self._param_rules.get(r.flow_id)
                    if entry is None:
                        continue
                    pfids.append(int(r.flow_id))
                    prows.append(
                        pcounts[entry[0], live].sum(axis=0).astype(np.int64)
                    )
            doc["param_fids"] = pfids
            doc["param_sums"] = (
                np.stack(prows) if prows
                else np.zeros(
                    (0, self.param_config.depth,
                     self.param_config.cell_width),
                    np.int64,
                )
            )
            return doc

    def import_namespace_state(self, doc: Dict[str, object]) -> None:
        """Install an :meth:`export_namespace_state` capture into THIS
        service: load the namespace's rules through the normal reload path
        (fresh local slots), then fold every shipped sum into the current
        ring bucket (see :meth:`_fold_into_current`). Token-lossless: the
        destination's first window sum over an imported row equals the
        source's last — admission resumes exactly where the source
        stopped."""
        from sentinel_tpu.engine.state import EngineState as _ES
        from sentinel_tpu.engine.state import flow_spec

        namespace = str(doc["namespace"])
        rules = list(doc["rules"])
        param_rules = list(doc["param_rules"])
        degrade_rules = list(doc.get("degrade_rules", ()))
        with self._rules_mutex:
            self.load_namespace_rules(namespace, rules)
            if degrade_rules:
                # the namespace's breakers move with it: rules first (slots
                # + br_* columns), then the state columns re-anchor below
                self.load_namespace_degrade_rules(namespace, degrade_rules)
            if param_rules:
                self.load_namespace_param_rules(namespace, param_rules)
            with self._lock:
                now = self._engine_now()
                spec = flow_spec(self.config)
                flow_ids = [int(f) for f in doc.get("flow_ids", [])]
                slots = (
                    np.asarray(
                        [self._index.slot_of[f] for f in flow_ids], np.int32
                    )
                    if flow_ids else None
                )
                flow = self._fold_into_current(
                    self._state.flow, spec, now, slots, doc["flow_sums"]
                )
                occupy = self._fold_into_current(
                    self._state.occupy, spec, now, slots, doc["occupy_sums"]
                )
                # pre-outcome blobs carry no key — moved flows start with an
                # empty completion window, the conservative default
                out_sums = doc.get("outcome_sums")
                outcome = (
                    self._fold_into_current(
                        self._state.outcome, spec, now, slots, out_sums
                    )
                    if out_sums is not None and slots is not None
                    else self._state.outcome
                )
                row = self._index.ns_of.get(namespace)
                ns = self._fold_into_current(
                    self._state.ns, spec, now,
                    None if row is None else [row],
                    None if row is None else np.asarray(doc["ns_sum"])[None],
                )
                # re-anchor the moved shaper clocks to THIS engine's epoch:
                # the blob ships them relative to the source's export now
                # (pre-shaping blobs simply carry no keys — clocks start
                # cold, the conservative default)
                shaping = self._state.shaping
                lpt_rel = doc.get("shaping_lpt_rel")
                if lpt_rel is not None and flow_ids:
                    from sentinel_tpu.stats.window import NEVER as _WNEVER

                    lpt_h = np.asarray(shaping.lpt).copy()
                    wtok_h = np.asarray(shaping.warm_tokens).copy()
                    wfill_h = np.asarray(shaping.warm_filled).copy()
                    wtok_in = np.asarray(doc["shaping_warm_tokens"])
                    wfill_in = np.asarray(doc["shaping_warm_filled_rel"])
                    lpt_in = np.asarray(lpt_rel)
                    for i, s in enumerate(np.asarray(slots)):
                        lpt_h[s] = (
                            int(_WNEVER) if lpt_in[i] == int(_WNEVER)
                            else int(np.clip(
                                now + int(lpt_in[i]), int(_WNEVER), 2**30
                            ))
                        )
                        wtok_h[s] = wtok_in[i]
                        wfill_h[s] = (
                            int(_WNEVER) if wfill_in[i] == int(_WNEVER)
                            else int(np.clip(
                                now + int(wfill_in[i]), int(_WNEVER), 2**30
                            ))
                        )
                    shaping = shaping._replace(
                        lpt=jnp.asarray(lpt_h),
                        warm_tokens=jnp.asarray(wtok_h),
                        warm_filled=jnp.asarray(wfill_h),
                    )
                # re-anchor the moved breaker columns the same way: state
                # verbatim, clocks shipped relative to the source's export
                # now (pre-breaker blobs carry no key — breakers start
                # CLOSED, which only under-protects until the stat window
                # refills, never over-admits the destination's own flows)
                breaker = self._state.breaker
                br_state_in = doc.get("breaker_state")
                if br_state_in is not None and flow_ids:
                    from sentinel_tpu.stats.window import NEVER as _WNEVER

                    bst_h = np.asarray(breaker.state).copy()
                    bop_h = np.asarray(breaker.opened_ms).copy()
                    bpr_h = np.asarray(breaker.probe_ms).copy()
                    bst_in = np.asarray(br_state_in)
                    bop_in = np.asarray(doc["breaker_opened_rel"])
                    bpr_in = np.asarray(doc["breaker_probe_rel"])

                    def _anchor(rel: int) -> int:
                        return (
                            int(_WNEVER) if rel == int(_WNEVER)
                            else int(np.clip(
                                now + int(rel), int(_WNEVER), 2**30
                            ))
                        )

                    for i, s in enumerate(np.asarray(slots)):
                        bst_h[s] = bst_in[i]
                        bop_h[s] = _anchor(int(bop_in[i]))
                        bpr_h[s] = _anchor(int(bpr_in[i]))
                    breaker = breaker._replace(
                        state=jnp.asarray(bst_h),
                        opened_ms=jnp.asarray(bop_h),
                        probe_ms=jnp.asarray(bpr_h),
                    )
                    # drop the stale transition mirror: the next scan
                    # re-baselines from CLOSED, so moved-in OPEN breakers
                    # surface as closed→open edges on the destination
                    self._breaker_prev = None
                self._state = self._place_state(
                    _ES(flow=flow, occupy=occupy, ns=ns, shaping=shaping,
                        outcome=outcome, breaker=breaker)
                )
                pfids = [int(f) for f in doc.get("param_fids", [])]
                if pfids:
                    from sentinel_tpu.sketch import fold_param_sums

                    prow = np.asarray(
                        [self._param_rules[f][0] for f in pfids], np.int32
                    )
                    self._param_state = fold_param_sums(
                        self.param_config, self._param_state, now, prow,
                        doc["param_sums"],
                    )
                    # the fold lands in the FAT sketch only — the slim twin
                    # never saw the source's touches. Mark the rows for a
                    # one-shot fat shipment so a delta-fed standby doesn't
                    # miss the moved-in window (moves are rare; one fat row
                    # per moved rule, not per tick).
                    if self._dirty is not None:
                        self._dirty["param"].update(int(r) for r in prow)
                        self._dirty.setdefault("param_fat", set()).update(
                            int(r) for r in prow
                        )

    # -- state snapshot / restore (ha.snapshot backing) ----------------------
    def export_state(self) -> Dict[str, object]:
        """Device→host capture of everything a warm standby needs to resume
        counting: rule sources, slot assignments, the flow/occupy/ns window
        tensors, the CMS param sketch, and the engine epoch. Arrays come
        back as host numpy copies; keys are stable (``ha.snapshot`` encodes
        them into the versioned artifact)."""

        def _win(ws) -> Dict[str, np.ndarray]:
            return {
                "starts": np.asarray(ws.starts),
                "counts": np.asarray(ws.counts),
            }

        with self._rules_mutex, self._lock:
            now = self._engine_now()  # pins the epoch, runs a due rebase
            return {
                "engine_now": int(now),
                "epoch_ms": int(self._epoch_ms),
                "wall_ms": int(_clock.now_ms()),
                "ns_max_qps": float(self._ns_max_qps),
                "connected": dict(self._connected),
                "namespace_set": sorted(self.namespace_set),
                "rules": [
                    r for m in self._rules_by_ns.values() for r in m.values()
                ],
                "param_rules": list(self._param_rules_src.values()),
                "degrade_rules": list(self._degrade_rules_src.values()),
                "slot_of": dict(self._index.slot_of),
                "ns_of": dict(self._index.ns_of),
                "param_slot_of": {
                    fid: slot
                    for fid, (slot, _, _) in self._param_rules.items()
                },
                "flow": _win(self._state.flow),
                "occupy": _win(self._state.occupy),
                "ns": _win(self._state.ns),
                # per-flow completion-outcome windows (rt_sum / complete /
                # exception / RT histogram channels; same ring epoch)
                "outcome": _win(self._state.outcome),
                # per-flow shaper clocks (engine-ms; same epoch as starts)
                "shaping": {
                    "lpt": np.asarray(self._state.shaping.lpt),
                    "warm_tokens": np.asarray(
                        self._state.shaping.warm_tokens
                    ),
                    "warm_filled": np.asarray(
                        self._state.shaping.warm_filled
                    ),
                },
                # per-flow circuit-breaker columns (state machine + engine-ms
                # clocks; clocks share the exported epoch, so restore is
                # bit-exact on the same service and remaps by flow_id)
                "breaker": {
                    "state": np.asarray(self._state.breaker.state),
                    "opened_ms": np.asarray(self._state.breaker.opened_ms),
                    "probe_ms": np.asarray(self._state.breaker.probe_ms),
                },
                "param": {
                    "starts": np.asarray(self._param_state.starts),
                    # fat cells ship RAW (bit-exact restore — for SALSA the
                    # in-band merge encoding rides inside the int16 cells),
                    # plus the slim twin, its authority flags, and the
                    # per-slot merge counters
                    "counts": np.asarray(self._param_state.counts),
                    "slim": np.asarray(self._param_state.slim),
                    "slim_auth": np.asarray(self._param_state.slim_auth),
                    "merges": np.asarray(self._param_state.merges),
                },
                # hierarchy ledger piggyback (pure JSON; absent when no
                # coordinator is co-located). A standby imports it into ITS
                # attached coordinator so promotion inherits the share map.
                **(
                    {"hier": self.hierarchy.export_doc()}
                    if self.hierarchy is not None else {}
                ),
            }

    def import_state(self, state: Dict[str, object]) -> None:
        """Restore an :meth:`export_state` capture into THIS service.

        Slot assignments are not trusted: rules reload through the normal
        path (fresh ``RuleIndex`` slots), then counter rows remap
        old-slot→new-slot per flow_id / namespace / param rule, so a standby
        that loaded rules in a different order still lands every counter on
        the right rule. Window starts carry over verbatim — engine time
        continues from the snapshot epoch, so counters older than one window
        expire naturally via the mask-on-read reads. Geometry (window/sketch
        shapes) must match this service's config; mismatch raises
        ``ValueError`` before anything mutates."""
        from sentinel_tpu.engine.state import EngineState as _ES
        from sentinel_tpu.stats.window import WindowState as _WS

        def _check(name: str, got, want) -> np.ndarray:
            arr = np.asarray(got)
            if arr.shape != tuple(want.shape):
                raise ValueError(
                    f"snapshot geometry mismatch: {name} {arr.shape} "
                    f"!= {tuple(want.shape)}"
                )
            return arr

        with self._rules_mutex:
            rules = list(state["rules"])
            param_rules = list(state["param_rules"])
            with self._lock:
                cur = self._state
                flow_c = _check("flow.counts", state["flow"]["counts"],
                                cur.flow.counts)
                flow_s = _check("flow.starts", state["flow"]["starts"],
                                cur.flow.starts)
                occ_c = _check("occupy.counts", state["occupy"]["counts"],
                               cur.occupy.counts)
                occ_s = _check("occupy.starts", state["occupy"]["starts"],
                               cur.occupy.starts)
                ns_c = _check("ns.counts", state["ns"]["counts"],
                              cur.ns.counts)
                ns_s = _check("ns.starts", state["ns"]["starts"],
                              cur.ns.starts)
                p_c = _check("param.counts", state["param"]["counts"],
                             self._param_state.counts)
                p_s = _check("param.starts", state["param"]["starts"],
                             self._param_state.starts)
                # slim/merge keys are tolerated absent (pre-sketch-subsystem
                # snapshots) — they default to zeros of this service's
                # geometry
                p_slim = state["param"].get("slim")
                if p_slim is not None:
                    p_slim = _check("param.slim", p_slim,
                                    self._param_state.slim)
                p_auth = state["param"].get("slim_auth")
                p_merges = state["param"].get("merges")
                # pre-shaping snapshots carry no shaper clocks — restore
                # them cold (NEVER/0), which is the conservative default
                shaping_doc = state.get("shaping")
                # pre-outcome snapshots carry no completion windows —
                # restore them empty (cold), same tolerant-absent discipline
                outcome_doc = state.get("outcome")
                # pre-breaker snapshots carry no breaker columns — restore
                # CLOSED everywhere (under-protects until the stat window
                # refills; never wrongly rejects)
                breaker_doc = state.get("breaker")
                if outcome_doc is not None:
                    out_c = _check("outcome.counts", outcome_doc["counts"],
                                   cur.outcome.counts)
                    out_s = _check("outcome.starts", outcome_doc["starts"],
                                   cur.outcome.starts)
                else:
                    out_c = np.zeros(
                        tuple(cur.outcome.counts.shape),
                        np.asarray(cur.outcome.counts[:0]).dtype,
                    )
                    out_s = np.asarray(cur.outcome.starts)
            with self._lock:
                # degrade rules must be in place BEFORE load_rules so the
                # rebuilt RuleTable carries the br_* columns the restored
                # breaker state refers to
                self._degrade_rules_src = {
                    d.flow_id: d for d in state.get("degrade_rules", ())
                }
            self.load_rules(
                rules,
                ns_max_qps=float(state["ns_max_qps"]),
                connected=dict(state["connected"]),
            )
            self.load_param_rules(param_rules)
            with self._lock:
                self.namespace_set |= set(state["namespace_set"])
                # remap flow/occupy rows: snapshot slot → this service's slot
                old_slot = state["slot_of"]
                new_flow_c = np.zeros_like(flow_c)
                new_occ_c = np.zeros_like(occ_c)
                new_out_c = np.zeros_like(out_c)
                from sentinel_tpu.stats.window import NEVER as _WNEVER

                n_flows = self.config.max_flows
                new_lpt = np.full(n_flows, int(_WNEVER), np.int32)
                new_wtok = np.zeros(n_flows, np.float32)
                new_wfill = np.full(n_flows, int(_WNEVER), np.int32)
                new_br_st = np.zeros(n_flows, np.int8)
                new_br_op = np.full(n_flows, int(_WNEVER), np.int32)
                new_br_pr = np.full(n_flows, int(_WNEVER), np.int32)
                for fid, new in self._index.slot_of.items():
                    old = old_slot.get(fid)
                    if old is None:
                        continue
                    new_flow_c[new] = flow_c[old]
                    new_occ_c[new] = occ_c[old]
                    new_out_c[new] = out_c[old]
                    if shaping_doc is not None:
                        new_lpt[new] = np.asarray(shaping_doc["lpt"])[old]
                        new_wtok[new] = np.asarray(
                            shaping_doc["warm_tokens"]
                        )[old]
                        new_wfill[new] = np.asarray(
                            shaping_doc["warm_filled"]
                        )[old]
                    if breaker_doc is not None:
                        new_br_st[new] = np.asarray(
                            breaker_doc["state"]
                        )[old]
                        new_br_op[new] = np.asarray(
                            breaker_doc["opened_ms"]
                        )[old]
                        new_br_pr[new] = np.asarray(
                            breaker_doc["probe_ms"]
                        )[old]
                # namespace guard rows remap by name
                old_ns = state["ns_of"]
                new_ns_c = np.zeros_like(ns_c)
                for name, new in self._index.ns_of.items():
                    old = old_ns.get(name)
                    if old is not None:
                        new_ns_c[new] = ns_c[old]
                # param sketch rows remap via the param slot maps (fat row,
                # slim row, and merge counter move together; the [B] global
                # slim-authority flags copy verbatim)
                old_pslot = state["param_slot_of"]
                new_p_c = np.zeros_like(p_c)
                new_p_slim = np.zeros(
                    self._param_state.slim.shape,
                    np.asarray(self._param_state.slim).dtype,
                )
                new_p_merges = np.zeros(
                    self._param_state.merges.shape, np.int32
                )
                for fid, (new, _, _) in self._param_rules.items():
                    old = old_pslot.get(fid)
                    if old is not None:
                        new_p_c[new] = p_c[old]
                        if p_slim is not None:
                            new_p_slim[new] = p_slim[old]
                        if p_merges is not None:
                            new_p_merges[new] = np.asarray(p_merges)[old]
                from sentinel_tpu.engine.state import (
                    BreakerState as _BRS,
                    ShapingState as _SHS,
                )

                self._state = self._place_state(_ES(
                    flow=_WS(jnp.asarray(flow_s), jnp.asarray(new_flow_c)),
                    occupy=_WS(jnp.asarray(occ_s), jnp.asarray(new_occ_c)),
                    ns=_WS(jnp.asarray(ns_s), jnp.asarray(new_ns_c)),
                    shaping=_SHS(
                        lpt=jnp.asarray(new_lpt),
                        warm_tokens=jnp.asarray(new_wtok),
                        warm_filled=jnp.asarray(new_wfill),
                    ),
                    outcome=_WS(jnp.asarray(out_s), jnp.asarray(new_out_c)),
                    breaker=_BRS(
                        state=jnp.asarray(new_br_st),
                        opened_ms=jnp.asarray(new_br_op),
                        probe_ms=jnp.asarray(new_br_pr),
                    ),
                ))
                # re-baseline the transition mirror from CLOSED so the
                # restore surfaces still-open breakers as closed→open edges
                self._breaker_prev = None
                self._param_state = self._param_state._replace(
                    starts=jnp.asarray(p_s),
                    counts=jnp.asarray(new_p_c),
                    slim=jnp.asarray(new_p_slim),
                    slim_auth=(
                        jnp.asarray(np.asarray(p_auth, bool))
                        if p_auth is not None
                        else jnp.zeros_like(self._param_state.slim_auth)
                    ),
                    merges=jnp.asarray(new_p_merges),
                )
                # resume the snapshot's engine timeline: wall − epoch keeps
                # advancing, so windows older than interval_ms expire on the
                # next read instead of resurrecting stale quota
                self._epoch_ms = int(state["epoch_ms"])
        # hierarchy ledger piggyback: a standby with an attached (idle)
        # coordinator inherits the primary's share map, so promotion keeps
        # every pod's share continuous
        hier_doc = state.get("hier")
        if hier_doc is not None and self.hierarchy is not None:
            self.hierarchy.import_doc(hier_doc)

    # -- warm-standby delta replication (ha.replication backing) -------------
    def replication_enable(self) -> None:
        """Arm dirty-slot tracking so :meth:`export_delta` has rows to ship.
        Idempotent; until called the dispatch paths skip the bookkeeping."""
        with self._lock:
            if self._dirty is None:
                self._dirty = {
                    "flow": set(), "param": set(), "param_fat": set(),
                    "outcome": set(), "breaker": set(),
                }

    def replication_disable(self) -> None:
        with self._lock:
            self._dirty = None

    def state_generation(self) -> int:
        """Bumped on every rule/param-rule reload. Deltas are row-keyed by
        slot assignments that only hold within one generation; a sender that
        observes a bump must ship a full snapshot before more deltas."""
        with self._lock:
            return self._state_gen

    def export_delta(self) -> Dict[str, object]:
        """Collect-and-clear the dirty counter rows since the last call.

        Returns a compact host-side document: the shared window ``starts``
        ring vectors (``[n_buckets]`` each — always shipped, they advance
        with engine time), plus per-dirty-slot ``counts`` rows keyed by
        flow_id / namespace name / param flow_id so the standby can land
        them on its OWN slot assignment. ``gen`` is the generation the rows
        were collected under; ``epoch_ms`` pins the engine timeline the
        starts are relative to (the standby refuses a delta from a foreign
        epoch). An idle tick returns a starts-only document — the sender's
        liveness heartbeat. Destructive: the dirty sets are cleared, so a
        sender that fails to deliver must fall back to a full snapshot."""
        with self._rules_mutex, self._lock:
            if self._dirty is None:
                raise RuntimeError("replication tracking not enabled")
            flow_slots = sorted(self._dirty["flow"])
            param_slots = sorted(self._dirty["param"])
            param_fat_slots = sorted(self._dirty.get("param_fat", ()))
            outcome_slots = sorted(self._dirty.get("outcome", ()))
            breaker_slots = sorted(self._dirty.get("breaker", ()))
            self._dirty = {
                "flow": set(), "param": set(), "param_fat": set(),
                "outcome": set(), "breaker": set(),
            }
            now = self._engine_now()  # pins the epoch, runs a due rebase
            delta: Dict[str, object] = {
                "gen": int(self._state_gen),
                "engine_now": int(now),
                "epoch_ms": int(self._epoch_ms),
                "wall_ms": int(_clock.now_ms()),
                "flow_starts": np.asarray(self._state.flow.starts),
                "occupy_starts": np.asarray(self._state.occupy.starts),
                "ns_starts": np.asarray(self._state.ns.starts),
                "outcome_starts": np.asarray(self._state.outcome.starts),
                "param_starts": np.asarray(self._param_state.starts),
            }
            # row gathers go through the shard-aware host collector: on a
            # mesh it walks addressable shards and numpy-gathers each one's
            # slab (the delta's row keys stay GLOBAL slots, so the wire
            # document is identical whatever mesh produced it); single-shard
            # it is one host copy + numpy index. Either way no device gather
            # kernel — the dirty set's size varies every tick, and a device
            # gather would pay a fresh XLA compile per distinct row count.
            from sentinel_tpu.parallel.sharding import host_rows
            if flow_slots:
                sl = np.asarray(flow_slots, np.int32)
                rev = {v: k for k, v in self._index.slot_of.items()}
                delta["flow_ids"] = [int(rev[s]) for s in flow_slots]
                delta["flow_counts"] = host_rows(self._state.flow.counts, sl)
                delta["occupy_counts"] = host_rows(
                    self._state.occupy.counts, sl
                )
                # shaper clocks ride the same dirty-row keying; values are
                # engine-ms in the shared epoch the delta already pins
                delta["shaping_lpt"] = host_rows(
                    self._state.shaping.lpt, sl
                )
                delta["shaping_warm_tokens"] = host_rows(
                    self._state.shaping.warm_tokens, sl
                )
                delta["shaping_warm_filled"] = host_rows(
                    self._state.shaping.warm_filled, sl
                )
                # namespace guard rows these slots feed
                ns_names, slot_ns = self._ns_snapshot
                rows = sorted(
                    {int(slot_ns[s]) for s in flow_slots if slot_ns[s] >= 0}
                )
                if rows:
                    delta["ns_names"] = [ns_names[r] for r in rows]
                    delta["ns_counts"] = host_rows(
                        self._state.ns.counts, np.asarray(rows, np.int32)
                    )
            if outcome_slots:
                # completion-outcome rows ride the same dirty-row keying,
                # tracked separately from flow rows — admission traffic and
                # completion reports dirty different slots on different
                # cadences, and mixing the sets would ship full flow rows
                # for every piggy-backed outcome batch
                osl = np.asarray(outcome_slots, np.int32)
                orev = {v: k for k, v in self._index.slot_of.items()}
                delta["outcome_fids"] = [int(orev[s]) for s in outcome_slots]
                delta["outcome_counts"] = host_rows(
                    self._state.outcome.counts, osl
                )
            if breaker_slots:
                # breaker columns ship raw engine-ms clocks — the standby
                # shares the epoch (checked on apply), so no re-anchoring.
                # Only touched∩breaker slots land here: transitions can only
                # occur for rows that were batched or reported this tick.
                bsl = np.asarray(breaker_slots, np.int32)
                brev = {v: k for k, v in self._index.slot_of.items()}
                delta["breaker_fids"] = [int(brev[s]) for s in breaker_slots]
                delta["breaker_state"] = host_rows(
                    self._state.breaker.state, bsl
                )
                delta["breaker_opened"] = host_rows(
                    self._state.breaker.opened_ms, bsl
                )
                delta["breaker_probe"] = host_rows(
                    self._state.breaker.probe_ms, bsl
                )
            if param_slots:
                pr = np.asarray(param_slots, np.int32)
                prev = {
                    s: fid for fid, (s, _, _) in self._param_rules.items()
                }
                delta["param_fids"] = [int(prev[s]) for s in param_slots]
                if self.param_config.slim_enabled:
                    # SF-sketch split: the every-tick wire document ships
                    # the SLIM twin rows, not the fat update sketch —
                    # that's the sentinel_repl_bytes_total cut (the fat
                    # rows still ship in full snapshots for bit-exact
                    # bootstrap). Rows a MOVE import just folded are the
                    # exception: their mass exists only in the fat sketch,
                    # so they ride along once, keyed separately.
                    delta["param_slim"] = host_rows(
                        self._param_state.slim, pr
                    )
                    if param_fat_slots:
                        fr = np.asarray(param_fat_slots, np.int32)
                        delta["param_fat_fids"] = [
                            int(prev[s]) for s in param_fat_slots
                        ]
                        delta["param_counts"] = host_rows(
                            self._param_state.counts, fr
                        )
                else:
                    delta["param_counts"] = host_rows(
                        self._param_state.counts, pr
                    )
            if self.hierarchy is not None:
                # hier ledger rides every tick as plain JSON (non-array keys
                # pass through encode_delta_blob untouched); it's tiny — one
                # entry per (global flow × pod)
                delta["hier"] = self.hierarchy.export_doc()
            return delta

    def apply_replication_delta(self, delta: Dict[str, object]) -> None:
        """Scatter a primary's :meth:`export_delta` into THIS (standby)
        service. Rows remap by flow_id / namespace / param flow_id onto the
        local slot assignment — the standby loaded the same rules from the
        bootstrap snapshot, but possibly in a different slot order. A delta
        naming a flow this service doesn't know, or carrying a foreign
        engine epoch, raises ``ValueError``: both mean the standby's base
        state predates a reload on the primary, and the caller must answer
        NEED_SNAPSHOT rather than apply rows against the wrong baseline."""
        from sentinel_tpu.engine.state import EngineState as _ES
        from sentinel_tpu.stats.window import WindowState as _WS

        def _rotate(ws, new_starts):
            """Mirror the primary's ring rotation on rows the delta does NOT
            carry: when the primary advanced ``starts[b]`` it zeroed column
            ``b`` for every resource (window.py rotation), so any local row
            whose column still holds counts from the previous occupancy of
            that ring slot must be zeroed too — otherwise applying the new
            starts would resurrect those stale counts as current-window
            traffic. Dirty rows are scattered with authoritative values
            afterwards, so pre-zeroing them is harmless."""
            changed = np.asarray(ws.starts) != np.asarray(new_starts)
            if not changed.any():
                return ws
            keep = jnp.asarray((~changed).astype(np.int32))
            shape = (1, keep.shape[0]) + (1,) * (ws.counts.ndim - 2)
            return ws._replace(
                counts=ws.counts * keep.reshape(shape).astype(
                    ws.counts.dtype
                )
            )

        with self._rules_mutex, self._lock:
            if (
                self._epoch_ms is None
                or int(delta["epoch_ms"]) != self._epoch_ms
            ):
                raise ValueError("replication epoch mismatch")
            flow = _rotate(self._state.flow, delta["flow_starts"])
            occupy = _rotate(self._state.occupy, delta["occupy_starts"])
            ns = _rotate(self._state.ns, delta["ns_starts"])
            # pre-outcome senders ship no outcome_starts: keep the local
            # ring untouched (it is empty on such a standby anyway)
            out_starts = delta.get("outcome_starts")
            outcome = (
                _rotate(self._state.outcome, out_starts)
                if out_starts is not None else self._state.outcome
            )
            flow_ids = delta.get("flow_ids")
            shaping = self._state.shaping
            if flow_ids:
                slots = []
                for fid in flow_ids:
                    s = self._index.slot_of.get(int(fid))
                    if s is None:
                        raise ValueError(f"delta names unknown flow {fid}")
                    slots.append(s)
                sl = jnp.asarray(np.asarray(slots, np.int32))
                flow = flow._replace(
                    counts=flow.counts.at[sl].set(
                        jnp.asarray(delta["flow_counts"])
                    )
                )
                occupy = occupy._replace(
                    counts=occupy.counts.at[sl].set(
                        jnp.asarray(delta["occupy_counts"])
                    )
                )
                if "shaping_lpt" in delta:
                    # shaper clocks are raw engine-ms: the epoch check above
                    # already guarantees both sides share the timeline
                    shaping = shaping._replace(
                        lpt=shaping.lpt.at[sl].set(
                            jnp.asarray(delta["shaping_lpt"])
                        ),
                        warm_tokens=shaping.warm_tokens.at[sl].set(
                            jnp.asarray(delta["shaping_warm_tokens"])
                        ),
                        warm_filled=shaping.warm_filled.at[sl].set(
                            jnp.asarray(delta["shaping_warm_filled"])
                        ),
                    )
            outcome_fids = delta.get("outcome_fids")
            if outcome_fids:
                oslots = []
                for fid in outcome_fids:
                    s = self._index.slot_of.get(int(fid))
                    if s is None:
                        raise ValueError(f"delta names unknown flow {fid}")
                    oslots.append(s)
                osl = jnp.asarray(np.asarray(oslots, np.int32))
                outcome = outcome._replace(
                    counts=outcome.counts.at[osl].set(
                        jnp.asarray(delta["outcome_counts"])
                    )
                )
            breaker = self._state.breaker
            breaker_fids = delta.get("breaker_fids")
            if breaker_fids:
                bslots = []
                for fid in breaker_fids:
                    s = self._index.slot_of.get(int(fid))
                    if s is None:
                        raise ValueError(f"delta names unknown flow {fid}")
                    bslots.append(s)
                bsl = jnp.asarray(np.asarray(bslots, np.int32))
                # clocks are raw engine-ms; the epoch check above already
                # guarantees both sides share the timeline
                breaker = breaker._replace(
                    state=breaker.state.at[bsl].set(
                        jnp.asarray(delta["breaker_state"])
                    ),
                    opened_ms=breaker.opened_ms.at[bsl].set(
                        jnp.asarray(delta["breaker_opened"])
                    ),
                    probe_ms=breaker.probe_ms.at[bsl].set(
                        jnp.asarray(delta["breaker_probe"])
                    ),
                )
            ns_names = delta.get("ns_names")
            if ns_names:
                rows = []
                for name in ns_names:
                    r = self._index.ns_of.get(name)
                    if r is None:
                        raise ValueError(
                            f"delta names unknown namespace {name!r}"
                        )
                    rows.append(r)
                nr = jnp.asarray(np.asarray(rows, np.int32))
                ns = ns._replace(
                    counts=ns.counts.at[nr].set(
                        jnp.asarray(delta["ns_counts"])
                    )
                )
            self._state = self._place_state(_ES(
                flow=_WS(jnp.asarray(delta["flow_starts"]), flow.counts),
                occupy=_WS(
                    jnp.asarray(delta["occupy_starts"]), occupy.counts
                ),
                ns=_WS(jnp.asarray(delta["ns_starts"]), ns.counts),
                shaping=shaping,
                outcome=(
                    _WS(jnp.asarray(out_starts), outcome.counts)
                    if out_starts is not None else outcome
                ),
                breaker=breaker,
            ))
            pstate = _rotate(self._param_state, delta["param_starts"])
            pcounts = pstate.counts
            pslim, pauth = pstate.slim, pstate.slim_auth
            # mirror the ring rotation on the slim twin too: a rotated
            # column's slim cells describe a dead window — zero them and
            # drop the bucket's authority flag
            pchanged = (
                np.asarray(self._param_state.starts)
                != np.asarray(delta["param_starts"])
            )
            if pchanged.any():
                keep = jnp.asarray((~pchanged).astype(np.int32))
                pslim = pslim * keep.reshape(1, -1, 1, 1).astype(pslim.dtype)
                pauth = pauth & jnp.asarray(~pchanged)

            def _prows(fids):
                rows = []
                for fid in fids:
                    entry = self._param_rules.get(int(fid))
                    if entry is None:
                        raise ValueError(
                            f"delta names unknown param rule {fid}"
                        )
                    rows.append(entry[0])
                return jnp.asarray(np.asarray(rows, np.int32))

            param_fids = delta.get("param_fids")
            if param_fids:
                if "param_slim" in delta:
                    # SF split: deltas carry slim twin rows. Landing any
                    # makes every live bucket slim-authoritative — the
                    # decide path then serves fat + slim, which
                    # double-counts at most one snapshot-to-delta gap
                    # (over-estimate, the safe direction) and converges to
                    # fat-only as the flagged buckets rotate off the ring.
                    pr = _prows(param_fids)
                    pslim = pslim.at[pr].set(
                        jnp.asarray(delta["param_slim"])
                    )
                    pauth = jnp.ones_like(pauth)
                    fat_fids = delta.get("param_fat_fids")
                    if fat_fids:
                        fr = _prows(fat_fids)
                        pcounts = pcounts.at[fr].set(
                            jnp.asarray(delta["param_counts"])
                        )
                elif "param_counts" in delta:
                    pr = _prows(param_fids)
                    pcounts = pcounts.at[pr].set(
                        jnp.asarray(delta["param_counts"])
                    )
            self._param_state = self._param_state._replace(
                starts=jnp.asarray(delta["param_starts"]), counts=pcounts,
                slim=pslim, slim_auth=pauth,
            )
        # hier ledger piggyback: landed OUTSIDE the counter locks (the
        # coordinator has its own) and only when a coordinator is attached —
        # an old standby without one ignores the key, like any unknown key
        hier_doc = delta.get("hier")
        if hier_doc is not None and self.hierarchy is not None:
            self.hierarchy.import_doc(hier_doc)

    # -- introspection (FetchClusterMetricCommandHandler analog) ------------
    def sketch_stats(self) -> Dict[str, object]:
        """Host snapshot of the param-sketch observability block: variant,
        fat/slim HBM bytes, SALSA merge counters. Pulled by the process-wide
        ``ServerMetrics`` on every scrape and by ``clusterServerStats``."""
        from sentinel_tpu.engine.param import resolve_param_impl
        from sentinel_tpu.sketch import sketch_stats as _sketch_stats

        with self._lock:
            stats = _sketch_stats(self.param_config, self._param_state)
        stats["impl"] = resolve_param_impl(self.param_config.impl)
        return stats

    def metrics_snapshot(self) -> Dict[int, Dict[str, float]]:
        from sentinel_tpu.engine.state import (
            ClusterEvent,
            OutcomeChannel,
            flow_spec,
        )
        from sentinel_tpu.stats import window as W

        with self._lock:
            now = self._engine_now()
            spec = flow_spec(self.config)
            sums = np.asarray(W.window_sum_all(spec, self._state.flow, jnp.int32(now)))
            osums = np.asarray(
                W.window_sum_all(spec, self._state.outcome, jnp.int32(now))
            )
            interval_s = spec.interval_ms / 1000.0
            out = {}
            for fid, slot in self._index.slot_of.items():
                n_complete = float(osums[slot, OutcomeChannel.COMPLETE])
                rt_sum = float(osums[slot, OutcomeChannel.RT_SUM])
                out[fid] = {
                    "pass_qps": float(sums[slot, ClusterEvent.PASS]) / interval_s,
                    "block_qps": float(sums[slot, ClusterEvent.BLOCK]) / interval_s,
                    "pass_req_qps": float(sums[slot, ClusterEvent.PASS_REQUEST]) / interval_s,
                    # hierarchy tier reads this for fleet-wide occupancy:
                    # live LEASED charge (client leases + share holds)
                    "leased_tokens": float(sums[slot, ClusterEvent.LEASED]),
                    # completion-outcome plane (MetricNode success/exception
                    # parity): windowed success rate, exception rate, avg RT
                    "success_qps": n_complete / interval_s,
                    "exception_qps": (
                        float(osums[slot, OutcomeChannel.EXCEPTION])
                        / interval_s
                    ),
                    "rt_avg_ms": rt_sum / n_complete if n_complete else 0.0,
                }
                rule = self._rule_of.get(fid)
                mv = (
                    self._moving.get(rule.namespace)
                    if rule is not None else None
                )
                if mv is not None:
                    # MOVING / committed-away: the counters froze at the
                    # begin-move device step and the DESTINATION now counts
                    # this flow. Stamp the shard-map epoch so
                    # aggregate_snapshots can drop this pod's stale copy
                    # instead of double-reporting during the redirect window.
                    out[fid]["moved_epoch"] = float(mv[1])
            return out

    # -- rev-6 completion-outcome ingest (OUTCOME_REPORT wire op) ------------
    def report_outcomes(self, flow_ids, rt_ms, exceptions, xid: int = 0) -> int:
        """Ingest one batched completion report: validate at the wire
        boundary, scatter accepted rows into the per-flow outcome window via
        the donated fused step, and feed every host metric plane (timeline,
        SLO burn, flight recorder, ServerMetrics counters).

        Returns the number of rows accepted. Fire-and-forget from the wire's
        point of view — both doors call this with no response frame, so the
        lease/request fast path stays at zero extra RPCs.

        Wire-boundary validation (never scattered, counted into
        ``sentinel_outcome_dropped_total{reason}``):

        - ``negative``: RT < 0 after the int cast (also where a client's
          NaN/int-cast garbage lands — the cast maps non-finite to INT_MIN)
        - ``non_finite``: RT arrived as a non-finite float (in-process
          callers; the wire always carries int32)
        - ``too_large``: RT > ``protocol.OUTCOME_MAX_RT_MS`` — a bogus
          report that would poison ``rt_sum`` for the whole window
        - ``unknown_flow``: no rule slot holds this flow_id
        """
        from sentinel_tpu.cluster import protocol as P

        flow_ids = np.asarray(flow_ids, np.int64).reshape(-1)
        k = int(flow_ids.shape[0])
        rt_in = np.asarray(rt_ms).reshape(-1)
        exc_in = np.asarray(exceptions).reshape(-1).astype(bool)
        if rt_in.shape[0] != k or exc_in.shape[0] != k:
            raise ValueError("outcome report arrays must share one length")
        if rt_in.dtype.kind == "f":
            finite = np.isfinite(rt_in)
            # non-finite floats must not reach the int cast (UB-ish numpy
            # warning + garbage); park them at -1, counted separately below
            rt = np.where(finite, rt_in, -1.0).astype(np.int64)
        else:
            finite = np.ones(k, bool)
            rt = rt_in.astype(np.int64)
        negative = finite & (rt < 0)
        too_large = finite & (rt > P.OUTCOME_MAX_RT_MS)
        slots = self.lookup_slots(flow_ids)
        unknown = slots < 0
        valid = finite & ~negative & ~too_large & ~unknown
        n_ok = int(valid.sum())
        drops = (
            ("non_finite", int((~finite).sum())),
            ("negative", int(negative.sum())),
            ("too_large", int((too_large & ~negative).sum())),
            ("unknown_flow", int((unknown & finite & ~negative & ~too_large).sum())),
        )
        # pad to a geometric shape ladder so the jitted scatter retraces a
        # bounded number of times, not once per distinct report size
        cap = 64
        while cap < k:
            cap *= 4
        pad = cap - k
        f = self.config.max_flows
        slots_p = np.concatenate(
            [np.where(valid, slots, f).astype(np.int32),
             np.full(pad, f, np.int32)]
        )
        rt_p = np.concatenate(
            [np.where(valid, rt, 0).astype(np.int32),
             np.zeros(pad, np.int32)]
        )
        exc_p = np.concatenate(
            [(exc_in & valid).astype(np.int32), np.zeros(pad, np.int32)]
        )
        valid_p = np.concatenate([valid, np.zeros(pad, bool)])
        with self._lock:
            for reason, n in drops:
                if n:
                    d = self._outcome_counts["dropped"]
                    d[reason] = d.get(reason, 0) + n
            self._outcome_counts["batches"] += 1
            if n_ok:
                if self._outcome_step is None:
                    from sentinel_tpu.engine.outcome import (
                        outcome_step_donating,
                    )

                    self._outcome_step = outcome_step_donating(self.config)
                now = self._engine_now()
                if self._has_breakers:
                    # breakers loaded: the step additionally counts the
                    # SLOW channel against each flow's DegradeRule cutoff
                    # and resolves HALF_OPEN probes (a separate jit trace;
                    # the 6-arg form below stays bit-identical to the
                    # pre-breaker step)
                    self._state = self._outcome_step(
                        self._state,
                        jnp.asarray(slots_p),
                        jnp.asarray(rt_p),
                        jnp.asarray(exc_p),
                        jnp.asarray(valid_p),
                        jnp.int32(now),
                        self._table.br_strategy,
                        self._table.br_slow_rt_ms,
                    )
                else:
                    self._state = self._outcome_step(
                        self._state,
                        jnp.asarray(slots_p),
                        jnp.asarray(rt_p),
                        jnp.asarray(exc_p),
                        jnp.asarray(valid_p),
                        jnp.int32(now),
                    )
                self._outcome_counts["reported"] += n_ok
                n_exc = int((exc_in & valid).sum())
                self._outcome_counts["exceptions"] += n_exc
                self._outcome_counts["rt_sum_ms"] += int(rt[valid].sum())
                if self._dirty is not None:
                    touched = {int(s) for s in np.unique(slots[valid])}
                    self._dirty.setdefault("outcome", set()).update(touched)
                    if self._has_breakers:
                        # a report can resolve a probe (HALF_OPEN →
                        # CLOSED/OPEN), so reported breaker slots are
                        # breaker-dirty too
                        self._dirty.setdefault("breaker", set()).update(
                            touched & self._breaker_slots
                        )
            ns_names, slot_ns = self._ns_snapshot
        if _TR.ARMED:
            _TR.record(_TR.OUTCOME, xid=xid, aux=n_ok)
        if not n_ok:
            return 0
        log_cluster("outcome_reported", count=n_ok)
        # per-namespace fan-out to the timeline + SLO burn planes (host-side
        # aggregation off the already-validated rows; no device read)
        from sentinel_tpu.metrics.timeline import timeline as _timeline
        from sentinel_tpu.trace.slo import slo_plane as _slo_plane

        ns_idx = slot_ns[slots[valid]]
        rt_ok = rt[valid]
        exc_ok = exc_in[valid]
        tl = _timeline()
        plane = _slo_plane()
        for ni in np.unique(ns_idx):
            if ni < 0:
                continue
            name = ns_names[int(ni)]
            m = ns_idx == ni
            rts = rt_ok[m]
            n_exc_ns = int(exc_ok[m].sum())
            tl.record(
                name, 0, 0, 0, 0,
                n_complete=int(m.sum()),
                n_exception=n_exc_ns,
                rt_sum_ms=float(rts.sum()),
            )
            plane.record_completion(name, rts, n_exception=n_exc_ns)
        return n_ok

    def outcome_stats(self) -> Dict[str, object]:
        """Host snapshot of the outcome plane: ingest counters (the
        reconciliation gate's server-side truth) plus per-flow windowed
        RT/exception reads for the ``sentinel_flow_rt_*`` scrape families.
        Pulled by the process-wide ``ServerMetrics`` on every scrape."""
        from sentinel_tpu.engine.state import (
            N_RT_BUCKETS,
            OutcomeChannel,
            RT_BUCKET_UPPER_MS,
            flow_spec,
        )
        from sentinel_tpu.stats import window as W

        with self._lock:
            c = self._outcome_counts
            out: Dict[str, object] = {
                "reported": int(c["reported"]),
                "exceptions": int(c["exceptions"]),
                "rt_sum_ms": int(c["rt_sum_ms"]),
                "batches": int(c["batches"]),
                "dropped": dict(c["dropped"]),
            }
            if not self._index.slot_of:
                out["flows"] = {}
                return out
            now = self._engine_now()
            spec = flow_spec(self.config)
            sums = np.asarray(
                W.window_sum_all(spec, self._state.outcome, jnp.int32(now))
            )
            interval_s = spec.interval_ms / 1000.0
            h0 = int(OutcomeChannel.RT_HIST0)
            flows: Dict[int, Dict[str, float]] = {}
            for fid, slot in self._index.slot_of.items():
                complete = int(sums[slot, OutcomeChannel.COMPLETE])
                exc = int(sums[slot, OutcomeChannel.EXCEPTION])
                if not complete and not exc:
                    continue  # idle flows stay off the scrape surface
                rt_sum = float(sums[slot, OutcomeChannel.RT_SUM])
                hist = sums[slot, h0 : h0 + N_RT_BUCKETS]
                total = int(hist.sum())
                if total:
                    target = -(-99 * total // 100)  # ceil(0.99 * total)
                    b = int(np.searchsorted(np.cumsum(hist), target))
                    b = min(b, N_RT_BUCKETS - 1)
                    edge = RT_BUCKET_UPPER_MS[b]
                    p99 = (
                        float(edge) if edge != float("inf")
                        else float((1 << N_RT_BUCKETS) - 1)
                    )
                else:
                    p99 = 0.0
                flows[int(fid)] = {
                    "complete_qps": complete / interval_s,
                    "exception_qps": exc / interval_s,
                    "rt_avg_ms": rt_sum / complete if complete else 0.0,
                    "rt_p99_ms": p99,
                }
            out["flows"] = flows
            return out

    # -- circuit-breaker observability (engine/degrade.py host plane) --------
    _BR_STATE_NAMES = ("closed", "open", "half_open")

    def _breaker_scan(self, force: bool = False) -> None:
        """Diff the device breaker state column against the host mirror and
        fold observed transitions into ``ServerMetrics`` (the
        ``sentinel_breaker_transitions_total{from,to}`` edges) plus a
        rate-limited blackbox dump on a trip to OPEN. The device is the
        authority — transitions happen inside the decide/outcome steps with
        no host round-trip — so this scan sees edges at its own cadence: a
        breaker that OPENs and recovers between two scans reports the net
        edge, not the intermediate states. ``force`` skips the ~1/s rate
        limit (scrape and drill paths; the serving materializer only scans
        when a batch actually produced DEGRADED verdicts)."""
        if not self._has_breakers:
            return
        edges: Dict[Tuple[int, int], int] = {}
        tripped: List[object] = []
        flips: List[Tuple[int, int]] = []  # (flow_id, new state) per edge
        with self._lock:
            now_s = time.monotonic()
            if not force and now_s - self._breaker_scan_ts < 1.0:
                return
            self._breaker_scan_ts = now_s
            st = np.array(np.asarray(self._state.breaker.state))
            prev = self._breaker_prev
            self._breaker_prev = st
            if prev is None:
                # first observation since the (re)load: surface non-CLOSED
                # states (a snapshot restore's open breakers) as edges
                # from CLOSED rather than losing them
                prev = np.zeros_like(st)
            changed = np.nonzero(st != prev)[0]
            if changed.size == 0:
                return
            rev = {v: k for k, v in self._index.slot_of.items()}
            for s in changed.tolist():
                if s not in self._breaker_slots:
                    continue  # stale mirror rows of dropped rules
                frm, to = int(prev[s]), int(st[s])
                edges[(frm, to)] = edges.get((frm, to), 0) + 1
                fid = rev.get(s)
                if fid is not None:
                    flips.append((int(fid), to))
                if to == 1:  # BR_OPEN
                    tripped.append(rev.get(s, s))
        names = self._BR_STATE_NAMES
        for (frm, to), count in edges.items():
            _SM.count_breaker_transition(
                names[frm] if frm < 3 else str(frm),
                names[to] if to < 3 else str(to),
                count,
            )
        # rev-7 push: every observed edge goes to the clients — OPEN parks
        # their local admission clocks (retry-after = the rule's recovery
        # timeout, the earliest the device could HALF_OPEN), CLOSED and
        # HALF_OPEN lift them so probe traffic reaches the wire again
        for fid, to in flips:
            retry = 0
            if to == 1:
                rule = self._degrade_rules_src.get(fid)
                retry = int(getattr(rule, "recovery_timeout_ms", 0) or 0)
            self._emit_push("push_breaker_flip", fid, to, retry)
        if tripped:
            from sentinel_tpu.trace import blackbox as _blackbox

            _blackbox.maybe_dump(
                "breaker_open:" + ",".join(str(f) for f in tripped)
            )

    def breaker_stats(self) -> Dict[str, object]:
        """Host snapshot of the breaker plane: per-flow state (read from
        the device ``BreakerState`` columns) plus clock ages, for the
        ``sentinel_breaker_state`` gauge and the ``breaker`` block of
        ``clusterServerStats``. Scans for transitions first, so a scrape
        is also the liveness floor of the transition counters."""
        if not self._has_breakers:
            return {}
        self._breaker_scan(force=True)
        from sentinel_tpu.stats.window import NEVER as _WNEVER

        names = self._BR_STATE_NAMES
        with self._lock:
            br = self._state.breaker
            st = np.asarray(br.state)
            opened = np.asarray(br.opened_ms)
            probe = np.asarray(br.probe_ms)
            now = self._engine_now()
            flows: Dict[int, Dict[str, object]] = {}
            for fid, rule in self._degrade_rules_src.items():
                slot = self._index.slot_of.get(fid)
                if slot is None:
                    continue
                code = int(st[slot])
                entry: Dict[str, object] = {
                    "state": names[code] if code < 3 else str(code),
                    "state_code": code,
                    "strategy": int(rule.strategy),
                }
                if int(opened[slot]) != int(_WNEVER):
                    entry["since_transition_ms"] = (
                        int(now) - int(opened[slot])
                    )
                if int(probe[slot]) != int(_WNEVER):
                    entry["probe_age_ms"] = int(now) - int(probe[slot])
                flows[int(fid)] = entry
            return {"rules": len(self._degrade_rules_src), "flows": flows}
