"""Elastic fleet: epoch-fenced live shard rebalancing (ISSUE 8 tentpole).

The static namespace→pod layout (:mod:`sentinel_tpu.cluster.namespaces`)
becomes movable *under traffic* with zero over-admission and zero lost
tokens. Three pieces live here:

- :class:`ShardMap` — an epoch-numbered namespace→endpoint map published
  through the existing :class:`~sentinel_tpu.core.property.DynamicProperty`
  plane (data sources push new maps; routing clients subscribe). Epochs are
  the fence: a client holding epoch *e* learns passively that it is stale
  when a ``MOVED`` verdict arrives carrying *e' > e* in its ``remaining``
  field, and never applies a map older than the one it holds.

- :class:`MoveCoordinator` — the source-side driver of the two-phase
  drain-and-move protocol on wire-rev-4 frames::

      source                               destination
        begin_move(ns)  (flows now answer MOVED, counters frozen)
        MOVE_BEGIN(epoch, ns)  ───────────▶  stage pending-begin
                        ◀─────────── REPL_ACK OK
        MOVE_STATE chunks (slim sums) ────▶  decode + STAGE (no mutation)
                        ◀─────────── REPL_ACK OK
        MOVE_COMMIT(epoch, ns) ───────────▶  import_namespace_state(doc)
                        ◀─────────── REPL_ACK OK
        (redirect tombstone stays until end_redirect)

  Any failure before the COMMIT ack — timeout, connection loss, chaos
  injection, a destination ERROR ack — runs the abort path: best-effort
  ``MOVE_ABORT`` plus ``service.abort_move(ns)``, which is lossless by
  construction because MOVED-masked requests never touched the counters.
  Crash matrix (who owns ``ns`` after a SIGKILL):

  ========================  ==========================================
  crash point               owner
  ========================  ==========================================
  source before COMMIT      source restart owns (dest staging expires)
  source after COMMIT sent  destination (it imported before acking)
  dest before COMMIT        source (ack timeout → abort_move restores)
  dest after COMMIT ack     destination (import completed before ack)
  ========================  ==========================================

  Exactly one owner in every row: the destination mutates state only at
  COMMIT, and the source frees its claim only on abort (restore) or
  ``end_redirect`` (release) — never both.

- :class:`MoveTarget` — the destination side, one
  :class:`MoveSession` per inbound connection behind either front door
  (the doors route ``MOVE_TYPES`` frames here exactly like rev-3 repl
  frames route to :class:`~sentinel_tpu.ha.replication.ReplSession`).
  State is STAGED on ``MOVE_STATE`` and applied only on ``MOVE_COMMIT``;
  staging is discarded on abort, disconnect, or deadline expiry.

The shipped document is the *slim* representation — per-row live-window
sums, not ring buckets (SF-sketch's fat-update/slim-query split applied to
handoff): ring- and epoch-free, so the destination folds it into its own
current bucket regardless of clock skew, and typically ~100× smaller than
a full snapshot of the same rows.

Leases (wire rev 5) cross a move as "transfer the charge, recall the
lease": ``begin_move`` revokes the source's lease registry for the
namespace (renewals answer MOVED and fall back to per-request RPCs), while
the LEASED event column — the full delegated charge — rides ``flow_sums``
to the destination like any other window sum. The destination therefore
keeps counting every outstanding delegated token against the global limit
from its first imported window, and clients re-grant fresh leases there;
no lease survives a move, but no delegated token escapes accounting.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from sentinel_tpu import chaos as _chaos
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.log import record_log
from sentinel_tpu.core.property import DynamicProperty
from sentinel_tpu.ha.snapshot import _dec_array, _enc_array
from sentinel_tpu.metrics.ha import ha_metrics

MOVE_STATE_VERSION = 1


# -- shard map ----------------------------------------------------------------
@dataclass(frozen=True)
class ShardMap:
    """Epoch-numbered namespace→endpoint assignment. Immutable; every
    change is a NEW map with a strictly larger epoch, so "is this map
    newer" is one integer compare — the fence stale clients are measured
    against.

    ``global_flows`` is the hierarchy tier's section: flow_id (as str —
    the map is a JSON document) → the global budget coordinator's
    endpoint. It rides the SAME epoch fence, so coordinator failover,
    MOVE, and routing all agree on one monotonic version — a stale map
    can no more point an agent at a dead coordinator than it can point a
    client at a moved namespace."""

    epoch: int = 0
    endpoint_of: Mapping[str, str] = field(default_factory=dict)
    global_flows: Mapping[str, str] = field(default_factory=dict)

    def assign(self, namespace: str, endpoint: str) -> "ShardMap":
        """Next-epoch map with ``namespace`` moved to ``endpoint``."""
        m = dict(self.endpoint_of)
        m[namespace] = endpoint
        return ShardMap(self.epoch + 1, m, dict(self.global_flows))

    def assign_global(self, flow_id, endpoint: str) -> "ShardMap":
        """Next-epoch map with ``flow_id``'s global budget coordinator at
        ``endpoint`` (pass ``None``/empty to delist the flow)."""
        g = dict(self.global_flows)
        if endpoint:
            g[str(int(flow_id))] = str(endpoint)
        else:
            g.pop(str(int(flow_id)), None)
        return ShardMap(self.epoch + 1, dict(self.endpoint_of), g)

    def coordinator_of(self, flow_id) -> Optional[str]:
        return self.global_flows.get(str(int(flow_id)))

    def to_doc(self) -> Dict[str, object]:
        return {
            "epoch": int(self.epoch),
            "endpoints": dict(self.endpoint_of),
            "global_flows": dict(self.global_flows),
        }

    @staticmethod
    def from_doc(doc: Mapping[str, object]) -> "ShardMap":
        return ShardMap(
            int(doc["epoch"]),
            {str(k): str(v) for k, v in dict(doc["endpoints"]).items()},
            # absent in pre-hierarchy documents — back-compat default
            {
                str(k): str(v)
                for k, v in dict(doc.get("global_flows") or {}).items()
            },
        )


class ShardMapPublisher:
    """The property-plane head of the shard map: holds the current
    :class:`ShardMap` in a :class:`DynamicProperty` (so any existing data
    source can feed it and any listener — routing clients, dashboards —
    subscribes with the same API rules use) and enforces the epoch fence
    on publish: an older-or-equal epoch never overwrites a newer map."""

    def __init__(self, prop: Optional[DynamicProperty] = None):
        self.property: DynamicProperty = (
            prop if prop is not None else DynamicProperty(ShardMap())
        )
        if self.property.value is None:
            self.property.update_value(ShardMap())
        self._lock = threading.Lock()

    def current(self) -> ShardMap:
        return self.property.value or ShardMap()

    def publish(self, shard_map: ShardMap) -> bool:
        """Install ``shard_map`` if its epoch is newer. Returns False (and
        publishes nothing) for a stale or same-epoch map."""
        with self._lock:
            cur = self.current()
            if shard_map.epoch <= cur.epoch:
                return False
            return self.property.update_value(shard_map)

    def listen(self, fn: Callable[[Optional[ShardMap]], None]):
        return self.property.listen(fn)


# -- shard-map doc codec (rev-7 SHARD_MAP_PUSH payload) -----------------------
def encode_shard_map_doc(shard_map: ShardMap) -> bytes:
    """``ShardMap`` → compressed JSON blob for the SHARD_MAP_PUSH data
    section. Same zlib+JSON idiom as the move-state blob; the push frame
    treats it as opaque bytes."""
    return zlib.compress(
        json.dumps(shard_map.to_doc(), separators=(",", ":")).encode("utf-8")
    )


def decode_shard_map_doc(blob: bytes) -> ShardMap:
    """Inverse of :func:`encode_shard_map_doc`. Raises ValueError only, so
    client push dispatch can contain a torn or hostile payload without
    dropping the connection."""
    try:
        doc = json.loads(zlib.decompress(bytes(blob)).decode("utf-8"))
        return ShardMap.from_doc(doc)
    except ValueError:
        raise
    except Exception as exc:
        raise ValueError(f"bad shard map doc: {exc}") from exc


# -- move-state blob codec ----------------------------------------------------
def encode_move_state_blob(doc: Dict[str, object]) -> bytes:
    """``export_namespace_state()`` document → compressed wire blob (rules
    serialize with the ha.snapshot idiom, arrays with its base64+zlib
    codec)."""
    from sentinel_tpu.engine.rules import encode_degrade_rule as _enc_degrade
    from sentinel_tpu.engine.rules import encode_rule as _encode_rule

    out: Dict[str, object] = {
        "version": MOVE_STATE_VERSION,
        "namespace": doc["namespace"],
        "wall_ms": int(doc["wall_ms"]),
        "interval_ms": int(doc["interval_ms"]),
        "rules": [_encode_rule(r) for r in doc["rules"]],
        "param_rules": [
            {
                "flow_id": r.flow_id,
                "count": r.count,
                "item_thresholds": [
                    [int(h), float(c)] for h, c in (r.item_thresholds or ())
                ],
                "namespace": r.namespace,
            }
            for r in doc["param_rules"]
        ],
        "flow_ids": [int(f) for f in doc["flow_ids"]],
        "flow_sums": _enc_array(doc["flow_sums"]),
        "occupy_sums": _enc_array(doc["occupy_sums"]),
        "ns_sum": _enc_array(doc["ns_sum"]),
        "param_fids": [int(f) for f in doc["param_fids"]],
        "param_sums": _enc_array(doc["param_sums"]),
    }
    # shaper clocks (relative-to-export-now; absent in pre-shaping exports)
    for k in (
        "shaping_lpt_rel", "shaping_warm_tokens", "shaping_warm_filled_rel"
    ):
        if k in doc:
            out[k] = _enc_array(doc[k])
    # the breaker plane: its rules, the moved flows' completion windows,
    # and the state columns with relative clocks (absent in pre-breaker
    # exports — the destination then starts those flows CLOSED/cold)
    if doc.get("degrade_rules"):
        out["degrade_rules"] = [_enc_degrade(d) for d in doc["degrade_rules"]]
    for k in (
        "outcome_sums", "breaker_state",
        "breaker_opened_rel", "breaker_probe_rel",
    ):
        if k in doc:
            out[k] = _enc_array(doc[k])
    return zlib.compress(json.dumps(out, separators=(",", ":")).encode())


def decode_move_state_blob(blob: bytes) -> Dict[str, object]:
    """Wire blob → the dict ``import_namespace_state`` consumes. Raises
    ``ValueError`` on any malformed input (fuzz-safe — corrupt bytes must
    never kill the destination door)."""
    from sentinel_tpu.cluster.token_service import ClusterParamFlowRule
    from sentinel_tpu.engine.rules import decode_degrade_rule as _dec_degrade
    from sentinel_tpu.engine.rules import decode_rule as _decode_rule

    try:
        out = json.loads(zlib.decompress(blob).decode())
        if out.pop("version", None) != MOVE_STATE_VERSION:
            raise ValueError("unsupported move-state version")
        return {
            "namespace": str(out["namespace"]),
            "wall_ms": int(out["wall_ms"]),
            "interval_ms": int(out["interval_ms"]),
            "rules": [_decode_rule(r) for r in out["rules"]],
            "param_rules": [
                ClusterParamFlowRule(
                    int(r["flow_id"]), float(r["count"]),
                    tuple(
                        (int(h), float(c)) for h, c in r["item_thresholds"]
                    ) or None,
                    str(r["namespace"]),
                )
                for r in out["param_rules"]
            ],
            "flow_ids": [int(f) for f in out["flow_ids"]],
            "flow_sums": _dec_array(out["flow_sums"]),
            "occupy_sums": _dec_array(out["occupy_sums"]),
            "ns_sum": _dec_array(out["ns_sum"]),
            "param_fids": [int(f) for f in out["param_fids"]],
            "param_sums": _dec_array(out["param_sums"]),
            **{
                k: _dec_array(out[k])
                for k in (
                    "shaping_lpt_rel",
                    "shaping_warm_tokens",
                    "shaping_warm_filled_rel",
                    "outcome_sums",
                    "breaker_state",
                    "breaker_opened_rel",
                    "breaker_probe_rel",
                )
                if k in out
            },
            **(
                {
                    "degrade_rules": [
                        _dec_degrade(d) for d in out["degrade_rules"]
                    ]
                }
                if "degrade_rules" in out else {}
            ),
        }
    except ValueError:
        raise
    except Exception as e:  # zlib.error, KeyError, TypeError, ...
        raise ValueError(f"malformed move-state blob: {e}") from None


# -- source side --------------------------------------------------------------
class MoveFailed(Exception):
    """The move aborted (source still owns the namespace). ``str()`` names
    the failing step — the drill and chaos tests assert on it."""


class MoveCoordinator:
    """Source-side driver of one-namespace-at-a-time live moves.

    Socket discipline mirrors :class:`~sentinel_tpu.ha.replication
    .ReplicationSender`: one blocking TCP connection per move, TCP_NODELAY,
    every frame acked with REPL_ACK inside ``ack_timeout_s``, chaos
    ``lane_delay``/``conn_reset`` probes on every outbound frame. The
    optional ``on_step`` hook fires with ``"begin"`` / ``"state"`` /
    ``"commit"`` just before each protocol step's frames go out — the
    deterministic kill-point the chaos tests hang their injections on.
    """

    def __init__(
        self,
        service,
        self_endpoint: str = "",
        publisher: Optional[ShardMapPublisher] = None,
        ack_timeout_s: float = 5.0,
        on_step: Optional[Callable[[str], None]] = None,
    ):
        self.service = service
        self.self_endpoint = self_endpoint
        self.publisher = publisher
        self.ack_timeout_s = float(ack_timeout_s)
        self.on_step = on_step
        self._xid = 0
        self.last_error: Optional[str] = None

    # -- protocol steps ------------------------------------------------------
    def move_namespace(
        self,
        namespace: str,
        dest: str,
        epoch: Optional[int] = None,
    ) -> bool:
        """Drain-and-move ``namespace`` to ``dest`` ("host:port"). Returns
        True on commit (the destination owns the namespace; this side keeps
        answering MOVED until :meth:`release`), False on abort (this side
        still owns it, counters untouched — ``last_error`` says why).
        ``epoch`` defaults to the publisher's next epoch."""
        if epoch is None:
            if self.publisher is None:
                raise ValueError("epoch required without a publisher")
            epoch = self.publisher.current().epoch + 1
        begun_wall = _clock.now_ms()
        self.last_error = None
        sock: Optional[socket.socket] = None
        began = False
        try:
            host, _, port = dest.rpartition(":")
            sock = socket.create_connection(
                (host, int(port)), timeout=self.ack_timeout_s
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            buf = [b""]

            # step 1: BEGIN — freeze the namespace (flows answer MOVED from
            # this point; their counters stop moving) and reserve the claim
            # on the destination
            self._hook("begin")
            self.service.begin_move(namespace, dest, int(epoch))
            began = True
            ha_metrics().count_rebalance("begin")
            self._send(sock, P.encode_move_ctrl(
                self._next_xid(), P.MsgType.MOVE_BEGIN, int(epoch),
                namespace, self.self_endpoint,
            ))
            self._expect_ok(sock, buf, "begin")

            # step 2: STATE — ship the slim representation; the destination
            # stages it without mutating anything
            self._hook("state")
            doc = self.service.export_namespace_state(namespace)
            blob = encode_move_state_blob(doc)
            frames = P.encode_repl_blob(
                self._next_xid(), P.MsgType.MOVE_STATE,
                int(self.service.state_generation()), int(epoch), blob,
            )
            for frame in frames:
                self._send(sock, frame)
            ha_metrics().add_rebalance_state_bytes(
                sum(len(f) for f in frames)
            )
            self._expect_ok(sock, buf, "state")

            # step 3: COMMIT — the destination imports atomically before
            # acking; after this ack there is exactly one owner: them
            self._hook("commit")
            self._send(sock, P.encode_move_ctrl(
                self._next_xid(), P.MsgType.MOVE_COMMIT, int(epoch),
                namespace, self.self_endpoint,
            ))
            self._expect_ok(sock, buf, "commit")
        except Exception as e:
            self.last_error = f"{type(e).__name__}: {e}"
            record_log.warning(
                "move of %r to %s aborted: %s", namespace, dest,
                self.last_error,
            )
            if began:
                self._abort(sock, namespace, int(epoch))
            self._close(sock)
            ha_metrics().count_rebalance("abort")
            return False
        self._close(sock)
        ha_metrics().count_rebalance("commit")
        ha_metrics().observe_move_ms(max(0, _clock.now_ms() - begun_wall))
        if self.publisher is not None:
            self.publisher.publish(
                self.publisher.current().assign(namespace, dest)
            )
        return True

    def release(self, namespace: str) -> None:
        """Drop the post-commit MOVED tombstone (and the namespace's rules)
        once clients have converged on the new owner."""
        self.service.end_redirect(namespace)

    # -- plumbing ------------------------------------------------------------
    def _hook(self, step: str) -> None:
        if self.on_step is not None:
            self.on_step(step)

    def _next_xid(self) -> int:
        self._xid += 1
        return self._xid

    def _send(self, sock: socket.socket, frame: bytes) -> None:
        if _chaos.ARMED:
            _chaos.maybe_sleep("lane_delay")
            if _chaos.should("conn_reset"):
                raise ConnectionResetError("chaos: move conn_reset")
        sock.sendall(frame)

    def _expect_ok(self, sock, buf: List[bytes], step: str) -> None:
        code = self._read_ack(sock, buf)
        if code != P.ReplAck.OK:
            raise MoveFailed(f"destination refused {step}: {code.name}")

    def _read_ack(self, sock: socket.socket, buf: List[bytes]) -> P.ReplAck:
        """Block for the next REPL_ACK on the move channel (same framing as
        the repl channel's ack read)."""
        sock.settimeout(self.ack_timeout_s)
        data = buf[0]
        while True:
            if len(data) >= 2:
                (length,) = struct.unpack_from(">H", data, 0)
                if len(data) >= 2 + length:
                    payload = data[2 : 2 + length]
                    buf[0] = data[2 + length :]
                    if (
                        len(payload) < 5
                        or P.peek_type(payload) != P.MsgType.REPL_ACK
                    ):
                        raise ConnectionError(
                            "non-ack frame on move channel"
                        )
                    _xid, code, _gen, _seq = P.decode_repl_ack(payload)
                    return code
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("move channel closed by destination")
            data = buf[0] = buf[0] + chunk

    def _abort(self, sock, namespace: str, epoch: int) -> None:
        """Best-effort MOVE_ABORT + local restore. The local restore is the
        part that matters for ownership (it is unconditional); the wire
        abort just lets the destination free its staging early instead of
        waiting out the deadline."""
        try:
            if sock is not None:
                sock.sendall(P.encode_move_ctrl(
                    self._next_xid(), P.MsgType.MOVE_ABORT, epoch,
                    namespace, self.self_endpoint,
                ))
        except OSError:
            pass
        self.service.abort_move(namespace)

    @staticmethod
    def _close(sock) -> None:
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


# -- destination side ---------------------------------------------------------
class _Staged:
    """One staged (not yet committed) inbound move."""

    __slots__ = ("namespace", "epoch", "peer", "doc", "deadline_ms",
                 "session_id")

    def __init__(self, namespace, epoch, peer, deadline_ms, session_id):
        self.namespace = namespace
        self.epoch = int(epoch)
        self.peer = peer
        self.doc: Optional[Dict[str, object]] = None
        self.deadline_ms = deadline_ms
        self.session_id = session_id


class MoveTarget:
    """Destination-side move handler shared by both front doors.

    Staging discipline: ``MOVE_BEGIN`` reserves a claim, ``MOVE_STATE``
    attaches the decoded document, and ONLY ``MOVE_COMMIT`` mutates the
    service (``import_namespace_state`` validates before touching state, so
    a failed import leaves this side clean and acks ERROR — the source then
    aborts and keeps ownership). Staging dies three ways: an explicit
    ``MOVE_ABORT``, the connection closing (a SIGKILLed source must not
    leave a claim behind), or ``stage_ttl_ms`` expiring (belt and braces
    for a source that wedges without closing the socket)."""

    def __init__(self, service, stage_ttl_ms: float = 10_000.0):
        self.service = service
        self.stage_ttl_ms = float(stage_ttl_ms)
        self._lock = threading.Lock()
        self._staged: Dict[str, _Staged] = {}  # namespace → claim
        self._session_seq = 0

    def connection(self) -> "MoveSession":
        with self._lock:
            self._session_seq += 1
            return MoveSession(self, self._session_seq)

    def status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "staged": [
                    {"namespace": s.namespace, "epoch": s.epoch,
                     "peer": s.peer, "hasState": s.doc is not None}
                    for s in self._staged.values()
                ],
            }

    # -- protocol steps (called from sessions) -------------------------------
    def _sweep_locked(self) -> None:
        now = _clock.now_ms()
        for ns in [
            ns for ns, s in self._staged.items() if now >= s.deadline_ms
        ]:
            record_log.warning(
                "staged move of %r expired unclaimed; discarding", ns
            )
            del self._staged[ns]

    def _begin(self, session_id, namespace, epoch, peer) -> int:
        with self._lock:
            self._sweep_locked()
            cur = self._staged.get(namespace)
            if cur is not None and cur.session_id != session_id:
                # two sources claiming one namespace is a split brain —
                # refuse the newcomer, keep the live claim
                record_log.warning(
                    "refusing concurrent move claim on %r (held by %s)",
                    namespace, cur.peer,
                )
                return int(P.ReplAck.ERROR)
            self._staged[namespace] = _Staged(
                namespace, epoch, peer,
                _clock.now_ms() + self.stage_ttl_ms, session_id,
            )
        ha_metrics().count_rebalance("begin")
        return int(P.ReplAck.OK)

    def _stage(self, session_id, epoch, blob) -> int:
        try:
            doc = decode_move_state_blob(blob)
        except ValueError as e:
            record_log.warning("move-state blob refused: %s", e)
            return int(P.ReplAck.ERROR)
        with self._lock:
            self._sweep_locked()
            s = self._staged.get(doc["namespace"])
            if s is None or s.session_id != session_id or s.epoch != epoch:
                return int(P.ReplAck.ERROR)
            s.doc = doc
            s.deadline_ms = _clock.now_ms() + self.stage_ttl_ms
        return int(P.ReplAck.OK)

    def _commit(self, session_id, namespace, epoch) -> int:
        with self._lock:
            self._sweep_locked()
            s = self._staged.get(namespace)
            if (
                s is None or s.session_id != session_id
                or s.epoch != epoch or s.doc is None
            ):
                return int(P.ReplAck.ERROR)
            del self._staged[namespace]
            doc = s.doc
        try:
            self.service.import_namespace_state(doc)
        except Exception:
            record_log.exception("move import of %r failed", namespace)
            return int(P.ReplAck.ERROR)
        ha_metrics().count_rebalance("commit")
        return int(P.ReplAck.OK)

    def _abort(self, session_id, namespace) -> int:
        with self._lock:
            s = self._staged.get(namespace)
            if s is not None and s.session_id == session_id:
                del self._staged[namespace]
        ha_metrics().count_rebalance("abort")
        return int(P.ReplAck.OK)

    def _session_closed(self, session_id) -> None:
        with self._lock:
            for ns in [
                ns for ns, s in self._staged.items()
                if s.session_id == session_id
            ]:
                record_log.warning(
                    "move channel for %r closed before commit; discarding "
                    "staged state", ns,
                )
                del self._staged[ns]


class MoveSession:
    """One move connection's state behind a front door: the chunk
    reassembler plus ack plumbing, mirroring
    :class:`~sentinel_tpu.ha.replication.ReplSession`. ``handle(payload,
    send)`` consumes one rev-4 frame; ``closed()`` must be called when the
    connection drops so staged state from a crashed source is discarded.
    Raises ``ValueError`` on a torn chunk stream (the door drops the
    connection, same contract as ``decode_request``)."""

    def __init__(self, target: MoveTarget, session_id: int):
        self.target = target
        self.session_id = session_id
        self._asm = P.ReplBlobAssembler()

    def handle(self, payload: bytes, send: Callable[[bytes], None]) -> None:
        mtype = P.peek_type(payload)
        if mtype == P.MsgType.MOVE_STATE:
            done = self._asm.feed(mtype, payload)
            if done is None:
                return
            _t, gen, epoch, blob = done
            code = self.target._stage(self.session_id, epoch, blob)
            send(P.encode_repl_ack(P.peek_xid(payload), code, gen, epoch))
            return
        xid, epoch, namespace, _peer = P.decode_move_ctrl(payload)
        if mtype == P.MsgType.MOVE_BEGIN:
            code = self.target._begin(self.session_id, namespace, epoch,
                                      _peer)
        elif mtype == P.MsgType.MOVE_COMMIT:
            code = self.target._commit(self.session_id, namespace, epoch)
        elif mtype == P.MsgType.MOVE_ABORT:
            code = self.target._abort(self.session_id, namespace)
        else:
            raise ValueError(f"unexpected frame on move channel: {mtype}")
        send(P.encode_repl_ack(xid, code, epoch, epoch))

    def closed(self) -> None:
        self.target._session_closed(self.session_id)
