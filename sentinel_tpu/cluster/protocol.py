"""Binary wire protocol for the token RPC.

Same shape as the reference's netty codec (``sentinel-cluster-common-default``):
a 2-byte big-endian length prefix (``LengthFieldBasedFrameDecoder(1024,0,2,0,2)``,
``NettyTransportServer.java:73-101``), then::

    | xid: int32 | type: uint8 | data... |

Request types (``ClusterConstants.java:24-28``): PING=0, FLOW=1, PARAM_FLOW=2,
CONCURRENT_ACQUIRE=3, CONCURRENT_RELEASE=4.

Flow request data  = ``flow_id:int64, count:int32, priority:uint8``
(``FlowRequestDataWriter.java:35-37``); flow responses carry
``status:int8, remaining:int32, wait_ms:int32`` (the reference moves status in
the response envelope and ``remaining/waitInMs`` in data,
``FlowResponseDataWriter.java:31-32`` — flattened here).

Param-flow request data = flow request + ``n_params:uint8`` + per-param
``hash:int64`` (the TPU server sketches param *hashes*; raw values never cross
the wire — see SURVEY.md §5 long-context note).

Concurrent (cluster-semaphore) messages: CONCURRENT_ACQUIRE uses the flow
request layout; its response appends ``token_id:int64`` (the reference moves
the token id in ``ConcurrentFlowAcquireResponseData``). CONCURRENT_RELEASE
reuses the ``flow_id`` slot to carry the token id being released
(``ConcurrentFlowReleaseRequestData`` carries only ``tokenId``).

BATCH_FLOW (TPU extension, no reference analog): one frame carries N flow
requests and one response frame carries their N verdicts — the client-side
mirror of the server's micro-batcher. Request data = ``n:uint16`` +
n × ``(flow_id:int64, count:int32, priority:uint8)``; response data =
``n:uint16`` + n × ``(status:int8, remaining:int32, wait_ms:int32)``.
Verdict order matches request order. Encode/decode are vectorized (numpy
structured dtypes, or the native C codec when built) — per-request Python
cost is what capped the round-2 front door at ~5k rps.

Codec rev 3 — replication frames (``sentinel_tpu.ha.replication``): a
primary token server streams state to warm standbys over the SAME wire as
the data plane (both front doors route the new type bytes to their control
planes; the C++ door forwards every non-data-plane type untouched, so no
native rebuild is needed):

- ``REPL_HELLO``: ``gen:int64, epoch_ms:int64, last_seq:int64`` + a UTF-8
  sender id — the primary's sync probe; the standby's REPL_ACK answer says
  whether it can take deltas for this (generation, epoch) or needs a full
  snapshot first.
- ``REPL_DELTA`` / ``REPL_SNAPSHOT``: a zlib blob (JSON document) CHUNKED
  across frames — ``gen:int64, seq:int64, idx:uint16, total:uint16`` +
  chunk bytes; a full snapshot easily exceeds the 2-byte frame cap, and
  chunking keeps replication inside MAX_FRAME instead of forking the
  length prefix. The standby acks once the last chunk lands.
- ``REPL_ACK``: ``code:uint8, gen:int64, seq:int64`` — OK / NEED_SNAPSHOT
  (resync) / NOT_STANDBY (promoted or misconfigured peer) / ERROR.

Codec rev 4 — live-rebalance frames (``sentinel_tpu.cluster.rebalance``):
a source token server hands one namespace's counter state to a live
destination over the same wire, two-phase:

- ``MOVE_BEGIN`` / ``MOVE_COMMIT`` / ``MOVE_ABORT``: ``epoch:int64`` +
  ``ns_len:uint16`` + namespace UTF-8 + peer-id UTF-8 — the control steps
  of the drain-and-move protocol. The destination answers each with a
  REPL_ACK (OK / ERROR), reusing the rev-3 ack frame.
- ``MOVE_STATE``: the namespace's exported counter document, chunked with
  the SAME ``(gen, seq, idx, total)`` layout as REPL_DELTA/REPL_SNAPSHOT
  (``encode_repl_blob`` accepts MOVE_STATE; ``ReplBlobAssembler``
  reassembles it) — the move channel inherits replication's framing,
  chaos instrumentation, and torn-stream detection.
- a ``MOVED`` (= 10) status on the single-request response path appends
  the new owner's ``host:port`` endpoint as a UTF-8 trailer; batch rows
  stay fixed-size and carry the shard-map epoch in ``remaining``.

Codec rev 5 — token-lease frames (client-local admission): the token
service grants a client a short-TTL slice of a flow's window; the client
admits locally from the lease and reports usage on renew/return. All
three request types share ONE fixed layout (simpler codec, one fuzz
surface)::

    | lease_id: int64 | flow_id: int64 | used: int32 | want: int32 |

- ``LEASE_GRANT``: ``lease_id``/``used`` are 0; ``want`` is the token
  count requested.
- ``LEASE_RENEW``: reports ``used`` tokens consumed from ``lease_id``
  since the last report (the server credits the unused remainder when
  provably still in-window) and asks for a fresh ``want``-token slice.
- ``LEASE_RETURN``: final usage report; ``want`` is 0.

Responses share one layout too: ``status:int8, lease_id:int64,
tokens:int32, ttl_ms:int32`` — ``status`` is a ``TokenStatus`` byte. OK
carries a live lease; NOT_LEASABLE (= 11) is the refusal (flow not
leasable, no headroom, lease revoked) telling the client to fall back to
per-request RPCs and back off leasing this flow; MOVED appends the new
owner's endpoint as the rev-4 UTF-8 trailer. Both doors route the lease
type bytes to the token service's host-side lease handler (the C++ door
forwards non-data-plane bytes untouched, so no native rebuild).

Rev-5 family, hierarchy tier — pods lease provisioned SHARES of a global
flow budget from the cluster's budget coordinator, exactly as clients
lease slices from a pod, one level up:

- ``SHARE_GRANT`` / ``SHARE_RENEW`` / ``SHARE_RETURN`` reuse the lease
  request AND response layouts byte for byte (``lease_id`` is the share
  id, ``want``/``tokens`` are share tokens, ``ttl_ms`` is the share TTL).
  Distinct type bytes — not a flag — because the coordinator runs
  co-located with a pod behind the SAME door: a LEASE_GRANT for global
  flow F is a client leasing from that pod's local window, a SHARE_GRANT
  for F is a pod leasing from the global ledger.
- ``DEMAND_REPORT`` carries a pod's per-tick observed demand:
  ``pod_len:uint16, n_entries:uint16`` + pod-id UTF-8 + ``n_entries`` ×
  ``(flow_id:int64, share_id:int64, rate_milli:int64)``. Rates ride as
  milli-tokens/s so sub-token arrival rates survive the integer wire.
  The coordinator answers with the shared lease-response frame
  (``tokens`` = entries accepted); NOT_LEASABLE means "no coordinator
  attached here" and the agent should walk its endpoint list.

Both doors route ``HIER_TYPES`` to the service's attached coordinator
(``service.hierarchy``); a standby answers STANDBY like any other
control op, so agent-side failover walks on.

Codec rev 6 — batched outcome reports (the completion-telemetry plane):
clients record per-entry completion (RT ms, success/exception) locally and
coalesce them into ONE fire-and-forget frame, piggy-backed in front of the
next request frame on the same connection (the shm door publishes it as its
own ring slot — one slot carries exactly one frame). Data =
``n:uint16`` + n × ``(flow_id:int64, rt_ms:int32, exc:uint8)``::

    | flow_id: int64 | rt_ms: int32 | exc: uint8 |

There is NO response frame: outcome telemetry is best-effort by design, so
the lease/request fast path stays at zero extra RPCs and a server that
predates rev 6 simply drops the unknown type byte. RT values are validated
server-side at this wire boundary (negative / oversized values are counted
into ``sentinel_outcome_dropped_total`` rather than scattered) — see
``OUTCOME_MAX_RT_MS`` below.

Codec rev 7 — PUSH frames (the server→client push control plane):
unsolicited server→client frames carried on the SAME connections the data
plane already holds (TCP streams and the shm ring's response lane). They
are the inverse of every frame above — the server originates them, the
client never answers — and they cut worst-case control staleness from
TTL/tick scale to one RTT. All five share one envelope::

    | xid: int32 | type: uint8 | stamp_ms: int64 | data... |

``xid`` is a server-assigned push sequence (clients treat it as opaque;
the staleness probe stamps known xids), ``stamp_ms`` is the server's wall
clock at emit time — the client-side apply records
``now_ms - stamp_ms`` into the ``sentinel_push_staleness_ms`` histogram.

- ``LEASE_REVOKE``: ``lease_id:int64, flow_id:int64, tokens:int32`` —
  the server recalled this lease (rule reload, MOVE drain, breaker flip
  on the leased flow). The client credits nothing back to the server
  (charge-at-grant means the server already reclaimed the unused slice);
  it drops the ``_FlowLease`` immediately so local admits stop now
  instead of at TTL expiry.
- ``BREAKER_FLIP``: ``flow_id:int64, state:int8, retry_after_ms:int32``
  — a device-resident breaker transition (CLOSED/OPEN/HALF_OPEN, the
  DEGRADE.md state codes). OPEN makes the client answer DEGRADED locally
  (with the pushed retry-after) until the clock expires; CLOSED clears
  the local clock.
- ``RULE_EPOCH_INVALIDATE``: ``epoch:int64`` — the server's rule state
  generation bumped (``load_rules``); every cached lease and lease
  backoff for that server is stale. Clients drop them and re-fetch.
- ``SHARD_MAP_PUSH``: a zlib-compressed ShardMap JSON doc (``to_doc``);
  the doc carries its own epoch and feeds the client's epoch-fenced
  ``apply_shard_map`` learn path — a stale push is a no-op by the same
  fence that already guards the polling path.
- ``BROWNOUT_ADVISORY``: ``level:int8, retry_ms:int32`` — the admission
  ladder escalated (SHED_LOW/DEGRADE). Failover clients treat it as an
  early walk hint instead of waiting to be refused.

Delivery is at-most-once and fire-and-forget: a push rides the reply lane
behind verdict writes (never blocking one), a full queue or dead
connection silently drops it, and EVERY pushed fact is re-derivable from
the polling path (lease TTL, breaker refusal, shard-map publish, OVERLOAD
answer) — push tightens the staleness bound, it never replaces the
fallback. Old clients skip unknown type bytes (the rev-7 reader contract;
pre-rev-7 readers dropped the connection, which is why the mixed-rev
fix ships in the same rev).
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from sentinel_tpu import chaos as _chaos

# codec revision this build speaks: 2 deadline trailer, 3 REPL, 4 MOVE,
# 5 LEASE + HIER share ops, 6 OUTCOME_REPORT, 7 PUSH control plane (the
# doc revisions above)
WIRE_REV = 7

# 2-byte big-endian length prefix caps a frame at 65535 bytes; single-request
# messages keep the reference's 1024-byte budget, BATCH_FLOW frames use the
# full range (~5000 requests/frame at 13 B each).
MAX_FRAME = 65535
MAX_SINGLE_FRAME = 1024
_HEAD = struct.Struct(">ib")  # xid, type
_FLOW_REQ = struct.Struct(">qib")  # flow_id, count, priority
_FLOW_RSP = struct.Struct(">bii")  # status, remaining, wait_ms
_LEN = struct.Struct(">H")
_BATCH_N = struct.Struct(">H")
# codec rev 2: an OPTIONAL uint32 deadline (relative ms budget) trailing a
# BATCH_FLOW request's rows. Back-compatible both ways: old frames simply
# lack the trailer (deadline 0 = none), and every decoder in the fleet —
# numpy (count=n), the native Python codec (sn_batch_decode_req) and the C++
# front door (parse_frames) — validates `len >= needed` and skips the whole
# frame by its length prefix, so trailing bytes pass through old servers
# untouched. Relative-not-absolute keeps clock skew out of the contract.
_DEADLINE = struct.Struct(">I")

# vectorized batch codecs: packed big-endian structured rows
BATCH_REQ_DTYPE = np.dtype([("flow_id", ">i8"), ("count", ">i4"), ("prio", "u1")])
BATCH_RSP_DTYPE = np.dtype([("status", "i1"), ("remaining", ">i4"), ("wait_ms", ">i4")])
MAX_BATCH_PER_FRAME = (MAX_FRAME - _HEAD.size - _BATCH_N.size) // BATCH_REQ_DTYPE.itemsize

# rev-6 outcome rows: (flow_id, rt_ms, exc) — same 13-byte shape discipline
# as BATCH_REQ_DTYPE so one frame coalesces ~5000 completions
OUTCOME_ROW_DTYPE = np.dtype([("flow_id", ">i8"), ("rt_ms", ">i4"), ("exc", "u1")])
MAX_OUTCOME_PER_FRAME = (MAX_FRAME - _HEAD.size - _BATCH_N.size) // OUTCOME_ROW_DTYPE.itemsize

# wire-boundary RT validation ceiling (ms). The reference clamps recorded RT
# at statisticMaxRt (SentinelConfig, 4900 ms default); we keep a wider valve
# for slow-dependency telemetry but anything above it is a bogus report —
# dropped and counted (reason="too_large"), never scattered into rt_sum.
# The floor of the valid range is 0; negative values drop (reason="negative")
# and non-integral garbage drops client-side before the int cast
# (reason="non_finite").
OUTCOME_MAX_RT_MS = 60_000


class MsgType(enum.IntEnum):
    PING = 0
    FLOW = 1
    PARAM_FLOW = 2
    CONCURRENT_ACQUIRE = 3
    CONCURRENT_RELEASE = 4
    BATCH_FLOW = 5
    # codec rev 3: primary → standby state replication (control plane)
    REPL_HELLO = 6
    REPL_DELTA = 7
    REPL_ACK = 8
    REPL_SNAPSHOT = 9
    # codec rev 4: live shard rebalancing (control plane)
    MOVE_BEGIN = 10
    MOVE_STATE = 11
    MOVE_COMMIT = 12
    MOVE_ABORT = 13
    # codec rev 5: client-local admission leases
    LEASE_GRANT = 14
    LEASE_RENEW = 15
    LEASE_RETURN = 16
    # rev-5 family, hierarchy tier: pods lease provisioned SHARES of a
    # global flow budget from the coordinator. Share ops reuse the lease
    # request/response structs byte for byte — a pod is just a lease
    # client with a long TTL — but carry their own type bytes so the
    # coordinator pod's door can tell a pod-share op from a client-lease
    # op on the same flow_id without any payload sniffing.
    DEMAND_REPORT = 17
    SHARE_GRANT = 18
    SHARE_RENEW = 19
    SHARE_RETURN = 20
    # codec rev 6: batched fire-and-forget completion telemetry
    OUTCOME_REPORT = 21
    # codec rev 7: unsolicited server→client PUSH control frames. The
    # server originates these on connections the data plane already
    # holds; the client never answers. At-most-once, fire-and-forget —
    # every pushed fact is re-derivable from the polling path.
    LEASE_REVOKE = 22
    BREAKER_FLIP = 23
    RULE_EPOCH_INVALIDATE = 24
    SHARD_MAP_PUSH = 25
    BROWNOUT_ADVISORY = 26


# front doors route these type bytes to the replication applier instead of
# decode_request (which rejects them — they are not request frames)
REPL_TYPES = frozenset(
    {MsgType.REPL_HELLO, MsgType.REPL_DELTA, MsgType.REPL_ACK,
     MsgType.REPL_SNAPSHOT}
)

# rev-4 move frames route to the server's MoveTarget the same way
MOVE_TYPES = frozenset(
    {MsgType.MOVE_BEGIN, MsgType.MOVE_STATE, MsgType.MOVE_COMMIT,
     MsgType.MOVE_ABORT}
)

# rev-5 lease frames route to the token service's host-side lease handler
# on both doors (cheap control-plane ops answered inline, never batched)
LEASE_TYPES = frozenset(
    {MsgType.LEASE_GRANT, MsgType.LEASE_RENEW, MsgType.LEASE_RETURN}
)

# hierarchy tier: pod-share ops reuse the lease frame layout but carry their
# own type bytes so the coordinator pod's door can separate them from client
# leases on the same flow
SHARE_TYPES = frozenset(
    {MsgType.SHARE_GRANT, MsgType.SHARE_RENEW, MsgType.SHARE_RETURN}
)

# everything both doors route to the attached hierarchy coordinator
HIER_TYPES = frozenset(SHARE_TYPES | {MsgType.DEMAND_REPORT})

# rev-6 outcome frames route to the token service's outcome ingester on both
# doors; fire-and-forget (no response is ever written for these)
OUTCOME_TYPES = frozenset({MsgType.OUTCOME_REPORT})

# rev-7 push frames: server→client only. Client readers dispatch these
# out-of-band (they never resolve a pending xid); the decision-plane
# request decoder REFUSES them — a client that sends one at a server is a
# protocol error and the door drops the connection.
PUSH_TYPES = frozenset(
    {MsgType.LEASE_REVOKE, MsgType.BREAKER_FLIP,
     MsgType.RULE_EPOCH_INVALIDATE, MsgType.SHARD_MAP_PUSH,
     MsgType.BROWNOUT_ADVISORY}
)

# every type byte this build speaks. Client readers SKIP (and count) a
# frame whose type is outside this set instead of dropping the connection —
# the forward-compat contract a mixed-rev fleet needs during rollout.
KNOWN_TYPES = frozenset(int(t) for t in MsgType)

# TokenStatus.MOVED — mirrored here as a bare int because this module must
# stay importable without jax (socket-only processes); decode_response keys
# the endpoint trailer on it
MOVED_STATUS = 10
# TokenStatus.NOT_LEASABLE, mirrored for the same reason: the rev-5 lease
# refusal (flow not leasable / no headroom / lease revoked)
NOT_LEASABLE_STATUS = 11
# TokenStatus.DEGRADED, mirrored for the same reason: the circuit-breaker
# refusal (resource breaker OPEN; ``remaining`` carries retry-after ms)
DEGRADED_STATUS = 12


class ReplAck(enum.IntEnum):
    """REPL_ACK codes."""

    OK = 0
    NEED_SNAPSHOT = 1  # gen/epoch mismatch or no sync yet: full resync first
    NOT_STANDBY = 2  # peer is promoted (or never was a standby)
    ERROR = 3  # frame understood but apply failed; sender resyncs


_REPL_HELLO = struct.Struct(">qqq")  # gen, epoch_ms, last_seq
_REPL_ACK = struct.Struct(">Bqq")  # code, gen, seq
_REPL_CHUNK = struct.Struct(">qqHH")  # gen, seq, idx, total
# room left in one frame for a delta/snapshot chunk's bytes
REPL_CHUNK_BYTES = MAX_FRAME - _HEAD.size - _REPL_CHUNK.size
_MOVE_CTRL = struct.Struct(">qH")  # epoch, ns_len (namespace + peer follow)


_NATIVE = None
_NATIVE_CHECKED = False


def _native_codec():
    """The native batch codec module, or None (numpy fallback)."""
    global _NATIVE, _NATIVE_CHECKED
    if not _NATIVE_CHECKED:
        try:
            from sentinel_tpu.native import lib as native_lib

            _NATIVE = native_lib if native_lib.available() else None
        except Exception:
            _NATIVE = None
        _NATIVE_CHECKED = True
    return _NATIVE


@dataclass(frozen=True)
class FlowRequest:
    xid: int
    flow_id: int
    count: int = 1
    prioritized: bool = False
    msg_type: MsgType = MsgType.FLOW
    param_hashes: Tuple[int, ...] = ()


@dataclass(frozen=True)
class FlowResponse:
    xid: int
    msg_type: MsgType
    status: int
    remaining: int = 0
    wait_ms: int = 0
    token_id: int = 0  # CONCURRENT_ACQUIRE only
    endpoint: str = ""  # MOVED only: the new owner's "host:port"


@dataclass(frozen=True)
class Ping:
    """Connection handshake/keepalive. Carries the client's namespace as its
    payload — the reference binds the connection to a namespace group this
    way (``TokenServerHandler.handlePingRequest`` reads the namespace string
    from the request data and answers with the group's connected count)."""

    xid: int
    namespace: str = "default"


def encode_request(req) -> bytes:
    if isinstance(req, Ping):
        payload = _HEAD.pack(req.xid, MsgType.PING) + req.namespace.encode(
            "utf-8"
        )
    elif isinstance(req, FlowRequest):
        payload = _HEAD.pack(req.xid, req.msg_type) + _FLOW_REQ.pack(
            req.flow_id, req.count, 1 if req.prioritized else 0
        )
        if req.msg_type == MsgType.PARAM_FLOW:
            payload += struct.pack(">B", len(req.param_hashes))
            for h in req.param_hashes:
                payload += struct.pack(">q", h)
    else:
        raise TypeError(f"unknown request {req!r}")
    if len(payload) > MAX_SINGLE_FRAME:
        raise ValueError("frame too large")
    return _LEN.pack(len(payload)) + payload


def encode_batch_request(
    xid: int, flow_ids, counts=None, prios=None, deadline_ms=None
) -> bytes:
    """One BATCH_FLOW frame carrying N flow requests (numpy-vectorized).

    ``deadline_ms`` (> 0) appends the rev-2 relative-deadline trailer: the
    sender's remaining budget in ms. A deadline-aware server drops the frame
    once the budget is blown (the client has already timed out); old servers
    ignore the trailer entirely.
    """
    flow_ids = np.asarray(flow_ids, dtype=np.int64)
    n = flow_ids.shape[0]
    if n > MAX_BATCH_PER_FRAME:
        raise ValueError(f"batch of {n} exceeds {MAX_BATCH_PER_FRAME}/frame")
    rows = np.empty(n, dtype=BATCH_REQ_DTYPE)
    rows["flow_id"] = flow_ids
    rows["count"] = 1 if counts is None else np.asarray(counts, dtype=np.int32)
    rows["prio"] = 0 if prios is None else np.asarray(prios, dtype=np.uint8)
    tail = b""
    if deadline_ms:
        tail = _DEADLINE.pack(min(int(deadline_ms), 0xFFFFFFFF))
    payload_len = (
        _HEAD.size + _BATCH_N.size + n * BATCH_REQ_DTYPE.itemsize + len(tail)
    )
    return (
        _LEN.pack(payload_len)
        + _HEAD.pack(xid, MsgType.BATCH_FLOW)
        + _BATCH_N.pack(n)
        + rows.tobytes()
        + tail
    )


def decode_batch_request(payload: bytes):
    """BATCH_FLOW payload → (xid, flow_ids int64[N], counts int32[N],
    prios bool[N]). Caller has already checked the type byte. Uses the
    native codec when built (GIL released during the row loop)."""
    native = _native_codec()
    if native is not None:
        return native.batch_decode_req(payload)
    xid, _ = _HEAD.unpack_from(payload, 0)
    (n,) = _BATCH_N.unpack_from(payload, _HEAD.size)
    off = _HEAD.size + _BATCH_N.size
    rows = np.frombuffer(payload, dtype=BATCH_REQ_DTYPE, count=n, offset=off)
    return (
        xid,
        rows["flow_id"].astype(np.int64),
        rows["count"].astype(np.int32),
        rows["prio"].astype(bool),
    )


def decode_batch_request_into(payload, ids_out, counts_out, prios_out, at=0):
    """Zero-copy BATCH_FLOW request decode: write the frame's N rows
    straight into caller-owned arrays starting at index ``at`` and return
    ``(xid, n)``.

    This is the staging-buffer entry point: the native intake lanes hand
    preallocated (freelist-recycled) ``int64/int32/bool`` staging arrays and
    frames land in them directly — no per-frame intermediate ndarrays, no
    realloc per pull. Decoded values are bit-identical to
    :func:`decode_batch_request` (property-tested); the only difference is
    where the rows land. Raises ``ValueError`` on a truncated frame or when
    the rows would overflow the staging span — callers treat both as a
    protocol error on that connection.
    """
    xid, _ = _HEAD.unpack_from(payload, 0)
    (n,) = _BATCH_N.unpack_from(payload, _HEAD.size)
    off = _HEAD.size + _BATCH_N.size
    if len(payload) < off + n * BATCH_REQ_DTYPE.itemsize:
        raise ValueError(
            f"truncated batch frame: {n} rows declared, "
            f"{len(payload) - off} payload bytes"
        )
    if at + n > ids_out.shape[0]:
        raise ValueError(
            f"staging overflow: rows [{at}, {at + n}) exceed capacity "
            f"{ids_out.shape[0]}"
        )
    rows = np.frombuffer(payload, dtype=BATCH_REQ_DTYPE, count=n, offset=off)
    # casted assignment decodes the big-endian rows during the copy into the
    # native-endian staging arrays — one pass per column, no intermediates
    ids_out[at : at + n] = rows["flow_id"]
    counts_out[at : at + n] = rows["count"]
    prios_out[at : at + n] = rows["prio"]
    return xid, n


def encode_outcome_report(xid: int, flow_ids, rt_ms, excs) -> bytes:
    """One OUTCOME_REPORT frame carrying N completion rows (rev 6).

    Fire-and-forget: the server never answers. Callers coalesce buffered
    completions and prepend this frame to the next request frame (TCP) or
    publish it as its own ring slot (shm)."""
    flow_ids = np.asarray(flow_ids, dtype=np.int64)
    n = flow_ids.shape[0]
    if n > MAX_OUTCOME_PER_FRAME:
        raise ValueError(f"outcome batch of {n} exceeds {MAX_OUTCOME_PER_FRAME}/frame")
    rows = np.empty(n, dtype=OUTCOME_ROW_DTYPE)
    rows["flow_id"] = flow_ids
    rows["rt_ms"] = np.asarray(rt_ms, dtype=np.int32)
    rows["exc"] = np.asarray(excs, dtype=np.uint8)
    payload_len = _HEAD.size + _BATCH_N.size + n * OUTCOME_ROW_DTYPE.itemsize
    return (
        _LEN.pack(payload_len)
        + _HEAD.pack(xid, MsgType.OUTCOME_REPORT)
        + _BATCH_N.pack(n)
        + rows.tobytes()
    )


def decode_outcome_report(payload: bytes):
    """OUTCOME_REPORT payload → (xid, flow_ids int64[N], rt_ms int32[N],
    excs bool[N]). Caller has already checked the type byte. Raises
    ``ValueError`` on a truncated frame (treated as a protocol error on
    that connection, like a truncated batch frame)."""
    xid, _ = _HEAD.unpack_from(payload, 0)
    (n,) = _BATCH_N.unpack_from(payload, _HEAD.size)
    off = _HEAD.size + _BATCH_N.size
    if len(payload) < off + n * OUTCOME_ROW_DTYPE.itemsize:
        raise ValueError(
            f"truncated outcome frame: {n} rows declared, "
            f"{len(payload) - off} payload bytes"
        )
    rows = np.frombuffer(payload, dtype=OUTCOME_ROW_DTYPE, count=n, offset=off)
    return (
        xid,
        rows["flow_id"].astype(np.int64),
        rows["rt_ms"].astype(np.int32),
        rows["exc"].astype(bool),
    )


class StagingPool:
    """Thread-safe freelist of preallocated staging blocks.

    ``factory()`` builds one block (any object — the native server uses a
    bundle of pinned request/frame-metadata arrays; the fused dispatcher
    uses stacked ``[depth, batch]`` RequestBatch leaves). ``acquire`` pops a
    recycled block or builds a fresh one when the freelist is dry (burst
    absorption — the pool never blocks a lane); ``release`` returns a block
    for reuse, dropping it once ``capacity`` blocks are already parked so a
    transient burst doesn't pin its high-water memory forever.

    Counters: ``reused`` / ``built`` expose the recycle rate — a healthy
    steady state reuses nearly always (``built`` ≈ the concurrency depth).
    ``outstanding`` counts blocks acquired but not yet released — the
    leak detector: once a server's lanes quiesce it must equal the number
    of blocks lanes legitimately hold (one per intake lane), or a
    shed/abandon path lost a block.
    """

    def __init__(self, factory, capacity: int = 16):
        import threading

        self._factory = factory
        self.capacity = int(capacity)
        self._free: List[object] = []
        self._lock = threading.Lock()
        self.reused = 0
        self.built = 0
        self.outstanding = 0

    def acquire(self):
        with self._lock:
            self.outstanding += 1
            if self._free:
                self.reused += 1
                return self._free.pop()
            self.built += 1
        return self._factory()

    def release(self, block) -> None:
        if block is None:
            return
        with self._lock:
            # outstanding decrements even when the block is dropped past
            # capacity: the lifecycle audit tracks acquire/release pairing,
            # not freelist residency
            self.outstanding -= 1
            if len(self._free) < self.capacity:
                self._free.append(block)


def decode_batch_deadline(payload: bytes) -> int:
    """The rev-2 relative deadline (ms) trailing a BATCH_FLOW request, or 0
    when absent (rev-1 frame / no budget declared). Tolerant of malformed
    payloads — the full decode is where validity is judged."""
    try:
        (n,) = _BATCH_N.unpack_from(payload, _HEAD.size)
    except struct.error:
        return 0
    tail = _HEAD.size + _BATCH_N.size + n * BATCH_REQ_DTYPE.itemsize
    if len(payload) >= tail + _DEADLINE.size:
        return _DEADLINE.unpack_from(payload, tail)[0]
    return 0


def encode_batch_response(xid: int, status, remaining, wait_ms) -> bytes:
    native = _native_codec()
    if native is not None:
        return native.batch_encode_rsp(xid, status, remaining, wait_ms)
    status = np.asarray(status, dtype=np.int8)
    n = status.shape[0]
    rows = np.empty(n, dtype=BATCH_RSP_DTYPE)
    rows["status"] = status
    rows["remaining"] = np.asarray(remaining, dtype=np.int32)
    rows["wait_ms"] = np.asarray(wait_ms, dtype=np.int32)
    payload_len = _HEAD.size + _BATCH_N.size + n * BATCH_RSP_DTYPE.itemsize
    return (
        _LEN.pack(payload_len)
        + _HEAD.pack(xid, MsgType.BATCH_FLOW)
        + _BATCH_N.pack(n)
        + rows.tobytes()
    )


def batch_responses_size(counts) -> int:
    """Exact byte size :func:`encode_batch_responses` needs for ``counts``
    (callers sizing reusable ``out=`` scatter buffers)."""
    counts = np.asarray(counts, dtype=np.int64)
    head = _HEAD.size + _BATCH_N.size
    return int(
        counts.shape[0] * (_LEN.size + head)
        + int(counts.sum()) * BATCH_RSP_DTYPE.itemsize
    )


def encode_batch_responses(xids, counts, status, remaining, wait_ms,
                           out=None):
    """F BATCH_FLOW response frames in ONE buffer — the vectorized reply
    path. ``counts[f]`` rows belong to frame f (``sum(counts)`` must equal
    ``len(status)``); the verdict arrays are concatenated in frame order.

    Scatter encode: with ``out=`` (a ``bytearray`` — e.g. one reusable
    per-writer buffer), the frames are laid directly into it (grown in
    place when too small) and a ``memoryview`` of the filled span is
    returned — zero allocation on the steady-state path. Without ``out``
    a fresh ``bytes`` is allocated and returned (the original behavior).

    Two encode paths, byte-identical (property-tested against each other):

    - **uniform counts** (every frame the same size — the closed-loop /
      fused steady state): ONE vectorized pass lays rows AND headers via a
      strided ``[F, frame_len]`` uint8 view; no per-frame Python at all.
    - **ragged counts**: one numpy pass for all rows, then a small F-loop
      packs the 9-byte headers.
    """
    xids = np.asarray(xids)
    counts = np.asarray(counts, dtype=np.int64)
    status = np.asarray(status, dtype=np.int8)
    F = xids.shape[0]
    total = int(counts.sum())
    if total != status.shape[0]:
        raise ValueError(
            f"frame counts sum to {total}, got {status.shape[0]} verdicts"
        )
    rows = np.empty(total, dtype=BATCH_RSP_DTYPE)
    rows["status"] = status
    rows["remaining"] = np.asarray(remaining, dtype=np.int32)
    rows["wait_ms"] = np.asarray(wait_ms, dtype=np.int32)
    isz = BATCH_RSP_DTYPE.itemsize
    head = _HEAD.size + _BATCH_N.size
    size = F * (_LEN.size + head) + total * isz
    if out is None:
        buf = bytearray(size)
    else:
        if len(out) < size:
            out.extend(bytes(size - len(out)))  # grow once, then steady
        buf = out
    uniform = F > 0 and int(counts.min()) == int(counts.max())
    if uniform and total:
        n = int(counts[0])
        plen = head + n * isz
        flen = _LEN.size + plen
        view = np.frombuffer(buf, np.uint8, count=F * flen).reshape(F, flen)
        view[:, 0] = plen >> 8
        view[:, 1] = plen & 0xFF
        view[:, 2:6] = (
            np.ascontiguousarray(xids, dtype=">i4")
            .view(np.uint8).reshape(F, 4)
        )
        view[:, 6] = int(MsgType.BATCH_FLOW)
        view[:, 7] = n >> 8
        view[:, 8] = n & 0xFF
        view[:, 9:] = rows.view(np.uint8).reshape(F, n * isz)
    else:
        blob = rows.tobytes()
        mv = memoryview(buf)
        off = 0
        row0 = 0
        for f in range(F):
            n = int(counts[f])
            _LEN.pack_into(buf, off, head + n * isz)
            _HEAD.pack_into(
                buf, off + _LEN.size, int(xids[f]), MsgType.BATCH_FLOW
            )
            _BATCH_N.pack_into(buf, off + _LEN.size + _HEAD.size, n)
            start = off + _LEN.size + head
            mv[start : start + n * isz] = blob[row0 * isz : (row0 + n) * isz]
            off = start + n * isz
            row0 += n
    if out is None:
        return bytes(buf)
    return memoryview(buf)[:size]


def decode_batch_response(payload: bytes):
    """BATCH_FLOW response payload → (xid, status int8[N], remaining int32[N],
    wait_ms int32[N])."""
    xid, _ = _HEAD.unpack_from(payload, 0)
    (n,) = _BATCH_N.unpack_from(payload, _HEAD.size)
    off = _HEAD.size + _BATCH_N.size
    rows = np.frombuffer(payload, dtype=BATCH_RSP_DTYPE, count=n, offset=off)
    return (
        xid,
        rows["status"].astype(np.int8),
        rows["remaining"].astype(np.int32),
        rows["wait_ms"].astype(np.int32),
    )


def peek_type(payload: bytes) -> int:
    """Message type byte without a full decode (IO-thread fast path)."""
    return payload[4]


def peek_xid(payload: bytes) -> int:
    """Frame xid without a full decode (error-ack paths)."""
    (xid,) = struct.unpack_from(">i", payload, 0)
    return xid


# -- codec rev 3: replication frames -----------------------------------------
def encode_repl_hello(
    xid: int, gen: int, epoch_ms: int, last_seq: int, sender_id: str = ""
) -> bytes:
    payload = (
        _HEAD.pack(xid, MsgType.REPL_HELLO)
        + _REPL_HELLO.pack(gen, epoch_ms, last_seq)
        + sender_id.encode("utf-8")[:256]
    )
    return _LEN.pack(len(payload)) + payload


def decode_repl_hello(payload: bytes):
    """REPL_HELLO payload → (xid, gen, epoch_ms, last_seq, sender_id)."""
    xid, _ = _HEAD.unpack_from(payload, 0)
    gen, epoch_ms, last_seq = _REPL_HELLO.unpack_from(payload, _HEAD.size)
    sender = payload[_HEAD.size + _REPL_HELLO.size :].decode(
        "utf-8", errors="replace"
    )
    return xid, gen, epoch_ms, last_seq, sender


def encode_repl_ack(xid: int, code: int, gen: int, seq: int) -> bytes:
    payload = _HEAD.pack(xid, MsgType.REPL_ACK) + _REPL_ACK.pack(
        int(code), gen, seq
    )
    return _LEN.pack(len(payload)) + payload


def decode_repl_ack(payload: bytes):
    """REPL_ACK payload → (xid, code, gen, seq)."""
    xid, _ = _HEAD.unpack_from(payload, 0)
    code, gen, seq = _REPL_ACK.unpack_from(payload, _HEAD.size)
    return xid, ReplAck(code), gen, seq


def encode_repl_blob(
    xid: int, msg_type: int, gen: int, seq: int, blob: bytes
) -> List[bytes]:
    """One replication document (already compressed) → its chunk frames.

    Every chunk carries (gen, seq, idx, total) so the standby can reassemble
    and DETECT a torn stream: a chunk whose (gen, seq) doesn't extend the
    in-progress assembly restarts it. Rev 4 reuses this codec for the move
    channel (``MOVE_STATE``: ``gen`` = source state generation, ``seq`` =
    move epoch). An empty blob still emits one chunk
    (total=1) — an empty delta is the sender's liveness heartbeat."""
    if msg_type not in (
        MsgType.REPL_DELTA, MsgType.REPL_SNAPSHOT, MsgType.MOVE_STATE
    ):
        raise ValueError(f"not a repl blob type: {msg_type}")
    total = max(1, -(-len(blob) // REPL_CHUNK_BYTES))
    if total > 0xFFFF:
        raise ValueError(f"repl blob needs {total} chunks (cap 65535)")
    frames = []
    for idx in range(total):
        chunk = blob[idx * REPL_CHUNK_BYTES : (idx + 1) * REPL_CHUNK_BYTES]
        payload = (
            _HEAD.pack(xid, msg_type)
            + _REPL_CHUNK.pack(gen, seq, idx, total)
            + chunk
        )
        frames.append(_LEN.pack(len(payload)) + payload)
    return frames


def decode_repl_chunk(payload: bytes):
    """REPL_DELTA/REPL_SNAPSHOT payload → (xid, gen, seq, idx, total,
    chunk bytes). Raises ``ValueError`` on a runt payload."""
    if len(payload) < _HEAD.size + _REPL_CHUNK.size:
        raise ValueError("runt repl chunk")
    xid, _ = _HEAD.unpack_from(payload, 0)
    gen, seq, idx, total = _REPL_CHUNK.unpack_from(payload, _HEAD.size)
    if total == 0 or idx >= total:
        raise ValueError(f"bad repl chunk index {idx}/{total}")
    return xid, gen, seq, idx, total, payload[_HEAD.size + _REPL_CHUNK.size :]


class ReplBlobAssembler:
    """Reassembles chunked replication blobs on the standby side.

    ``feed`` returns ``(msg_type, gen, seq, blob)`` once the last chunk of a
    document lands, else None. Out-of-order or interleaved chunks restart
    the assembly (the repl channel is one TCP stream per sender — a gap can
    only mean the stream was torn and resumed); a malformed chunk raises
    ``ValueError`` so the server can drop the connection."""

    def __init__(self):
        self._key = None  # (msg_type, gen, seq, total)
        self._parts: List[bytes] = []

    def feed(self, msg_type: int, payload: bytes):
        _xid, gen, seq, idx, total, chunk = decode_repl_chunk(payload)
        key = (int(msg_type), gen, seq, total)
        if idx == 0:
            self._key, self._parts = key, [chunk]
        elif self._key == key and idx == len(self._parts):
            self._parts.append(chunk)
        else:
            self._key, self._parts = None, []
            raise ValueError("torn repl chunk stream")
        if len(self._parts) == total:
            blob = b"".join(self._parts)
            self._key, self._parts = None, []
            return int(msg_type), gen, seq, blob
        return None


# -- codec rev 4: move control frames -----------------------------------------
def encode_move_ctrl(
    xid: int, msg_type: int, epoch: int, namespace: str, peer: str = ""
) -> bytes:
    """MOVE_BEGIN / MOVE_COMMIT / MOVE_ABORT frame: the move's shard-map
    epoch, the namespace being moved, and the sender's peer id (the source
    server's ``host:port`` — what redirected clients are steered AWAY from,
    logged on the destination for the crash matrix)."""
    if msg_type not in (
        MsgType.MOVE_BEGIN, MsgType.MOVE_COMMIT, MsgType.MOVE_ABORT
    ):
        raise ValueError(f"not a move control type: {msg_type}")
    ns = namespace.encode("utf-8")
    if len(ns) > 0xFFFF:
        raise ValueError("namespace too long")
    payload = (
        _HEAD.pack(xid, msg_type)
        + _MOVE_CTRL.pack(epoch, len(ns))
        + ns
        + peer.encode("utf-8")[:256]
    )
    if len(payload) > MAX_FRAME:
        raise ValueError("move control frame too large")
    return _LEN.pack(len(payload)) + payload


def decode_move_ctrl(payload: bytes):
    """MOVE_BEGIN/COMMIT/ABORT payload → (xid, epoch, namespace, peer).
    Raises ``ValueError`` on a runt or torn payload (the door drops the
    connection, same contract as ``decode_request``)."""
    if len(payload) < _HEAD.size + _MOVE_CTRL.size:
        raise ValueError("runt move control frame")
    xid, _ = _HEAD.unpack_from(payload, 0)
    epoch, ns_len = _MOVE_CTRL.unpack_from(payload, _HEAD.size)
    off = _HEAD.size + _MOVE_CTRL.size
    if len(payload) < off + ns_len:
        raise ValueError("torn move control frame")
    namespace = payload[off : off + ns_len].decode("utf-8", errors="replace")
    peer = payload[off + ns_len :].decode("utf-8", errors="replace")
    return xid, epoch, namespace, peer


# -- codec rev 5: lease frames ------------------------------------------------
_LEASE_REQ = struct.Struct(">qqii")  # lease_id, flow_id, used, want
_LEASE_RSP = struct.Struct(">bqii")  # status, lease_id, tokens, ttl_ms


@dataclass(frozen=True)
class LeaseResponse:
    """Decoded rev-5 lease answer (grant/renew/return share the layout)."""

    xid: int
    msg_type: MsgType
    status: int
    lease_id: int = 0
    tokens: int = 0
    ttl_ms: int = 0
    endpoint: str = ""  # MOVED only: the new owner's "host:port"


def encode_lease_request(
    xid: int, msg_type: int, flow_id: int, want: int,
    lease_id: int = 0, used: int = 0,
) -> bytes:
    """LEASE_GRANT / LEASE_RENEW / LEASE_RETURN request frame. The
    hierarchy tier's SHARE_* ops reuse the same layout (a pod is a lease
    client with a long TTL), so they encode through here too."""
    if msg_type not in LEASE_TYPES and msg_type not in SHARE_TYPES:
        raise ValueError(f"not a lease type: {msg_type}")
    payload = _HEAD.pack(xid, msg_type) + _LEASE_REQ.pack(
        lease_id, flow_id, used, want
    )
    return _LEN.pack(len(payload)) + payload


def decode_lease_request(payload: bytes):
    """Lease request payload → (xid, msg_type, lease_id, flow_id, used,
    want). Raises ``ValueError`` on a runt or torn payload (the door drops
    the connection, same contract as ``decode_request``)."""
    if len(payload) < _HEAD.size + _LEASE_REQ.size:
        raise ValueError("runt lease request frame")
    xid, mtype = _HEAD.unpack_from(payload, 0)
    if mtype not in LEASE_TYPES and mtype not in SHARE_TYPES:
        raise ValueError(f"not a lease type: {mtype}")
    lease_id, flow_id, used, want = _LEASE_REQ.unpack_from(payload, _HEAD.size)
    return xid, MsgType(mtype), lease_id, flow_id, used, want


def encode_lease_response(
    xid: int, msg_type: int, status: int, lease_id: int = 0,
    tokens: int = 0, ttl_ms: int = 0, endpoint: str = "",
) -> bytes:
    """Lease answer frame; a MOVED status appends the rev-4 endpoint
    trailer so a redirected client learns the new owner in one round
    trip."""
    payload = _HEAD.pack(xid, msg_type) + _LEASE_RSP.pack(
        int(status), lease_id, tokens, ttl_ms
    )
    if int(status) == MOVED_STATUS and endpoint:
        payload += endpoint.encode("utf-8")[:256]
    return _LEN.pack(len(payload)) + payload


def decode_lease_response(payload: bytes) -> LeaseResponse:
    """Lease answer payload → :class:`LeaseResponse`. Raises ``ValueError``
    on a runt payload (client readers degrade to a dropped connection)."""
    if len(payload) < _HEAD.size + _LEASE_RSP.size:
        raise ValueError("runt lease response frame")
    xid, mtype = _HEAD.unpack_from(payload, 0)
    status, lease_id, tokens, ttl_ms = _LEASE_RSP.unpack_from(
        payload, _HEAD.size
    )
    endpoint = ""
    off = _HEAD.size + _LEASE_RSP.size
    if status == MOVED_STATUS and len(payload) > off:
        endpoint = payload[off:].decode("utf-8", errors="replace")
    return LeaseResponse(
        xid, MsgType(mtype), status, lease_id, tokens, ttl_ms, endpoint
    )


# -- hierarchy tier: demand-report frames -------------------------------------
# A pod's share agent ships one DEMAND_REPORT per tick: the pod id plus one
# entry per globally-limited flow carrying the share it holds and the arrival
# rate it observed (milli-tokens/s, so sub-token rates survive the int wire).
# The coordinator answers with the shared lease-response frame (status +
# tokens = entries accepted) — no second response layout to fuzz.
_DEMAND_HEAD = struct.Struct(">HH")  # pod_len, n_entries
_DEMAND_ENTRY = struct.Struct(">qqq")  # flow_id, share_id, rate_milli
MAX_DEMAND_ENTRIES = (
    MAX_FRAME - _HEAD.size - _DEMAND_HEAD.size - 256
) // _DEMAND_ENTRY.size


def encode_demand_report(
    xid: int, pod_id: str, entries: List[Tuple[int, int, int]]
) -> bytes:
    """DEMAND_REPORT frame: ``entries`` is ``[(flow_id, share_id,
    rate_milli), ...]``."""
    pod = pod_id.encode("utf-8")[:256]
    if len(entries) > MAX_DEMAND_ENTRIES:
        raise ValueError(f"too many demand entries: {len(entries)}")
    payload = bytearray(_HEAD.pack(xid, MsgType.DEMAND_REPORT))
    payload += _DEMAND_HEAD.pack(len(pod), len(entries))
    payload += pod
    for flow_id, share_id, rate_milli in entries:
        payload += _DEMAND_ENTRY.pack(int(flow_id), int(share_id), int(rate_milli))
    return _LEN.pack(len(payload)) + bytes(payload)


def decode_demand_report(payload: bytes):
    """DEMAND_REPORT payload → ``(xid, pod_id, entries)``. Raises
    ``ValueError`` on ANY runt, torn, or mistyped payload — the door drops
    the connection, never a partial decode."""
    if len(payload) < _HEAD.size + _DEMAND_HEAD.size:
        raise ValueError("runt demand report frame")
    xid, mtype = _HEAD.unpack_from(payload, 0)
    if mtype != MsgType.DEMAND_REPORT:
        raise ValueError(f"not a demand report: {mtype}")
    pod_len, n_entries = _DEMAND_HEAD.unpack_from(payload, _HEAD.size)
    off = _HEAD.size + _DEMAND_HEAD.size
    need = off + pod_len + n_entries * _DEMAND_ENTRY.size
    if len(payload) != need:
        raise ValueError("torn demand report frame")
    pod_id = payload[off : off + pod_len].decode("utf-8", errors="replace")
    off += pod_len
    entries: List[Tuple[int, int, int]] = []
    for _ in range(n_entries):
        entries.append(_DEMAND_ENTRY.unpack_from(payload, off))
        off += _DEMAND_ENTRY.size
    return xid, pod_id, entries


# -- codec rev 7: push frames --------------------------------------------------
# Every push payload starts with the server's emit stamp (wall-clock ms) so
# the client-side apply can record end-to-end staleness; per-type data
# follows. Fixed layouts, runt checks raise ValueError only — the client
# reader SKIPS a malformed push (and counts it) instead of dropping the
# connection, because a push never gates a pending request.
_PUSH_STAMP = struct.Struct(">q")  # stamp_ms
_PUSH_REVOKE = struct.Struct(">qqqi")  # stamp_ms, lease_id, flow_id, tokens
_PUSH_BREAKER = struct.Struct(">qqbi")  # stamp_ms, flow_id, state, retry_ms
_PUSH_EPOCH = struct.Struct(">qq")  # stamp_ms, epoch
_PUSH_BROWNOUT = struct.Struct(">qbi")  # stamp_ms, level, retry_ms


@dataclass(frozen=True)
class PushFrame:
    """One decoded rev-7 push. Only the fields the ``msg_type`` defines are
    meaningful; the rest stay at their zero values."""

    xid: int
    msg_type: MsgType
    stamp_ms: int = 0
    lease_id: int = 0
    flow_id: int = 0
    tokens: int = 0
    state: int = 0
    retry_after_ms: int = 0
    epoch: int = 0
    level: int = 0
    doc: bytes = b""  # SHARD_MAP_PUSH only: zlib-compressed map JSON


def encode_push_lease_revoke(
    xid: int, stamp_ms: int, lease_id: int, flow_id: int, tokens: int
) -> bytes:
    payload = _HEAD.pack(xid, MsgType.LEASE_REVOKE) + _PUSH_REVOKE.pack(
        stamp_ms, lease_id, flow_id, tokens
    )
    return _LEN.pack(len(payload)) + payload


def encode_push_breaker_flip(
    xid: int, stamp_ms: int, flow_id: int, state: int, retry_after_ms: int
) -> bytes:
    payload = _HEAD.pack(xid, MsgType.BREAKER_FLIP) + _PUSH_BREAKER.pack(
        stamp_ms, flow_id, int(state), int(retry_after_ms)
    )
    return _LEN.pack(len(payload)) + payload


def encode_push_rule_epoch(xid: int, stamp_ms: int, epoch: int) -> bytes:
    payload = _HEAD.pack(xid, MsgType.RULE_EPOCH_INVALIDATE) + _PUSH_EPOCH.pack(
        stamp_ms, epoch
    )
    return _LEN.pack(len(payload)) + payload


def encode_push_shard_map(xid: int, stamp_ms: int, doc: bytes) -> bytes:
    """``doc`` is the zlib-compressed ShardMap JSON (``to_doc``). A map too
    big for one frame is refused here — the polling publish path still
    carries it; push is an accelerator, not the only channel."""
    payload = _HEAD.pack(xid, MsgType.SHARD_MAP_PUSH) + _PUSH_STAMP.pack(
        stamp_ms
    ) + doc
    if len(payload) > MAX_FRAME:
        raise ValueError("shard map push frame too large")
    return _LEN.pack(len(payload)) + payload


def encode_push_brownout(
    xid: int, stamp_ms: int, level: int, retry_ms: int
) -> bytes:
    payload = _HEAD.pack(xid, MsgType.BROWNOUT_ADVISORY) + _PUSH_BROWNOUT.pack(
        stamp_ms, int(level), int(retry_ms)
    )
    return _LEN.pack(len(payload)) + payload


def decode_push(payload: bytes) -> PushFrame:
    """Any rev-7 push payload → :class:`PushFrame`. Raises ``ValueError`` on
    a runt payload or a non-push type byte — and ONLY ValueError (the fuzz
    containment contract): client readers catch it, count the frame, and
    keep the connection."""
    if len(payload) < _HEAD.size:
        raise ValueError("runt push frame")
    xid, mtype = _HEAD.unpack_from(payload, 0)
    if mtype not in PUSH_TYPES:
        raise ValueError(f"not a push type: {mtype}")
    mtype = MsgType(mtype)
    off = _HEAD.size
    if mtype == MsgType.LEASE_REVOKE:
        if len(payload) < off + _PUSH_REVOKE.size:
            raise ValueError("runt lease revoke push")
        stamp, lease_id, flow_id, tokens = _PUSH_REVOKE.unpack_from(payload, off)
        return PushFrame(xid, mtype, stamp, lease_id=lease_id,
                         flow_id=flow_id, tokens=tokens)
    if mtype == MsgType.BREAKER_FLIP:
        if len(payload) < off + _PUSH_BREAKER.size:
            raise ValueError("runt breaker flip push")
        stamp, flow_id, state, retry = _PUSH_BREAKER.unpack_from(payload, off)
        return PushFrame(xid, mtype, stamp, flow_id=flow_id, state=state,
                         retry_after_ms=retry)
    if mtype == MsgType.RULE_EPOCH_INVALIDATE:
        if len(payload) < off + _PUSH_EPOCH.size:
            raise ValueError("runt rule epoch push")
        stamp, epoch = _PUSH_EPOCH.unpack_from(payload, off)
        return PushFrame(xid, mtype, stamp, epoch=epoch)
    if mtype == MsgType.BROWNOUT_ADVISORY:
        if len(payload) < off + _PUSH_BROWNOUT.size:
            raise ValueError("runt brownout push")
        stamp, level, retry = _PUSH_BROWNOUT.unpack_from(payload, off)
        return PushFrame(xid, mtype, stamp, level=level, retry_after_ms=retry)
    # SHARD_MAP_PUSH: stamp + opaque doc bytes (the doc may legitimately be
    # any length ≥ 0; an empty doc is a no-op push)
    if len(payload) < off + _PUSH_STAMP.size:
        raise ValueError("runt shard map push")
    (stamp,) = _PUSH_STAMP.unpack_from(payload, off)
    return PushFrame(xid, mtype, stamp, doc=payload[off + _PUSH_STAMP.size:])


def encode_response(rsp: FlowResponse) -> bytes:
    payload = _HEAD.pack(rsp.xid, rsp.msg_type) + _FLOW_RSP.pack(
        rsp.status, rsp.remaining, rsp.wait_ms
    )
    if rsp.msg_type == MsgType.CONCURRENT_ACQUIRE:
        payload += struct.pack(">q", rsp.token_id)
    elif rsp.status == MOVED_STATUS and rsp.endpoint:
        # rev 4: the redirect target rides as a UTF-8 trailer. Back-compat
        # both ways — a rev-3 decoder's unpack_from ignores trailing bytes,
        # and a rev-4 decoder only reads the trailer on a MOVED status.
        payload += rsp.endpoint.encode("utf-8")[:256]
    return _LEN.pack(len(payload)) + payload


def decode_request(payload: bytes):
    xid, mtype = _HEAD.unpack_from(payload, 0)
    mtype = MsgType(mtype)
    if mtype == MsgType.PING:
        ns = payload[_HEAD.size :].decode("utf-8", errors="replace")
        # lenient where the reference answers "bad" on a blank namespace:
        # an empty payload (older client) falls back to the default group
        return Ping(xid, ns or "default")
    if mtype in (MsgType.FLOW, MsgType.CONCURRENT_ACQUIRE, MsgType.CONCURRENT_RELEASE):
        flow_id, count, prio = _FLOW_REQ.unpack_from(payload, _HEAD.size)
        return FlowRequest(xid, flow_id, count, bool(prio), mtype)
    if mtype == MsgType.PARAM_FLOW:
        off = _HEAD.size
        flow_id, count, prio = _FLOW_REQ.unpack_from(payload, off)
        off += _FLOW_REQ.size
        (n,) = struct.unpack_from(">B", payload, off)
        off += 1
        hashes = struct.unpack_from(f">{n}q", payload, off) if n else ()
        return FlowRequest(xid, flow_id, count, bool(prio), mtype, tuple(hashes))
    raise ValueError(f"unknown message type {mtype}")


def decode_response(payload: bytes) -> FlowResponse:
    xid, mtype = _HEAD.unpack_from(payload, 0)
    mtype = MsgType(mtype)
    status, remaining, wait_ms = _FLOW_RSP.unpack_from(payload, _HEAD.size)
    token_id = 0
    endpoint = ""
    off = _HEAD.size + _FLOW_RSP.size
    if mtype == MsgType.CONCURRENT_ACQUIRE and len(payload) >= off + 8:
        (token_id,) = struct.unpack_from(">q", payload, off)
    elif status == MOVED_STATUS and len(payload) > off:
        endpoint = payload[off:].decode("utf-8", errors="replace")
    return FlowResponse(
        xid, mtype, status, remaining, wait_ms, token_id, endpoint
    )


class FrameReader:
    """Incremental length-prefixed frame splitter for a byte stream."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        if _chaos.ARMED:  # inbound bit-rot injection (frame_corrupt)
            data = _chaos.mangle("frame_corrupt", data)
        self._buf.extend(data)
        frames = []
        while True:
            if len(self._buf) < _LEN.size:
                break
            (n,) = _LEN.unpack_from(self._buf, 0)
            # a 2-byte length cannot exceed MAX_FRAME (65535), but a frame
            # too short for even a header is garbage — drop the connection
            if n < _HEAD.size:
                raise ValueError("runt frame")
            if len(self._buf) < _LEN.size + n:
                break
            frames.append(bytes(self._buf[_LEN.size : _LEN.size + n]))
            del self._buf[: _LEN.size + n]
        return frames
