"""Namespace-routing token client.

The reference points each app at its namespace's token server through
assignment config (``ClusterClientAssignConfig`` pushed via the property
system); an app in several namespaces would run several clients. This client
generalizes that: it holds one ``TokenClient`` per pod and routes each
request by ``flow_id → namespace → pod``, so a caller is oblivious to the
partitioning (``cluster/namespaces.py``).

Reconfiguration (``update``) swaps the routing tables atomically — in-flight
requests finish against the old pod (its verdict is still valid: counters
are ephemeral and the old owner keeps enforcing until clients drain), new
requests go to the new owner.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Mapping, Optional, Tuple

from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.token_service import TokenResult, TokenService
from sentinel_tpu.engine import TokenStatus

Endpoint = Tuple[str, int]


class RoutingTokenClient(TokenService):
    def __init__(
        self,
        timeout_ms: int = 20,
        namespace_of: Optional[Mapping[int, str]] = None,
        pod_of: Optional[Mapping[str, str]] = None,
        endpoints: Optional[Mapping[str, Endpoint]] = None,
        client_factory: Callable[..., TokenService] = TokenClient,
    ):
        self.timeout_ms = timeout_ms
        self._factory = client_factory
        self._lock = threading.Lock()
        # routing tables — replaced wholesale by update(), never mutated
        self._namespace_of: Mapping[int, str] = dict(namespace_of or {})
        self._pod_of: Mapping[str, str] = dict(pod_of or {})
        self._endpoints: Mapping[str, Endpoint] = dict(endpoints or {})
        self._clients: Dict[str, TokenService] = {}
        # namespaces each pod's client has declared via the PING handshake —
        # a pod can serve several, and AVG_LOCAL counts need every one
        self._declared: Dict[str, set] = {}
        # concurrent-mode: per-pod token ids are local counters (each pod's
        # ConcurrencyManager counts from 1), so the router namespaces the
        # ids it returns by embedding a pod number in the high bits — the
        # caller-visible id is globally unique and release routes exactly
        self._pod_nums: Dict[str, int] = {}  # pod_id → 1-based number
        self._pods_by_num: Dict[int, str] = {}

    # -- reconfiguration ----------------------------------------------------
    def update(
        self,
        namespace_of: Optional[Mapping[int, str]] = None,
        pod_of: Optional[Mapping[str, str]] = None,
        endpoints: Optional[Mapping[str, Endpoint]] = None,
    ) -> None:
        """Install new routing tables (assignment-config push analog).
        Pods that disappeared get their clients closed."""
        with self._lock:
            if namespace_of is not None:
                self._namespace_of = dict(namespace_of)
            if pod_of is not None:
                self._pod_of = dict(pod_of)
            if endpoints is not None:
                self._endpoints = dict(endpoints)
                for pod_id in list(self._clients):
                    if pod_id not in self._endpoints:
                        client = self._clients.pop(pod_id)
                        self._declared.pop(pod_id, None)
                        close = getattr(client, "close", None)
                        if close:
                            close()

    def _route_for(self, flow_id: int):
        """(client, pod_id) actually routed to, or None. One lock acquisition
        decides the route — callers that need the pod identity (concurrent
        token-id prefixing) must use THIS pair, not re-derive the pod, or a
        concurrent update() can name a different pod than the issuer."""
        declare = False
        with self._lock:
            ns = self._namespace_of.get(flow_id)
            if ns is None:
                return None
            pod_id = self._pod_of.get(ns)
            if pod_id is None:
                return None
            client = self._clients.get(pod_id)
            if client is None:
                endpoint = self._endpoints.get(pod_id)
                if endpoint is None:
                    return None
                client = self._factory(
                    endpoint[0], endpoint[1],
                    timeout_ms=self.timeout_ms, namespace=ns,
                )
                self._clients[pod_id] = client
                self._declared[pod_id] = {ns}  # ctor namespace auto-pings
            elif ns not in self._declared.setdefault(pod_id, set()):
                self._declared[pod_id].add(ns)
                declare = True
        if declare:
            # additional namespace on an existing pod connection: declare it
            # so the server's AVG_LOCAL connection count includes us
            # (best-effort, outside the lock — a lost ping only delays the
            # count to the next keepalive)
            ping = getattr(client, "ping", None)
            if ping is not None:
                ping(namespace=ns)
        return client, pod_id

    def _client_for(self, flow_id: int) -> Optional[TokenService]:
        route = self._route_for(flow_id)
        return None if route is None else route[0]

    # -- TokenService -------------------------------------------------------
    def request_token(self, flow_id, acquire=1, prioritized=False) -> TokenResult:
        client = self._client_for(flow_id)
        if client is None:
            # unknown flow/namespace/pod: same shape as the reference's
            # no-rule path — caller falls back to its local check
            return TokenResult(TokenStatus.NO_RULE_EXISTS)
        return client.request_token(flow_id, acquire, prioritized)

    def request_params_token(self, flow_id, acquire, param_hashes) -> TokenResult:
        client = self._client_for(flow_id)
        if client is None:
            return TokenResult(TokenStatus.NO_RULE_EXISTS)
        return client.request_params_token(flow_id, acquire, param_hashes)

    # pod number lives in bits 48+ of the caller-visible token id; pod-local
    # ids below 2^48 (a per-pod counter would take >8900 years at 1M acq/s)
    _POD_ID_SHIFT = 48
    _LOCAL_ID_MASK = (1 << 48) - 1

    def request_concurrent_token(self, flow_id, acquire=1, prioritized=False):
        route = self._route_for(flow_id)
        if route is None:
            return TokenResult(TokenStatus.NO_RULE_EXISTS)
        client, pod_id = route
        result = client.request_concurrent_token(flow_id, acquire, prioritized)
        if (
            result.ok and result.token_id
            and result.token_id <= self._LOCAL_ID_MASK
        ):
            with self._lock:
                num = self._pod_nums.get(pod_id)
                if num is None:
                    num = len(self._pod_nums) + 1
                    self._pod_nums[pod_id] = num
                    self._pods_by_num[num] = pod_id
            return TokenResult(
                result.status, result.remaining, result.wait_ms,
                (num << self._POD_ID_SHIFT) | result.token_id,
            )
        return result

    def release_concurrent_token(self, token_id):
        token_id = int(token_id)
        num = token_id >> self._POD_ID_SHIFT
        local_id = token_id & self._LOCAL_ID_MASK
        with self._lock:
            pod_id = self._pods_by_num.get(num)
            if pod_id is not None and pod_id in self._clients:
                clients = [self._clients[pod_id]]
            elif num:
                # prefixed id whose issuing pod left the routing table: only
                # that pod could hold the token (ids are pod-scoped), and its
                # counters died with it — fail fast as already-released.
                # Broadcasting the masked local id could wrongly release an
                # UNRELATED token that another pod issued under the same
                # local counter value (round-3 advisor finding).
                return TokenResult(TokenStatus.ALREADY_RELEASE)
            else:
                # genuinely unprefixed id (issued outside the router):
                # degrade to first-success fan-out with the raw id
                clients = list(self._clients.values())
        result = TokenResult(TokenStatus.FAIL)
        for client in clients:
            r = client.release_concurrent_token(local_id)
            if r.ok:  # RELEASE_OK — a release never answers plain OK
                return r
            result = r
        return result

    def close(self) -> None:
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
            self._declared.clear()
        for client in clients:
            close = getattr(client, "close", None)
            if close:
                close()
