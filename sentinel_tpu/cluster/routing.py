"""Namespace-routing token client.

The reference points each app at its namespace's token server through
assignment config (``ClusterClientAssignConfig`` pushed via the property
system); an app in several namespaces would run several clients. This client
generalizes that: it holds one ``TokenClient`` per pod and routes each
request by ``flow_id → namespace → pod``, so a caller is oblivious to the
partitioning (``cluster/namespaces.py``).

Reconfiguration (``update``) swaps the routing tables atomically — in-flight
requests finish against the old pod (its verdict is still valid: counters
are ephemeral and the old owner keeps enforcing until clients drain), new
requests go to the new owner. The whole routing view lives in ONE immutable
``_RouteState`` object replaced wholesale under the lock: readers take a
single reference-read snapshot, so no request can observe half of an update
(new pod table, old endpoint table), and retired clients are closed only
AFTER the new state is visible — never under the lock, never while a reader
that snapshotted the old state may still be dispatching on them.

Live rebalancing (``cluster.rebalance``) plugs in two ways: shard maps
pushed through the property system land via :meth:`apply_shard_map`
(epoch-fenced — a stale map is ignored), and a server answering
``TokenStatus.MOVED`` teaches the client passively: the response's
``remaining`` carries the new shard-map epoch and (on transports that
support it) ``endpoint`` names the destination, so the client installs the
route, retries once against the new owner, and degrades through the local
fallback policy if the destination is unreachable.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Mapping, Optional, Tuple

from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.token_service import TokenResult, TokenService
from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine import TokenStatus
from sentinel_tpu.metrics.ha import ha_metrics

Endpoint = Tuple[str, int]


class _RouteState:
    """One immutable snapshot of the entire routing view. Never mutated
    after construction — reconfiguration builds a replacement and swaps the
    single ``RoutingTokenClient._state`` reference (atomic in CPython)."""

    __slots__ = ("epoch", "namespace_of", "pod_of", "endpoints", "clients",
                 "global_flows")

    def __init__(self, epoch, namespace_of, pod_of, endpoints, clients,
                 global_flows=None):
        self.epoch = int(epoch)  # shard-map epoch fence
        self.namespace_of: Mapping[int, str] = namespace_of
        self.pod_of: Mapping[str, str] = pod_of
        self.endpoints: Mapping[str, Endpoint] = endpoints
        self.clients: Mapping[str, TokenService] = clients
        # hierarchy tier: flow_id (str) → global budget coordinator
        # endpoint, carried verbatim from the shard map's global_flows
        # section under the same epoch fence
        self.global_flows: Mapping[str, str] = global_flows or {}

    def replace(self, **kw) -> "_RouteState":
        fields = {s: kw.get(s, getattr(self, s)) for s in self.__slots__}
        return _RouteState(**fields)


def _parse_endpoint(text: str) -> Optional[Endpoint]:
    host, sep, port = str(text).rpartition(":")
    if not sep or not host:
        return None
    try:
        return host, int(port)
    except ValueError:
        return None


class RoutingTokenClient(TokenService):
    def __init__(
        self,
        timeout_ms: int = 20,
        namespace_of: Optional[Mapping[int, str]] = None,
        pod_of: Optional[Mapping[str, str]] = None,
        endpoints: Optional[Mapping[str, Endpoint]] = None,
        client_factory: Callable[..., TokenService] = TokenClient,
        fallback=None,
        shard_maps=None,
    ):
        self.timeout_ms = timeout_ms
        self._factory = client_factory
        self._lock = threading.Lock()
        # the one mutable cell: an immutable routing snapshot, swapped
        # wholesale (see module docstring)
        self._state = _RouteState(
            0, dict(namespace_of or {}), dict(pod_of or {}),
            dict(endpoints or {}), {},
        )
        # when the cluster moves a namespace out from under us and the
        # destination is unreachable, this policy answers locally instead of
        # surfacing MOVED to the caller (None → MOVED is surfaced)
        self.fallback = fallback
        # namespaces each pod's client has declared via the PING handshake —
        # a pod can serve several, and AVG_LOCAL counts need every one
        self._declared: Dict[str, set] = {}
        # concurrent-mode: per-pod token ids are local counters (each pod's
        # ConcurrencyManager counts from 1), so the router namespaces the
        # ids it returns by embedding a pod number in the high bits — the
        # caller-visible id is globally unique and release routes exactly
        self._pod_nums: Dict[str, int] = {}  # pod_id → 1-based number
        self._pods_by_num: Dict[int, str] = {}
        if shard_maps is not None:
            # ShardMapPublisher (cluster.rebalance): follow pushes passively
            shard_maps.listen(self.apply_shard_map)

    # -- reconfiguration ----------------------------------------------------
    @property
    def epoch(self) -> int:
        """Shard-map epoch of the installed routing view."""
        return self._state.epoch

    @property
    def _clients(self) -> Mapping[str, TokenService]:
        """Read-only view of the live per-pod clients (tests and
        introspection; the authoritative copy lives in ``_state``)."""
        return self._state.clients

    def update(
        self,
        namespace_of: Optional[Mapping[int, str]] = None,
        pod_of: Optional[Mapping[str, str]] = None,
        endpoints: Optional[Mapping[str, Endpoint]] = None,
    ) -> None:
        """Install new routing tables (assignment-config push analog).
        Pods that disappeared get their clients closed — only after the new
        state is published, so a reader that routed on the old snapshot
        never dispatches on a client closed mid-request by this thread."""
        retired = []
        with self._lock:
            st = self._state
            kw = {}
            if namespace_of is not None:
                kw["namespace_of"] = dict(namespace_of)
            if pod_of is not None:
                kw["pod_of"] = dict(pod_of)
            if endpoints is not None:
                kw["endpoints"] = dict(endpoints)
                clients = dict(st.clients)
                for pod_id in list(clients):
                    if pod_id not in kw["endpoints"]:
                        retired.append(clients.pop(pod_id))
                        self._declared.pop(pod_id, None)
                kw["clients"] = clients
            self._state = st.replace(**kw)
        for client in retired:  # after the swap, outside the lock
            close = getattr(client, "close", None)
            if close:
                close()

    def apply_shard_map(self, shard_map) -> bool:
        """Point every namespace the map names at its endpoint. Epoch-fenced:
        a map no newer than the installed view is ignored (returns False),
        so out-of-order pushes can't roll routes back."""
        with self._lock:
            st = self._state
            if int(shard_map.epoch) <= st.epoch:
                return False
            pod_of = dict(st.pod_of)
            endpoints = dict(st.endpoints)
            for ns, ep_text in shard_map.endpoint_of.items():
                ep = _parse_endpoint(ep_text)
                if ep is None:
                    record_log.warning(
                        "shard map epoch %s names unparseable endpoint %r "
                        "for %r; keeping old route",
                        shard_map.epoch, ep_text, ns,
                    )
                    continue
                pod_of[ns] = str(ep_text)
                endpoints[str(ep_text)] = ep
            kw = {}
            gf = getattr(shard_map, "global_flows", None)
            if gf:
                # the hierarchy section replaces wholesale — it is part of
                # the same epoched document, not a per-entry merge
                kw["global_flows"] = dict(gf)
            self._state = st.replace(
                epoch=int(shard_map.epoch), pod_of=pod_of,
                endpoints=endpoints, **kw,
            )
        return True

    def _wire_push(self, client) -> None:
        """Subscribe a freshly-built pod client to rev-7 shard-map pushes:
        decoded maps feed :meth:`apply_shard_map`, so a MOVE or election
        outcome re-routes us within one RTT instead of a MOVED round trip.
        The epoch fence makes stale or duplicate pushes harmless."""
        if not hasattr(client, "on_shard_map"):
            return

        def _learn(blob: bytes) -> None:
            from sentinel_tpu.cluster.rebalance import decode_shard_map_doc

            try:
                self.apply_shard_map(decode_shard_map_doc(blob))
            except ValueError:
                pass  # torn push payload; the polling plane will catch up

        client.on_shard_map = _learn

    def coordinator_of(self, flow_id) -> Optional[str]:
        """The global budget coordinator endpoint for ``flow_id`` per the
        installed shard map's ``global_flows`` section, or None when the
        flow has no hierarchical budget. Lock-free snapshot read."""
        return self._state.global_flows.get(str(int(flow_id)))

    def _learn_move(self, namespace: str, ep_text: str, epoch: int) -> bool:
        """Install a single route learned from a MOVED redirect. Same epoch
        fence as :meth:`apply_shard_map`."""
        ep = _parse_endpoint(ep_text)
        if ep is None:
            return False
        with self._lock:
            st = self._state
            if int(epoch) <= st.epoch:
                return False
            pod_of = dict(st.pod_of)
            endpoints = dict(st.endpoints)
            pod_of[namespace] = str(ep_text)
            endpoints[str(ep_text)] = ep
            self._state = st.replace(
                epoch=int(epoch), pod_of=pod_of, endpoints=endpoints,
            )
        return True

    # -- routing ------------------------------------------------------------
    def _route_for(self, flow_id: int):
        """(client, pod_id) actually routed to, or None. One state snapshot
        decides the route — callers that need the pod identity (concurrent
        token-id prefixing) must use THIS pair, not re-derive the pod, or a
        concurrent update() can name a different pod than the issuer."""
        st = self._state  # one atomic snapshot; no lock for the happy path
        ns = st.namespace_of.get(flow_id)
        if ns is None:
            return None
        pod_id = st.pod_of.get(ns)
        if pod_id is None:
            return None
        client = st.clients.get(pod_id)
        declare = False
        if client is None:
            with self._lock:
                st = self._state  # re-snapshot: tables may have moved on
                pod_id = st.pod_of.get(ns, pod_id)
                endpoint = st.endpoints.get(pod_id)
                if endpoint is None:
                    return None
                client = st.clients.get(pod_id)
                if client is None:
                    client = self._factory(
                        endpoint[0], endpoint[1],
                        timeout_ms=self.timeout_ms, namespace=ns,
                    )
                    self._wire_push(client)
                    clients = dict(st.clients)
                    clients[pod_id] = client
                    self._state = st.replace(clients=clients)
                    self._declared[pod_id] = {ns}  # ctor namespace auto-pings
                elif ns not in self._declared.setdefault(pod_id, set()):
                    self._declared[pod_id].add(ns)
                    declare = True
        else:
            with self._lock:
                if ns not in self._declared.setdefault(pod_id, set()):
                    self._declared[pod_id].add(ns)
                    declare = True
        if declare:
            # additional namespace on an existing pod connection: declare it
            # so the server's AVG_LOCAL connection count includes us
            # (best-effort, outside the lock — a lost ping only delays the
            # count to the next keepalive)
            ping = getattr(client, "ping", None)
            if ping is not None:
                ping(namespace=ns)
        return client, pod_id

    def _client_for(self, flow_id: int) -> Optional[TokenService]:
        route = self._route_for(flow_id)
        return None if route is None else route[0]

    # -- MOVED redirects ----------------------------------------------------
    @staticmethod
    def _is_moved(result) -> bool:
        return (
            isinstance(result, TokenResult)
            and result.status == TokenStatus.MOVED
        )

    def _follow_move(self, flow_id, from_pod, moved, op, decide):
        """A server answered MOVED: learn the new route (from the response's
        endpoint trailer, or a shard-map push that already landed), retry
        ONCE against the new owner, and degrade through the local fallback
        policy when the destination is unreachable or unknown. Returns
        (result, pod_id) with the pod that actually issued the verdict."""
        ha_metrics().count_fallback("moved_follow")
        st = self._state
        ns = st.namespace_of.get(flow_id)
        endpoint = getattr(moved, "endpoint", "") or ""
        epoch = int(getattr(moved, "remaining", 0))
        if ns is not None and endpoint:
            self._learn_move(ns, endpoint, epoch)
        route = self._route_for(flow_id)
        if route is not None and route[1] != from_pod:
            client, pod_id = route
            try:
                result = op(client)
            except Exception:
                record_log.exception(
                    "moved-to destination %s raised; degrading", pod_id,
                )
                result = None
            if result is not None and not self._is_moved(result):
                return result, pod_id
        # no newer route, destination unreachable, or it answered MOVED
        # again (a second hop inside one request is a routing storm, not a
        # redirect to chase): answer locally or surface the redirect
        if self.fallback is not None:
            ha_metrics().count_fallback("moved_degraded")
            return decide(), from_pod
        return moved, from_pod

    # -- TokenService -------------------------------------------------------
    def request_token(self, flow_id, acquire=1, prioritized=False) -> TokenResult:
        route = self._route_for(flow_id)
        if route is None:
            # unknown flow/namespace/pod: same shape as the reference's
            # no-rule path — caller falls back to its local check
            return TokenResult(TokenStatus.NO_RULE_EXISTS)
        client, pod_id = route
        result = client.request_token(flow_id, acquire, prioritized)
        if self._is_moved(result):
            result, _ = self._follow_move(
                flow_id, pod_id, result,
                lambda c: c.request_token(flow_id, acquire, prioritized),
                lambda: self.fallback.decide(flow_id, acquire, prioritized),
            )
        return result

    def request_params_token(self, flow_id, acquire, param_hashes) -> TokenResult:
        route = self._route_for(flow_id)
        if route is None:
            return TokenResult(TokenStatus.NO_RULE_EXISTS)
        client, pod_id = route
        result = client.request_params_token(flow_id, acquire, param_hashes)
        if self._is_moved(result):
            result, _ = self._follow_move(
                flow_id, pod_id, result,
                lambda c: c.request_params_token(
                    flow_id, acquire, param_hashes
                ),
                lambda: self.fallback.decide(flow_id, acquire),
            )
        return result

    # pod number lives in bits 48+ of the caller-visible token id; pod-local
    # ids below 2^48 (a per-pod counter would take >8900 years at 1M acq/s)
    _POD_ID_SHIFT = 48
    _LOCAL_ID_MASK = (1 << 48) - 1

    def request_concurrent_token(self, flow_id, acquire=1, prioritized=False):
        route = self._route_for(flow_id)
        if route is None:
            return TokenResult(TokenStatus.NO_RULE_EXISTS)
        client, pod_id = route
        result = client.request_concurrent_token(flow_id, acquire, prioritized)
        if self._is_moved(result):
            result, pod_id = self._follow_move(
                flow_id, pod_id, result,
                lambda c: c.request_concurrent_token(
                    flow_id, acquire, prioritized
                ),
                lambda: self.fallback.decide(flow_id, acquire, prioritized),
            )
        if (
            result.ok and result.token_id
            and result.token_id <= self._LOCAL_ID_MASK
        ):
            with self._lock:
                num = self._pod_nums.get(pod_id)
                if num is None:
                    num = len(self._pod_nums) + 1
                    self._pod_nums[pod_id] = num
                    self._pods_by_num[num] = pod_id
            return TokenResult(
                result.status, result.remaining, result.wait_ms,
                (num << self._POD_ID_SHIFT) | result.token_id,
            )
        return result

    def release_concurrent_token(self, token_id):
        token_id = int(token_id)
        num = token_id >> self._POD_ID_SHIFT
        local_id = token_id & self._LOCAL_ID_MASK
        st = self._state
        with self._lock:
            pod_id = self._pods_by_num.get(num)
        if pod_id is not None and pod_id in st.clients:
            clients = [st.clients[pod_id]]
        elif num:
            # prefixed id whose issuing pod left the routing table: only
            # that pod could hold the token (ids are pod-scoped), and its
            # counters died with it — fail fast as already-released.
            # Broadcasting the masked local id could wrongly release an
            # UNRELATED token that another pod issued under the same
            # local counter value (round-3 advisor finding).
            return TokenResult(TokenStatus.ALREADY_RELEASE)
        else:
            # genuinely unprefixed id (issued outside the router):
            # degrade to first-success fan-out with the raw id
            clients = list(st.clients.values())
        result = TokenResult(TokenStatus.FAIL)
        for client in clients:
            r = client.release_concurrent_token(local_id)
            if r.ok:  # RELEASE_OK — a release never answers plain OK
                return r
            result = r
        return result

    def close(self) -> None:
        with self._lock:
            st = self._state
            self._state = st.replace(clients={})
            self._declared.clear()
        for client in st.clients.values():
            close = getattr(client, "close", None)
            if close:
                close()
