"""Namespace-routing token client.

The reference points each app at its namespace's token server through
assignment config (``ClusterClientAssignConfig`` pushed via the property
system); an app in several namespaces would run several clients. This client
generalizes that: it holds one ``TokenClient`` per pod and routes each
request by ``flow_id → namespace → pod``, so a caller is oblivious to the
partitioning (``cluster/namespaces.py``).

Reconfiguration (``update``) swaps the routing tables atomically — in-flight
requests finish against the old pod (its verdict is still valid: counters
are ephemeral and the old owner keeps enforcing until clients drain), new
requests go to the new owner.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Mapping, Optional, Tuple

from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.token_service import TokenResult, TokenService
from sentinel_tpu.engine import TokenStatus

Endpoint = Tuple[str, int]


class RoutingTokenClient(TokenService):
    def __init__(
        self,
        timeout_ms: int = 20,
        namespace_of: Optional[Mapping[int, str]] = None,
        pod_of: Optional[Mapping[str, str]] = None,
        endpoints: Optional[Mapping[str, Endpoint]] = None,
        client_factory: Callable[..., TokenService] = TokenClient,
    ):
        self.timeout_ms = timeout_ms
        self._factory = client_factory
        self._lock = threading.Lock()
        # routing tables — replaced wholesale by update(), never mutated
        self._namespace_of: Mapping[int, str] = dict(namespace_of or {})
        self._pod_of: Mapping[str, str] = dict(pod_of or {})
        self._endpoints: Mapping[str, Endpoint] = dict(endpoints or {})
        self._clients: Dict[str, TokenService] = {}
        # namespaces each pod's client has declared via the PING handshake —
        # a pod can serve several, and AVG_LOCAL counts need every one
        self._declared: Dict[str, set] = {}

    # -- reconfiguration ----------------------------------------------------
    def update(
        self,
        namespace_of: Optional[Mapping[int, str]] = None,
        pod_of: Optional[Mapping[str, str]] = None,
        endpoints: Optional[Mapping[str, Endpoint]] = None,
    ) -> None:
        """Install new routing tables (assignment-config push analog).
        Pods that disappeared get their clients closed."""
        with self._lock:
            if namespace_of is not None:
                self._namespace_of = dict(namespace_of)
            if pod_of is not None:
                self._pod_of = dict(pod_of)
            if endpoints is not None:
                self._endpoints = dict(endpoints)
                for pod_id in list(self._clients):
                    if pod_id not in self._endpoints:
                        client = self._clients.pop(pod_id)
                        self._declared.pop(pod_id, None)
                        close = getattr(client, "close", None)
                        if close:
                            close()

    def _client_for(self, flow_id: int) -> Optional[TokenService]:
        declare = False
        with self._lock:
            ns = self._namespace_of.get(flow_id)
            if ns is None:
                return None
            pod_id = self._pod_of.get(ns)
            if pod_id is None:
                return None
            client = self._clients.get(pod_id)
            if client is None:
                endpoint = self._endpoints.get(pod_id)
                if endpoint is None:
                    return None
                client = self._factory(
                    endpoint[0], endpoint[1],
                    timeout_ms=self.timeout_ms, namespace=ns,
                )
                self._clients[pod_id] = client
                self._declared[pod_id] = {ns}  # ctor namespace auto-pings
            elif ns not in self._declared.setdefault(pod_id, set()):
                self._declared[pod_id].add(ns)
                declare = True
        if declare:
            # additional namespace on an existing pod connection: declare it
            # so the server's AVG_LOCAL connection count includes us
            # (best-effort, outside the lock — a lost ping only delays the
            # count to the next keepalive)
            ping = getattr(client, "ping", None)
            if ping is not None:
                ping(namespace=ns)
        return client

    # -- TokenService -------------------------------------------------------
    def request_token(self, flow_id, acquire=1, prioritized=False) -> TokenResult:
        client = self._client_for(flow_id)
        if client is None:
            # unknown flow/namespace/pod: same shape as the reference's
            # no-rule path — caller falls back to its local check
            return TokenResult(TokenStatus.NO_RULE_EXISTS)
        return client.request_token(flow_id, acquire, prioritized)

    def request_params_token(self, flow_id, acquire, param_hashes) -> TokenResult:
        client = self._client_for(flow_id)
        if client is None:
            return TokenResult(TokenStatus.NO_RULE_EXISTS)
        return client.request_params_token(flow_id, acquire, param_hashes)

    def request_concurrent_token(self, flow_id, acquire=1, prioritized=False):
        client = self._client_for(flow_id)
        if client is None:
            return TokenResult(TokenStatus.NO_RULE_EXISTS)
        return client.request_concurrent_token(flow_id, acquire, prioritized)

    def release_concurrent_token(self, token_id):
        # token ids don't carry the flow — broadcast the release; exactly
        # one pod holds the token (reference releases against the issuing
        # server; a router must fan out or remember issuance — we fan out)
        with self._lock:
            clients = list(self._clients.values())
        result = TokenResult(TokenStatus.FAIL)
        for client in clients:
            r = client.release_concurrent_token(token_id)
            if r.status == TokenStatus.OK:
                result = r
        return result

    def close(self) -> None:
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
            self._declared.clear()
        for client in clients:
            close = getattr(client, "close", None)
            if close:
                close()
