"""Token client over the shared-memory ring front door (co-located).

``ShmTokenClient`` is ``TokenClient`` with the socket swapped for one
mmap'd SPSC ring pair (``native/src/sentinel_shm.cpp``): same xid
correlation, pending-promise map, pipelined batch chunking, deadline
stamping, reconnect backoff ladder and chaos hooks — the request methods
are inherited verbatim and only the transport layer (connect / send /
read loop / teardown) is replaced. A co-located sidecar (Envoy RLS, a
per-host agent) gets token verdicts without the TCP loopback's
syscall+copy tax: the steady state is two memcpys and zero syscalls per
batch (the futex doorbell only rings when the peer advertised it went to
sleep).

Teardown is the one structural difference from TCP: the native client
handle is freed by ``sn_shm_client_destroy``, so the reader thread —
which blocks inside ``sn_shm_client_recv`` — must be the thread that
closes it. ``close()``/``_drop_ring`` only *detach* the ring; the reader
notices within one recv timeout, closes the segment, and exits.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from sentinel_tpu import chaos
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.client import (
    RECONNECT_JITTER,
    TokenClient,
    _count_recv,
    _count_unknown_frame,
)
from sentinel_tpu.core.log import record_log
from sentinel_tpu.native.lib import ShmRingClient

# reader poll granularity: only teardown latency, never a batching stall
# (the C recv spins/parks on the ring and returns the moment a response
# publishes; this bounds how long a detached reader lingers)
_READER_POLL_MS = 100


class ShmTokenClient(TokenClient):
    """``TokenClient`` API over one shm ring segment in ``shm_dir``.

    ``shm_dir`` must be the directory a ``NativeTokenServer(shm_dir=...)``
    door is serving on this host. Connection failures (no live door, door
    restarted) follow the TCP client's lazy-reconnect contract: requests
    return FAIL/None immediately and the next attempt re-creates a fresh
    segment under the same exponential backoff ladder.
    """

    def __init__(self, shm_dir: str, timeout_ms: int = 20,
                 namespace: str = "default", slot_payload: int = 65536,
                 n_slots: int = 16, spin_us: Optional[int] = None,
                 lease: bool = False, lease_want: int = 256,
                 lease_backoff_s: float = 0.1, wait_and_admit: bool = False):
        super().__init__(f"shm:{shm_dir}", -1, timeout_ms, namespace,
                         lease=lease, lease_want=lease_want,
                         lease_backoff_s=lease_backoff_s,
                         wait_and_admit=wait_and_admit)
        self.shm_dir = shm_dir
        self.slot_payload = slot_payload
        self.n_slots = n_slots
        self.spin_us = spin_us
        self._ring: Optional[ShmRingClient] = None

    # -- transport layer (everything above this rides the superclass) -------
    def _ensure_connected(self) -> bool:
        if self._ring is not None:
            return True
        with self._state_lock:
            if self._ring is not None:
                return True
            now = time.monotonic()
            if now - self._last_connect_attempt < self._reconnect_delay_s:
                return False
            self._last_connect_attempt = now
            try:
                # raises RuntimeError (propagated: permanent, the native
                # lib lacks the shm door) vs ConnectionRefusedError/OSError
                # (transient: no live server — backoff and retry)
                ring = ShmRingClient(
                    self.shm_dir, slot_payload=self.slot_payload,
                    n_slots=self.n_slots, spin_us=self.spin_us,
                )
            except OSError as e:
                self._consecutive_failures += 1
                k = min(self._consecutive_failures, 16)
                self._reconnect_delay_s = min(
                    self._reconnect_base_s * (2 ** (k - 1)),
                    self._reconnect_max_s,
                ) * (1.0 + RECONNECT_JITTER * random.random())
                if self._consecutive_failures <= 3:
                    record_log.warning(
                        "shm token door unreachable (%d consecutive): %s",
                        self._consecutive_failures, e,
                    )
                return False
            self._ring = ring
            self._consecutive_failures = 0
            self._reconnect_delay_s = 0.0
            self._reader = threading.Thread(
                target=self._read_loop, args=(ring,), daemon=True,
                name="sentinel-shm-client-reader",
            )
            self._reader.start()
        # handshake outside _state_lock (ping → _send → _ensure_connected
        # would re-enter); best-effort, same as the TCP client
        self.ping()
        return True

    def _drop_ring(self, ring: ShmRingClient) -> None:
        """Detach (never destroy — the reader owns the native handle's
        final close) and fail waiters so they fall back immediately."""
        with self._state_lock:
            was_active = self._ring is ring
            if was_active:
                self._ring = None
        if was_active:
            for pending in list(self._pending.values()):
                pending.event.set()

    def close(self) -> None:
        try:
            self.flush_outcomes()  # best-effort, same as TCP
        except Exception:
            pass
        self._return_leases()  # best-effort conservation, same as TCP
        ring = self._ring
        if ring is not None:
            self._drop_ring(ring)
        reader = self._reader
        if reader is not None and reader is not threading.current_thread():
            # deterministic segment teardown for callers that check: the
            # reader notices the detach within one poll and unlinks
            reader.join(timeout=1.0)

    def _read_loop(self, ring: ShmRingClient) -> None:
        try:
            while True:
                try:
                    payload = ring.recv_payload(timeout_ms=_READER_POLL_MS)
                except (ConnectionResetError, OSError):
                    break  # server dropped the segment or died
                if self._ring is not ring:
                    break  # detached by close()/reconnect
                if payload is None:
                    continue
                if chaos.ARMED:  # inbound bit-rot injection
                    payload = chaos.mangle("frame_corrupt", payload)
                _count_recv(len(payload))
                try:
                    mtype = P.peek_type(payload)
                    if mtype in P.PUSH_TYPES:
                        # rev-7 push off the ring's response lane: applied
                        # out-of-band, never resolves a pending xid, and a
                        # malformed push is counted + skipped inside the
                        # handler — the segment survives
                        self._handle_push(bytes(payload))
                        continue
                    if mtype not in P.KNOWN_TYPES:
                        # a newer server's frame type: skip + count instead
                        # of dropping the segment (mixed-rev fleets)
                        _count_unknown_frame()
                        continue
                    if mtype == P.MsgType.BATCH_FLOW:
                        xid = int.from_bytes(payload[:4], "big", signed=True)
                        pending = self._pending.get(xid)
                        if pending is not None:
                            pending.response = bytes(payload)
                            pending.event.set()
                        continue
                    if mtype in P.LEASE_TYPES:
                        rsp = P.decode_lease_response(bytes(payload))
                    else:
                        rsp = P.decode_response(bytes(payload))
                except Exception:
                    # corrupt server bytes degrade to a dropped connection,
                    # never a dead reader with a traceback (TCP contract)
                    record_log.warning(
                        "malformed shm frame from server; dropping segment"
                    )
                    break
                pending = self._pending.get(rsp.xid)
                if pending is not None:
                    pending.response = rsp
                    pending.event.set()
        finally:
            self._drop_ring(ring)
            # sole closer of the native handle; under _send_lock so a
            # request thread that raced the detach finishes its in-flight
            # send before the mapping is freed (send_frame then raises on
            # the cleared handle instead of touching freed memory)
            with self._send_lock:
                ring.close()

    def _send_outcome_frames(self, frames) -> bool:
        """Rev-6 outcome frames over shm: one ring slot carries exactly ONE
        frame (``send_frame`` strips the whole buffer's 2-byte length
        prefix), so the TCP client's coalesced single-write is replaced by
        one slot per frame — still fire-and-forget, still zero round
        trips."""
        ok = True
        for f in frames:
            ok = self._send(f, piggyback=False) and ok
        return ok

    def _send(self, data: bytes, piggyback: bool = True) -> bool:
        if piggyback and self._outcome_buf:
            # publish buffered outcomes as their own slots ahead of this
            # request frame (no prefix-concatenation on a ring transport)
            self._send_outcome_frames(self._drain_outcome_frames())
        if not self._ensure_connected():
            return False
        ring = self._ring
        if ring is None:
            return False
        if chaos.ARMED:
            if chaos.should("conn_reset"):  # segment death mid-request
                self._drop_ring(ring)
                return False
            data = chaos.mangle("frame_corrupt", data)  # outbound bit rot
        try:
            with self._send_lock:
                # ring full past the request budget = backpressure, not
                # death: fail this request (caller falls back) but keep
                # the segment — the server is draining, just slower than
                # we produce
                return ring.send_frame(data, timeout_ms=self.timeout_ms)
        except (ConnectionResetError, OSError):
            self._drop_ring(ring)
            return False
