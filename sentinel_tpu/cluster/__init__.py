"""Cluster flow control: token client/server (analog of ``sentinel-cluster``).

- ``protocol``: binary wire codec (5 request types, length-prefixed frames —
  the shape of ``sentinel-cluster-common-default``'s netty codec).
- ``token_service``: the ``TokenService`` SPI and its engine-backed default
  (``DefaultTokenService.java:36`` analog whose decision path is the jitted
  ``sentinel_tpu.engine.decide`` kernel).
- ``server``: asyncio transport + micro-batcher (``NettyTransportServer``
  analog; the batcher is the host front door that turns the 20ms RPC budget
  into ≤~1ms device batches).
- ``client``: sync token client with xid-correlated responses, timeout and
  reconnect (``DefaultClusterTokenClient``/``NettyTransportClient`` analog).
- ``api``: process-global cluster state (CLIENT/SERVER/OFF) consumed by the
  local flow checker's cluster branch (``ClusterStateManager`` analog).

Re-exports are LAZY (PEP 562): importing a jax-free submodule (``protocol``,
``connection``) must not pull the jax-backed service stack — socket-only
processes (bench load clients, the ASan fuzz harness, sidecars that only
speak the wire format) depend on that boundary.
"""

_EXPORTS = {
    "TokenResult": "sentinel_tpu.cluster.token_service",
    "TokenService": "sentinel_tpu.cluster.token_service",
    "DefaultTokenService": "sentinel_tpu.cluster.token_service",
    "ConcurrencyManager": "sentinel_tpu.cluster.concurrent",
    "ConcurrentFlowRule": "sentinel_tpu.cluster.concurrent",
    "ExpiryTask": "sentinel_tpu.cluster.concurrent",
    "ClusterMode": "sentinel_tpu.cluster.api",
    "get_mode": "sentinel_tpu.cluster.api",
    "set_client": "sentinel_tpu.cluster.api",
    "set_embedded_server": "sentinel_tpu.cluster.api",
    "set_mode": "sentinel_tpu.cluster.api",
    "ConnectionManager": "sentinel_tpu.cluster.connection",
    "NamespaceAssignment": "sentinel_tpu.cluster.namespaces",
    "aggregate_snapshots": "sentinel_tpu.cluster.namespaces",
    "flow_namespaces": "sentinel_tpu.cluster.namespaces",
    "partition_rules": "sentinel_tpu.cluster.namespaces",
    "RoutingTokenClient": "sentinel_tpu.cluster.routing",
    "MoveCoordinator": "sentinel_tpu.cluster.rebalance",
    "MoveTarget": "sentinel_tpu.cluster.rebalance",
    "ShardMap": "sentinel_tpu.cluster.rebalance",
    "ShardMapPublisher": "sentinel_tpu.cluster.rebalance",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module 'sentinel_tpu.cluster' has no attribute {name!r}"
        )
    import importlib

    return getattr(importlib.import_module(mod), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
