"""Cluster flow control: token client/server (analog of ``sentinel-cluster``).

- ``protocol``: binary wire codec (5 request types, length-prefixed frames —
  the shape of ``sentinel-cluster-common-default``'s netty codec).
- ``token_service``: the ``TokenService`` SPI and its engine-backed default
  (``DefaultTokenService.java:36`` analog whose decision path is the jitted
  ``sentinel_tpu.engine.decide`` kernel).
- ``server``: asyncio transport + micro-batcher (``NettyTransportServer``
  analog; the batcher is the host front door that turns the 20ms RPC budget
  into ≤~1ms device batches).
- ``client``: sync token client with xid-correlated responses, timeout and
  reconnect (``DefaultClusterTokenClient``/``NettyTransportClient`` analog).
- ``api``: process-global cluster state (CLIENT/SERVER/OFF) consumed by the
  local flow checker's cluster branch (``ClusterStateManager`` analog).
"""

from sentinel_tpu.cluster.token_service import (
    TokenResult,
    TokenService,
    DefaultTokenService,
)
from sentinel_tpu.cluster.concurrent import (
    ConcurrencyManager,
    ConcurrentFlowRule,
    ExpiryTask,
)
from sentinel_tpu.cluster.api import (
    ClusterMode,
    get_mode,
    set_client,
    set_embedded_server,
    set_mode,
)
from sentinel_tpu.cluster.connection import ConnectionManager
from sentinel_tpu.cluster.namespaces import (
    NamespaceAssignment,
    aggregate_snapshots,
    flow_namespaces,
    partition_rules,
)
from sentinel_tpu.cluster.routing import RoutingTokenClient

__all__ = [
    "ConnectionManager",
    "NamespaceAssignment",
    "RoutingTokenClient",
    "aggregate_snapshots",
    "flow_namespaces",
    "partition_rules",
    "TokenResult",
    "TokenService",
    "DefaultTokenService",
    "ConcurrencyManager",
    "ConcurrentFlowRule",
    "ExpiryTask",
    "ClusterMode",
    "get_mode",
    "set_mode",
    "set_client",
    "set_embedded_server",
]
