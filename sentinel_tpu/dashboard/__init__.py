"""Dashboard: the out-of-process console (``sentinel-dashboard`` analog).

Pull-based, like the reference (SURVEY.md §1 L8): apps POST heartbeats to
``/registry/machine``; the ``MetricFetcher`` polls each healthy machine's
``/metric`` command endpoint and aggregates into an in-memory repository
(5-minute retention, ``InMemoryMetricsRepository.java:40-63``); rule CRUD is
proxied to the app's command center via ``ApiClient``
(``SentinelApiClient.java:93,384,416``). The web UI is one embedded HTML page
over the REST API (the reference ships an AngularJS app; the console's value
is the API, not the framework it renders with).
"""

from sentinel_tpu.dashboard.discovery import AppManagement, MachineInfo
from sentinel_tpu.dashboard.repository import InMemoryMetricsRepository, MetricEntry
from sentinel_tpu.dashboard.api_client import ApiClient
from sentinel_tpu.dashboard.fetcher import MetricFetcher
from sentinel_tpu.dashboard.server import DashboardServer
from sentinel_tpu.dashboard.dynamic_rules import (
    ApiRuleProvider,
    ApiRulePublisher,
    DynamicRuleProvider,
    DynamicRulePublisher,
    FileRuleStore,
    StoreRuleProvider,
    StoreRulePublisher,
)

__all__ = [
    "AppManagement",
    "MachineInfo",
    "InMemoryMetricsRepository",
    "MetricEntry",
    "ApiClient",
    "MetricFetcher",
    "DashboardServer",
    "DynamicRuleProvider",
    "DynamicRulePublisher",
    "ApiRuleProvider",
    "ApiRulePublisher",
    "StoreRuleProvider",
    "StoreRulePublisher",
    "FileRuleStore",
]
