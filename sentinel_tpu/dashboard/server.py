"""Dashboard HTTP server: heartbeat sink + REST API + minimal console page.

Analog of the Spring Boot side of ``sentinel-dashboard``:
``MachineRegistryController`` (``/registry/machine``), metric queries over
the in-memory repository, and rule CRUD proxied to app command centers
(``FlowControllerV1`` + ``SentinelApiClient``). Runs on the stdlib
threading HTTP server — the console is an ops tool, not a hot path.
"""

from __future__ import annotations

import hmac
import json
import secrets
import threading
from typing import Optional, Tuple

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.httpd import (
    HttpService,
    Response,
    html_response,
    json_response,
)
from sentinel_tpu.dashboard.api_client import ApiClient
from sentinel_tpu.dashboard.discovery import AppManagement, MachineInfo
from sentinel_tpu.dashboard.fetcher import MetricFetcher
from sentinel_tpu.dashboard.repository import InMemoryMetricsRepository
from sentinel_tpu.dashboard.rules_repo import InMemoryRuleRepository
from sentinel_tpu.dashboard.validation import validate_rule

RULE_TYPES = ("flow", "degrade", "system", "authority", "paramFlow", "gateway")

# Paths reachable without a session when auth is enabled: machine heartbeats
# (apps can't log in) and the login exchange itself + the console shell,
# which renders a login form client-side (same exclusions as the
# reference's LoginAuthenticationFilter).
AUTH_EXEMPT = {"registry/machine", "auth/login", "", "index.html"}

_INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>sentinel-tpu console</title>
<style>
 :root{color-scheme:light;
  --surface-1:#fcfcfb; --text-primary:#0b0b0b; --text-secondary:#52514e;
  --series-1:#2a78d6; --series-2:#eb6834; --series-3:#7b5cd6;
  --grid:#e4e3df; --border:#ccc}
 @media (prefers-color-scheme: dark){
  :root{color-scheme:dark;
   --surface-1:#1a1a19; --text-primary:#ffffff; --text-secondary:#c3c2b7;
   --series-1:#3987e5; --series-2:#d95926; --series-3:#9b7ff0;
   --grid:#33332f; --border:#444}}
 body{font-family:system-ui,sans-serif;margin:2rem;color:var(--text-primary);
  background:var(--surface-1)}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.5rem}
 h3{font-size:.95rem;margin:.8rem 0 .3rem}
 table{border-collapse:collapse;min-width:40rem}
 th,td{border:1px solid var(--border);padding:.35rem .6rem;text-align:left;
  font-size:.9rem}
 th{background:color-mix(in srgb, var(--text-primary) 6%, var(--surface-1))}
 .dead{color:#b00} .ok{color:#070}
 input,select{margin:.1rem .2rem .1rem 0}
 .tab{margin-right:.3rem} .tab.on{font-weight:bold;text-decoration:underline}
 #chartwrap{margin-top:1rem} .legend{font-size:.85rem;color:var(--text-secondary)}
 .legend b{font-weight:600;color:var(--text-primary)}
 .sw{display:inline-block;width:10px;height:10px;border-radius:2px;
  vertical-align:baseline;margin:0 .3rem 0 .8rem}
 #tip{position:absolute;pointer-events:none;background:var(--surface-1);
  border:1px solid var(--border);padding:.25rem .5rem;font-size:.8rem;
  display:none;border-radius:4px}
</style></head><body>
<h1>sentinel-tpu console</h1>
<div id="login" style="display:none">
 <h2>login</h2>
 <input id="u" placeholder="username"> <input id="p" type="password"
  placeholder="password"> <button onclick="login()">sign in</button>
 <span id="loginmsg" class="dead"></span>
</div>
<div class="legend" id="resfilterwrap" style="display:none">filter resources
 <input id="resfilter" oninput="filterChanged()" placeholder="substring"></div>
<div id="apps"></div>
<div id="ruled" style="display:none">
 <h2>rules: <span id="ruleapp"></span></h2>
 <div id="ruletabs"></div>
 <div id="ruleview"></div>
 <span id="rulemsg" class="legend"></span>
</div>
<div id="clusterd" style="display:none">
 <h2>cluster monitor: <span id="clusterapp"></span></h2>
 <div id="clusterview"></div>
</div>
<div id="machined" style="display:none">
 <h2>machine: <span id="machineres"></span></h2>
 <div id="machineview"></div>
</div>
<div id="chartwrap" style="display:none">
 <h2>timeline: <span id="chartres"></span></h2>
 <div class="legend">machine <select id="chartmachine"
   onchange="chartCtx.machine=this.value;loadChart()"></select>
  window <select id="chartwin" onchange="loadChart()">
   <option value="60000">1 min</option>
   <option value="180000">3 min</option>
   <option value="300000" selected>5 min</option></select></div>
 <div class="legend"><span class="sw" style="background:var(--series-1)"></span>
  <b>pass qps</b><span class="sw" style="background:var(--series-2)"></span>
  <b>block qps</b><span class="sw" style="background:var(--series-3)"></span>
  <b>exception qps</b></div>
 <svg id="chart" width="720" height="220" role="img"
  aria-label="pass, block and exception qps over time"></svg>
 <div class="legend"><span class="sw" style="background:var(--series-1)"></span>
  <b>avg rt (ms)</b></div>
 <svg id="rtchart" width="720" height="140" role="img"
  aria-label="average response time over time"></svg>
 <div id="tip"></div>
</div>
<script>
// resource names and machine fields are attacker-influenced (a resource is
// often a raw request path) — build rows with textContent only, never
// string-interpolated HTML
const RULE_TYPES = ['flow','degrade','system','authority','paramFlow','gateway'];
// editable fields per rule type, in the agent's JSON schema
const SCHEMAS = {
 flow: ['resource','count','grade','limitApp','strategy','refResource',
        'controlBehavior','warmUpPeriodSec','maxQueueingTimeMs','clusterMode'],
 degrade: ['resource','grade','count','timeWindow','minRequestAmount',
           'statIntervalMs','slowRatioThreshold','limitApp'],
 system: ['highestSystemLoad','highestCpuUsage','qps','avgRt','maxThread'],
 authority: ['resource','limitApp','strategy'],
 paramFlow: ['resource','paramIdx','count','grade','durationInSec',
             'burstCount','controlBehavior','maxQueueingTimeMs',
             'paramFlowItemList'],
 gateway: ['resource','resourceMode','count','grade','intervalSec',
           'controlBehavior','burst','maxQueueingTimeoutMs','paramItem'],
};
let curApp = '', curType = 'flow', editId = null;
function row(table, cells, tag){
  const tr = document.createElement('tr');
  for (const c of cells){
    const td = document.createElement(tag || 'td');
    if (c && c.nodeType) td.appendChild(c);
    else if (c && typeof c === 'object' && c.cls !== undefined){
      td.textContent = c.text; td.className = c.cls;
    }
    else td.textContent = c;
    tr.appendChild(td);
  }
  table.appendChild(tr);
}
async function api(path){
  const r = await fetch(path);
  if (r.status === 401){ showLogin(); throw new Error('auth'); }
  return r.json();
}
function showLogin(){ document.getElementById('login').style.display=''; }
async function login(){
  const body = JSON.stringify({username: u.value, password: p.value});
  const r = await fetch('auth/login', {method:'POST', body});
  if (r.status === 200){ login_el().style.display='none'; refresh(); }
  else document.getElementById('loginmsg').textContent = 'invalid credentials';
}
function login_el(){ return document.getElementById('login'); }
function openRules(app){
  curApp = app;
  document.getElementById('ruled').style.display='';
  document.getElementById('ruleapp').textContent = app;
  const tabs = document.getElementById('ruletabs');
  tabs.innerHTML = '';
  for (const t of RULE_TYPES){
    const b = document.createElement('button');
    b.textContent = t; b.className = 'tab' + (t===curType?' on':'');
    b.onclick = () => { curType = t; editId = null; openRules(curApp); };
    tabs.appendChild(b);
  }
  const ab = document.createElement('button');
  ab.textContent = 'api groups'; ab.className = 'tab' + (curType==='apiGroups'?' on':'');
  ab.onclick = () => { curType = 'apiGroups'; openRules(curApp); };
  tabs.appendChild(ab);
  if (curType === 'apiGroups') loadApiGroups(); else loadRules();
}
function coerce(text){
  if (text === '') return undefined;
  if (text === 'true') return true;
  if (text === 'false') return false;
  if (text[0] === '{' || text[0] === '[') {
    try { return JSON.parse(text); } catch(e) { return text; }
  }
  const n = Number(text);
  return Number.isNaN(n) ? text : n;
}
function fieldValue(rule, f){
  const v = rule[f];
  if (v === undefined || v === null) return '';
  return (typeof v === 'object') ? JSON.stringify(v) : String(v);
}
let lastRules = [];
async function loadRules(){
  if (curType === 'apiGroups') return loadApiGroups();
  const qs = `app=${encodeURIComponent(curApp)}&type=${encodeURIComponent(curType)}`;
  let rules = [];
  try { rules = await api('v1/rules?' + qs); } catch(e){}
  if (!Array.isArray(rules)) rules = [];
  lastRules = rules;
  renderView();
}
// render from lastRules WITHOUT re-fetching: a v1/rules fetch re-syncs the
// dashboard repository and assigns fresh ids, which would orphan the id an
// in-progress edit captured
function renderView(fill){
  const fields = SCHEMAS[curType];
  if (!fields) return;  // non-CRUD tab (apiGroups) owns #ruleview itself
  const qs = `app=${encodeURIComponent(curApp)}&type=${encodeURIComponent(curType)}`;
  const view = document.getElementById('ruleview');
  view.innerHTML = '';
  const table = document.createElement('table');
  row(table, ['id', ...fields, '', ''], 'th');
  for (const r of lastRules){
    const eb = document.createElement('button'); eb.textContent = 'edit';
    eb.onclick = () => { editId = r.id; renderView(r); };
    const db = document.createElement('button'); db.textContent = 'delete';
    db.onclick = async () => {
      const resp = await fetch(`v1/rule?${qs}&id=${r.id}`, {method:'DELETE'});
      msg(await resp.json()); loadRules();
    };
    row(table, [String(r.id), ...fields.map(f => fieldValue(r, f)), eb, db]);
  }
  view.appendChild(table);
  const form = document.createElement('div');
  const title = document.createElement('h3');
  title.textContent = editId === null ? 'add rule' : `edit rule ${editId}`;
  form.appendChild(title);
  for (const f of fields){
    const inp = document.createElement('input');
    inp.id = 'f_' + f; inp.placeholder = f; inp.size = Math.max(f.length, 8);
    if (fill) inp.value = fieldValue(fill, f);
    form.appendChild(inp);
  }
  const save = document.createElement('button');
  save.textContent = editId === null ? 'add' : 'save';
  save.onclick = async () => {
    const rule = {};
    for (const f of fields){
      const v = coerce(document.getElementById('f_' + f).value);
      if (v !== undefined) rule[f] = v;
    }
    const url = editId === null ? `v1/rule?${qs}`
      : `v1/rule?${qs}&id=${editId}`;
    const resp = await fetch(url, {
      method: editId === null ? 'POST' : 'PUT', body: JSON.stringify(rule)});
    msg(await resp.json()); editId = null; loadRules();
  };
  form.appendChild(save);
  if (editId !== null){
    const cancel = document.createElement('button');
    cancel.textContent = 'cancel';
    cancel.onclick = () => { editId = null; renderView(); };
    form.appendChild(cancel);
  }
  view.appendChild(form);
}
function msg(obj){
  document.getElementById('rulemsg').textContent = JSON.stringify(obj);
}
// gateway custom-API group editor (GatewayApiController analog): the
// definitions are a small nested structure, edited as a JSON document
async function loadApiGroups(){
  const view = document.getElementById('ruleview');
  view.innerHTML = '';
  let defs = null;
  try { defs = await api('v1/gateway/apis?app='+encodeURIComponent(curApp)); }
  catch(e){}
  if (!Array.isArray(defs)){
    // a failed fetch must NOT render an empty editor — saving it would
    // wipe every machine's live definitions (same guard as v1/rule's
    // seed-before-push)
    msg(defs || {error: 'fetching api groups failed'});
    const p = document.createElement('p');
    p.textContent = 'could not load live api groups; editor disabled';
    p.className = 'dead';
    view.appendChild(p);
    return;
  }
  const ta = document.createElement('textarea');
  ta.rows = 12; ta.cols = 80;
  ta.value = JSON.stringify(defs, null, 2);
  view.appendChild(ta);
  const hint = document.createElement('div');
  hint.className = 'legend';
  hint.textContent = 'array of {apiName, predicateItems: [{pattern, ' +
    'matchStrategy: 0 exact | 1 prefix | 2 regex}]}';
  view.appendChild(hint);
  const save = document.createElement('button');
  save.textContent = 'save api groups';
  save.onclick = async () => {
    let parsed;
    try { parsed = JSON.parse(ta.value); }
    catch(e){ msg({error: 'invalid JSON: ' + e.message}); return; }
    const r = await fetch('v1/gateway/apis?app='+encodeURIComponent(curApp),
      {method:'POST', body: JSON.stringify(parsed)});
    msg(await r.json());
  };
  view.appendChild(save);
}
async function assign(app, machine){
  const r = await fetch(`cluster/assign?app=${encodeURIComponent(app)}`,
    {method:'POST', body: JSON.stringify({server: machine})});
  alert(JSON.stringify(await r.json())); refresh();
}
// ---- metric timelines: qps chart (pass/block/exception) + rt chart ----
// per-machine drill-down + history window (metric.js analog): the machine
// selector switches between the app-wide sum and one machine's own series
let chartData = null;
let chartCtx = {app:'', resource:'', machine:''};
async function openChart(app, resource, machine){
  document.getElementById('chartwrap').style.display = '';
  chartCtx = {app, resource, machine: machine || ''};
  const sel = document.getElementById('chartmachine');
  sel.innerHTML = '';
  const all = document.createElement('option');
  all.value = ''; all.textContent = 'all machines (sum)';
  sel.appendChild(all);
  try {
    for (const mk of await api(`metric/machines?app=${encodeURIComponent(app)}` +
        `&identity=${encodeURIComponent(resource)}`)){
      const o = document.createElement('option');
      o.value = mk; o.textContent = mk; sel.appendChild(o);
    }
  } catch(e){}
  if (chartCtx.machine &&
      ![...sel.options].some(o => o.value === chartCtx.machine)){
    // the machines fetch failed or lagged: add the requested machine so
    // the selector always names the series actually plotted
    const o = document.createElement('option');
    o.value = chartCtx.machine; o.textContent = chartCtx.machine;
    sel.appendChild(o);
  }
  sel.value = chartCtx.machine;
  await loadChart();
}
// ---- per-machine resource view (identity.js analog) ----
let machineSeq = 0;
async function openMachine(app, mkey){
  const seq = ++machineSeq;  // a newer click supersedes this render
  const d = document.getElementById('machined');
  d.style.display = '';
  document.getElementById('machineres').textContent = mkey;
  const view = document.getElementById('machineview');
  view.innerHTML = '';
  const res = await api(`resources?app=${encodeURIComponent(app)}` +
    `&machine=${encodeURIComponent(mkey)}`);
  const now = Date.now();
  const series = await Promise.all(res.map(r =>
    api(`metric?app=${encodeURIComponent(app)}` +
      `&identity=${encodeURIComponent(r)}&machine=${encodeURIComponent(mkey)}` +
      `&startTime=${now-15000}&endTime=${now}`).catch(() => [])));
  if (seq !== machineSeq) return;  // superseded while fetching
  const t = document.createElement('table');
  row(t, ['resource', 'pass qps', 'block qps', 'rt ms', ''], 'th');
  res.forEach((r, i) => {
    const last = series[i][series[i].length-1] || {};
    const cb = document.createElement('button');
    cb.textContent = 'timeline';
    cb.onclick = () => openChart(app, r, mkey);
    row(t, [r, last.passQps??'', last.blockQps??'', last.rt??'', cb]);
  });
  view.appendChild(t);
  if (!res.length){
    const p = document.createElement('p');
    p.className = 'legend';
    p.textContent = 'no live samples from this machine';
    view.appendChild(p);
  }
}
async function loadChart(){
  const {app, resource, machine} = chartCtx;
  document.getElementById('chartres').textContent =
    resource + (machine ? ' @ ' + machine : '');
  const win = +document.getElementById('chartwin').value;
  const now = Date.now();
  const ms = await api(`metric?app=${encodeURIComponent(app)}` +
    `&identity=${encodeURIComponent(resource)}` +
    `&startTime=${now-win}&endTime=${now}` +
    (machine ? `&machine=${encodeURIComponent(machine)}` : ''));
  chartData = ms.map(e => ({t: e.timestamp, pass: e.passQps,
    block: e.blockQps, exc: e.exceptionQps, rt: e.rt}));
  drawChart();
}
const QPS_SERIES = [['pass','var(--series-1)'], ['block','var(--series-2)'],
                    ['exc','var(--series-3)']];
const RT_SERIES = [['rt','var(--series-1)']];
function drawChart(){
  renderChart(document.getElementById('chart'), 220, QPS_SERIES,
    d => 'pass ' + d.pass + '  block ' + d.block + '  exc ' + d.exc);
  renderChart(document.getElementById('rtchart'), 140, RT_SERIES,
    d => 'rt ' + d.rt + ' ms');
}
function renderChart(svg, H, series, fmt){
  svg.innerHTML = '';
  const NS = 'http://www.w3.org/2000/svg';
  const W = 720, L = 48, R = 10, T = 10, B = 24;
  const data = chartData || [];
  if (!data.length){
    const t = document.createElementNS(NS, 'text');
    t.setAttribute('x', W/2); t.setAttribute('y', H/2);
    t.setAttribute('text-anchor', 'middle');
    t.setAttribute('fill', 'var(--text-secondary)');
    t.textContent = 'no samples in the last 5 minutes';
    svg.appendChild(t); return;
  }
  const t0 = data[0].t, t1 = data[data.length-1].t || t0 + 1;
  const ymax = Math.max(1,
    ...data.map(d => Math.max(...series.map(([k]) => d[k] || 0))));
  const x = t => L + (W-L-R) * (t1 === t0 ? 0.5 : (t - t0)/(t1 - t0));
  const y = v => T + (H-T-B) * (1 - v/ymax);
  // recessive grid: 3 horizontal lines + y labels in secondary ink
  for (const f of [0, .5, 1]){
    const g = document.createElementNS(NS, 'line');
    g.setAttribute('x1', L); g.setAttribute('x2', W-R);
    g.setAttribute('y1', y(ymax*f)); g.setAttribute('y2', y(ymax*f));
    g.setAttribute('stroke', 'var(--grid)'); svg.appendChild(g);
    const lab = document.createElementNS(NS, 'text');
    lab.setAttribute('x', L-6); lab.setAttribute('y', y(ymax*f)+4);
    lab.setAttribute('text-anchor', 'end');
    lab.setAttribute('font-size', '11');
    lab.setAttribute('fill', 'var(--text-secondary)');
    lab.textContent = Math.round(ymax*f); svg.appendChild(lab);
  }
  for (const [key, color] of series){
    const pl = document.createElementNS(NS, 'polyline');
    pl.setAttribute('points',
      data.map(d => `${x(d.t)},${y(d[key] || 0)}`).join(' '));
    pl.setAttribute('fill', 'none');
    pl.setAttribute('stroke', color);
    pl.setAttribute('stroke-width', '2');
    pl.setAttribute('stroke-linejoin', 'round');
    svg.appendChild(pl);
  }
  // hover layer: nearest-sample crosshair + tooltip
  const hover = document.createElementNS(NS, 'rect');
  hover.setAttribute('x', L); hover.setAttribute('y', T);
  hover.setAttribute('width', W-L-R); hover.setAttribute('height', H-T-B);
  hover.setAttribute('fill', 'transparent');
  const cross = document.createElementNS(NS, 'line');
  cross.setAttribute('y1', T); cross.setAttribute('y2', H-B);
  cross.setAttribute('stroke', 'var(--text-secondary)');
  cross.setAttribute('stroke-dasharray', '3,3');
  cross.style.display = 'none';
  svg.appendChild(cross);
  const tip = document.getElementById('tip');
  hover.onmousemove = (ev) => {
    const rect = svg.getBoundingClientRect();
    const px = ev.clientX - rect.left;
    let best = data[0], bd = Infinity;
    for (const d of data){
      const dd = Math.abs(x(d.t) - px);
      if (dd < bd){ bd = dd; best = d; }
    }
    cross.setAttribute('x1', x(best.t)); cross.setAttribute('x2', x(best.t));
    cross.style.display = '';
    tip.style.display = 'block';
    tip.style.left = (ev.pageX + 12) + 'px';
    tip.style.top = (ev.pageY - 10) + 'px';
    tip.textContent = new Date(best.t).toLocaleTimeString() + '  ' + fmt(best);
  };
  hover.onmouseleave = () => {
    cross.style.display = 'none'; tip.style.display = 'none';
  };
  svg.appendChild(hover);
}
// ---- cluster monitor (cluster_app_server_monitor.js analog) ----
async function openCluster(app){
  document.getElementById('clusterd').style.display='';
  document.getElementById('clusterapp').textContent = app;
  const view = document.getElementById('clusterview');
  view.innerHTML = '';
  let mon;
  try { mon = await api('cluster/monitor?app='+encodeURIComponent(app)); }
  catch(e){ return; }
  for (const s of mon.servers || []){
    const h = document.createElement('h3');
    h.textContent = 'token server ' + s.machine +
      (s.info.port !== undefined ? ' (port ' + s.info.port + ')' : '');
    view.appendChild(h);
    const flow = s.info.flow || {};
    const ct = document.createElement('table');
    row(ct, ['namespaces', 'max allowed qps', 'interval ms', 'buckets',
             'embedded'], 'th');
    row(ct, [(s.info.namespaceSet || []).join(', '),
             String(flow.maxAllowedQps ?? ''),
             String(flow.intervalMs ?? ''),
             String(flow.sampleCount ?? ''),
             String(s.info.embedded ?? '')]);
    view.appendChild(ct);
    const conns = s.info.connection || [];
    const gt = document.createElement('table');
    row(gt, ['namespace', 'connected', 'clients'], 'th');
    for (const g of conns)
      row(gt, [g.namespace, String(g.connectedCount),
               (g.clients || []).join(', ')]);
    if (conns.length) view.appendChild(gt);
    const entries = Object.entries(s.metrics || {});
    if (entries.length){
      const mt2 = document.createElement('table');
      row(mt2, ['flow id', 'pass qps', 'block qps'], 'th');
      for (const [fid, m] of entries)
        row(mt2, [fid, String(m.pass_qps ?? m.passQps ?? ''),
                  String(m.block_qps ?? m.blockQps ?? '')]);
      view.appendChild(mt2);
    }
  }
  for (const c of mon.clients || []){
    const h = document.createElement('h3');
    h.textContent = 'token client ' + c.machine;
    view.appendChild(h);
    const t = document.createElement('table');
    row(t, ['server', 'timeout ms', 'namespace'], 'th');
    row(t, [(c.config.serverHost ?? '') + ':' + (c.config.serverPort ?? ''),
            String(c.config.requestTimeout ?? ''),
            c.config.namespace ?? '']);
    view.appendChild(t);
  }
  if (!(mon.servers || []).length && !(mon.clients || []).length){
    const p = document.createElement('p');
    p.textContent = 'no machines in cluster mode';
    p.className = 'legend';
    view.appendChild(p);
  }
  await renderAssignManage(app, view);
}
// ---- assignment management (cluster_app_assign_manage.js analog) ----
// server groups with their clients, group unassignment back to standalone,
// and a new-group form (pick a server + client set + token port)
async function renderAssignManage(app, view){
  const h = document.createElement('h3');
  h.textContent = 'assignment management';
  view.appendChild(h);
  let st;
  try { st = await api('cluster/assign/state?app='+encodeURIComponent(app)); }
  catch(e){ return; }
  const gt = document.createElement('table');
  row(gt, ['server group', 'token port', 'clients', ''], 'th');
  for (const g of st.servers || []){
    const ub = document.createElement('button');
    ub.textContent = 'unassign group';
    ub.onclick = () => manageAssign(app,
      {unassign: [g.machine, ...g.clients]});
    row(gt, [g.machine, String(g.port), (g.clients || []).join(', '), ub]);
  }
  if ((st.servers || []).length) view.appendChild(gt);
  const pool = [...(st.unassigned || []),
                ...(st.servers || []).flatMap(g => [g.machine, ...g.clients])];
  const form = document.createElement('div');
  const lbl = document.createElement('span');
  lbl.className = 'legend'; lbl.textContent = 'new group: server ';
  form.appendChild(lbl);
  const ssel = document.createElement('select');
  for (const mk of pool){
    const o = document.createElement('option');
    o.value = mk; o.textContent = mk; ssel.appendChild(o);
  }
  form.appendChild(ssel);
  const plbl = document.createElement('span');
  plbl.className = 'legend'; plbl.textContent = ' port ';
  form.appendChild(plbl);
  const port = document.createElement('input');
  port.value = '18730'; port.size = 6; form.appendChild(port);
  const boxes = [];
  for (const mk of pool){
    const cb = document.createElement('input');
    cb.type = 'checkbox'; cb.value = mk; boxes.push(cb);
    const cl = document.createElement('label');
    cl.className = 'legend';
    cl.appendChild(cb); cl.appendChild(document.createTextNode(mk));
    form.appendChild(cl);
  }
  const apply = document.createElement('button');
  apply.textContent = 'assign group';
  apply.onclick = () => manageAssign(app, {groups: [{
    server: ssel.value, tokenPort: +port.value || 18730,
    clients: boxes.filter(b => b.checked && b.value !== ssel.value)
                  .map(b => b.value)}]});
  form.appendChild(apply);
  view.appendChild(form);
  if (st.unknown && st.unknown.length){
    const p = document.createElement('p');
    p.className = 'legend';
    p.textContent = 'unreachable: ' + st.unknown.join(', ');
    view.appendChild(p);
  }
}
async function manageAssign(app, payload){
  const r = await fetch('cluster/assign/manage?app='+encodeURIComponent(app),
    {method:'POST', body: JSON.stringify(payload)});
  alert(JSON.stringify(await r.json()));
  openCluster(app);
}
const MODES = {'-1':'off','0':'client','1':'server'};
// single-flight refresh: overlapping runs (interval + filter keystrokes)
// would interleave their async appends into #apps and duplicate sections
let refreshBusy = false, refreshAgain = false, filterTimer = null;
function filterChanged(){
  // debounce: the filter is client-side, but the repaint walks the full
  // fetch loop — one run per typing pause, not per keystroke
  clearTimeout(filterTimer);
  filterTimer = setTimeout(refresh, 300);
}
async function refresh(){
  if (refreshBusy){ refreshAgain = true; return; }
  refreshBusy = true;
  try { await refreshOnce(); }
  finally {
    refreshBusy = false;
    if (refreshAgain){ refreshAgain = false; refresh(); }
  }
}
async function refreshOnce(){
  let apps;
  try { apps = await api('apps'); } catch(e){ return; }
  // authenticated and serving: reveal the filter control (it starts
  // hidden so the login screen shows no stray live input)
  document.getElementById('resfilterwrap').style.display = '';
  const root = document.getElementById('apps');
  root.innerHTML = '';
  for (const app of apps){
    const h = document.createElement('h2'); h.textContent = app.name;
    const btn = document.createElement('button');
    btn.textContent = 'rules'; btn.style.marginLeft = '1rem';
    btn.onclick = () => openRules(app.name);
    h.appendChild(btn);
    const cbtn2 = document.createElement('button');
    cbtn2.textContent = 'cluster'; cbtn2.style.marginLeft = '.3rem';
    cbtn2.onclick = () => openCluster(app.name);
    h.appendChild(cbtn2); root.appendChild(h);
    let modes = {};
    try {
      for (const s of await api('cluster/state?app='+encodeURIComponent(app.name)))
        modes[s.machine] = s.mode;
    } catch(e){}
    const mt = document.createElement('table');
    row(mt, ['machine', 'version', 'status', 'cluster', ''], 'th');
    for (const m of app.machines){
      const key = `${m.ip}:${m.port}`;
      const abtn = document.createElement('button');
      abtn.textContent = 'make token server';
      abtn.onclick = () => assign(app.name, key);
      const rbtn = document.createElement('button');
      rbtn.textContent = 'resources';
      rbtn.onclick = () => openMachine(app.name, key);
      const cell = document.createElement('span');
      cell.appendChild(rbtn); cell.appendChild(abtn);
      row(mt, [key, m.version,
               {text: m.healthy?'healthy':'dead', cls: m.healthy?'ok':'dead'},
               MODES[String(modes[key])] ?? '?', cell]);
    }
    root.appendChild(mt);
    let res = await api('resources?app='+encodeURIComponent(app.name));
    // client-side substring filter (the reference sidebar's search box);
    // the input lives outside #apps so it survives the 3s re-render
    const f = (document.getElementById('resfilter').value || '').toLowerCase();
    if (f) res = res.filter(r => r.toLowerCase().includes(f));
    const rt = document.createElement('table');
    row(rt, ['resource', 'pass qps', 'block qps', 'rt ms', ''], 'th');
    const now = Date.now();
    for (const r of res){
      const ms = await api(`metric?app=${encodeURIComponent(app.name)}` +
        `&identity=${encodeURIComponent(r)}&startTime=${now-15000}&endTime=${now}`);
      const last = ms[ms.length-1] || {};
      const cbtn = document.createElement('button');
      cbtn.textContent = 'timeline';
      cbtn.onclick = () => openChart(app.name, r);
      row(rt, [r, last.passQps??'', last.blockQps??'', last.rt??'', cbtn]);
    }
    root.appendChild(rt);
  }
}
refresh(); setInterval(refresh, 3000);
</script></body></html>"""


class DashboardServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8080,
        fetch_interval_s: float = 1.0,
        auth: Optional[Tuple[str, str]] = None,
        rule_plugins: Optional[dict] = None,
    ):
        """``auth=(username, password)`` enables login (the reference's
        ``sentinel.dashboard.auth.username/password`` simple auth); default
        is open access, matching the reference's default ``sentinel/sentinel``
        stance for dev use.

        ``rule_plugins`` maps rule type → ``(DynamicRuleProvider,
        DynamicRulePublisher)`` and backs the ``v2/rules`` route
        (FlowControllerV2 analog — see dashboard/dynamic_rules.py): types
        without a plugin fall back to the direct-to-machine Api pair."""
        self.apps = AppManagement()
        self.repository = InMemoryMetricsRepository()
        self.rules = InMemoryRuleRepository()
        self.client = ApiClient()
        self.fetcher = MetricFetcher(
            self.apps, self.repository, self.client, fetch_interval_s
        )
        self.rule_plugins = dict(rule_plugins or {})
        self.auth = auth
        # token → expiry-ms; bounded and TTL'd (an unbounded forever-valid
        # session set would grow with every login and keep stolen cookies
        # alive until restart)
        self._sessions: dict = {}
        # ThreadingHTTPServer handles each request on its own thread — every
        # _sessions access goes through this lock (prune in place, never
        # rebind, so a concurrent logout can't be lost on an old dict)
        self._sessions_lock = threading.Lock()
        self.session_ttl_ms = 24 * 3600 * 1000
        self.max_sessions = 1000
        self._service = HttpService(
            self._respond, host, port, name="sentinel-dashboard"
        )

    @property
    def host(self) -> str:
        return self._service.host

    @property
    def port(self) -> int:
        return self._service.port

    # -- auth ----------------------------------------------------------------
    def _session_of(self, headers) -> Optional[str]:
        cookie = headers.get("Cookie", "") if headers is not None else ""
        now = _clock.now_ms()
        for part in cookie.split(";"):
            k, _, v = part.strip().partition("=")
            if k == "sentinel_session":
                with self._sessions_lock:
                    expiry = self._sessions.get(v)
                    if expiry is not None and expiry > now:
                        return v
                    self._sessions.pop(v, None)  # expired
        return None

    def _login(self, params: dict, body: str):
        data = json.loads(body) if body else dict(params)
        user, password = self.auth
        if not (
            hmac.compare_digest(str(data.get("username", "")), user)
            and hmac.compare_digest(str(data.get("password", "")), password)
        ):
            return (401, json.dumps({"error": "invalid credentials"}),
                    "application/json; charset=utf-8")
        token = secrets.token_urlsafe(24)
        now = _clock.now_ms()
        with self._sessions_lock:
            for t in [t for t, exp in self._sessions.items() if exp <= now]:
                del self._sessions[t]
            while len(self._sessions) >= self.max_sessions:
                self._sessions.pop(next(iter(self._sessions)))  # oldest first
            self._sessions[token] = now + self.session_ttl_ms
        return (
            200,
            json.dumps({"code": 0}),
            "application/json; charset=utf-8",
            {"Set-Cookie":
             f"sentinel_session={token}; HttpOnly; Path=/; SameSite=Lax"},
        )

    # -- request handling ----------------------------------------------------
    def _respond(
        self, method: str, path: str, params: dict, body: str, headers=None
    ) -> Response:
        if self.auth is not None:
            if method == "POST" and path == "auth/login":
                return self._login(params, body)
            if method == "POST" and path == "auth/logout":
                token = self._session_of(headers)
                if token is not None:
                    with self._sessions_lock:
                        self._sessions.pop(token, None)
                return json_response(200, json.dumps({"code": 0}))
            if path not in AUTH_EXEMPT and self._session_of(headers) is None:
                return json_response(401, json.dumps({"error": "login required"}))
        result = self._route(method, path, params, body)
        if result is None:
            return json_response(404, json.dumps({"error": "not found"}))
        if isinstance(result, str):
            return html_response(200, result)
        return json_response(200, json.dumps(result))

    def _route(self, method: str, path: str, params: dict, body: str):
        if method == "POST" and path == "registry/machine":
            data = json.loads(body) if body else dict(params)
            machine = MachineInfo(
                app=str(data.get("app", "")),
                ip=str(data.get("ip", "")),
                port=int(data.get("port", 0)),
                hostname=str(data.get("hostname", "")),
                version=str(data.get("version", "")),
                last_heartbeat_ms=_clock.now_ms(),
            )
            self.apps.register(machine)
            return {"code": 0, "msg": "success"}
        if path == "apps":
            return [
                {
                    "name": app,
                    "machines": [m.to_dict() for m in self.apps.machines(app)],
                }
                for app in self.apps.apps()
            ]
        if path == "resources":
            # app-wide, or one machine's own resource list when
            # ``machine=ip:port`` is given (identity.js analog)
            machine = params.get("machine", "")
            if machine:
                return self.repository.resources_of_machine(
                    params.get("app", ""), machine
                )
            return self.repository.resources_of_app(params.get("app", ""))
        if path == "metric":
            # app-wide merged series, or one machine's own series when
            # ``machine=ip:port`` is given (metric.js drill-down analog)
            machine = params.get("machine", "")
            if machine:
                entries = self.repository.query_machine(
                    params.get("app", ""),
                    machine,
                    params.get("identity", ""),
                    int(params.get("startTime", 0)),
                    int(params.get("endTime", 2**62)),
                )
            else:
                entries = self.repository.query(
                    params.get("app", ""),
                    params.get("identity", ""),
                    int(params.get("startTime", 0)),
                    int(params.get("endTime", 2**62)),
                )
            return [e.to_dict() for e in entries]
        if path == "metric/machines":
            # machines with live data for a resource — populates the
            # drill-down selector
            return self.repository.machines_of_resource(
                params.get("app", ""), params.get("identity", "")
            )
        if path == "rules":
            app = params.get("app", "")
            rule_type = params.get("type", "flow")
            if rule_type not in RULE_TYPES:
                return {"error": f"unknown rule type {rule_type}"}
            machines = self.apps.healthy_machines(app)
            if not machines:
                return {"error": f"no healthy machine for app {app}"}
            if method == "POST":
                try:
                    rules = json.loads(body)
                except (json.JSONDecodeError, TypeError):
                    return {"error": "body is not valid JSON"}
                if not isinstance(rules, list):
                    return {"error": "body must be a JSON array of rules"}
                for i, r in enumerate(rules):
                    bad = validate_rule(rule_type, r)
                    if bad:
                        return {"error": f"rule[{i}]: {bad}"}
                pushed = sum(
                    self.client.push_rules(m, rule_type, rules) for m in machines
                )
                return {"pushed": pushed, "machines": len(machines)}
            return self.client.fetch_rules(machines[0], rule_type)
        if path == "v2/rules":
            # pluggable provider/publisher route (FlowControllerV2.java:63-64
            # analog): GET reads the authoritative list through the type's
            # DynamicRuleProvider, POST validates then hands the WHOLE list
            # to its DynamicRulePublisher — with a store-backed plugin the
            # dashboard never touches the machines; their datasource
            # watchers converge on the store
            app = params.get("app", "")
            rule_type = params.get("type", "flow")
            if rule_type not in RULE_TYPES:
                return {"error": f"unknown rule type {rule_type}"}
            plugin = self.rule_plugins.get(rule_type)
            if plugin is None:
                from sentinel_tpu.dashboard.dynamic_rules import (
                    ApiRuleProvider,
                    ApiRulePublisher,
                )

                plugin = (
                    ApiRuleProvider(self.apps, self.client, rule_type),
                    ApiRulePublisher(self.apps, self.client, rule_type),
                )
                self.rule_plugins[rule_type] = plugin
            provider, publisher = plugin
            if method == "POST":
                try:
                    rules = json.loads(body)
                except (json.JSONDecodeError, TypeError):
                    return {"error": "body is not valid JSON"}
                if not isinstance(rules, list):
                    return {"error": "body must be a JSON array of rules"}
                for i, r in enumerate(rules):
                    bad = validate_rule(rule_type, r)
                    if bad:
                        return {"error": f"rule[{i}]: {bad}"}
                try:
                    publisher.publish(app, rules)
                except Exception as e:
                    return {"error": f"publish failed: {e}"}
                return {"published": len(rules)}
            try:
                rules = provider.get_rules(app)
            except Exception as e:
                return {"error": f"provider failed: {e}"}
            return rules if rules is not None else {
                "error": f"no rules available for app {app}"
            }
        if path == "v1/rules":
            # per-rule-type console view: fetch live, sync ids, return
            # entities (FlowControllerV1.apiQueryMachineRules analog)
            app = params.get("app", "")
            rule_type = params.get("type", "flow")
            if rule_type not in RULE_TYPES:
                return {"error": f"unknown rule type {rule_type}"}
            machines = self.apps.healthy_machines(app)
            if not machines:
                return {"error": f"no healthy machine for app {app}"}
            live = self.client.fetch_rules(machines[0], rule_type)
            if live is None:
                return {"error": "fetch from app failed"}
            return self.rules.sync(app, rule_type, live)
        if path == "v1/rule":
            # single-rule CRUD (apiAddFlowRule / apiUpdateFlowRule /
            # apiDeleteRule): mutate the id-keyed repository, then publish
            # the assembled list to every healthy machine
            app = params.get("app", "")
            rule_type = params.get("type", "flow")
            if rule_type not in RULE_TYPES:
                return {"error": f"unknown rule type {rule_type}"}
            machines = self.apps.healthy_machines(app)
            if not machines:
                return {"error": f"no healthy machine for app {app}"}
            if not self.rules.known(app, rule_type):
                # never synced (fresh dashboard): seed from the live agent
                # first, or this mutation's push would overwrite whatever
                # rules the agent already holds
                live = self.client.fetch_rules(machines[0], rule_type)
                if live is None:
                    return {"error": "fetch from app failed"}
                self.rules.sync(app, rule_type, live)
            if method in ("POST", "PUT"):
                try:
                    rule = json.loads(body)
                except (json.JSONDecodeError, TypeError):
                    return {"error": "body is not valid JSON"}
                # reject malformed rules BEFORE storing/pushing — the
                # reference's checkEntityInternal chains
                # (FlowControllerV1.java:89-134); see dashboard/validation
                bad = validate_rule(rule_type, rule)
                if bad:
                    return {"error": bad}
                rule.pop("id", None)
            if method == "POST":
                rule_id = self.rules.add(app, rule_type, rule)
            elif method == "PUT":
                rule_id = int(params.get("id", 0))
                if not self.rules.update(app, rule_type, rule_id, rule):
                    return {"error": f"no rule with id {rule_id}"}
            elif method == "DELETE":
                rule_id = int(params.get("id", 0))
                if not self.rules.delete(app, rule_type, rule_id):
                    return {"error": f"no rule with id {rule_id}"}
            else:
                return {"error": "POST/PUT/DELETE only"}
            plain = self.rules.plain_rules(app, rule_type)
            pushed = sum(
                self.client.push_rules(m, rule_type, plain) for m in machines
            )
            return {"id": rule_id, "pushed": pushed, "machines": len(machines)}
        if path == "v1/gateway/apis":
            # gateway custom-API group management (GatewayApiController
            # analog): GET lists the live definitions, POST replaces them on
            # every healthy machine
            app = params.get("app", "")
            machines = self.apps.healthy_machines(app)
            if not machines:
                return {"error": f"no healthy machine for app {app}"}
            if method == "POST":
                # validate before fanning out: a malformed body (from a
                # non-UI client) must return a parse error, not one failing
                # HTTP push per machine with pushed:0 and no explanation
                # (r4 advisor)
                try:
                    defs = json.loads(body)
                except (json.JSONDecodeError, TypeError):
                    return {"error": "body is not valid JSON"}
                if not isinstance(defs, list) or any(
                    not isinstance(d, dict) or "apiName" not in d
                    for d in defs
                ):
                    return {
                        "error": "body must be a list of {apiName, "
                                 "predicateItems} objects"
                    }
                pushed = sum(
                    1 for m in machines
                    if self.client.push_api_definitions(m, body)
                )
                return {"pushed": pushed, "machines": len(machines)}
            result = self.client.fetch_json(
                machines[0], "gateway/getApiDefinitions"
            )
            return result if result is not None else {"error": "fetch failed"}
        if method == "POST" and path == "machine/remove":
            # per-machine deregistration; ip+port name the machine
            removed = self.apps.remove_machine(
                params.get("app", ""), params.get("ip", ""),
                int(params.get("port", 0)),
            )
            return {"code": 0 if removed else 1}
        if method == "POST" and path == "app/remove":
            self.apps.remove_app(params.get("app", ""))
            return {"code": 0}
        if path == "cluster/state":
            # per-machine cluster mode snapshot (ClusterAssignController's
            # read side): -1 off, 0 client, 1 server, null unreachable
            app = params.get("app", "")
            return [
                {
                    "machine": m.key,
                    "ip": m.ip,
                    "port": m.port,
                    "mode": self.client.get_cluster_mode(m),
                }
                for m in self.apps.healthy_machines(app)
            ]
        if path == "cluster/monitor":
            # cluster monitor screen data (cluster_app_server_monitor.js
            # analog): for each server-mode machine the token-server info
            # (port, namespaces, flow config, connection groups) and live
            # per-flow metrics; for each client-mode machine its assignment
            app = params.get("app", "")
            out = {"servers": [], "clients": []}
            for m in self.apps.healthy_machines(app):
                mode = self.client.get_cluster_mode(m)
                if mode == 1:
                    out["servers"].append({
                        "machine": m.key,
                        "info": self.client.fetch_json(
                            m, "cluster/server/info") or {},
                        "metrics": self.client.fetch_json(
                            m, "cluster/server/metrics") or {},
                        # pipeline breakdown: verdict counters by namespace,
                        # stage latency histograms, queue/connection gauges
                        "stats": self.client.fetch_json(
                            m, "clusterServerStats") or {},
                    })
                elif mode == 0:
                    out["clients"].append({
                        "machine": m.key,
                        "config": self.client.fetch_json(
                            m, "cluster/client/fetchConfig") or {},
                    })
            return out
        if method == "POST" and path == "cluster/assign":
            # one-shot assignment (ClusterAssignServiceImpl analog): flip the
            # chosen machine to server mode, everything else to client mode
            # pointed at it — the single-group case of _apply_assign_groups,
            # with this route's historical response shape preserved
            data = json.loads(body) if body else {}
            app = params.get("app", "") or data.get("app", "")
            server_key = data.get("server", "")
            machines = self.apps.healthy_machines(app)
            if not any(m.key == server_key for m in machines):
                return {"error": f"machine {server_key} not found/healthy"}
            res = self._apply_assign_groups(
                machines,
                [{
                    "server": server_key,
                    "tokenPort": data.get("tokenPort", 18730),
                    "clients": [m.key for m in machines
                                if m.key != server_key],
                }],
                (),
            )
            g = res["groups"][0]
            if "error" in g:
                # fail-stop happened inside the group apply: no client of
                # this group was reconfigured
                return {"error": f"promoting {server_key} to token server "
                        "failed; no clients were reconfigured"}
            return {"server": True, "clients": g["clients"],
                    "failed": res["failed"]}
        if path == "cluster/assign/state":
            # live assignment view (cluster_app_assign_manage.js analog):
            # server groups with their pointed-at clients, plus machines in
            # neither role — reconstructed from each machine's own mode and
            # client config, so the view is truth, not dashboard memory
            app = params.get("app", "")
            machines = self.apps.healthy_machines(app)
            by_addr = {}  # "ip:tokenPort" → server group
            state = {"servers": [], "unassigned": [], "unknown": []}
            clients = []
            for m in machines:
                mode = self.client.get_cluster_mode(m)
                if mode == 1:
                    info = self.client.fetch_json(m, "cluster/server/info")
                    if info is None:
                        # a known server whose info fetch failed: transport
                        # trouble, not definitive state — 'unknown', never
                        # 'unassigned' (an operator acting on 'unassigned'
                        # would re-assign a live server)
                        state["unknown"].append(m.key)
                        continue
                    group = {
                        "machine": m.key,
                        "ip": m.ip,
                        "port": int(info.get("port", 0) or 0),
                        "clients": [],
                    }
                    state["servers"].append(group)
                    by_addr[f"{m.ip}:{group['port']}"] = group
                elif mode == 0:
                    clients.append(m)
                elif mode is None:
                    state["unknown"].append(m.key)
                else:
                    state["unassigned"].append(m.key)
            for m in clients:
                cfg = self.client.fetch_json(m, "cluster/client/fetchConfig")
                if cfg is None:
                    # active client, config unreadable right now: transport
                    # failure is 'unknown', not a standalone verdict
                    state["unknown"].append(m.key)
                    continue
                addr = f"{cfg.get('serverHost', '')}:{cfg.get('serverPort', '')}"
                group = by_addr.get(addr)
                if group is not None:
                    group["clients"].append(m.key)
                else:
                    # definitively points at a server outside this app's
                    # healthy set (an orphan client)
                    state["unassigned"].append(m.key)
            return state
        if method == "POST" and path == "cluster/assign/manage":
            # full assignment management (ClusterAssignServiceImpl
            # applyAssignToApp / unbindClusterServers analog): multiple
            # server GROUPS, each with its own client set, plus explicit
            # unassignment back to standalone (mode -1). Per-group
            # fail-stop: a group whose server promotion fails reconfigures
            # none of its clients.
            data = json.loads(body) if body else {}
            app = params.get("app", "") or data.get("app", "")
            return self._apply_assign_groups(
                self.apps.healthy_machines(app),
                data.get("groups", ()),
                data.get("unassign", ()),
            )
        if path in ("", "index.html"):
            return _INDEX_HTML
        return None

    def _apply_assign_groups(self, healthy, groups, unassign) -> dict:
        """Apply server groups + unassignments (the one sequence behind both
        POST cluster/assign and POST cluster/assign/manage). Per-group
        fail-stop: a group whose server promotion fails reconfigures none
        of its clients."""
        machines = {m.key: m for m in healthy}
        results = {"groups": [], "unassigned": 0, "failed": []}
        for group in groups:
            server_key = group.get("server", "")
            token_port = int(group.get("tokenPort", 18730))
            server = machines.get(server_key)
            gres = {"server": server_key, "clients": 0}
            if server is None or not self.client.set_cluster_mode(
                server, 1, token_port
            ):
                gres["error"] = "server not found/healthy or promote failed"
                results["groups"].append(gres)
                results["failed"].append(server_key)
                continue
            for ckey in group.get("clients", ()):
                m = machines.get(ckey)
                ok = m is not None and self.client.push_cluster_client_config(
                    m, server.ip, token_port
                ) and self.client.set_cluster_mode(m, 0)
                if ok:
                    gres["clients"] += 1
                else:
                    results["failed"].append(ckey)
            results["groups"].append(gres)
        for ckey in unassign:
            m = machines.get(ckey)
            # mode -1 = standalone: the agent tears down its token
            # client/server and local checks take over (the unbind path of
            # the reference's assign service)
            if m is not None and self.client.set_cluster_mode(m, -1):
                results["unassigned"] += 1
            else:
                results["failed"].append(ckey)
        return results

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "DashboardServer":
        self._service.start()
        self.fetcher.start()
        return self

    def stop(self) -> None:
        self.fetcher.stop()
        self._service.stop()
