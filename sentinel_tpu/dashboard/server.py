"""Dashboard HTTP server: heartbeat sink + REST API + minimal console page.

Analog of the Spring Boot side of ``sentinel-dashboard``:
``MachineRegistryController`` (``/registry/machine``), metric queries over
the in-memory repository, and rule CRUD proxied to app command centers
(``FlowControllerV1`` + ``SentinelApiClient``). Runs on the stdlib
threading HTTP server — the console is an ops tool, not a hot path.
"""

from __future__ import annotations

import json

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.httpd import (
    HttpService,
    Response,
    html_response,
    json_response,
)
from sentinel_tpu.dashboard.api_client import ApiClient
from sentinel_tpu.dashboard.discovery import AppManagement, MachineInfo
from sentinel_tpu.dashboard.fetcher import MetricFetcher
from sentinel_tpu.dashboard.repository import InMemoryMetricsRepository

RULE_TYPES = ("flow", "degrade", "system", "authority", "paramFlow")

_INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>sentinel-tpu console</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;color:#222}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.5rem}
 table{border-collapse:collapse;min-width:40rem}
 th,td{border:1px solid #ccc;padding:.35rem .6rem;text-align:left;font-size:.9rem}
 th{background:#f5f5f5} .dead{color:#b00} .ok{color:#070}
 code{background:#f0f0f0;padding:0 .3rem}
</style></head><body>
<h1>sentinel-tpu console</h1>
<div id="apps"></div>
<script>
// resource names and machine fields are attacker-influenced (a resource is
// often a raw request path) — build rows with textContent only, never
// string-interpolated HTML
function row(table, cells, tag){
  const tr = document.createElement('tr');
  for (const c of cells){
    const td = document.createElement(tag || 'td');
    if (c && c.cls) { td.textContent = c.text; td.className = c.cls; }
    else td.textContent = c;
    tr.appendChild(td);
  }
  table.appendChild(tr);
}
async function refresh(){
  const apps = await (await fetch('apps')).json();
  const root = document.getElementById('apps');
  root.innerHTML = '';
  for (const app of apps){
    const h = document.createElement('h2'); h.textContent = app.name; root.appendChild(h);
    const mt = document.createElement('table');
    row(mt, ['machine', 'version', 'status'], 'th');
    for (const m of app.machines)
      row(mt, [`${m.ip}:${m.port}`, m.version,
               {text: m.healthy?'healthy':'dead', cls: m.healthy?'ok':'dead'}]);
    root.appendChild(mt);
    const res = await (await fetch('resources?app='+encodeURIComponent(app.name))).json();
    const rt = document.createElement('table');
    row(rt, ['resource', 'pass qps', 'block qps', 'rt ms'], 'th');
    const now = Date.now();
    for (const r of res){
      const ms = await (await fetch(`metric?app=${encodeURIComponent(app.name)}` +
        `&identity=${encodeURIComponent(r)}&startTime=${now-15000}&endTime=${now}`)).json();
      const last = ms[ms.length-1] || {};
      row(rt, [r, last.passQps??'', last.blockQps??'', last.rt??'']);
    }
    root.appendChild(rt);
  }
}
refresh(); setInterval(refresh, 3000);
</script></body></html>"""


class DashboardServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8080,
        fetch_interval_s: float = 1.0,
    ):
        self.apps = AppManagement()
        self.repository = InMemoryMetricsRepository()
        self.client = ApiClient()
        self.fetcher = MetricFetcher(
            self.apps, self.repository, self.client, fetch_interval_s
        )
        self._service = HttpService(
            self._respond, host, port, name="sentinel-dashboard"
        )

    @property
    def host(self) -> str:
        return self._service.host

    @property
    def port(self) -> int:
        return self._service.port

    # -- request handling ----------------------------------------------------
    def _respond(self, method: str, path: str, params: dict, body: str) -> Response:
        result = self._route(method, path, params, body)
        if result is None:
            return json_response(404, json.dumps({"error": "not found"}))
        if isinstance(result, str):
            return html_response(200, result)
        return json_response(200, json.dumps(result))

    def _route(self, method: str, path: str, params: dict, body: str):
        if method == "POST" and path == "registry/machine":
            data = json.loads(body) if body else dict(params)
            machine = MachineInfo(
                app=str(data.get("app", "")),
                ip=str(data.get("ip", "")),
                port=int(data.get("port", 0)),
                hostname=str(data.get("hostname", "")),
                version=str(data.get("version", "")),
                last_heartbeat_ms=_clock.now_ms(),
            )
            self.apps.register(machine)
            return {"code": 0, "msg": "success"}
        if path == "apps":
            return [
                {
                    "name": app,
                    "machines": [m.to_dict() for m in self.apps.machines(app)],
                }
                for app in self.apps.apps()
            ]
        if path == "resources":
            return self.repository.resources_of_app(params.get("app", ""))
        if path == "metric":
            entries = self.repository.query(
                params.get("app", ""),
                params.get("identity", ""),
                int(params.get("startTime", 0)),
                int(params.get("endTime", 2**62)),
            )
            return [e.to_dict() for e in entries]
        if path == "rules":
            app = params.get("app", "")
            rule_type = params.get("type", "flow")
            if rule_type not in RULE_TYPES:
                return {"error": f"unknown rule type {rule_type}"}
            machines = self.apps.healthy_machines(app)
            if not machines:
                return {"error": f"no healthy machine for app {app}"}
            if method == "POST":
                rules = json.loads(body)
                pushed = sum(
                    self.client.push_rules(m, rule_type, rules) for m in machines
                )
                return {"pushed": pushed, "machines": len(machines)}
            return self.client.fetch_rules(machines[0], rule_type)
        if path in ("", "index.html"):
            return _INDEX_HTML
        return None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "DashboardServer":
        self._service.start()
        self.fetcher.start()
        return self

    def stop(self) -> None:
        self.fetcher.stop()
        self._service.stop()
