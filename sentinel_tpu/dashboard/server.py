"""Dashboard HTTP server: heartbeat sink + REST API + minimal console page.

Analog of the Spring Boot side of ``sentinel-dashboard``:
``MachineRegistryController`` (``/registry/machine``), metric queries over
the in-memory repository, and rule CRUD proxied to app command centers
(``FlowControllerV1`` + ``SentinelApiClient``). Runs on the stdlib
threading HTTP server — the console is an ops tool, not a hot path.
"""

from __future__ import annotations

import hmac
import json
import secrets
import threading
from typing import Optional, Tuple

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.httpd import (
    HttpService,
    Response,
    html_response,
    json_response,
)
from sentinel_tpu.dashboard.api_client import ApiClient
from sentinel_tpu.dashboard.discovery import AppManagement, MachineInfo
from sentinel_tpu.dashboard.fetcher import MetricFetcher
from sentinel_tpu.dashboard.repository import InMemoryMetricsRepository

RULE_TYPES = ("flow", "degrade", "system", "authority", "paramFlow", "gateway")

# Paths reachable without a session when auth is enabled: machine heartbeats
# (apps can't log in) and the login exchange itself + the console shell,
# which renders a login form client-side (same exclusions as the
# reference's LoginAuthenticationFilter).
AUTH_EXEMPT = {"registry/machine", "auth/login", "", "index.html"}

_INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>sentinel-tpu console</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;color:#222}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.5rem}
 table{border-collapse:collapse;min-width:40rem}
 th,td{border:1px solid #ccc;padding:.35rem .6rem;text-align:left;font-size:.9rem}
 th{background:#f5f5f5} .dead{color:#b00} .ok{color:#070}
 code{background:#f0f0f0;padding:0 .3rem}
</style></head><body>
<h1>sentinel-tpu console</h1>
<div id="login" style="display:none">
 <h2>login</h2>
 <input id="u" placeholder="username"> <input id="p" type="password"
  placeholder="password"> <button onclick="login()">sign in</button>
 <span id="loginmsg" class="dead"></span>
</div>
<div id="apps"></div>
<div id="ruled" style="display:none">
 <h2>rules: <span id="ruleapp"></span></h2>
 <select id="ruletype"></select>
 <button onclick="loadRules()">load</button>
 <button onclick="pushRules()">push to app</button>
 <span id="rulemsg"></span><br>
 <textarea id="rulebox" rows="14" cols="100" spellcheck="false"></textarea>
</div>
<script>
// resource names and machine fields are attacker-influenced (a resource is
// often a raw request path) — build rows with textContent only, never
// string-interpolated HTML
const RULE_TYPES = ['flow','degrade','system','authority','paramFlow','gateway'];
function row(table, cells, tag){
  const tr = document.createElement('tr');
  for (const c of cells){
    const td = document.createElement(tag || 'td');
    if (c && c.nodeType) td.appendChild(c);
    else if (c && c.cls) { td.textContent = c.text; td.className = c.cls; }
    else td.textContent = c;
    tr.appendChild(td);
  }
  table.appendChild(tr);
}
async function api(path){
  const r = await fetch(path);
  if (r.status === 401){ showLogin(); throw new Error('auth'); }
  return r.json();
}
function showLogin(){ document.getElementById('login').style.display=''; }
async function login(){
  const body = JSON.stringify({username: u.value, password: p.value});
  const r = await fetch('auth/login', {method:'POST', body});
  if (r.status === 200){ login_el().style.display='none'; refresh(); }
  else document.getElementById('loginmsg').textContent = 'invalid credentials';
}
function login_el(){ return document.getElementById('login'); }
function openRules(app){
  document.getElementById('ruled').style.display='';
  document.getElementById('ruleapp').textContent = app;
  const sel = document.getElementById('ruletype');
  if (!sel.options.length)
    for (const t of RULE_TYPES){
      const o = document.createElement('option'); o.textContent = t; sel.appendChild(o);
    }
  loadRules();
}
async function loadRules(){
  const app = document.getElementById('ruleapp').textContent;
  const t = document.getElementById('ruletype').value;
  const rules = await api(`rules?app=${encodeURIComponent(app)}&type=${encodeURIComponent(t)}`);
  document.getElementById('rulebox').value = JSON.stringify(rules, null, 2);
}
async function pushRules(){
  const app = document.getElementById('ruleapp').textContent;
  const t = document.getElementById('ruletype').value;
  let parsed;
  try { parsed = JSON.parse(document.getElementById('rulebox').value); }
  catch(e){ document.getElementById('rulemsg').textContent = 'invalid JSON'; return; }
  const r = await fetch(`rules?app=${encodeURIComponent(app)}&type=${encodeURIComponent(t)}`,
    {method:'POST', body: JSON.stringify(parsed)});
  document.getElementById('rulemsg').textContent = JSON.stringify(await r.json());
}
async function assign(app, machine){
  const r = await fetch(`cluster/assign?app=${encodeURIComponent(app)}`,
    {method:'POST', body: JSON.stringify({server: machine})});
  alert(JSON.stringify(await r.json())); refresh();
}
const MODES = {'-1':'off','0':'client','1':'server'};
async function refresh(){
  let apps;
  try { apps = await api('apps'); } catch(e){ return; }
  const root = document.getElementById('apps');
  root.innerHTML = '';
  for (const app of apps){
    const h = document.createElement('h2'); h.textContent = app.name;
    const btn = document.createElement('button');
    btn.textContent = 'rules'; btn.style.marginLeft = '1rem';
    btn.onclick = () => openRules(app.name);
    h.appendChild(btn); root.appendChild(h);
    let modes = {};
    try {
      for (const s of await api('cluster/state?app='+encodeURIComponent(app.name)))
        modes[s.machine] = s.mode;
    } catch(e){}
    const mt = document.createElement('table');
    row(mt, ['machine', 'version', 'status', 'cluster', ''], 'th');
    for (const m of app.machines){
      const key = `${m.ip}:${m.port}`;
      const abtn = document.createElement('button');
      abtn.textContent = 'make token server';
      abtn.onclick = () => assign(app.name, key);
      row(mt, [key, m.version,
               {text: m.healthy?'healthy':'dead', cls: m.healthy?'ok':'dead'},
               MODES[String(modes[key])] ?? '?', abtn]);
    }
    root.appendChild(mt);
    const res = await api('resources?app='+encodeURIComponent(app.name));
    const rt = document.createElement('table');
    row(rt, ['resource', 'pass qps', 'block qps', 'rt ms'], 'th');
    const now = Date.now();
    for (const r of res){
      const ms = await api(`metric?app=${encodeURIComponent(app.name)}` +
        `&identity=${encodeURIComponent(r)}&startTime=${now-15000}&endTime=${now}`);
      const last = ms[ms.length-1] || {};
      row(rt, [r, last.passQps??'', last.blockQps??'', last.rt??'']);
    }
    root.appendChild(rt);
  }
}
refresh(); setInterval(refresh, 3000);
</script></body></html>"""


class DashboardServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8080,
        fetch_interval_s: float = 1.0,
        auth: Optional[Tuple[str, str]] = None,
    ):
        """``auth=(username, password)`` enables login (the reference's
        ``sentinel.dashboard.auth.username/password`` simple auth); default
        is open access, matching the reference's default ``sentinel/sentinel``
        stance for dev use."""
        self.apps = AppManagement()
        self.repository = InMemoryMetricsRepository()
        self.client = ApiClient()
        self.fetcher = MetricFetcher(
            self.apps, self.repository, self.client, fetch_interval_s
        )
        self.auth = auth
        # token → expiry-ms; bounded and TTL'd (an unbounded forever-valid
        # session set would grow with every login and keep stolen cookies
        # alive until restart)
        self._sessions: dict = {}
        # ThreadingHTTPServer handles each request on its own thread — every
        # _sessions access goes through this lock (prune in place, never
        # rebind, so a concurrent logout can't be lost on an old dict)
        self._sessions_lock = threading.Lock()
        self.session_ttl_ms = 24 * 3600 * 1000
        self.max_sessions = 1000
        self._service = HttpService(
            self._respond, host, port, name="sentinel-dashboard"
        )

    @property
    def host(self) -> str:
        return self._service.host

    @property
    def port(self) -> int:
        return self._service.port

    # -- auth ----------------------------------------------------------------
    def _session_of(self, headers) -> Optional[str]:
        cookie = headers.get("Cookie", "") if headers is not None else ""
        now = _clock.now_ms()
        for part in cookie.split(";"):
            k, _, v = part.strip().partition("=")
            if k == "sentinel_session":
                with self._sessions_lock:
                    expiry = self._sessions.get(v)
                    if expiry is not None and expiry > now:
                        return v
                    self._sessions.pop(v, None)  # expired
        return None

    def _login(self, params: dict, body: str):
        data = json.loads(body) if body else dict(params)
        user, password = self.auth
        if not (
            hmac.compare_digest(str(data.get("username", "")), user)
            and hmac.compare_digest(str(data.get("password", "")), password)
        ):
            return (401, json.dumps({"error": "invalid credentials"}),
                    "application/json; charset=utf-8")
        token = secrets.token_urlsafe(24)
        now = _clock.now_ms()
        with self._sessions_lock:
            for t in [t for t, exp in self._sessions.items() if exp <= now]:
                del self._sessions[t]
            while len(self._sessions) >= self.max_sessions:
                self._sessions.pop(next(iter(self._sessions)))  # oldest first
            self._sessions[token] = now + self.session_ttl_ms
        return (
            200,
            json.dumps({"code": 0}),
            "application/json; charset=utf-8",
            {"Set-Cookie":
             f"sentinel_session={token}; HttpOnly; Path=/; SameSite=Lax"},
        )

    # -- request handling ----------------------------------------------------
    def _respond(
        self, method: str, path: str, params: dict, body: str, headers=None
    ) -> Response:
        if self.auth is not None:
            if method == "POST" and path == "auth/login":
                return self._login(params, body)
            if method == "POST" and path == "auth/logout":
                token = self._session_of(headers)
                if token is not None:
                    with self._sessions_lock:
                        self._sessions.pop(token, None)
                return json_response(200, json.dumps({"code": 0}))
            if path not in AUTH_EXEMPT and self._session_of(headers) is None:
                return json_response(401, json.dumps({"error": "login required"}))
        result = self._route(method, path, params, body)
        if result is None:
            return json_response(404, json.dumps({"error": "not found"}))
        if isinstance(result, str):
            return html_response(200, result)
        return json_response(200, json.dumps(result))

    def _route(self, method: str, path: str, params: dict, body: str):
        if method == "POST" and path == "registry/machine":
            data = json.loads(body) if body else dict(params)
            machine = MachineInfo(
                app=str(data.get("app", "")),
                ip=str(data.get("ip", "")),
                port=int(data.get("port", 0)),
                hostname=str(data.get("hostname", "")),
                version=str(data.get("version", "")),
                last_heartbeat_ms=_clock.now_ms(),
            )
            self.apps.register(machine)
            return {"code": 0, "msg": "success"}
        if path == "apps":
            return [
                {
                    "name": app,
                    "machines": [m.to_dict() for m in self.apps.machines(app)],
                }
                for app in self.apps.apps()
            ]
        if path == "resources":
            return self.repository.resources_of_app(params.get("app", ""))
        if path == "metric":
            entries = self.repository.query(
                params.get("app", ""),
                params.get("identity", ""),
                int(params.get("startTime", 0)),
                int(params.get("endTime", 2**62)),
            )
            return [e.to_dict() for e in entries]
        if path == "rules":
            app = params.get("app", "")
            rule_type = params.get("type", "flow")
            if rule_type not in RULE_TYPES:
                return {"error": f"unknown rule type {rule_type}"}
            machines = self.apps.healthy_machines(app)
            if not machines:
                return {"error": f"no healthy machine for app {app}"}
            if method == "POST":
                rules = json.loads(body)
                pushed = sum(
                    self.client.push_rules(m, rule_type, rules) for m in machines
                )
                return {"pushed": pushed, "machines": len(machines)}
            return self.client.fetch_rules(machines[0], rule_type)
        if method == "POST" and path == "machine/remove":
            # per-machine deregistration; ip+port name the machine
            removed = self.apps.remove_machine(
                params.get("app", ""), params.get("ip", ""),
                int(params.get("port", 0)),
            )
            return {"code": 0 if removed else 1}
        if method == "POST" and path == "app/remove":
            self.apps.remove_app(params.get("app", ""))
            return {"code": 0}
        if path == "cluster/state":
            # per-machine cluster mode snapshot (ClusterAssignController's
            # read side): -1 off, 0 client, 1 server, null unreachable
            app = params.get("app", "")
            return [
                {
                    "machine": m.key,
                    "ip": m.ip,
                    "port": m.port,
                    "mode": self.client.get_cluster_mode(m),
                }
                for m in self.apps.healthy_machines(app)
            ]
        if method == "POST" and path == "cluster/assign":
            # one-shot assignment (ClusterAssignServiceImpl analog): flip the
            # chosen machine to server mode, everything else to client mode
            # pointed at it
            data = json.loads(body) if body else {}
            app = params.get("app", "") or data.get("app", "")
            server_key = data.get("server", "")
            token_port = int(data.get("tokenPort", 18730))
            machines = self.apps.healthy_machines(app)
            server = next((m for m in machines if m.key == server_key), None)
            if server is None:
                return {"error": f"machine {server_key} not found/healthy"}
            if not self.client.set_cluster_mode(server, 1, token_port):
                # abort BEFORE touching clients: re-pointing the fleet at a
                # machine that failed to become a server would break every
                # cluster check at once
                return {"error": f"promoting {server_key} to token server "
                        "failed; no clients were reconfigured"}
            results = {"server": True, "clients": 0, "failed": []}
            for m in machines:
                if m.key == server_key:
                    continue
                ok = self.client.push_cluster_client_config(
                    m, server.ip, token_port
                ) and self.client.set_cluster_mode(m, 0)
                if ok:
                    results["clients"] += 1
                else:
                    results["failed"].append(m.key)
            return results
        if path in ("", "index.html"):
            return _INDEX_HTML
        return None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "DashboardServer":
        self._service.start()
        self.fetcher.start()
        return self

    def stop(self) -> None:
        self.fetcher.stop()
        self._service.stop()
