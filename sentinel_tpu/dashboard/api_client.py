"""HTTP client to app command centers (``client/SentinelApiClient.java:93``).

Fetches metric log lines and rules from, and pushes rules to, a machine's
command center (the embedded HTTP server every guarded app runs).
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from typing import List, Optional

from sentinel_tpu.core.log import record_log
from sentinel_tpu.dashboard.discovery import MachineInfo
from sentinel_tpu.metrics.log import MetricNode


# Budget for promoting an agent to token server: the handler jit-compiles
# the decision kernels (two shape buckets × two variants — seconds on CPU,
# tens of seconds on a cold TPU) before acking. The handler is idempotent,
# so even a timeout here reconciles on retry.
PROMOTE_TIMEOUT_S = 120.0


class ApiClient:
    def __init__(self, timeout_s: float = 3.0,
                 promote_timeout_s: float = PROMOTE_TIMEOUT_S):
        self.timeout_s = timeout_s
        self.promote_timeout_s = promote_timeout_s

    def _get(self, machine: MachineInfo, command: str, params: dict,
             timeout_s: Optional[float] = None) -> Optional[str]:
        query = urllib.parse.urlencode({k: v for k, v in params.items() if v is not None})
        url = f"http://{machine.ip}:{machine.port}/{command}?{query}"
        try:
            with urllib.request.urlopen(
                url, timeout=timeout_s or self.timeout_s
            ) as rsp:
                return rsp.read().decode()
        except Exception as e:
            record_log.warning("command %s on %s failed: %s", command, machine.key, e)
            return None

    def _post(self, machine: MachineInfo, command: str, params: dict,
              body: str) -> Optional[str]:
        query = urllib.parse.urlencode(params)
        url = f"http://{machine.ip}:{machine.port}/{command}?{query}"
        try:
            req = urllib.request.Request(
                url, data=body.encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=self.timeout_s) as rsp:
                return rsp.read().decode()
        except Exception as e:
            record_log.warning("command %s on %s failed: %s", command, machine.key, e)
            return None

    # -- metrics (MetricFetcher's transport) --------------------------------
    def fetch_metrics(
        self, machine: MachineInfo, start_ms: int, end_ms: int
    ) -> Optional[List[MetricNode]]:
        """Metric lines for the window, or ``None`` on transport failure —
        the fetcher must not advance a machine's window past data it never
        received."""
        text = self._get(
            machine, "metric", {"startTime": start_ms, "endTime": end_ms}
        )
        if text is None:
            return None
        if not text:
            return []
        nodes = []
        for line in text.splitlines():
            if not line.strip():
                continue
            try:
                nodes.append(MetricNode.from_line(line))
            except (ValueError, IndexError):
                continue
        return nodes

    # -- rules (SentinelApiClient.fetchRules / setRulesAsync) ---------------
    def fetch_json(self, machine: MachineInfo, command: str,
                   params: Optional[dict] = None):
        """GET a command and parse its JSON body; None on transport/parse
        failure. The cluster monitor screens ride this for
        ``cluster/server/info``, ``cluster/server/metrics`` and
        ``cluster/client/fetchConfig`` (the dashboard-side counterpart of
        ``ClusterConfigService``'s state fetches)."""
        text = self._get(machine, command, params or {})
        if text is None:
            return None
        try:
            return json.loads(text)
        except json.JSONDecodeError:
            record_log.warning(
                "bad %s payload from %s", command, machine.key
            )
            return None

    def fetch_rules(self, machine: MachineInfo, rule_type: str) -> Optional[list]:
        return self.fetch_json(machine, "getRules", {"type": rule_type})

    def get_cluster_mode(self, machine: MachineInfo) -> Optional[int]:
        raw = self._get(machine, "getClusterMode", {})
        if raw is None:
            return None
        try:
            return int(json.loads(raw).get("mode", -1))
        except (ValueError, AttributeError):
            return None

    def set_cluster_mode(
        self, machine: MachineInfo, mode: int, token_port: Optional[int] = None
    ) -> bool:
        params = {"mode": str(mode)}
        if token_port is not None:
            params["tokenPort"] = str(token_port)
        timeout_s = (
            max(self.timeout_s, self.promote_timeout_s) if mode == 1 else None
        )
        return self._get(
            machine, "setClusterMode", params, timeout_s=timeout_s
        ) is not None

    def push_cluster_client_config(
        self, machine: MachineInfo, server_host: str, server_port: int
    ) -> bool:
        body = json.dumps(
            {"serverHost": server_host, "serverPort": server_port}
        )
        return self._post(machine, "cluster/client/modifyConfig", {}, body) is not None

    def push_api_definitions(self, machine: MachineInfo, body: str) -> bool:
        """Replace a machine's gateway custom-API groups (raw JSON array)."""
        rsp = self._post(machine, "gateway/updateApiDefinitions", {}, body)
        return rsp is not None and "success" in rsp

    def push_rules(self, machine: MachineInfo, rule_type: str, rules: list) -> bool:
        rsp = self._post(
            machine, "setRules", {"type": rule_type}, json.dumps(rules)
        )
        return rsp is not None and "success" in rsp
