"""Pluggable rule providers/publishers — the v2 console contract.

Analog of the reference's ``DynamicRuleProvider.java:22`` (``getRules``)
and ``DynamicRulePublisher.java:22`` (``publish``), the seam behind
``controller/v2/FlowControllerV2.java:63-64``: the v1 console talks to app
machines directly (fetch from one, push to all), while v2 decouples the
console from the fleet through a configuration store — the publisher
WRITES the app's authoritative rule list to the store, the provider READS
it back, and the agents converge by watching the same store through their
datasource layer (``sentinel_tpu.datasource.*``), never receiving a direct
dashboard push.

Python idiom: providers/publishers are small objects (or callables) wired
per ``(rule_type)`` into ``DashboardServer(rule_plugins=...)``; the
``ApiRule*`` pair reproduces v1's direct-to-machine behavior as a plugin so
both models ride one route, and ``FileRuleStore`` gives the store-backed
pair a zero-dependency backend whose files pair with each agent's
``FileRefreshableDataSource`` watcher.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Callable, Optional


class DynamicRuleProvider:
    """Reads the authoritative rule list for an app from somewhere."""

    def get_rules(self, app: str) -> Optional[list]:
        raise NotImplementedError


class DynamicRulePublisher:
    """Writes the authoritative rule list for an app to somewhere."""

    def publish(self, app: str, rules: list) -> None:
        raise NotImplementedError


# --------------------------------------------------------------------------
# Direct-to-machine pair (v1 behavior as a v2 plugin):
# FlowRuleApiProvider / FlowRuleApiPublisher analogs
# --------------------------------------------------------------------------


class ApiRuleProvider(DynamicRuleProvider):
    def __init__(self, apps, client, rule_type: str):
        self.apps = apps
        self.client = client
        self.rule_type = rule_type

    def get_rules(self, app: str) -> Optional[list]:
        machines = self.apps.healthy_machines(app)
        if not machines:
            return None
        return self.client.fetch_rules(machines[0], self.rule_type)


class ApiRulePublisher(DynamicRulePublisher):
    def __init__(self, apps, client, rule_type: str):
        self.apps = apps
        self.client = client
        self.rule_type = rule_type

    def publish(self, app: str, rules: list) -> None:
        machines = self.apps.healthy_machines(app)
        if not machines:
            raise RuntimeError(f"no healthy machine for app {app}")
        pushed = sum(
            self.client.push_rules(m, self.rule_type, rules)
            for m in machines
        )
        if pushed == 0:
            raise RuntimeError("push failed on every machine")


# --------------------------------------------------------------------------
# Store-backed pair (config-center model): the store is any get/set pair,
# so the same classes bind to etcd/nacos/redis via their client callables
# --------------------------------------------------------------------------


class StoreRuleProvider(DynamicRuleProvider):
    """``get(key) -> str | None`` + a key template → provider."""

    def __init__(self, get: Callable[[str], Optional[str]],
                 rule_type: str, key_fmt: str = "{app}-{type}-rules"):
        self.get = get
        self.rule_type = rule_type
        self.key_fmt = key_fmt

    def get_rules(self, app: str) -> Optional[list]:
        raw = self.get(self.key_fmt.format(app=app, type=self.rule_type))
        if raw is None:
            return []  # nothing published yet — an empty authoritative list
        rules = json.loads(raw)
        return rules if isinstance(rules, list) else []


class StoreRulePublisher(DynamicRulePublisher):
    """``set(key, value_str)`` + a key template → publisher."""

    def __init__(self, set_: Callable[[str, str], None],
                 rule_type: str, key_fmt: str = "{app}-{type}-rules"):
        self.set = set_
        self.rule_type = rule_type
        self.key_fmt = key_fmt

    def publish(self, app: str, rules: list) -> None:
        self.set(
            self.key_fmt.format(app=app, type=self.rule_type),
            json.dumps(rules),
        )


class FileRuleStore:
    """Directory-of-JSON-files store: key → ``<dir>/<key>.json``.

    The written path is exactly what an agent hands to its
    ``FileRefreshableDataSource`` (datasource/file.py), so publishing from
    the dashboard and converging on the agent share one file with no
    dashboard→machine connection. Writes are atomic (tmp + rename), the
    same torn-read guard as ``FileWritableDataSource``.
    """

    def __init__(self, root: str):
        self.root = root
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)

    def path_for(self, key: str) -> str:
        # keys embed app names, which arrive from heartbeats — never let
        # one traverse out of the store directory
        return os.path.join(self.root, re.sub(r"[^\w.-]", "_", key) + ".json")

    def get(self, key: str) -> Optional[str]:
        try:
            with open(self.path_for(key), "r", encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    def set(self, key: str, value: str) -> None:
        path = self.path_for(key)
        with self._lock:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(value)
            os.replace(tmp, path)

    def provider(self, rule_type: str) -> StoreRuleProvider:
        return StoreRuleProvider(self.get, rule_type)

    def publisher(self, rule_type: str) -> StoreRulePublisher:
        return StoreRulePublisher(self.set, rule_type)

    def plugins(self, rule_types) -> dict:
        """``rule_plugins`` mapping for DashboardServer: every type backed
        by this store."""
        return {
            t: (self.provider(t), self.publisher(t)) for t in rule_types
        }
