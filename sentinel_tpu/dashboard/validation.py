"""Server-side rule validation for the console CRUD routes.

Behavioral analog of the reference controllers' ``checkEntityInternal``
chains (``FlowControllerV1.java:89-134``, ``DegradeController.java:169-215``,
``SystemController``, ``AuthorityRuleController``, ``ParamFlowRuleController``,
``GatewayFlowRuleController``): a malformed rule must be rejected with a
named reason BEFORE it is stored or pushed to any agent — never fanned out
to fail on every machine. App/ip/port identity checks live in the routes
(our dashboard pushes per-app, not per-machine), so validators here cover
the rule payload itself.

Each validator returns an error string, or ``None`` when the rule is valid.
"""

from __future__ import annotations

from typing import Optional


def _num(d: dict, key: str):
    v = d.get(key)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return v


def _require_resource(d: dict) -> Optional[str]:
    if not str(d.get("resource", "") or "").strip():
        return "resource can't be null or empty"
    return None


def validate_flow(d: dict) -> Optional[str]:
    """``FlowControllerV1.checkEntityInternal`` contract."""
    err = _require_resource(d)
    if err:
        return err
    if not str(d.get("limitApp", "default") or "").strip():
        return "limitApp can't be null or empty"
    grade = d.get("grade", 1)
    if grade not in (0, 1):
        return f"grade must be 0 or 1, but {grade} got"
    count = _num(d, "count") if "count" in d else 0
    if count is None or count < 0:
        return "count should be at least zero"
    strategy = d.get("strategy", 0)
    if strategy not in (0, 1, 2):
        return f"invalid strategy: {strategy}"
    if strategy != 0 and not str(d.get("refResource", "") or "").strip():
        return "refResource can't be null or empty when strategy!=0"
    cb = d.get("controlBehavior", 0)
    if cb not in (0, 1, 2, 3):
        return f"invalid controlBehavior: {cb}"
    if cb in (1, 3):
        warm = _num(d, "warmUpPeriodSec") if "warmUpPeriodSec" in d else 10
        if warm is None or warm <= 0:
            return "warmUpPeriodSec should be positive when controlBehavior"\
                " uses warm-up"
    if cb in (2, 3):
        q = _num(d, "maxQueueingTimeMs") if "maxQueueingTimeMs" in d else 500
        if q is None or q < 0:
            return "maxQueueingTimeMs can't be negative when controlBehavior"\
                " uses pacing"
    if d.get("clusterMode") and not isinstance(
        d.get("clusterConfig", {}), dict
    ):
        return "cluster config should be valid"
    return None


def validate_degrade(d: dict) -> Optional[str]:
    """``DegradeController.checkEntityInternal`` contract."""
    err = _require_resource(d)
    if err:
        return err
    count = _num(d, "count")
    if count is None or count < 0:
        return f"invalid threshold: {d.get('count')}"
    tw = _num(d, "timeWindow")
    if tw is None or tw <= 0:
        return "recoveryTimeout (timeWindow) should be positive"
    # absent defaults to 0 (slow-ratio), matching the agent-side converter
    # (datasource/converters.py:65) and the reference's int default
    grade = d.get("grade", 0)
    if grade not in (0, 1, 2):
        return f"invalid circuit breaker strategy: {grade}"
    mra = _num(d, "minRequestAmount") if "minRequestAmount" in d else 5
    if mra is None or mra <= 0:
        return "invalid minRequestAmount"
    si = _num(d, "statIntervalMs") if "statIntervalMs" in d else 1000
    if si is None or si <= 0:
        return "invalid statIntervalMs"
    if grade == 0 and "slowRatioThreshold" in d:
        # absent is fine: the agent-side converter defaults it to 1.0
        # (datasource/converters.py); a PRESENT value must be a ratio
        ratio = _num(d, "slowRatioThreshold")
        if ratio is None or not (0 <= ratio <= 1):
            return "slowRatioThreshold must be in [0, 1] for the slow-ratio"\
                " strategy"
    return None


def validate_system(d: dict) -> Optional[str]:
    """``SystemController`` contract: at least one threshold, sane ranges."""
    keys = ("highestSystemLoad", "highestCpuUsage", "qps", "avgRt",
            "maxThread")
    set_keys = [k for k in keys if d.get(k) is not None]
    if not set_keys:
        return "at least one threshold must be set"
    for k in set_keys:
        v = _num(d, k)
        if v is None or v < 0:
            return f"invalid {k}: {d.get(k)}"
        if k == "highestCpuUsage" and v > 1:
            return "highestCpuUsage must be in [0, 1]"
    return None


def validate_authority(d: dict) -> Optional[str]:
    err = _require_resource(d)
    if err:
        return err
    if not str(d.get("limitApp", "") or "").strip():
        return "limitApp (origins) can't be null or empty"
    if d.get("strategy", 0) not in (0, 1):
        return f"invalid strategy: {d.get('strategy')}"
    return None


def validate_param_flow(d: dict) -> Optional[str]:
    err = _require_resource(d)
    if err:
        return err
    idx = _num(d, "paramIdx")
    if idx is None or idx < 0 or int(idx) != idx:
        return f"invalid paramIdx: {d.get('paramIdx')}"
    count = _num(d, "count")
    if count is None or count < 0:
        return f"invalid count: {d.get('count')}"
    dur = _num(d, "durationInSec") if "durationInSec" in d else 1
    if dur is None or dur <= 0:
        return "durationInSec should be positive"
    return None


def validate_gateway(d: dict) -> Optional[str]:
    err = _require_resource(d)
    if err:
        return err
    if d.get("resourceMode", 0) not in (0, 1):
        return f"invalid resourceMode: {d.get('resourceMode')}"
    count = _num(d, "count")
    if count is None or count < 0:
        return f"invalid count: {d.get('count')}"
    interval = _num(d, "intervalSec") if "intervalSec" in d else 1
    if interval is None or interval <= 0:
        return "intervalSec should be positive"
    return None


VALIDATORS = {
    "flow": validate_flow,
    "degrade": validate_degrade,
    "system": validate_system,
    "authority": validate_authority,
    "paramFlow": validate_param_flow,
    "gateway": validate_gateway,
}


def validate_rule(rule_type: str, rule: dict) -> Optional[str]:
    """Error string for an invalid (type, rule) payload, else None.
    Non-dict payloads are invalid for every type."""
    if not isinstance(rule, dict):
        return "rule must be a JSON object"
    v = VALIDATORS.get(rule_type)
    return v(rule) if v else None
