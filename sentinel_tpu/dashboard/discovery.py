"""Machine discovery: who is alive, per app.

Analog of ``discovery/SimpleMachineDiscovery.java`` + ``AppManagement`` +
``MachineInfo`` (heartbeat staleness marks machines dead, SURVEY.md §5).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from sentinel_tpu.core import clock as _clock

HEARTBEAT_STALE_MS = 30_000  # reference marks dead after missed heartbeats


@dataclass
class MachineInfo:
    app: str
    ip: str
    port: int
    hostname: str = ""
    version: str = ""
    last_heartbeat_ms: int = 0

    @property
    def key(self) -> str:
        return f"{self.ip}:{self.port}"

    def healthy(self, now_ms: Optional[int] = None) -> bool:
        now = _clock.now_ms() if now_ms is None else now_ms
        return now - self.last_heartbeat_ms < HEARTBEAT_STALE_MS

    def to_dict(self) -> dict:
        return {
            "app": self.app,
            "ip": self.ip,
            "port": self.port,
            "hostname": self.hostname,
            "version": self.version,
            "lastHeartbeat": self.last_heartbeat_ms,
            "healthy": self.healthy(),
        }


class AppManagement:
    """app → {ip:port → MachineInfo}; single lock, registration idempotent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._apps: Dict[str, Dict[str, MachineInfo]] = {}

    def register(self, machine: MachineInfo) -> None:
        if not machine.app or not machine.ip:
            raise ValueError("machine must carry app and ip")
        if machine.last_heartbeat_ms == 0:
            machine.last_heartbeat_ms = _clock.now_ms()
        with self._lock:
            self._apps.setdefault(machine.app, {})[machine.key] = machine

    def apps(self) -> List[str]:
        with self._lock:
            return sorted(self._apps)

    def machines(self, app: str) -> List[MachineInfo]:
        with self._lock:
            return list(self._apps.get(app, {}).values())

    def healthy_machines(self, app: str) -> List[MachineInfo]:
        now = _clock.now_ms()
        return [m for m in self.machines(app) if m.healthy(now)]

    def remove_app(self, app: str) -> None:
        with self._lock:
            self._apps.pop(app, None)

    def remove_machine(self, app: str, ip: str, port: int) -> bool:
        """Deregister one machine; drops the app when it was the last one."""
        key = f"{ip}:{port}"
        with self._lock:
            machines = self._apps.get(app)
            if machines is None or machines.pop(key, None) is None:
                return False
            if not machines:
                self._apps.pop(app, None)
            return True
