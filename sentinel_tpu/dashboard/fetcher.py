"""Scheduled metric pull from every healthy machine.

Analog of ``metric/MetricFetcher.java:70-210``: for each app, poll each
healthy machine's ``/metric`` command for the window since the last fetch,
sum the per-machine lines by (resource, second), and store into the
repository. The reference trails real time by a few seconds so machines have
flushed their metric logs; same here (``FETCH_DELAY_MS``).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.log import record_log
from sentinel_tpu.dashboard.api_client import ApiClient
from sentinel_tpu.dashboard.discovery import AppManagement
from sentinel_tpu.dashboard.repository import InMemoryMetricsRepository, MetricEntry

FETCH_DELAY_MS = 2_000  # let apps flush their 1s aggregation first
MAX_WINDOW_MS = 60_000  # don't backfill more than a minute on catch-up


class MetricFetcher:
    def __init__(
        self,
        apps: AppManagement,
        repository: InMemoryMetricsRepository,
        client: Optional[ApiClient] = None,
        interval_s: float = 1.0,
    ):
        self.apps = apps
        self.repository = repository
        self.client = client or ApiClient()
        self.interval_s = interval_s
        # (app, machine-key) → end of that machine's last successful window.
        # Per-machine windows: one machine timing out must not advance the
        # others' (or its own) window past data not yet pulled.
        self._last_fetch: Dict[tuple, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def fetch_once(self, app: str) -> int:
        """Pull each machine's pending window for ``app``; returns the number
        of entries merged into the repository.

        Cross-machine sums by (resource, second) happen in the repository
        (``save_all(..., merge=True)``): each machine's lines are fetched
        exactly once, so merge-adds are safe even when machines are on
        different catch-up windows.
        """
        now = _clock.now_ms()
        end = now - FETCH_DELAY_MS
        stored = 0
        healthy = self.apps.healthy_machines(app)
        # prune cursors for machine incarnations that left discovery entirely
        # (pods restarting on ephemeral ports would otherwise leak one key
        # per incarnation); still-registered-but-dead machines keep theirs
        registered = {(app, m.key) for m in self.apps.machines(app)}
        for key in [
            k for k in self._last_fetch if k[0] == app and k not in registered
        ]:
            del self._last_fetch[key]
        for machine in healthy:
            key = (app, machine.key)
            # MetricSearcher windows are inclusive on both ends, so the next
            # window starts one ms after the last — a second-aligned line at
            # exactly the boundary must not be fetched (and merge-summed) twice
            start = self._last_fetch.get(key, end - 5_000 - 1) + 1
            if end < start:
                continue
            start = max(start, end - MAX_WINDOW_MS)
            nodes = self.client.fetch_metrics(machine, start, end)
            if nodes is None:
                continue  # transport failure: retry the same window next tick
            entries = [
                MetricEntry(
                    app=app,
                    resource=node.resource,
                    timestamp_ms=node.timestamp_ms,
                    pass_qps=node.pass_qps,
                    block_qps=node.block_qps,
                    success_qps=node.success_qps,
                    exception_qps=node.exception_qps,
                    rt=node.rt,
                    # machine tag feeds the per-machine drill-down series;
                    # the merged app-wide series strips it on save
                    machine=machine.key,
                )
                for node in nodes
            ]
            self.repository.save_all(entries, merge=True)
            self._last_fetch[key] = end
            stored += len(entries)
        return stored

    def prune_dead_apps(self, live_apps) -> None:
        """Drop cursors of apps that left discovery entirely — fetch_once
        prunes per-machine cursors within a live app, but never visits a
        vanished app, so ephemeral per-deploy app names would otherwise leak
        one cursor set each."""
        live = set(live_apps)
        for key in [k for k in self._last_fetch if k[0] not in live]:
            del self._last_fetch[key]

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            live_apps = self.apps.apps()
            self.prune_dead_apps(live_apps)
            for app in live_apps:
                try:
                    self.fetch_once(app)
                except Exception:
                    record_log.exception("metric fetch for %s failed", app)

    def start(self) -> "MetricFetcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="sentinel-metric-fetcher"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            if thread.is_alive():
                return
            self._thread = None
        self._stop.clear()
