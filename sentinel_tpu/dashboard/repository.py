"""In-memory metric store with bounded retention.

Analog of ``repository/metric/InMemoryMetricsRepository.java:40-63``
(5-minute in-memory window, per app+resource).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Tuple

from sentinel_tpu.core import clock as _clock

RETENTION_MS = 5 * 60 * 1000  # InMemoryMetricsRepository.java:42


@dataclass
class MetricEntry:
    app: str
    resource: str
    timestamp_ms: int
    pass_qps: float = 0.0
    block_qps: float = 0.0
    success_qps: float = 0.0
    exception_qps: float = 0.0
    rt: float = 0.0
    machine: str = ""  # "ip:port" for per-machine series; "" = app-wide sum

    def to_dict(self) -> dict:
        return {
            "app": self.app,
            "resource": self.resource,
            "timestamp": self.timestamp_ms,
            "passQps": self.pass_qps,
            "blockQps": self.block_qps,
            "successQps": self.success_qps,
            "exceptionQps": self.exception_qps,
            "rt": self.rt,
            "machine": self.machine,
        }


class InMemoryMetricsRepository:
    def __init__(self, retention_ms: int = RETENTION_MS):
        self._lock = threading.Lock()
        self.retention_ms = retention_ms
        # (app, resource) → {timestamp → MetricEntry}
        self._store: Dict[Tuple[str, str], Dict[int, MetricEntry]] = {}
        # per-machine drill-down series (the reference's metric.js charts
        # one machine at a time): (app, machine, resource) → {ts → entry}
        self._machine_store: Dict[
            Tuple[str, str, str], Dict[int, MetricEntry]
        ] = {}
        self._last_sweep_ms = 0

    def save(self, entry: MetricEntry, merge: bool = False) -> None:
        with self._lock:
            series = self._store.setdefault((entry.app, entry.resource), {})
            existing = series.get(entry.timestamp_ms) if merge else None
            if existing is not None:
                existing.pass_qps += entry.pass_qps
                existing.block_qps += entry.block_qps
                existing.success_qps += entry.success_qps
                existing.exception_qps += entry.exception_qps
                existing.rt = max(existing.rt, entry.rt)
            else:
                # the app-wide series never carries a machine tag: merge
                # sums lines from several machines into one entry
                series[entry.timestamp_ms] = replace(entry, machine="")
            if entry.machine:
                mkey = (entry.app, entry.machine, entry.resource)
                self._machine_store.setdefault(mkey, {})[
                    entry.timestamp_ms
                ] = entry
            self._sweep_locked()

    def save_all(self, entries: List[MetricEntry], merge: bool = False) -> None:
        for e in entries:
            self.save(e, merge=merge)

    def _sweep_locked(self) -> None:
        """Evict past-retention entries across *all* series (at most once per
        second): idle series must age out too, or per-URL resource cardinality
        grows the store without bound."""
        now = _clock.now_ms()
        if now - self._last_sweep_ms < 1_000:
            return
        self._last_sweep_ms = now
        horizon = now - self.retention_ms
        for store in (self._store, self._machine_store):
            for key in list(store):
                series = store[key]
                for ts in [t for t in series if t < horizon]:
                    del series[ts]
                if not series:
                    del store[key]

    def query(
        self, app: str, resource: str, start_ms: int, end_ms: int
    ) -> List[MetricEntry]:
        horizon = _clock.now_ms() - self.retention_ms
        start_ms = max(start_ms, horizon)  # never serve past-retention data
        with self._lock:
            series = self._store.get((app, resource), {})
            # copies: merge-saves mutate stored entries in place, and readers
            # serialize outside the lock
            return sorted(
                (
                    replace(e)
                    for ts, e in series.items()
                    if start_ms <= ts <= end_ms
                ),
                key=lambda e: e.timestamp_ms,
            )

    def query_machine(
        self, app: str, machine: str, resource: str,
        start_ms: int, end_ms: int
    ) -> List[MetricEntry]:
        """One machine's own series for a resource (``metric.js`` drill-down
        analog) — the un-merged lines the fetcher pulled from that machine."""
        horizon = _clock.now_ms() - self.retention_ms
        start_ms = max(start_ms, horizon)
        with self._lock:
            series = self._machine_store.get((app, machine, resource), {})
            return sorted(
                (
                    replace(e)
                    for ts, e in series.items()
                    if start_ms <= ts <= end_ms
                ),
                key=lambda e: e.timestamp_ms,
            )

    def machines_of_resource(self, app: str, resource: str) -> List[str]:
        """Machines with live (in-retention) data for a resource."""
        horizon = _clock.now_ms() - self.retention_ms
        with self._lock:
            return sorted(
                m
                for (a, m, r), series in self._machine_store.items()
                if a == app and r == resource
                and any(t >= horizon for t in series)
            )

    @staticmethod
    def _by_volume(pairs, now: int, horizon: int) -> List[str]:
        """Resources sorted by last-minute pass+block volume, live
        (in-retention) series only — the reference's sidebar order. One
        implementation for the app-wide and per-machine views so the two
        sidebars can never diverge."""
        volume: Dict[str, float] = {}
        for resource, series in pairs:
            if not any(t >= horizon for t in series):
                continue
            volume[resource] = sum(
                e.pass_qps + e.block_qps
                for ts, e in series.items()
                if ts >= now - 60_000
            )
        return sorted(volume, key=lambda r: (-volume[r], r))

    def resources_of_machine(self, app: str, machine: str) -> List[str]:
        """One machine's resources sorted by its own recent volume
        (``identity.js`` analog: the per-machine resource view)."""
        now = _clock.now_ms()
        with self._lock:
            return self._by_volume(
                (
                    (resource, series)
                    for (a, m, resource), series
                    in self._machine_store.items()
                    if a == app and m == machine
                ),
                now, now - self.retention_ms,
            )

    def resources_of_app(self, app: str) -> List[str]:
        """Resources sorted by recent pass+block volume (the reference sorts
        the sidebar by last-minute QPS); past-retention series are excluded."""
        now = _clock.now_ms()
        with self._lock:
            return self._by_volume(
                (
                    (resource, series)
                    for (a, resource), series in self._store.items()
                    if a == app
                ),
                now, now - self.retention_ms,
            )
