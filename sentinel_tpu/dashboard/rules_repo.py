"""Id-keyed rule repository backing the per-rule-type console views.

Analog of the reference dashboard's ``InMemoryRuleRepositoryAdapter``
(``sentinel-dashboard/.../repository/rule/InMemoryRuleRepositoryAdapter.java``)
behind ``FlowControllerV1`` and its siblings: the console edits individual
rules by id; the dashboard keeps the id ↔ rule mapping (agents only ever see
whole lists) and pushes the assembled list to every healthy machine after
each mutation.

Rules are plain dicts in the agent's JSON schema (the same payloads
``setRules`` accepts) — the repository is storage + identity, not parsing.
"""

from __future__ import annotations

import itertools
import json
import threading
from typing import Dict, List, Optional, Tuple


class InMemoryRuleRepository:
    def __init__(self):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        # (app, rule_type) → {id: rule-dict}
        self._rules: Dict[Tuple[str, str], Dict[int, dict]] = {}

    @staticmethod
    def _content_key(rule: dict) -> str:
        return json.dumps(rule, sort_keys=True, default=str)

    def sync(self, app: str, rule_type: str, rules: List[dict]) -> List[dict]:
        """Replace the stored set from a live fetch. Ids are STABLE across
        syncs: a fetched rule whose content matches an existing entry keeps
        that entry's id (like the reference's ``InMemoryRuleRepositoryAdapter``
        keeping ids server-side), so concurrent console tabs and page reloads
        don't orphan an in-flight edit's id. Only genuinely new rules get
        fresh ids. Returns the stored entries with ids attached."""
        with self._lock:
            prev = self._rules.get((app, rule_type), {})
            # content → ids of previous entries, consumed first-come (stable
            # for duplicates: N identical rules keep N distinct ids)
            by_content: Dict[str, List[int]] = {}
            for rule_id, rule in sorted(prev.items()):
                by_content.setdefault(self._content_key(rule), []).append(rule_id)
            entries: Dict[int, dict] = {}
            for rule in rules:
                pool = by_content.get(self._content_key(rule))
                rule_id = pool.pop(0) if pool else next(self._ids)
                entries[rule_id] = dict(rule)
            self._rules[(app, rule_type)] = entries
            return [{"id": i, **r} for i, r in sorted(entries.items())]

    def known(self, app: str, rule_type: str) -> bool:
        """Whether this (app, type) has ever been synced/mutated — a fresh
        dashboard must sync from the live agent before its first mutation or
        the push would overwrite rules the agent already holds."""
        with self._lock:
            return (app, rule_type) in self._rules

    def list(self, app: str, rule_type: str) -> List[dict]:
        with self._lock:
            entries = self._rules.get((app, rule_type), {})
            return [{"id": i, **r} for i, r in sorted(entries.items())]

    def add(self, app: str, rule_type: str, rule: dict) -> int:
        with self._lock:
            rule_id = next(self._ids)
            self._rules.setdefault((app, rule_type), {})[rule_id] = dict(rule)
            return rule_id

    def update(self, app: str, rule_type: str, rule_id: int,
               rule: dict) -> bool:
        with self._lock:
            entries = self._rules.get((app, rule_type), {})
            if rule_id not in entries:
                return False
            entries[rule_id] = dict(rule)
            return True

    def delete(self, app: str, rule_type: str, rule_id: int) -> bool:
        with self._lock:
            entries = self._rules.get((app, rule_type), {})
            return entries.pop(rule_id, None) is not None

    def plain_rules(self, app: str, rule_type: str) -> List[dict]:
        """The id-less list an agent's setRules expects."""
        with self._lock:
            entries = self._rules.get((app, rule_type), {})
            return [dict(r) for _, r in sorted(entries.items())]
