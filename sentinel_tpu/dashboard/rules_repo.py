"""Id-keyed rule repository backing the per-rule-type console views.

Analog of the reference dashboard's ``InMemoryRuleRepositoryAdapter``
(``sentinel-dashboard/.../repository/rule/InMemoryRuleRepositoryAdapter.java``)
behind ``FlowControllerV1`` and its siblings: the console edits individual
rules by id; the dashboard keeps the id ↔ rule mapping (agents only ever see
whole lists) and pushes the assembled list to every healthy machine after
each mutation.

Rules are plain dicts in the agent's JSON schema (the same payloads
``setRules`` accepts) — the repository is storage + identity, not parsing.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional, Tuple


class InMemoryRuleRepository:
    def __init__(self):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        # (app, rule_type) → {id: rule-dict}
        self._rules: Dict[Tuple[str, str], Dict[int, dict]] = {}

    def sync(self, app: str, rule_type: str, rules: List[dict]) -> List[dict]:
        """Replace the stored set from a live fetch, assigning fresh ids
        (the reference re-saves on every page load too). Returns the stored
        entries with ids attached."""
        with self._lock:
            entries = {next(self._ids): dict(rule) for rule in rules}
            self._rules[(app, rule_type)] = entries
            return [{"id": i, **r} for i, r in sorted(entries.items())]

    def known(self, app: str, rule_type: str) -> bool:
        """Whether this (app, type) has ever been synced/mutated — a fresh
        dashboard must sync from the live agent before its first mutation or
        the push would overwrite rules the agent already holds."""
        with self._lock:
            return (app, rule_type) in self._rules

    def list(self, app: str, rule_type: str) -> List[dict]:
        with self._lock:
            entries = self._rules.get((app, rule_type), {})
            return [{"id": i, **r} for i, r in sorted(entries.items())]

    def add(self, app: str, rule_type: str, rule: dict) -> int:
        with self._lock:
            rule_id = next(self._ids)
            self._rules.setdefault((app, rule_type), {})[rule_id] = dict(rule)
            return rule_id

    def update(self, app: str, rule_type: str, rule_id: int,
               rule: dict) -> bool:
        with self._lock:
            entries = self._rules.get((app, rule_type), {})
            if rule_id not in entries:
                return False
            entries[rule_id] = dict(rule)
            return True

    def delete(self, app: str, rule_type: str, rule_id: int) -> bool:
        with self._lock:
            entries = self._rules.get((app, rule_type), {})
            return entries.pop(rule_id, None) is not None

    def plain_rules(self, app: str, rule_type: str) -> List[dict]:
        """The id-less list an agent's setRules expects."""
        with self._lock:
            entries = self._rules.get((app, rule_type), {})
            return [dict(r) for _, r in sorted(entries.items())]
