"""BBR-style admission control with a brownout ladder for the token server.

The reference protects a node with ``SystemSlot``'s BBR gate
(``SystemRuleManager.java:334-340``, mirrored in
``local/system_adaptive.py:_check_bbr``): under pressure, keep admitting
while ``concurrency <= maxSuccessQps * minRt``. That inequality is Little's
law — the left side is work in the system, the right side is the
bandwidth-delay product (BDP) the pipeline can actually hold. Anything
beyond the BDP only sits in queues, inflating every request's latency
without adding throughput, which is precisely the state an overloaded
token server must refuse instead of absorb.

This module applies the same estimator to the *serving pipeline* using the
signals :mod:`sentinel_tpu.metrics.server` already collects:

- **throughput** — the windowed verdicts/sec rate (``verdict_rate``),
- **minRt** — the decide-stage p50 (``decide_ms`` histogram), floored so a
  sub-100µs CPU step can't collapse the BDP to zero,
- **concurrency** — requests admitted by the front door and not yet
  answered, counted by the server via ``note_enqueued``/``note_done``.

The verdict is a **brownout level**, re-evaluated at most every
``recheck_ms`` so the hot path never pays for the histogramming:

``NORMAL``
    inflight within ``headroom_shed × BDP`` — admit everything.
``SHED_LOW``
    inflight beyond it — shed the lowest-priority rows first (answered
    with ``OVERLOAD`` + a retry hint), prioritized rows still reach the
    device. The reference's priority semantics, applied to survival.
``DEGRADE``
    inflight beyond ``headroom_degrade × BDP`` — the device is no longer
    consulted at all; the server answers locally, admitting a probabilistic
    fraction (``BDP / inflight``) with ``OK`` and refusing the rest with
    ``OVERLOAD``. Cheap, bounded, and it keeps the answer rate pinned to
    what the pipeline can actually sustain until the backlog drains.

Every decision is an *answer*, never silence — the client-side failover
breaker treats ``OVERLOAD`` as "alive, back off" (``ha/failover.py``), so a
browning-out server is not evicted from rotation.
"""

from __future__ import annotations

import enum
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.metrics.server import ServerMetrics, server_metrics

KEY_ENABLED = "sentinel.tpu.overload.enabled"
KEY_HEADROOM_SHED = "sentinel.tpu.overload.headroom.shed"
KEY_HEADROOM_DEGRADE = "sentinel.tpu.overload.headroom.degrade"
KEY_MIN_BDP = "sentinel.tpu.overload.min.bdp"
KEY_RECHECK_MS = "sentinel.tpu.overload.recheck.ms"
KEY_SUSTAIN_MS = "sentinel.tpu.overload.sustain.ms"
# per-namespace guaranteed shares for weighted shedding, e.g.
# "tenant-a=0.25,tenant-b=0.25" (fractions of each shed batch)
KEY_SHARES = "sentinel.tpu.overload.shares"


def parse_shares(spec: str) -> Dict[str, float]:
    """``"a=0.25,b=0.5"`` → ``{"a": 0.25, "b": 0.5}``; malformed entries
    are dropped, negatives clamped to 0 (a bad knob must not crash the
    door's shed path)."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        name = name.strip()
        if not name:
            continue
        try:
            out[name] = max(0.0, float(val))
        except ValueError:
            continue
    return out


class BrownoutLevel(enum.IntEnum):
    NORMAL = 0
    SHED_LOW = 1  # shed non-prioritized rows, serve the rest
    DEGRADE = 2  # probabilistic local answers, no device dispatch


@dataclass
class OverloadConfig:
    """Knobs for the admission controller (all config-overridable).

    The defaults are deliberately conservative: a closed-loop client fleet
    in steady state sits at inflight ≈ 1–4 × BDP (pipelining), so the shed
    ladder only engages on a genuine open-loop backlog.
    """

    enabled: bool = True
    headroom_shed: float = 8.0
    headroom_degrade: float = 32.0
    # BDP floor in requests: below this the estimator has too little signal
    # (cold server, idle rate window) to justify shedding anything
    min_bdp: float = 1024.0
    # decide-p50 floor: a sub-50µs CPU step must not zero the BDP
    min_rt_floor_ms: float = 0.05
    recheck_ms: float = 25.0
    # the over-threshold condition must hold THIS long before the ladder
    # escalates: a healthy pipeline absorbing a burst spikes past the BDP
    # headroom for tens of ms while draining fine — only a backlog that
    # *stays* means the pipeline is genuinely behind
    sustain_ms: float = 500.0
    # wait_ms hint carried on OVERLOAD verdicts (client backoff guidance)
    retry_hint_ms: int = 5
    # rebalance advisories: when sustained pressure engages the ladder, name
    # the hottest namespaces (by verdict rate since the last advisory) so an
    # operator — or an automated rebalancer — knows what to move off this
    # server. Rate-limited; 0 disables.
    advise_top_n: int = 3
    advise_interval_ms: float = 5_000.0
    # per-namespace guaranteed shares (fraction of each shed batch a tenant
    # keeps before the ladder touches it); empty → legacy whole-class shed.
    # Tenants absent from the map get ``ns_default_share``.
    ns_shares: Dict[str, float] = field(default_factory=dict)
    ns_default_share: float = 0.0

    @classmethod
    def from_config(cls) -> "OverloadConfig":
        return cls(
            enabled=SentinelConfig.get_bool(KEY_ENABLED, True),
            headroom_shed=SentinelConfig.get_float(KEY_HEADROOM_SHED, 8.0),
            headroom_degrade=SentinelConfig.get_float(
                KEY_HEADROOM_DEGRADE, 32.0
            ),
            min_bdp=SentinelConfig.get_float(KEY_MIN_BDP, 1024.0),
            recheck_ms=SentinelConfig.get_float(KEY_RECHECK_MS, 25.0),
            sustain_ms=SentinelConfig.get_float(KEY_SUSTAIN_MS, 500.0),
            ns_shares=parse_shares(SentinelConfig.get(KEY_SHARES, "") or ""),
        )


class AdmissionController:
    """BBR admission gate shared by a server's front-door lanes.

    Thread-safe; one instance per server (both front doors construct a
    default one). The level read is a cached attribute outside the
    re-evaluation window, so per-batch cost is O(1).
    """

    def __init__(
        self,
        config: Optional[OverloadConfig] = None,
        metrics: Optional[ServerMetrics] = None,
        seed: Optional[int] = None,
    ):
        self.config = config or OverloadConfig.from_config()
        self._m = metrics if metrics is not None else server_metrics()
        self._lock = threading.Lock()
        self._inflight = 0
        self._level = BrownoutLevel.NORMAL
        self._admit_frac = 1.0
        self._next_eval = 0.0
        self._over_since: Optional[float] = None
        self._rng = random.Random(seed)
        # rebalance advisories (cluster.rebalance): last advice emitted, a
        # baseline of per-namespace verdict totals to diff rates against,
        # and an optional listener (e.g. a controller that triggers a move)
        self.last_advice: Optional[dict] = None
        self.on_advice = None
        self._ns_baseline: dict = {}
        self._next_advise = 0.0
        # brownout level-change listener (rev-7 push plane): called with
        # (level_int, retry_hint_ms) on EVERY transition — escalations so
        # clients can pre-back-off before their next refusal, recoveries
        # so they stop. Same contract as on_advice: best-effort, must not
        # raise into the gate.
        self.on_level_change = None

    # -- inflight accounting (front doors call these) -----------------------
    def note_enqueued(self, n: int) -> None:
        with self._lock:
            self._inflight += int(n)

    def note_done(self, n: int) -> None:
        with self._lock:
            self._inflight -= int(n)
            if self._inflight < 0:  # lost accounting must not wedge shedding
                self._inflight = 0

    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def retry_hint_ms(self) -> int:
        return self.config.retry_hint_ms

    # -- the gate -----------------------------------------------------------
    def level(self, now: Optional[float] = None) -> BrownoutLevel:
        if not self.config.enabled:
            return BrownoutLevel.NORMAL
        if now is None:
            now = time.monotonic()
        if now >= self._next_eval:  # racy read is fine; eval is idempotent
            self._evaluate(now)
        return self._level

    def _evaluate(self, now: float) -> None:
        cfg = self.config
        with self._lock:
            self._next_eval = now + cfg.recheck_ms / 1000.0
            inflight = self._inflight
        bdp = self.estimated_bdp()
        if inflight > bdp * cfg.headroom_degrade:
            level = BrownoutLevel.DEGRADE
        elif inflight > bdp * cfg.headroom_shed:
            level = BrownoutLevel.SHED_LOW
        else:
            level = BrownoutLevel.NORMAL
        # escalation needs SUSTAINED pressure (a draining burst recovers
        # before the window elapses); recovery is immediate
        if level is BrownoutLevel.NORMAL:
            self._over_since = None
        else:
            if self._over_since is None:
                self._over_since = now
            if (now - self._over_since) * 1000.0 < cfg.sustain_ms:
                level = BrownoutLevel.NORMAL
        with self._lock:
            prev = self._level
            self._level = level
            self._admit_frac = (
                min(1.0, bdp / inflight) if inflight > 0 else 1.0
            )
        if level is not BrownoutLevel.NORMAL:
            # the ladder engaged on SUSTAINED pressure: this server is
            # genuinely behind, so advise which namespaces to move away
            self._maybe_advise(now, level)
            if level.value > prev.value:
                # escalation: freeze the flight-recorder evidence while
                # the window leading INTO the brownout is still in the
                # rings
                from sentinel_tpu.trace import blackbox as _blackbox
                from sentinel_tpu.trace import ring as _TR

                if _TR.ARMED:
                    _TR.record(_TR.BROWNOUT, aux=int(level.value))
                _blackbox.maybe_dump(f"brownout:{level.name.lower()}")
        if level is not prev:
            listener = self.on_level_change
            if listener is not None:
                try:
                    listener(int(level), self.config.retry_hint_ms)
                except Exception:
                    pass

    def _maybe_advise(self, now: float, level: BrownoutLevel) -> None:
        """Emit a ``rebalance-advise`` event naming the hottest namespaces
        (by verdict rate since the last advisory). Rate-limited to
        ``advise_interval_ms``; consumed via :attr:`last_advice`, the
        optional :attr:`on_advice` listener, and the HA metrics surface."""
        cfg = self.config
        if cfg.advise_top_n <= 0 or now < self._next_advise:
            return
        self._next_advise = now + cfg.advise_interval_ms / 1000.0
        totals = self._m.verdict_totals_by_namespace()
        baseline, self._ns_baseline = self._ns_baseline, totals
        rates = sorted(
            (
                (ns, count - baseline.get(ns, 0))
                for ns, count in totals.items()
            ),
            key=lambda kv: kv[1], reverse=True,
        )
        hottest = [
            {"namespace": ns, "verdicts": int(delta)}
            for ns, delta in rates[: cfg.advise_top_n]
            if delta > 0
        ]
        if not hottest:
            return
        advice = {
            "level": level.name,
            "namespaces": hottest,
            "monotonicMs": int(now * 1000.0),
        }
        self.last_advice = advice
        from sentinel_tpu.core.log import record_log
        from sentinel_tpu.metrics.ha import ha_metrics

        ha_metrics().count_rebalance("advise")
        record_log.warning(
            "rebalance-advise: sustained %s pressure; hottest namespaces %s",
            level.name,
            ", ".join(
                f"{e['namespace']}={e['verdicts']}" for e in hottest
            ),
        )
        listener = self.on_advice
        if listener is not None:
            try:
                listener(advice)
            except Exception:
                record_log.exception("rebalance-advise listener failed")

    def estimated_bdp(self) -> float:
        """max(rate × minRt, floor) — requests the pipeline can hold."""
        cfg = self.config
        rate = self._m.verdict_rate()
        min_rt = max(
            self._m.decide_ms.snapshot()["p50"] or 0.0, cfg.min_rt_floor_ms
        )
        return max(rate * min_rt / 1000.0, cfg.min_bdp)

    # -- brownout verdict helpers ------------------------------------------
    def set_shares(self, shares: Optional[Dict[str, float]]) -> None:
        """Install (or clear) per-namespace guaranteed shares for weighted
        ``SHED_LOW`` shedding. Scenario/ops entry point — rule loading
        does not set shares implicitly."""
        self.config.ns_shares = dict(shares) if shares else {}

    def shed_mask(self, prios, level: BrownoutLevel,
                  ns_idx=None, ns_names=()) -> np.ndarray:
        """bool[N] — True rows are refused with OVERLOAD at this level.

        ``SHED_LOW`` sheds the non-prioritized rows — *weighted by tenant
        share* when shares are configured and the caller supplies the
        batch's ``(ns_idx, ns_names)`` attribution (the
        ``TokenService.namespace_index`` shape both doors already
        compute): each tenant keeps a guaranteed ``ceil(share × N)`` rows
        of the batch; only its most recent non-prioritized rows beyond
        that are shed, and prioritized rows are never shed at this level,
        so a single flooding tenant browns itself out while in-share
        tenants ride through (the fairness gate's mechanism). Without
        shares (or without attribution) the legacy whole-class shed
        applies. ``DEGRADE`` sheds a random ``1 - admit_frac`` of ALL
        rows; the survivors get a local (device-free) answer from
        :meth:`degrade_verdicts`.
        """
        prios = np.asarray(prios, dtype=bool)
        if level == BrownoutLevel.SHED_LOW:
            shares = self.config.ns_shares
            if shares and ns_idx is not None and len(ns_names):
                return self._weighted_shed(
                    prios, np.asarray(ns_idx), tuple(ns_names), shares
                )
            return ~prios
        if level == BrownoutLevel.DEGRADE:
            with self._lock:
                frac = self._admit_frac
                if frac >= 1.0:
                    return np.zeros(prios.shape[0], dtype=bool)
                draws = np.array(
                    [self._rng.random() for _ in range(prios.shape[0])]
                )
            return draws >= frac
        return np.zeros(prios.shape[0], dtype=bool)

    def _weighted_shed(
        self,
        prios: np.ndarray,
        ns_idx: np.ndarray,
        ns_names,
        shares: Dict[str, float],
    ) -> np.ndarray:
        """Share-weighted SHED_LOW: per tenant, shed only the non-prio
        rows beyond ``ceil(share × N)``, newest-first (the tail of the
        batch arrived last; shedding it keeps the served prefix FIFO).
        Rows with no rule (``ns_idx < 0``) and tenants absent from the
        share map get ``ns_default_share`` (0 by default → legacy
        whole-class shed for them)."""
        n = prios.shape[0]
        shed = np.zeros(n, dtype=bool)
        default = self.config.ns_default_share
        for j in range(-1, len(ns_names)):
            rows = np.nonzero(ns_idx == j)[0]
            if rows.size == 0:
                continue
            share = shares.get(ns_names[j], default) if j >= 0 else default
            guaranteed = int(np.ceil(max(0.0, share) * n))
            excess = rows.size - guaranteed
            if excess <= 0:
                continue
            cand = rows[~prios[rows]]  # prioritized rows never shed here
            k = min(excess, cand.size)
            if k > 0:
                shed[cand[-k:]] = True
        return shed

    def degrade_verdicts(self, shed: np.ndarray):
        """(status, remaining, wait_ms) for a fully-local DEGRADE answer:
        admitted rows pass, shed rows get OVERLOAD + the retry hint."""
        from sentinel_tpu.engine import TokenStatus

        n = shed.shape[0]
        status = np.where(
            shed, np.int8(int(TokenStatus.OVERLOAD)), np.int8(int(TokenStatus.OK))
        ).astype(np.int8)
        remaining = np.zeros(n, np.int32)
        wait = np.where(shed, np.int32(self.config.retry_hint_ms), np.int32(0)).astype(
            np.int32
        )
        return status, remaining, wait

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "level": int(self._level),
                "levelName": self._level.name,
                "inflight": self._inflight,
                "admitFrac": round(self._admit_frac, 4),
                "estimatedBdp": round(self.estimated_bdp(), 1),
                "enabled": self.config.enabled,
                "nsShares": dict(self.config.ns_shares),
                "lastAdvice": self.last_advice,
            }
