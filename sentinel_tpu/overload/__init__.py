"""Server-side overload protection: deadline-aware admission + shedding.

See :mod:`sentinel_tpu.overload.admission` for the BBR-style controller and
the brownout ladder, and ``docs/ROBUSTNESS.md`` for the operational model.
"""

from sentinel_tpu.overload.admission import (
    AdmissionController,
    BrownoutLevel,
    OverloadConfig,
    parse_shares,
)

__all__ = [
    "AdmissionController",
    "BrownoutLevel",
    "OverloadConfig",
    "parse_shares",
]
