"""Chaos fault-injection registry: env/config-armed, zero overhead when off.

Robustness claims ("every request resolves, no deadlock, clean drain") are
only worth what the failure modes they survived are worth — so the serving
path carries explicit injection points and this registry decides, per event,
whether a fault fires. Six injectors cover the failure classes the cluster
subsystem must absorb:

``lane_delay``
    Sleep inside a serving lane (asyncio micro-batcher / native intake) —
    models a descheduled or GC-stalled host thread.
``frame_drop``
    A decoded request frame vanishes before the device sees it — models a
    lossy middlebox / dropped TCP segment past the kernel. The client's
    timeout is the only resolution path, which is exactly the invariant
    under test.
``frame_corrupt``
    Flip one byte of a wire buffer (outbound on the client, inbound in
    ``FrameReader``) — models bit rot and framing bugs; the peer must drop
    the connection gracefully, never a thread.
``device_stall``
    Sleep ahead of the device dispatch in ``TokenService`` — models a slow
    XLA step / preempted accelerator; backpressure and deadline shed must
    hold.
``clock_skew``
    Constant offset added to :func:`sentinel_tpu.core.clock.now_ms` —
    models NTP step/drift against the windowed estimators.
``conn_reset``
    The client tears its socket down mid-request — models RST storms;
    breakers and reconnect backoff must absorb it.

Arming is explicit (:func:`arm`) or via the environment at import time::

    SENTINEL_CHAOS="lane_delay:p=0.2,ms=5;frame_drop:p=0.05" \
    SENTINEL_CHAOS_SEED=1234 python -m ...

Spec grammar: ``point[:k=v[,k=v...]]`` joined by ``;`` — keys are ``p``
(fire probability, default 1), ``ms`` (magnitude for delay/stall/skew,
default 0) and ``n`` (max firings, 0 = unlimited). A fixed seed makes a
chaos run reproducible; firings are counted per point (:func:`fired`) so
tests can assert a fault actually happened.

Hot paths guard every probe with the module attribute ``ARMED`` — one
attribute read when chaos is off, which is the "zero overhead" contract.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Union

# Module-level fast flag: call sites do `if chaos.ARMED and chaos.should(..)`
# so a disarmed process pays one attribute read per probe, nothing else.
ARMED = False

POINTS = (
    "lane_delay",
    "frame_drop",
    "frame_corrupt",
    "device_stall",
    "clock_skew",
    "conn_reset",
)

ENV_SPEC = "SENTINEL_CHAOS"
ENV_SEED = "SENTINEL_CHAOS_SEED"


@dataclass
class Injector:
    point: str
    p: float = 1.0  # fire probability per probe
    ms: float = 0.0  # magnitude (delay/stall/skew), milliseconds
    n: int = 0  # max firings; 0 = unlimited


def parse_spec(spec: str) -> Dict[str, Injector]:
    """``"lane_delay:p=0.2,ms=5;frame_drop"`` → {point: Injector}."""
    out: Dict[str, Injector] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, args = part.partition(":")
        name = name.strip()
        if name not in POINTS:
            raise ValueError(f"unknown chaos point {name!r} (valid: {POINTS})")
        inj = Injector(name)
        for kv in args.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            k = k.strip()
            if k == "p":
                inj.p = float(v)
            elif k == "ms":
                inj.ms = float(v)
            elif k == "n":
                inj.n = int(v)
            else:
                raise ValueError(f"unknown chaos arg {k!r} in {part!r}")
        out[name] = inj
    return out


class ChaosRegistry:
    """Thread-safe injector set + seeded RNG + per-point fire counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inj: Dict[str, Injector] = {}
        self._rng = random.Random()
        self._fired: Dict[str, int] = {}

    # -- arming -------------------------------------------------------------
    def arm(
        self,
        spec: Union[str, Dict[str, Injector]],
        seed: Optional[int] = None,
    ) -> None:
        global ARMED
        inj = parse_spec(spec) if isinstance(spec, str) else dict(spec)
        with self._lock:
            self._inj = inj
            self._fired = {}
            if seed is not None:
                self._rng = random.Random(seed)
        ARMED = bool(inj)

    def disarm(self) -> None:
        global ARMED
        with self._lock:
            self._inj = {}
            self._fired = {}
        ARMED = False

    def arm_from_env(self, environ=None) -> bool:
        """Arm from ``SENTINEL_CHAOS``/``SENTINEL_CHAOS_SEED``; returns
        whether anything armed. Called once at import."""
        env = os.environ if environ is None else environ
        spec = env.get(ENV_SPEC, "").strip()
        if not spec:
            return False
        seed = env.get(ENV_SEED)
        self.arm(spec, seed=int(seed) if seed else None)
        return True

    # -- introspection ------------------------------------------------------
    @property
    def armed(self) -> bool:
        return bool(self._inj)

    def injectors(self) -> Dict[str, Injector]:
        with self._lock:
            return dict(self._inj)

    def fired(self) -> Dict[str, int]:
        """Per-point firing counts since arm() — chaos tests assert the
        fault under test actually happened."""
        with self._lock:
            return dict(self._fired)

    # -- probes (hot path; call only behind `chaos.ARMED`) ------------------
    def should(self, point: str) -> bool:
        inj = self._inj.get(point)
        if inj is None:
            return False
        with self._lock:
            if inj.n and self._fired.get(point, 0) >= inj.n:
                return False
            if inj.p < 1.0 and self._rng.random() >= inj.p:
                return False
            self._fired[point] = self._fired.get(point, 0) + 1
        return True

    def delay_s(self, point: str) -> float:
        inj = self._inj.get(point)
        if inj is None or inj.ms <= 0:
            return 0.0
        return inj.ms / 1000.0 if self.should(point) else 0.0

    def maybe_sleep(self, point: str) -> None:
        d = self.delay_s(point)
        if d:
            time.sleep(d)

    def mangle(self, point: str, data: bytes) -> bytes:
        """Flip one byte of ``data`` when the injector fires."""
        if not data or not self.should(point):
            return data
        buf = bytearray(data)
        with self._lock:
            i = self._rng.randrange(len(buf))
        buf[i] ^= 0xFF
        return bytes(buf)

    def skew_ms(self) -> float:
        """Constant clock offset while a ``clock_skew`` injector is armed
        (not probabilistic — a skewed clock stays skewed)."""
        inj = self._inj.get("clock_skew")
        return inj.ms if inj is not None else 0.0


_REG = ChaosRegistry()


def registry() -> ChaosRegistry:
    return _REG


# module-level aliases so call sites read `chaos.should(...)`
def arm(spec, seed: Optional[int] = None) -> None:
    _REG.arm(spec, seed=seed)


def disarm() -> None:
    _REG.disarm()


def should(point: str) -> bool:
    return _REG.should(point)


def delay_s(point: str) -> float:
    return _REG.delay_s(point)


def maybe_sleep(point: str) -> None:
    _REG.maybe_sleep(point)


def mangle(point: str, data: bytes) -> bytes:
    return _REG.mangle(point, data)


def skew_ms() -> float:
    return _REG.skew_ms()


def fired() -> Dict[str, int]:
    return _REG.fired()


_REG.arm_from_env()
