"""Multi-chip scale-out: the flow axis sharded over a device mesh.

Analog of the reference's only scale dimensions (SURVEY.md §2g): resource
parallelism (independent counters per flowId) becomes tensor sharding along
the flow axis; namespace parallelism stays a partition of that axis; and the
"distributed communication backend" is XLA collectives over ICI instead of
Netty TCP — three tiny ``[batch]``-sized ``psum``\\ s per step (ownership,
namespace ids, verdicts), while the ``[flows, buckets, events]`` counter
tensors never leave their shard.
"""

from sentinel_tpu.parallel.sharding import (
    make_flow_mesh,
    make_sharded_decide,
    shard_state,
    shard_rules,
)

__all__ = [
    "make_flow_mesh",
    "make_sharded_decide",
    "shard_state",
    "shard_rules",
]
