"""shard_map-based multi-chip decision step.

The mesh has one axis, ``"flows"``: ``state.flow`` / ``state.occupy`` and the
per-flow rule arrays are sharded along it; the namespace window, namespace
config arrays, request batch and clock are replicated. ``_decide_core`` runs
per shard with ``axis_name="flows"`` and stitches global verdicts with psums
(see its docstring).

Requests need no routing: every device sees the whole batch and answers only
for flows it owns — the right trade for this workload, where a batch row is
16 bytes but a flow's window history is O(buckets × events) and must not
move. (The scaling-book recipe: pick the mesh, annotate shardings, let the
collectives ride ICI.)
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sentinel_tpu.engine.config import EngineConfig
from sentinel_tpu.engine.decide import RequestBatch, VerdictBatch, _core_for
from sentinel_tpu.engine.rules import RuleTable
from sentinel_tpu.engine.state import BreakerState, EngineState, ShapingState
from sentinel_tpu.stats.window import WindowState

try:  # jax >= 0.4.35 exposes shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore


def shard_map(f, mesh, in_specs, out_specs):
    """``shard_map`` with replication checking off, across jax versions.

    The verdict outputs are replicated *by value* (every shard psums the same
    global answer) but the checker cannot statically infer that through the
    cond-gated namespace guard, so it must be disabled. The kwarg that does
    that was renamed (``check_rep`` → ``check_vma``) across jax releases;
    probe for whichever this jax accepts.
    """
    for kw in ("check_vma", "check_rep"):
        try:
            return _shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **{kw: False}
            )
        except TypeError:
            continue
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def make_flow_mesh(devices=None, axis: str = "flows") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def _state_specs(axis: str) -> EngineState:
    return EngineState(
        flow=WindowState(starts=P(), counts=P(axis)),
        occupy=WindowState(starts=P(), counts=P(axis)),
        ns=WindowState(starts=P(), counts=P()),
        shaping=ShapingState(
            lpt=P(axis), warm_tokens=P(axis), warm_filled=P(axis)
        ),
        outcome=WindowState(starts=P(), counts=P(axis)),
        breaker=BreakerState(
            state=P(axis), opened_ms=P(axis), probe_ms=P(axis)
        ),
    )


def _rules_specs(axis: str, br: bool = True) -> RuleTable:
    # ``br=False`` mirrors a table built with no degrade rules, whose six
    # br_* columns are None (and so absent from the pytree structure)
    brp = P(axis) if br else None
    return RuleTable(
        valid=P(axis),
        count=P(axis),
        mode=P(axis),
        namespace_id=P(axis),
        ns_max_qps=P(),
        ns_connected=P(),
        behavior=P(axis),
        warning_token=P(axis),
        max_token=P(axis),
        slope=P(axis),
        cold_count=P(axis),
        max_queue_ms=P(axis),
        br_strategy=brp,
        br_threshold=brp,
        br_slow_rt_ms=brp,
        br_min_request=brp,
        br_stat_ms=brp,
        br_recovery_ms=brp,
    )


def _batch_specs() -> RequestBatch:
    return RequestBatch(flow_slot=P(), acquire=P(), prioritized=P(), valid=P())


def shard_state(state: EngineState, mesh: Mesh, axis: str = "flows") -> EngineState:
    """Place an EngineState on the mesh with flow-axis sharding."""
    specs = _state_specs(axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs
    )


def shard_rules(rules: RuleTable, mesh: Mesh, axis: str = "flows") -> RuleTable:
    specs = _rules_specs(axis, br=rules.br_strategy is not None)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), rules, specs
    )


def host_rows(arr, rows: np.ndarray) -> np.ndarray:
    """Gather ``arr[rows]`` (global row indices, axis 0) to host numpy,
    shard-aware.

    For an array sharded along axis 0 this walks the addressable shards and
    copies each shard's slab ONCE per shard that owns a requested row, then
    numpy-gathers locally — no device gather kernel, so the replication tick
    never pays a per-row-count XLA compile (the dirty set's size varies every
    delta). Replicated/unsharded arrays (and plain numpy) take one host copy.
    Requires every shard to be addressable (single-process mesh or a fully
    replicated axis) — the only topologies the host-side exporter runs in.
    """
    rows = np.asarray(rows, np.int64)
    if rows.size == 0:
        return np.empty((0,) + tuple(arr.shape[1:]), np.asarray(arr[:0]).dtype)
    if not isinstance(arr, jax.Array) or arr.is_fully_replicated:
        return np.asarray(arr)[rows]
    shards = arr.addressable_shards
    out = None
    seen = np.zeros(rows.shape[0], bool)
    for shard in shards:
        idx = shard.index[0]
        start = idx.start or 0
        stop = idx.stop if idx.stop is not None else arr.shape[0]
        mask = (rows >= start) & (rows < stop) & ~seen
        if not mask.any():
            continue
        data = np.asarray(shard.data)
        if out is None:
            out = np.empty((rows.shape[0],) + data.shape[1:], data.dtype)
        out[mask] = data[rows[mask] - start]
        seen |= mask
    if not seen.all():
        raise ValueError(
            "host_rows: rows not covered by addressable shards "
            f"(multi-process mesh?): {rows[~seen].tolist()}"
        )
    return out


def make_sharded_decide(
    config: EngineConfig,
    mesh: Mesh,
    axis: str = "flows",
    grouped: bool = False,
    uniform: bool = False,
    donate: bool = False,
    depth: Optional[int] = None,
):
    """Build the jitted multi-chip step.

    ``config.max_flows`` must divide evenly by the mesh size; each shard owns
    ``max_flows // n_devices`` consecutive slots (the host RuleIndex hands
    out global slots, which the kernel maps to shard-local via its
    ``axis_index``).

    ``donate=True`` donates the state buffers exactly like the single-shard
    ``decide_donating`` path: XLA updates the sharded window tensors in
    place instead of copying the full per-shard state every dispatch.

    ``depth=F`` builds the fused variant: one ``lax.scan`` of the sharded
    step over ``[F, batch_size]`` stacked request frames, inside a single
    ``shard_map`` entry. Each scan iteration psum-stitches that frame's
    verdicts over ICI before the next frame decides, so per-frame verdicts
    are bit-identical to F sequential sharded dispatches — but the host
    pays one dispatch, one shard_map entry, and (with ``donate``) zero
    state copies for the whole group.
    """
    n = mesh.devices.size
    if config.max_flows % n != 0:
        raise ValueError(
            f"max_flows={config.max_flows} must be divisible by mesh size {n}"
        )

    # decide_impl-aware: the Pallas megakernel runs per shard inside the
    # shard_map body (its psums ride the [N]-sized verdict stitching exactly
    # like the XLA pipeline's — the kernel itself never sees a collective)
    core = _core_for(config, grouped)

    if depth is None:
        def step(state, rules, batch, now):
            return core(
                config, state, rules, batch, now, axis_name=axis,
                grouped=grouped, uniform=uniform,
            )
    else:
        if depth < 2:
            raise ValueError(f"fused depth must be >= 2, got {depth}")

        def step(state, rules, batches, now):
            def body(st, batch):
                st, verdicts = core(
                    config, st, rules, batch, now, axis_name=axis,
                    grouped=grouped, uniform=uniform,
                )
                return st, verdicts

            return jax.lax.scan(body, state, batches, length=depth)

    # two spec shapes, matching the two RuleTable pytree structures: with
    # br_* columns (degrade rules loaded) and without (None columns, so the
    # compile skips the breaker arm). Built lazily on first use of each.
    def _build(br: bool):
        mapped = shard_map(
            step,
            mesh=mesh,
            in_specs=(
                _state_specs(axis),
                _rules_specs(axis, br=br),
                _batch_specs(),
                P(),
            ),
            out_specs=(
                _state_specs(axis),
                VerdictBatch(status=P(), wait_ms=P(), remaining=P()),
            ),
        )
        return jax.jit(mapped, donate_argnums=(0,) if donate else ())

    impls = {}

    def sharded_step(state, rules, batch, now):
        br = rules.br_strategy is not None
        if br not in impls:
            impls[br] = _build(br)
        return impls[br](state, rules, batch, now)

    return sharded_step
