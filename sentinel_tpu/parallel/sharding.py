"""shard_map-based multi-chip decision step.

The mesh has one axis, ``"flows"``: ``state.flow`` / ``state.occupy`` and the
per-flow rule arrays are sharded along it; the namespace window, namespace
config arrays, request batch and clock are replicated. ``_decide_core`` runs
per shard with ``axis_name="flows"`` and stitches global verdicts with psums
(see its docstring).

Requests need no routing: every device sees the whole batch and answers only
for flows it owns — the right trade for this workload, where a batch row is
16 bytes but a flow's window history is O(buckets × events) and must not
move. (The scaling-book recipe: pick the mesh, annotate shardings, let the
collectives ride ICI.)
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sentinel_tpu.engine.config import EngineConfig
from sentinel_tpu.engine.decide import RequestBatch, VerdictBatch, _decide_core
from sentinel_tpu.engine.rules import RuleTable
from sentinel_tpu.engine.state import EngineState
from sentinel_tpu.stats.window import WindowState

try:  # jax >= 0.4.35 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def make_flow_mesh(devices=None, axis: str = "flows") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def _state_specs(axis: str) -> EngineState:
    return EngineState(
        flow=WindowState(starts=P(), counts=P(axis)),
        occupy=WindowState(starts=P(), counts=P(axis)),
        ns=WindowState(starts=P(), counts=P()),
    )


def _rules_specs(axis: str) -> RuleTable:
    return RuleTable(
        valid=P(axis),
        count=P(axis),
        mode=P(axis),
        namespace_id=P(axis),
        ns_max_qps=P(),
        ns_connected=P(),
    )


def _batch_specs() -> RequestBatch:
    return RequestBatch(flow_slot=P(), acquire=P(), prioritized=P(), valid=P())


def shard_state(state: EngineState, mesh: Mesh, axis: str = "flows") -> EngineState:
    """Place an EngineState on the mesh with flow-axis sharding."""
    specs = _state_specs(axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs
    )


def shard_rules(rules: RuleTable, mesh: Mesh, axis: str = "flows") -> RuleTable:
    specs = _rules_specs(axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), rules, specs
    )


def make_sharded_decide(
    config: EngineConfig,
    mesh: Mesh,
    axis: str = "flows",
    grouped: bool = False,
    uniform: bool = False,
):
    """Build the jitted multi-chip step.

    ``config.max_flows`` must divide evenly by the mesh size; each shard owns
    ``max_flows // n_devices`` consecutive slots (the host RuleIndex hands
    out global slots, which the kernel maps to shard-local via its
    ``axis_index``).
    """
    n = mesh.devices.size
    if config.max_flows % n != 0:
        raise ValueError(
            f"max_flows={config.max_flows} must be divisible by mesh size {n}"
        )

    def step(state, rules, batch, now):
        return _decide_core(
            config, state, rules, batch, now, axis_name=axis,
            grouped=grouped, uniform=uniform,
        )

    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(_state_specs(axis), _rules_specs(axis), _batch_specs(), P()),
        out_specs=(
            _state_specs(axis),
            VerdictBatch(status=P(), wait_ms=P(), remaining=P()),
        ),
        check_vma=False,
    )
    return jax.jit(mapped)
