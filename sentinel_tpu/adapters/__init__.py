"""Adapters: the API surface into user frameworks (``sentinel-adapter`` analog).

Every adapter follows the one idiom of the reference's 19 modules
(SURVEY.md §1 L7): parse resource name + origin from the framework request →
enter context → ``entry`` → proceed → trace on error → ``exit``.

- ``decorator``: ``@sentinel_resource`` function guard with
  block-handler/fallback dispatch (``sentinel-annotation-aspectj`` analog).
- ``wsgi``: WSGI middleware (``sentinel-web-servlet`` ``CommonFilter`` /
  ``CommonTotalFilter`` analog).
- ``asgi``: ASGI middleware (``spring-webmvc``/``webflux`` interceptor
  analog; async-safe because the context is a ``contextvars.ContextVar``).
- ``grpc_interceptor``: server + client interceptors
  (``sentinel-grpc-adapter`` analog; gated on ``grpcio``).
- ``http_client``: outbound-call guards for ``requests`` and ``httpx``
  (``sentinel-okhttp/apache-httpclient-adapter`` analog; gated).
- ``gateway``: param-based gateway flow rules + request parser
  (``sentinel-api-gateway-adapter-common`` analog).
- ``aiohttp_middleware``: aiohttp server middleware (gated on ``aiohttp``).
- ``tornado_handler``: Tornado ``RequestHandler`` mixin (gated on
  ``tornado``).
"""

from sentinel_tpu.adapters.decorator import (
    sentinel_intercept,
    sentinel_resource,
)
from sentinel_tpu.adapters.wsgi import SentinelWsgiMiddleware
from sentinel_tpu.adapters.asgi import SentinelAsgiMiddleware
from sentinel_tpu.adapters.gateway import (
    GatewayFlowRule,
    GatewayGuard,
    GatewayParamFlowItem,
    GatewayRuleManager,
    MatchStrategy,
    ParseStrategy,
    RequestAdapter,
    ResourceMode,
    SentinelGatewayAsgiMiddleware,
    SentinelGatewayWsgiMiddleware,
)
from sentinel_tpu.adapters.gateway_api import (
    ApiDefinition,
    ApiPathPredicateItem,
    ApiPredicateGroupItem,
    GatewayApiDefinitionManager,
    GatewayApiMatcherManager,
    UrlMatchStrategy,
)

__all__ = [
    "sentinel_intercept",
    "sentinel_resource",
    "SentinelWsgiMiddleware",
    "SentinelAsgiMiddleware",
    "GatewayFlowRule",
    "GatewayGuard",
    "GatewayParamFlowItem",
    "GatewayRuleManager",
    "MatchStrategy",
    "ParseStrategy",
    "RequestAdapter",
    "ResourceMode",
    "SentinelGatewayAsgiMiddleware",
    "SentinelGatewayWsgiMiddleware",
    "ApiDefinition",
    "ApiPathPredicateItem",
    "ApiPredicateGroupItem",
    "GatewayApiDefinitionManager",
    "GatewayApiMatcherManager",
    "UrlMatchStrategy",
]
