"""gRPC server + client interceptors (``sentinel-grpc-adapter`` analog).

Reference: ``SentinelGrpcServerInterceptor.java`` /
``SentinelGrpcClientInterceptor.java`` — resource is the full method name;
server blocks map to RESOURCE_EXHAUSTED; client guards the outbound call as
an OUT-type resource. Gated on ``grpcio``.
"""

from __future__ import annotations

from typing import Callable, Optional

try:
    import grpc
except ImportError:  # pragma: no cover - grpcio baked into this image
    grpc = None

from sentinel_tpu.local import BlockException, EntryType
from sentinel_tpu.local import context as _ctx
from sentinel_tpu.local.sph import async_entry as _async_entry
from sentinel_tpu.local.sph import entry as _entry

BLOCK_MSG = "Blocked by Sentinel (flow limiting)"


def _require_grpc():
    if grpc is None:
        raise ImportError(
            "grpcio is not installed; the gRPC adapter is unavailable"
        )


if grpc is not None:

    class SentinelServerInterceptor(grpc.ServerInterceptor):
        """Guard every unary/streaming handler by its full method name."""

        def __init__(self, origin_metadata_key: str = "sentinel-origin"):
            self._origin_key = origin_metadata_key

        def intercept_service(self, continuation, handler_call_details):
            handler = continuation(handler_call_details)
            if handler is None:
                return None
            resource = handler_call_details.method
            origin = ""
            for key, value in handler_call_details.invocation_metadata or ():
                if key == self._origin_key:
                    origin = value
                    break

            def guard(behavior, request_streaming, response_streaming):
                def guarded(request_or_iterator, servicer_context):
                    _ctx.enter(name=f"grpc_context:{resource}", origin=origin)
                    try:
                        try:
                            entry = _entry(resource, EntryType.IN)
                        except BlockException:
                            servicer_context.abort(
                                grpc.StatusCode.RESOURCE_EXHAUSTED, BLOCK_MSG
                            )
                            return  # pragma: no cover - abort raises
                        try:
                            return behavior(request_or_iterator, servicer_context)
                        except BaseException as err:
                            entry.trace(err)
                            raise
                        finally:
                            entry.exit()
                    finally:
                        _ctx.exit()

                return guarded

            return _wrap_handler(handler, guard)

    def _wrap_handler(handler, guard):
        if handler.unary_unary:
            return grpc.unary_unary_rpc_method_handler(
                guard(handler.unary_unary, False, False),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        if handler.unary_stream:
            return grpc.unary_stream_rpc_method_handler(
                guard(handler.unary_stream, False, True),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        if handler.stream_unary:
            return grpc.stream_unary_rpc_method_handler(
                guard(handler.stream_unary, True, False),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        return grpc.stream_stream_rpc_method_handler(
            guard(handler.stream_stream, True, True),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )

    class SentinelClientInterceptor(
        grpc.UnaryUnaryClientInterceptor, grpc.UnaryStreamClientInterceptor
    ):
        """Guard outbound calls; a block raises ``BlockException`` to the
        caller before any network I/O (the reference fails the call with
        UNAVAILABLE — raising keeps the local API uniform). The guard is a
        detached ``async_entry``: the done-callback may fire on a channel
        thread, out of order with other in-flight RPCs from the same caller,
        without corrupting the caller's entry stack — RT/error stats still
        cover the real call duration."""

        def _intercept(self, continuation, client_call_details, request):
            e = _async_entry(client_call_details.method, EntryType.OUT)
            try:
                call = continuation(client_call_details, request)
            except BaseException as err:
                e.trace(err)
                e.exit()
                raise

            def on_done(completed):
                try:
                    exc = completed.exception()
                except BaseException:
                    exc = None  # cancelled
                if exc is not None:
                    e.trace(exc)
                e.exit()

            call.add_done_callback(on_done)
            return call

        def intercept_unary_unary(self, continuation, client_call_details, request):
            return self._intercept(continuation, client_call_details, request)

        def intercept_unary_stream(self, continuation, client_call_details, request):
            return self._intercept(continuation, client_call_details, request)

else:  # pragma: no cover

    class SentinelServerInterceptor:  # type: ignore[no-redef]
        def __init__(self, *a, **k):
            _require_grpc()

    class SentinelClientInterceptor:  # type: ignore[no-redef]
        def __init__(self, *a, **k):
            _require_grpc()
