"""Cross-service origin propagation for the HTTP adapters.

The reference's RPC adapters carry the caller's identity through framework
attachments so authority rules work across service hops — e.g. the dubbo
provider filter reads the application name the consumer filter attached
(``SentinelDubboProviderFilter.java``), and the servlet filter falls back to
an ``S-user`` header (``CommonFilter``). HTTP has no attachment channel, so
this module standardizes one header both directions agree on:

- **Outbound** (``adapters/http_client.py`` requests/httpx wrappers): inject
  ``X-Sentinel-Origin: <this agent's app name>``.
- **Inbound** (asgi / wsgi / aiohttp / tornado default origin parsers):
  prefer ``X-Sentinel-Origin``, then the legacy ``S-User`` user header, then
  the peer IP.

The gRPC interceptors carry the same value in metadata (their natural
attachment channel); this header is the plain-HTTP equivalent.

Security note (same stance as the reference's header fallback): the header
is caller-asserted. Authority rules gate *cooperating* services by name —
for untrusted edges, keep the peer-IP fallback or a gateway-verified header.
"""

from __future__ import annotations

from typing import Dict, Optional

ORIGIN_HEADER = "X-Sentinel-Origin"
# legacy user-identity header the servlet CommonFilter reads
USER_HEADER = "S-User"

_WSGI_ORIGIN_KEY = "HTTP_X_SENTINEL_ORIGIN"
_WSGI_USER_KEY = "HTTP_S_USER"


def origin_value() -> str:
    """What this agent advertises as its origin: the configured app name
    (the dubbo consumer attaches ``ApplicationName`` the same way)."""
    from sentinel_tpu.core.config import SentinelConfig

    return SentinelConfig.app_name()


def origin_headers() -> Dict[str, str]:
    """Headers an outbound HTTP call should carry."""
    value = origin_value()
    return {ORIGIN_HEADER: value} if value else {}


def inject(headers: Optional[dict]) -> dict:
    """Merge the origin header into a (possibly None) header mapping without
    overriding an explicit caller value."""
    merged = dict(headers or {})
    if not any(k.lower() == ORIGIN_HEADER.lower() for k in merged):
        merged.update(origin_headers())
    return merged


def from_wsgi(environ) -> str:
    return (
        environ.get(_WSGI_ORIGIN_KEY, "")
        or environ.get(_WSGI_USER_KEY, "")
        or environ.get("REMOTE_ADDR", "")
    )


def from_asgi_scope(scope) -> str:
    want_origin = ORIGIN_HEADER.lower().encode()
    want_user = USER_HEADER.lower().encode()
    origin = user = ""
    for name, value in scope.get("headers") or ():
        lowered = name.lower()
        if lowered == want_origin and value:
            origin = value.decode("latin-1")
        elif lowered == want_user and value:
            user = value.decode("latin-1")
    if origin or user:
        return origin or user
    client = scope.get("client")
    return client[0] if client else ""


def from_headers(headers, fallback: str = "") -> str:
    """Case-insensitive mapping (aiohttp/tornado header objects)."""
    return (
        headers.get(ORIGIN_HEADER, "")
        or headers.get(USER_HEADER, "")
        or fallback
    )
