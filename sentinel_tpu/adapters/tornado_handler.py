"""Tornado adapter: a ``RequestHandler`` mixin guarding every HTTP verb.

Reference adapter idiom (resource + origin → context → entry → proceed →
trace → exit) mapped onto Tornado's prepare/on_finish lifecycle — the same
interceptor shape as ``AbstractSentinelInterceptor.java:55,88,137``.

Usage::

    class Hello(SentinelRequestHandlerMixin, web.RequestHandler):
        def get(self):
            self.write("hi")

Blocked requests get ``block_status`` (429) and the verb never runs.
"""

from __future__ import annotations

from typing import Optional

from sentinel_tpu.local import BlockException, EntryType
from sentinel_tpu.local import context as _ctx
from sentinel_tpu.local.sph import entry as _entry

DEFAULT_BLOCK_BODY = '{"error": "Blocked by Sentinel (flow limiting)"}'


class SentinelRequestHandlerMixin:
    sentinel_block_status = 429
    sentinel_block_body = DEFAULT_BLOCK_BODY

    def sentinel_resource(self) -> str:
        """Override to rename/skip (return "" to leave unguarded)."""
        return f"{self.request.method}:{self.request.path}"

    def sentinel_origin(self) -> str:
        """``X-Sentinel-Origin`` → ``S-User`` → peer IP (adapters/origin.py)."""
        from sentinel_tpu.adapters.origin import from_headers

        return from_headers(
            self.request.headers, self.request.remote_ip or ""
        )

    def prepare(self):
        super().prepare()
        resource = self.sentinel_resource()
        self._sentinel_entry = None
        self._sentinel_ctx = False
        if not resource:
            return
        _ctx.enter(
            name=f"tornado_context:{resource}", origin=self.sentinel_origin()
        )
        self._sentinel_ctx = True
        try:
            self._sentinel_entry = _entry(resource, EntryType.IN).__enter__()
        except BlockException:
            self._sentinel_exit_context()
            self.set_status(self.sentinel_block_status)
            self.finish(self.sentinel_block_body)

    def _sentinel_exit_context(self):
        if getattr(self, "_sentinel_ctx", False):
            _ctx.exit()
            self._sentinel_ctx = False

    def _sentinel_close(self, error: Optional[BaseException] = None):
        # getattr: Tornado can finish a request without ever calling
        # prepare() (e.g. HTTPError(405) for an unsupported method raised
        # inside _execute before the prepare hook)
        e = getattr(self, "_sentinel_entry", None)
        self._sentinel_entry = None
        if e is not None:
            if error is not None:
                e.trace(error)
            e.exit()
        self._sentinel_exit_context()

    def on_finish(self):
        self._sentinel_close()
        super().on_finish()

    def log_exception(self, typ, value, tb):
        from tornado.web import HTTPError

        # HTTPError is framework control flow (404s, 405s), not a business
        # failure — tracing it would inflate exception ratios and could trip
        # exception-ratio circuit breakers (the aiohttp middleware excludes
        # web.HTTPException for the same reason)
        if (
            value is not None
            and not isinstance(value, (BlockException, HTTPError))
        ):
            e = getattr(self, "_sentinel_entry", None)
            if e is not None:
                e.trace(value)
        super().log_exception(typ, value, tb)
