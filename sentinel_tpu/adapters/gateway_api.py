"""Custom API groups for gateway flow control.

Analog of ``sentinel-api-gateway-adapter-common``'s API layer:

- ``ApiDefinition`` (``api/ApiDefinition.java``): a named group of path
  predicates — a "custom API" a gateway rule can target by name
  (``ResourceMode.CUSTOM_API_NAME``).
- ``ApiPathPredicateItem`` (``api/ApiPathPredicateItem.java``): one path
  pattern with a match strategy (``SentinelGatewayConstants.URL_MATCH_STRATEGY_
  {EXACT,PREFIX,REGEX}``).
- ``ApiPredicateGroupItem`` (``api/ApiPredicateGroupItem.java``): OR-group of
  sub-predicates.
- ``GatewayApiDefinitionManager`` (``api/GatewayApiDefinitionManager.java``):
  definition registry driven by a ``DynamicProperty`` (register a datasource
  property exactly like rule managers), fanning updates out to change
  observers (``ApiDefinitionChangeObserver`` analog).
- ``GatewayApiMatcherManager`` (``sentinel-spring-cloud-gateway-adapter/.../
  GatewayApiMatcherManager.java``): definition → compiled matcher;
  ``pick_matching_api_names(path)`` is what adapters call per request to map
  a path onto its custom API resources before entering the gateway slot.
"""

from __future__ import annotations

import enum
import re
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from sentinel_tpu.core.log import record_log
from sentinel_tpu.core.property import DynamicProperty


class UrlMatchStrategy(enum.IntEnum):
    """``SentinelGatewayConstants.URL_MATCH_STRATEGY_*``."""

    EXACT = 0
    PREFIX = 1
    REGEX = 2


@dataclass(frozen=True)
class ApiPathPredicateItem:
    """One path predicate (``ApiPathPredicateItem.java``)."""

    pattern: str
    match_strategy: UrlMatchStrategy = UrlMatchStrategy.EXACT

    def matches(self, path: str) -> bool:
        if self.match_strategy == UrlMatchStrategy.EXACT:
            return path == self.pattern
        if self.match_strategy == UrlMatchStrategy.PREFIX:
            return path.startswith(self.pattern)
        try:
            # full-path match like the reference (Zuul's Pattern.matches /
            # SCG's route predicate): an unanchored fragment must not pull
            # every path merely containing it under the API
            return re.fullmatch(self.pattern, path) is not None
        except re.error:
            return False


@dataclass(frozen=True)
class ApiPredicateGroupItem:
    """OR-group of predicates (``ApiPredicateGroupItem.java``)."""

    items: Tuple[ApiPathPredicateItem, ...] = ()

    def matches(self, path: str) -> bool:
        return any(item.matches(path) for item in self.items)


@dataclass(frozen=True)
class ApiDefinition:
    """A named custom API = OR of its predicates (``ApiDefinition.java``)."""

    api_name: str
    predicate_items: Tuple[object, ...] = ()  # path items and/or groups

    def matches(self, path: str) -> bool:
        return any(item.matches(path) for item in self.predicate_items)


def _is_valid(definition: ApiDefinition) -> bool:
    """``GatewayApiDefinitionManager.isValidApi``: a name and ≥1 predicate."""
    return bool(definition.api_name) and bool(definition.predicate_items)


class GatewayApiDefinitionManager:
    """Definition registry + change fan-out (class-level, like the rule
    managers — the reference's statics)."""

    _lock = threading.RLock()
    # serializes whole load→notify sequences: without it two concurrent
    # loads could deliver observer snapshots out of order, leaving matchers
    # permanently stale relative to the registry
    _load_lock = threading.Lock()
    _definitions: Dict[str, ApiDefinition] = {}
    _observers: List[Callable[[List[ApiDefinition]], None]] = []
    _property: Optional[DynamicProperty] = None
    _listener = None

    @classmethod
    def load_api_definitions(cls, definitions: Iterable[ApiDefinition]) -> None:
        with cls._load_lock:
            with cls._lock:
                valid = {}
                for d in definitions or ():
                    if _is_valid(d):
                        valid[d.api_name] = d
                    else:
                        record_log.warning(
                            "ignoring invalid api definition: %r", d
                        )
                cls._definitions = valid
                observers = list(cls._observers)
                snapshot = list(valid.values())
            for observer in observers:
                try:
                    observer(snapshot)
                except Exception:
                    record_log.exception("api definition observer failed")

    @classmethod
    def get_api_definition(cls, api_name: str) -> Optional[ApiDefinition]:
        with cls._lock:
            return cls._definitions.get(api_name)

    @classmethod
    def get_api_definitions(cls) -> List[ApiDefinition]:
        with cls._lock:
            return list(cls._definitions.values())

    @classmethod
    def add_observer(cls, observer: Callable[[List[ApiDefinition]], None]) -> None:
        """``ApiDefinitionChangeObserver`` analog; called with the full
        definition list on every load. Serialized with loads under
        ``_load_lock`` so the registration snapshot can't race a concurrent
        load and overwrite its (newer) delivery."""
        with cls._load_lock:
            with cls._lock:
                cls._observers.append(observer)
                snapshot = list(cls._definitions.values())
            observer(snapshot)

    @classmethod
    def register_property(cls, prop: DynamicProperty) -> None:
        """Drive definitions from a datasource-backed property
        (``register2Property``): the property's value is a list of
        ``ApiDefinition`` (or dicts in the same shape, as a datasource
        converter would produce)."""
        with cls._lock:
            if cls._property is not None and cls._listener is not None:
                cls._property.remove_listener(cls._listener)
            cls._property = prop
        # listen() takes the property's lock and fires the first load
        # synchronously — must happen OUTSIDE cls._lock or a concurrent
        # update_value (property lock → load → cls._lock) deadlocks against
        # us (cls._lock → property lock). Same discipline as the other rule
        # managers' register_property.
        listener = prop.listen(
            lambda value: cls.load_api_definitions(
                [parse_api_definition(v) for v in (value or [])]
            )
        )
        with cls._lock:
            cls._listener = listener

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            if cls._property is not None and cls._listener is not None:
                cls._property.remove_listener(cls._listener)
            cls._definitions = {}
            cls._observers = []
            cls._property = None
            cls._listener = None


def parse_api_definition(obj) -> ApiDefinition:
    """Dict → ApiDefinition (datasource/command payload shape, matching the
    reference's JSON: apiName + predicateItems[{pattern, matchStrategy} |
    {items: [...]}])."""
    if isinstance(obj, ApiDefinition):
        return obj
    items = []
    for it in obj.get("predicateItems", obj.get("predicate_items", [])) or []:
        if "items" in it:
            items.append(
                ApiPredicateGroupItem(
                    tuple(
                        ApiPathPredicateItem(
                            sub["pattern"],
                            UrlMatchStrategy(
                                sub.get("matchStrategy",
                                        sub.get("match_strategy", 0))
                            ),
                        )
                        for sub in it["items"]
                    )
                )
            )
        else:
            items.append(
                ApiPathPredicateItem(
                    it["pattern"],
                    UrlMatchStrategy(
                        it.get("matchStrategy", it.get("match_strategy", 0))
                    ),
                )
            )
    return ApiDefinition(
        obj.get("apiName", obj.get("api_name", "")), tuple(items)
    )


def api_definition_to_dict(definition: ApiDefinition) -> dict:
    """ApiDefinition → JSON-shape dict (command/dashboard payloads)."""

    def item_to_dict(item):
        if isinstance(item, ApiPredicateGroupItem):
            return {"items": [item_to_dict(s) for s in item.items]}
        return {
            "pattern": item.pattern,
            "matchStrategy": int(item.match_strategy),
        }

    return {
        "apiName": definition.api_name,
        "predicateItems": [item_to_dict(i) for i in definition.predicate_items],
    }


class GatewayApiMatcherManager:
    """apiName → matcher, rebuilt on definition change
    (``GatewayApiMatcherManager.java`` — registered as a change observer).

    The "matcher" here is the definition itself (predicates are already
    compiled Python); what this manager adds is the per-request pick."""

    _lock = threading.RLock()
    _matchers: Dict[str, ApiDefinition] = {}
    _registered = False

    @classmethod
    def _ensure_registered(cls) -> None:
        with cls._lock:
            if not cls._registered:
                cls._registered = True
                GatewayApiDefinitionManager.add_observer(cls._on_change)

    @classmethod
    def _on_change(cls, definitions: List[ApiDefinition]) -> None:
        with cls._lock:
            cls._matchers = {d.api_name: d for d in definitions}

    @classmethod
    def pick_matching_api_names(cls, path: str) -> List[str]:
        """Every custom API whose predicates match the request path — the
        resources a gateway adapter enters IN ADDITION to the route
        (``pickMatchingApiDefinitions`` in the reference adapters)."""
        cls._ensure_registered()
        with cls._lock:
            matchers = list(cls._matchers.values())
        return [d.api_name for d in matchers if d.matches(path)]

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._matchers = {}
            cls._registered = False
