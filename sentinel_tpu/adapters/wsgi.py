"""WSGI middleware — the servlet ``CommonFilter``/``CommonTotalFilter`` analog.

Reference idiom (``sentinel-web-servlet/.../CommonFilter.java:50,79``):
resource = HTTP target (optionally prefixed by method), origin parsed from
the request, block → configurable response (the reference redirects or
writes a default block page; here a 429).
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from sentinel_tpu.local import BlockException, EntryType
from sentinel_tpu.local import context as _ctx
from sentinel_tpu.local.sph import entry as _entry

DEFAULT_BLOCK_BODY = b"Blocked by Sentinel (flow limiting)"
TOTAL_RESOURCE = "wsgi_total_inbound_traffic"  # CommonTotalFilter's TOTAL_URL


def default_resource(environ) -> str:
    return f"{environ.get('REQUEST_METHOD', 'GET')}:{environ.get('PATH_INFO', '/')}"


def default_origin(environ) -> str:
    """``X-Sentinel-Origin`` → ``S-User`` → peer IP (adapters/origin.py)."""
    from sentinel_tpu.adapters.origin import from_wsgi

    return from_wsgi(environ)


class SentinelWsgiMiddleware:
    """Wrap a WSGI app so every request is a guarded resource.

    ``resource_extractor(environ)`` names the resource (default
    ``METHOD:path``); return "" to skip guarding a request (the reference's
    URL-cleaner excluding e.g. static assets). ``origin_parser(environ)``
    feeds authority rules and per-origin statistics. ``with_total`` adds the
    CommonTotalFilter-style umbrella entry around every request.
    """

    def __init__(
        self,
        app: Callable,
        resource_extractor: Callable = default_resource,
        origin_parser: Callable = default_origin,
        block_handler: Optional[Callable] = None,
        with_total: bool = False,
    ):
        self.app = app
        self.resource_extractor = resource_extractor
        self.origin_parser = origin_parser
        self.block_handler = block_handler
        self.with_total = with_total

    def __call__(self, environ, start_response) -> Iterable[bytes]:
        resource = self.resource_extractor(environ)
        if not resource:
            return self.app(environ, start_response)
        origin = self.origin_parser(environ)
        _ctx.enter(name=f"wsgi_context:{resource}", origin=origin)
        total = None
        entry = None

        def finish():
            if entry is not None:
                entry.exit()
            if total is not None:
                total.exit()
            _ctx.exit()

        try:
            if self.with_total:
                total = _entry(TOTAL_RESOURCE, EntryType.IN)
            entry = _entry(resource, EntryType.IN)
        except BlockException as e:
            try:
                if self.block_handler is not None:
                    return self.block_handler(environ, start_response, e)
                start_response(
                    "429 Too Many Requests",
                    [("Content-Type", "text/plain"),
                     ("Content-Length", str(len(DEFAULT_BLOCK_BODY)))],
                )
                return [DEFAULT_BLOCK_BODY]
            finally:
                finish()
        try:
            body = self.app(environ, start_response)
        except BaseException as err:
            entry.trace(err)
            finish()
            raise
        # exit only after the body is consumed: streaming responses hold the
        # entry open for their full duration, so THREAD-grade rules see the
        # real concurrency, RT covers iteration, and iteration-time errors
        # are traced (PEP 3333 guarantees close() is called)
        return _GuardedBody(body, entry, finish)


class _GuardedBody:
    """Response-body wrapper that completes the entry on close/exhaustion."""

    def __init__(self, body: Iterable[bytes], entry, finish: Callable):
        self._body = body
        self._entry = entry
        self._finish = finish
        self._done = False

    def __iter__(self):
        try:
            for chunk in self._body:
                yield chunk
        except BaseException as err:
            self._entry.trace(err)
            raise
        finally:
            self.close()

    def close(self):
        if self._done:
            return
        self._done = True
        try:
            close = getattr(self._body, "close", None)
            if close is not None:
                close()
        finally:
            self._finish()
