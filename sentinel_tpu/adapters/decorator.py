"""``@sentinel_resource`` — function-level guard with handler dispatch.

Analog of the ``@SentinelResource`` annotation + aspect
(``sentinel-annotation-aspectj/.../SentinelResourceAspect.java:36-68``,
``AbstractSentinelAspectSupport.java:83-140``): the wrapped callable is the
resource; on block the ``block_handler`` runs; on a business exception the
error is traced and the ``fallback`` runs (unless the exception type is
ignored). The reference dispatches handlers by reflected method name — here
they are plain callables, and async callables get an async wrapper.
"""

from __future__ import annotations

import functools
import inspect
from typing import Callable, Optional, Tuple, Type

from sentinel_tpu.local import BlockException, EntryType
from sentinel_tpu.local.sph import entry as _entry


def sentinel_resource(
    resource: Optional[str] = None,
    entry_type: EntryType = EntryType.OUT,
    block_handler: Optional[Callable] = None,
    fallback: Optional[Callable] = None,
    exceptions_to_ignore: Tuple[Type[BaseException], ...] = (),
    args_as_params: bool = False,
):
    """Guard a function as a sentinel resource.

    - ``resource``: resource name; defaults to the function's qualified name
      (the aspect's ``getResourceName`` fallback).
    - ``block_handler(*args, ex=BlockException, **kwargs)``: runs on block.
    - ``fallback(*args, ex=Exception, **kwargs)``: runs on business error
      (after tracing), and on block when no ``block_handler`` is given —
      the reference's degrade-to-fallback order
      (``AbstractSentinelAspectSupport.handleBlockException``).
    - ``exceptions_to_ignore``: business exceptions re-raised untraced.
    - ``args_as_params``: pass the call's positional args to the slot chain
      so hot-param (``ParamFlowRule``) rules see them.
    """

    def decorate(fn: Callable) -> Callable:
        name = resource or f"{fn.__module__}.{fn.__qualname__}"

        def on_block(e, args, kwargs):
            if block_handler is not None:
                return block_handler(*args, ex=e, **kwargs)
            if fallback is not None:
                return fallback(*args, ex=e, **kwargs)
            raise e

        def on_error(e, args, kwargs):
            if isinstance(e, exceptions_to_ignore):
                raise e
            if fallback is not None:
                return fallback(*args, ex=e, **kwargs)
            raise e

        if inspect.iscoroutinefunction(fn):

            async def _maybe_await(value):
                # handlers may themselves be async — await their result
                return await value if inspect.isawaitable(value) else value

            @functools.wraps(fn)
            async def async_wrapper(*args, **kwargs):
                try:
                    e = _entry(
                        name, entry_type,
                        args=tuple(args) if args_as_params else (),
                    )
                except BlockException as be:
                    return await _maybe_await(on_block(be, args, kwargs))
                try:
                    return await fn(*args, **kwargs)
                except BaseException as err:
                    if not isinstance(err, exceptions_to_ignore):
                        e.trace(err)
                    return await _maybe_await(on_error(err, args, kwargs))
                finally:
                    e.exit()

            async_wrapper.__sentinel_resource__ = name
            return async_wrapper

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            try:
                e = _entry(
                    name, entry_type,
                    args=tuple(args) if args_as_params else (),
                )
            except BlockException as be:
                return on_block(be, args, kwargs)
            try:
                return fn(*args, **kwargs)
            except BaseException as err:
                if not isinstance(err, exceptions_to_ignore):
                    e.trace(err)
                return on_error(err, args, kwargs)
            finally:
                e.exit()

        wrapper.__sentinel_resource__ = name
        return wrapper

    return decorate


def sentinel_intercept(
    include: Optional[Callable[[str], bool]] = None,
    exclude: Tuple[str, ...] = (),
    resource_fmt: str = "{cls}.{method}",
    **resource_kwargs,
):
    """Class-level interceptor: guard every public method of a class.

    Analog of the CDI interceptor binding
    (``sentinel-annotation-cdi-interceptor/.../SentinelResourceInterceptor.java:35-70``,
    ``SentinelResourceBinding.java``): where CDI weaves an ``@AroundInvoke``
    interceptor around every business method of a bound bean, Python's
    idiom is a class decorator that wraps the class's own public methods
    with :func:`sentinel_resource`. Semantics match the reference:

    - every public method defined ON the class becomes a resource named
      ``resource_fmt.format(cls=..., method=...)``;
    - a method already bound with ``@sentinel_resource`` keeps its own
      binding (method-level annotation wins over the class binding — the
      CDI interceptor consults the method annotation first);
    - dunders, private methods (``_``-prefixed), static/class methods'
      descriptors, and non-callables are left alone;
    - ``include(name) -> bool`` / ``exclude`` narrow the set;
    - ``resource_kwargs`` (block_handler, fallback, entry_type, …) apply
      to every bound method, like binding-level defaults.

    Usage::

        @sentinel_intercept(fallback=my_fallback)
        class CheckoutService:
            def checkout(self, order): ...
            def refund(self, order): ...
    """

    def decorate(cls):
        def bind(fn: Callable, attr: str) -> Callable:
            return sentinel_resource(
                resource=resource_fmt.format(cls=cls.__name__, method=attr),
                **resource_kwargs,
            )(fn)

        for attr, member in list(vars(cls).items()):
            if attr.startswith("_") or attr in exclude:
                continue
            if include is not None and not include(attr):
                continue
            if isinstance(member, (staticmethod, classmethod)):
                inner = member.__func__
                if getattr(inner, "__sentinel_resource__", None):
                    continue
                setattr(cls, attr, type(member)(bind(inner, attr)))
                continue
            # plain FUNCTIONS only: nested classes and callable instances
            # are also callable, but wrapping them would corrupt them (a
            # function wrapper is a descriptor — it would bind self and
            # break isinstance/subclassing). The CDI interceptor likewise
            # wraps business METHODS, nothing else.
            if not inspect.isfunction(member):
                continue
            if getattr(member, "__sentinel_resource__", None):
                continue  # method-level @sentinel_resource wins
            setattr(cls, attr, bind(member, attr))
        return cls

    return decorate
