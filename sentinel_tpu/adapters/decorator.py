"""``@sentinel_resource`` — function-level guard with handler dispatch.

Analog of the ``@SentinelResource`` annotation + aspect
(``sentinel-annotation-aspectj/.../SentinelResourceAspect.java:36-68``,
``AbstractSentinelAspectSupport.java:83-140``): the wrapped callable is the
resource; on block the ``block_handler`` runs; on a business exception the
error is traced and the ``fallback`` runs (unless the exception type is
ignored). The reference dispatches handlers by reflected method name — here
they are plain callables, and async callables get an async wrapper.
"""

from __future__ import annotations

import functools
import inspect
from typing import Callable, Optional, Tuple, Type

from sentinel_tpu.local import BlockException, EntryType
from sentinel_tpu.local.sph import entry as _entry


def sentinel_resource(
    resource: Optional[str] = None,
    entry_type: EntryType = EntryType.OUT,
    block_handler: Optional[Callable] = None,
    fallback: Optional[Callable] = None,
    exceptions_to_ignore: Tuple[Type[BaseException], ...] = (),
    args_as_params: bool = False,
):
    """Guard a function as a sentinel resource.

    - ``resource``: resource name; defaults to the function's qualified name
      (the aspect's ``getResourceName`` fallback).
    - ``block_handler(*args, ex=BlockException, **kwargs)``: runs on block.
    - ``fallback(*args, ex=Exception, **kwargs)``: runs on business error
      (after tracing), and on block when no ``block_handler`` is given —
      the reference's degrade-to-fallback order
      (``AbstractSentinelAspectSupport.handleBlockException``).
    - ``exceptions_to_ignore``: business exceptions re-raised untraced.
    - ``args_as_params``: pass the call's positional args to the slot chain
      so hot-param (``ParamFlowRule``) rules see them.
    """

    def decorate(fn: Callable) -> Callable:
        name = resource or f"{fn.__module__}.{fn.__qualname__}"

        def on_block(e, args, kwargs):
            if block_handler is not None:
                return block_handler(*args, ex=e, **kwargs)
            if fallback is not None:
                return fallback(*args, ex=e, **kwargs)
            raise e

        def on_error(e, args, kwargs):
            if isinstance(e, exceptions_to_ignore):
                raise e
            if fallback is not None:
                return fallback(*args, ex=e, **kwargs)
            raise e

        if inspect.iscoroutinefunction(fn):

            async def _maybe_await(value):
                # handlers may themselves be async — await their result
                return await value if inspect.isawaitable(value) else value

            @functools.wraps(fn)
            async def async_wrapper(*args, **kwargs):
                try:
                    e = _entry(
                        name, entry_type,
                        args=tuple(args) if args_as_params else (),
                    )
                except BlockException as be:
                    return await _maybe_await(on_block(be, args, kwargs))
                try:
                    return await fn(*args, **kwargs)
                except BaseException as err:
                    if not isinstance(err, exceptions_to_ignore):
                        e.trace(err)
                    return await _maybe_await(on_error(err, args, kwargs))
                finally:
                    e.exit()

            return async_wrapper

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            try:
                e = _entry(
                    name, entry_type,
                    args=tuple(args) if args_as_params else (),
                )
            except BlockException as be:
                return on_block(be, args, kwargs)
            try:
                return fn(*args, **kwargs)
            except BaseException as err:
                if not isinstance(err, exceptions_to_ignore):
                    e.trace(err)
                return on_error(err, args, kwargs)
            finally:
                e.exit()

        return wrapper

    return decorate
