"""Gateway flow control: param-based rules for routes/APIs.

Analog of ``sentinel-api-gateway-adapter-common``:

- ``GatewayFlowRule`` (``rule/GatewayFlowRule.java:27``): a flow rule scoped
  to a route id or logical API, optionally keyed by a request attribute
  (client IP, host, header, URL param, cookie).
- ``GatewayRuleConverter`` (``rule/GatewayRuleConverter.java``): each gateway
  rule becomes a hot-param rule — the request attribute is the hot param.
  Rules without a param item get a synthetic constant param so they still
  ride the same vectorized param path.
- ``GatewayParamParser`` (``param/GatewayParamParser.java:34,51``): pulls the
  per-rule attribute values out of the request into the args tuple, applying
  the item's match strategy (exact/prefix/regex/contains); non-matching
  values collapse into one "not matched" bucket.
- The param args feed the ordinary ``ParamFlowSlot`` — the reference inserts
  a dedicated ``GatewayFlowSlot`` at order −4000 whose checker is the
  param-flow checker; reusing ``ParamFlowSlot`` here is the same pipeline
  with one fewer moving part.
"""

from __future__ import annotations

import enum
import re
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.local import ParamFlowItem, ParamFlowRule, ParamFlowRuleManager
from sentinel_tpu.local import context as _ctx
from sentinel_tpu.local.base import BlockException, EntryType
from sentinel_tpu.local.flow import ControlBehavior, FlowGrade
from sentinel_tpu.local.sph import entry as _entry


class ResourceMode(enum.IntEnum):
    """``SentinelGatewayConstants``: rule targets a route id or a custom API."""

    ROUTE_ID = 0
    CUSTOM_API_NAME = 1


class ParseStrategy(enum.IntEnum):
    """Where the hot param comes from (``SentinelGatewayConstants.PARAM_PARSE_STRATEGY_*``)."""

    CLIENT_IP = 0
    HOST = 1
    HEADER = 2
    URL_PARAM = 3
    COOKIE = 4


class MatchStrategy(enum.IntEnum):
    """How the extracted value is matched (``PARAM_MATCH_STRATEGY_*``)."""

    EXACT = 0
    PREFIX = 1
    REGEX = 2
    CONTAINS = 3


# values that fail the match pattern share one bucket; absent values another
NOT_MATCH = "$NM"
ABSENT = "$D"


@dataclass
class GatewayParamFlowItem:
    """``GatewayParamFlowItem.java`` — the keyed attribute of a gateway rule."""

    parse_strategy: ParseStrategy = ParseStrategy.CLIENT_IP
    field_name: Optional[str] = None  # header/url-param/cookie name
    pattern: Optional[str] = None
    match_strategy: MatchStrategy = MatchStrategy.EXACT


@dataclass
class GatewayFlowRule:
    """``GatewayFlowRule.java:27``."""

    resource: str  # route id or API name
    resource_mode: ResourceMode = ResourceMode.ROUTE_ID
    count: float = 0.0
    grade: FlowGrade = FlowGrade.QPS
    interval_sec: int = 1
    control_behavior: ControlBehavior = ControlBehavior.DEFAULT
    burst: int = 0
    max_queueing_time_ms: int = 500
    param_item: Optional[GatewayParamFlowItem] = None


class RequestAdapter:
    """Framework-neutral request view the parser reads from. Adapters (WSGI,
    ASGI, any gateway) implement these five accessors."""

    def client_ip(self) -> str:
        return ""

    def host(self) -> str:
        return ""

    def header(self, name: str) -> Optional[str]:
        return None

    def url_param(self, name: str) -> Optional[str]:
        return None

    def cookie(self, name: str) -> Optional[str]:
        return None


@dataclass
class DictRequestAdapter(RequestAdapter):
    """Simple adapter over plain dicts (tests, WSGI environ pre-digestion)."""

    ip: str = ""
    host_name: str = ""
    headers: Dict[str, str] = field(default_factory=dict)
    params: Dict[str, str] = field(default_factory=dict)
    cookies: Dict[str, str] = field(default_factory=dict)

    def client_ip(self) -> str:
        return self.ip

    def host(self) -> str:
        return self.host_name

    def header(self, name: str) -> Optional[str]:
        # case-insensitive like HTTP headers: adapters normalize keys to
        # lowercase, rules are usually written canonically ("X-Api-Key") —
        # two dict gets cover both; the scan is only for hand-built dicts
        # with exotic casing
        value = self.headers.get(name)
        if value is None:
            value = self.headers.get(name.lower())
        if value is not None:
            return value
        lname = name.lower()
        for key, val in self.headers.items():
            if key.lower() == lname:
                return val
        return None

    def url_param(self, name: str) -> Optional[str]:
        return self.params.get(name)

    def cookie(self, name: str) -> Optional[str]:
        return self.cookies.get(name)


def _extract(item: GatewayParamFlowItem, request: RequestAdapter) -> str:
    s = item.parse_strategy
    if s == ParseStrategy.CLIENT_IP:
        raw = request.client_ip()
    elif s == ParseStrategy.HOST:
        raw = request.host()
    elif s == ParseStrategy.HEADER:
        raw = request.header(item.field_name or "")
    elif s == ParseStrategy.URL_PARAM:
        raw = request.url_param(item.field_name or "")
    else:
        raw = request.cookie(item.field_name or "")
    if raw is None or raw == "":
        return ABSENT
    if item.pattern:
        m = item.match_strategy
        if m == MatchStrategy.EXACT:
            matched = raw == item.pattern
        elif m == MatchStrategy.PREFIX:
            matched = raw.startswith(item.pattern)
        elif m == MatchStrategy.REGEX:
            matched = re.search(item.pattern, raw) is not None
        else:
            matched = item.pattern in raw
        if not matched:
            return NOT_MATCH
    return raw


class GatewayRuleManager:
    """Converts gateway rules to hot-param rules and parses request params.

    ``loadRules`` → ``GatewayRuleConverter.applyToParamRule`` analog: gateway
    rule *i* for a resource becomes a ``ParamFlowRule`` with
    ``param_idx = i``; ``parse(resource, request)`` then builds the aligned
    args tuple for ``entry(..., args=...)``.
    """

    _lock = threading.RLock()
    _rules: Dict[str, List[GatewayFlowRule]] = {}

    @classmethod
    def load_rules(cls, rules: Sequence[GatewayFlowRule]) -> None:
        grouped: Dict[str, List[GatewayFlowRule]] = {}
        for rule in rules:
            if not rule.resource or rule.count < 0:
                continue
            grouped.setdefault(rule.resource, []).append(rule)
        param_rules: List[ParamFlowRule] = []
        for resource, lst in grouped.items():
            for idx, rule in enumerate(lst):
                param_rules.append(
                    ParamFlowRule(
                        resource=resource,
                        param_idx=idx,
                        count=rule.count,
                        grade=rule.grade,
                        duration_sec=rule.interval_sec,
                        burst_count=rule.burst,
                        control_behavior=rule.control_behavior,
                        max_queueing_time_ms=rule.max_queueing_time_ms,
                    )
                )
        with cls._lock:
            # the gateway owns every resource it EVER named: rules generated
            # for resources dropped from the new set must be unloaded too,
            # not preserved as if they were user-defined param rules
            gateway_owned = set(cls._rules) | set(grouped)
            cls._rules = grouped
            existing = [
                r
                for res, lst in ParamFlowRuleManager.all_rules().items()
                if res not in gateway_owned
                for r in lst
            ]
            ParamFlowRuleManager.load_rules(existing + param_rules)

    @classmethod
    def rules_for(cls, resource: str) -> List[GatewayFlowRule]:
        with cls._lock:
            return list(cls._rules.get(resource, []))

    @classmethod
    def parse(cls, resource: str, request: RequestAdapter) -> Tuple[str, ...]:
        """``GatewayParamParser.parseParameterFor``: one arg per rule, indexed
        by the rule's position; rules without a param item get a constant so
        the whole rule behaves like a plain flow rule on the param path."""
        args = []
        for rule in cls.rules_for(resource):
            if rule.param_item is None:
                args.append(ABSENT)
            else:
                args.append(_extract(rule.param_item, request))
        return tuple(args)

    @classmethod
    def entry(cls, resource: str, request: RequestAdapter,
              origin: str = "", count: int = 1):
        """Guard a gateway route: parse params, enter the slot chain.
        Raises ``BlockException`` on a block verdict."""
        args = cls.parse(resource, request)
        _ctx.enter(name=GatewayGuard.CONTEXT_NAME, origin=origin)
        return _entry(resource, EntryType.IN, count, args)

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._rules = {}


class GatewayGuard:
    """Guard one gateway request: the route resource PLUS every custom API
    whose path predicates match (``GatewayApiMatcherManager`` pick), each
    entered with its own parsed params — the reference adapters' doSentinelEntry
    sequence (route entry, then one entry per matching ApiDefinition).

    Use as a context manager; raises ``BlockException`` from ``__enter__``
    with nothing left entered if ANY resource blocks.
    """

    def __init__(self, route: str, request: RequestAdapter, path: str = "",
                 origin: str = ""):
        from sentinel_tpu.adapters.gateway_api import GatewayApiMatcherManager

        self.route = route
        self.request = request
        self.path = path
        self.origin = origin
        self._matcher = GatewayApiMatcherManager
        self._entries = []
        self._ctx_entered = False

    # One fixed entrance-context for all gateway traffic (the reference's
    # GATEWAY_CONTEXT prefix is bounded by ROUTE IDS; a WSGI/ASGI front only
    # has raw paths, whose cardinality would exhaust the context-name cap
    # and silently disable flow control past it). Per-route stats still
    # exist — resources are per-route; only the entrance node is shared.
    CONTEXT_NAME = "sentinel_gateway_context"

    def __enter__(self):
        _ctx.enter(name=self.CONTEXT_NAME, origin=self.origin)
        self._ctx_entered = True
        try:
            resources = [self.route]
            if self.path:
                resources.extend(
                    self._matcher.pick_matching_api_names(self.path)
                )
            for resource in resources:
                args = GatewayRuleManager.parse(resource, self.request)
                self._entries.append(_entry(resource, EntryType.IN, 1, args))
        except BaseException:
            self._unwind()
            raise
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and not isinstance(exc, BlockException):
            self.trace(exc)
        self._unwind()
        return False

    def trace(self, exc: BaseException) -> None:
        """Record an app error on the ROUTE entry (entered first) — an
        exception-ratio rule on the route must see errors regardless of
        which custom APIs happened to match."""
        if self._entries:
            try:
                self._entries[0].trace(exc)
            except Exception:
                pass

    def _unwind(self) -> None:
        while self._entries:
            try:
                self._entries.pop().exit()
            except Exception:
                pass
        if self._ctx_entered:
            _ctx.exit()
            self._ctx_entered = False


def _parse_cookies(header_value: str) -> Dict[str, str]:
    cookies = {}
    for part in header_value.split(";"):
        if "=" in part:
            k, v = part.split("=", 1)
            cookies[k.strip()] = v.strip()
    return cookies


def _wsgi_request_adapter(environ) -> "DictRequestAdapter":
    from urllib.parse import parse_qsl

    headers = {
        k[5:].replace("_", "-").lower(): v
        for k, v in environ.items() if k.startswith("HTTP_")
    }
    return DictRequestAdapter(
        ip=environ.get("REMOTE_ADDR", ""),
        host_name=environ.get("HTTP_HOST", environ.get("SERVER_NAME", "")),
        headers=headers,
        params=dict(parse_qsl(environ.get("QUERY_STRING", ""))),
        cookies=_parse_cookies(headers.get("cookie", "")),
    )


def _asgi_request_adapter(scope) -> "DictRequestAdapter":
    from urllib.parse import parse_qsl

    headers = {
        k.decode("latin-1").lower(): v.decode("latin-1")
        for k, v in scope.get("headers", [])
    }
    client = scope.get("client")
    return DictRequestAdapter(
        ip=client[0] if client else "",
        host_name=headers.get("host", ""),
        headers=headers,
        params=dict(
            parse_qsl(scope.get("query_string", b"").decode("latin-1"))
        ),
        cookies=_parse_cookies(headers.get("cookie", "")),
    )


class SentinelGatewayWsgiMiddleware:
    """WSGI front for the gateway pipeline: route extraction → custom-API
    matching → per-resource param parsing → gateway entries. The analog of
    mounting the reference's Zuul/SCG filter at the edge.

    ``route_extractor`` should return a BOUNDED set of route ids (the
    reference's routes come from gateway config). The default — the raw
    path — is fine behind a router that normalizes paths, but a front
    serving unbounded distinct paths (REST ids in the path) must map them
    to route ids or per-resource stats grow without bound."""

    def __init__(self, app, route_extractor=None, origin_parser=None,
                 block_handler=None):
        self.app = app
        self.route_extractor = route_extractor or (
            lambda environ: environ.get("PATH_INFO", "/")
        )
        self.origin_parser = origin_parser or (
            lambda environ: environ.get("REMOTE_ADDR", "")
        )
        self.block_handler = block_handler

    def __call__(self, environ, start_response):
        route = self.route_extractor(environ)
        if not route:
            return self.app(environ, start_response)
        request = _wsgi_request_adapter(environ)
        path = environ.get("PATH_INFO", "/")
        guard = GatewayGuard(route, request, path, self.origin_parser(environ))
        try:
            # only the guard's own admission block is answered with 429 —
            # a BlockException raised by the app (nested entry) propagates,
            # because the app may already have called start_response
            guard.__enter__()
        except BlockException as e:
            if self.block_handler is not None:
                return self.block_handler(environ, start_response, e)
            body = b"Blocked by Sentinel (gateway flow limiting)"
            start_response(
                "429 Too Many Requests",
                [("Content-Type", "text/plain"),
                 ("Content-Length", str(len(body)))],
            )
            return [body]
        try:
            body = self.app(environ, start_response)
        except BaseException as err:
            guard.trace(err)
            guard._unwind()
            raise
        # exit only after the body is consumed (mirrors SentinelWsgiMiddleware):
        # streaming responses hold the entries open for their full duration
        from sentinel_tpu.adapters.wsgi import _GuardedBody

        return _GuardedBody(body, guard._entries[0], guard._unwind)


class SentinelGatewayAsgiMiddleware:
    """ASGI twin of ``SentinelGatewayWsgiMiddleware``."""

    def __init__(self, app, route_extractor=None, origin_parser=None,
                 block_status: int = 429,
                 block_body: bytes = b'{"error": "Blocked by Sentinel (gateway flow limiting)"}'):
        self.app = app
        self.route_extractor = route_extractor or (
            lambda scope: scope.get("path", "/")
        )
        self.origin_parser = origin_parser or (
            lambda scope: (scope.get("client") or ("",))[0]
        )
        self.block_status = block_status
        self.block_body = block_body

    async def __call__(self, scope, receive, send):
        if scope.get("type") != "http":
            await self.app(scope, receive, send)
            return
        route = self.route_extractor(scope)
        if not route:
            await self.app(scope, receive, send)
            return
        request = _asgi_request_adapter(scope)
        path = scope.get("path", "/")
        try:
            guard = GatewayGuard(route, request, path, self.origin_parser(scope))
            guard.__enter__()
        except BlockException:
            from sentinel_tpu.adapters.asgi import send_block_response

            await send_block_response(send, self.block_status, self.block_body)
            return
        try:
            await self.app(scope, receive, send)
        except BaseException as exc:
            guard.__exit__(type(exc), exc, exc.__traceback__)
            raise
        else:
            guard.__exit__(None, None, None)
