"""ASGI middleware — the Spring WebMVC/WebFlux interceptor analog.

Reference idiom (``AbstractSentinelInterceptor.java:55,88,137``,
``SentinelReactorSubscriber.java:37``): guard the request on the way in,
record the outcome on the way out. Safe under asyncio concurrency because
the engine context is a ``contextvars.ContextVar`` (each task sees its own
entry stack — the capability the reference needs ``AsyncEntry`` for).
"""

from __future__ import annotations

import json
from typing import Callable, Optional

from sentinel_tpu.local import BlockException, EntryType
from sentinel_tpu.local import context as _ctx
from sentinel_tpu.local.sph import entry as _entry

DEFAULT_BLOCK_BODY = b'{"error": "Blocked by Sentinel (flow limiting)"}'


async def send_block_response(send, status: int, body: bytes) -> None:
    """One canonical 429 response pair (shared with the gateway middleware)."""
    await send(
        {
            "type": "http.response.start",
            "status": status,
            "headers": [
                (b"content-type", b"application/json"),
                (b"content-length", str(len(body)).encode()),
            ],
        }
    )
    await send({"type": "http.response.body", "body": body})


def default_resource(scope) -> str:
    return f"{scope.get('method', 'GET')}:{scope.get('path', '/')}"


def default_origin(scope) -> str:
    """Cross-service convention: ``X-Sentinel-Origin`` (set by the
    ``http_client`` wrappers), then the legacy ``S-User`` identity header,
    then the peer IP — see ``adapters/origin.py``."""
    from sentinel_tpu.adapters.origin import from_asgi_scope

    return from_asgi_scope(scope)


class SentinelAsgiMiddleware:
    def __init__(
        self,
        app: Callable,
        resource_extractor: Callable = default_resource,
        origin_parser: Callable = default_origin,
        block_status: int = 429,
        block_body: bytes = DEFAULT_BLOCK_BODY,
    ):
        self.app = app
        self.resource_extractor = resource_extractor
        self.origin_parser = origin_parser
        self.block_status = block_status
        self.block_body = block_body

    async def __call__(self, scope, receive, send) -> None:
        if scope.get("type") != "http":
            await self.app(scope, receive, send)
            return
        resource = self.resource_extractor(scope)
        if not resource:
            await self.app(scope, receive, send)
            return
        _ctx.enter(name=f"asgi_context:{resource}", origin=self.origin_parser(scope))
        try:
            try:
                entry = _entry(resource, EntryType.IN)
            except BlockException:
                await send_block_response(send, self.block_status, self.block_body)
                return
            try:
                await self.app(scope, receive, send)
            except BaseException as err:
                entry.trace(err)
                raise
            finally:
                entry.exit()
        finally:
            _ctx.exit()
