"""Outbound HTTP-call guards for ``requests`` and ``httpx``.

Analog of ``sentinel-okhttp-adapter`` / ``sentinel-apache-httpclient-adapter``:
the outbound URL (normalized to ``METHOD:scheme://host/path``) is an OUT-type
resource; blocks raise ``BlockException`` before any connection is made;
HTTP errors are traced. Gated on the respective client library.

Both wrappers also attach ``X-Sentinel-Origin: <app name>`` so the callee's
Sentinel adapter sees the calling *application* as the request origin — the
dubbo consumer→provider attachment idiom for plain HTTP (see
``adapters/origin.py``). Pass ``propagate_origin=False`` to opt out.
"""

from __future__ import annotations

from typing import Callable, Optional
from urllib.parse import urlsplit

from sentinel_tpu.local import BlockException, EntryType  # noqa: F401 (re-export)
from sentinel_tpu.local.sph import entry as _entry


def default_resource(method: str, url: str) -> str:
    parts = urlsplit(url)
    return f"{method.upper()}:{parts.scheme}://{parts.netloc}{parts.path}"


def guarded_call(fn: Callable, method: str, url: str,
                 resource_extractor: Callable = default_resource, **kwargs):
    """Framework-neutral core: guard ``fn(**kwargs)`` as an outbound call."""
    with _entry(resource_extractor(method, url), EntryType.OUT) as e:
        try:
            return fn(**kwargs)
        except BaseException as err:
            e.trace(err)
            raise


# -- requests ---------------------------------------------------------------

def guarded_requests_session(
    session=None, resource_extractor: Callable = default_resource,
    propagate_origin: bool = True,
):
    """Wrap a ``requests.Session`` so every request is guarded."""
    import requests

    from sentinel_tpu.adapters.origin import inject as _inject_origin

    session = session or requests.Session()
    inner = session.request

    def request(method, url, *args, **kwargs):
        # requests.Session.request takes headers as its 5th positional arg
        # (after params, data) — only inject via kwargs when the caller
        # didn't already pass it positionally
        if propagate_origin and len(args) < 3:
            kwargs["headers"] = _inject_origin(kwargs.get("headers"))
        with _entry(resource_extractor(method, url), EntryType.OUT) as e:
            resp = inner(method, url, *args, **kwargs)
            if resp.status_code >= 500:
                e.trace(RuntimeError(f"HTTP {resp.status_code}"))
            return resp

    session.request = request
    return session


# -- httpx ------------------------------------------------------------------

class SentinelHttpxTransport:
    """``httpx`` custom transport wrapper: ``httpx.Client(transport=...)``."""

    def __init__(self, inner=None, resource_extractor: Callable = default_resource,
                 propagate_origin: bool = True):
        import httpx

        self._inner = inner or httpx.HTTPTransport()
        self._extract = resource_extractor
        self._propagate_origin = propagate_origin

    def handle_request(self, request):
        if self._propagate_origin:
            from sentinel_tpu.adapters.origin import ORIGIN_HEADER, origin_value

            if ORIGIN_HEADER not in request.headers:
                value = origin_value()
                if value:
                    request.headers[ORIGIN_HEADER] = value
        resource = self._extract(request.method, str(request.url))
        with _entry(resource, EntryType.OUT) as e:
            response = self._inner.handle_request(request)
            if response.status_code >= 500:
                e.trace(RuntimeError(f"HTTP {response.status_code}"))
            return response

    def close(self):
        self._inner.close()
