"""Outbound HTTP-call guards for ``requests`` and ``httpx``.

Analog of ``sentinel-okhttp-adapter`` / ``sentinel-apache-httpclient-adapter``:
the outbound URL (normalized to ``METHOD:scheme://host/path``) is an OUT-type
resource; blocks raise ``BlockException`` before any connection is made;
HTTP errors are traced. Gated on the respective client library.
"""

from __future__ import annotations

from typing import Callable, Optional
from urllib.parse import urlsplit

from sentinel_tpu.local import BlockException, EntryType  # noqa: F401 (re-export)
from sentinel_tpu.local.sph import entry as _entry


def default_resource(method: str, url: str) -> str:
    parts = urlsplit(url)
    return f"{method.upper()}:{parts.scheme}://{parts.netloc}{parts.path}"


def guarded_call(fn: Callable, method: str, url: str,
                 resource_extractor: Callable = default_resource, **kwargs):
    """Framework-neutral core: guard ``fn(**kwargs)`` as an outbound call."""
    with _entry(resource_extractor(method, url), EntryType.OUT) as e:
        try:
            return fn(**kwargs)
        except BaseException as err:
            e.trace(err)
            raise


# -- requests ---------------------------------------------------------------

def guarded_requests_session(
    session=None, resource_extractor: Callable = default_resource
):
    """Wrap a ``requests.Session`` so every request is guarded."""
    import requests

    session = session or requests.Session()
    inner = session.request

    def request(method, url, *args, **kwargs):
        with _entry(resource_extractor(method, url), EntryType.OUT) as e:
            resp = inner(method, url, *args, **kwargs)
            if resp.status_code >= 500:
                e.trace(RuntimeError(f"HTTP {resp.status_code}"))
            return resp

    session.request = request
    return session


# -- httpx ------------------------------------------------------------------

class SentinelHttpxTransport:
    """``httpx`` custom transport wrapper: ``httpx.Client(transport=...)``."""

    def __init__(self, inner=None, resource_extractor: Callable = default_resource):
        import httpx

        self._inner = inner or httpx.HTTPTransport()
        self._extract = resource_extractor

    def handle_request(self, request):
        resource = self._extract(request.method, str(request.url))
        with _entry(resource, EntryType.OUT) as e:
            response = self._inner.handle_request(request)
            if response.status_code >= 500:
                e.trace(RuntimeError(f"HTTP {response.status_code}"))
            return response

    def close(self):
        self._inner.close()
