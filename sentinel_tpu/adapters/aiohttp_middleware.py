"""aiohttp server middleware (async-web adapter, the webflux analog).

Same idiom as every reference adapter (``CommonFilter.java:50``-style:
parse resource + origin → enter context → entry → proceed → trace errors →
exit): resource is ``METHOD:path``, block answers 429. Safe under asyncio
concurrency because the engine context rides a ``contextvars.ContextVar``
(each task sees its own entry stack).

Usage::

    from aiohttp import web
    from sentinel_tpu.adapters.aiohttp_middleware import sentinel_middleware

    app = web.Application(middlewares=[sentinel_middleware()])
"""

from __future__ import annotations

import inspect
from typing import Callable, Optional

from sentinel_tpu.local import BlockException, EntryType
from sentinel_tpu.local import context as _ctx
from sentinel_tpu.local.sph import entry as _entry

DEFAULT_BLOCK_BODY = {"error": "Blocked by Sentinel (flow limiting)"}


def default_resource(request) -> str:
    return f"{request.method}:{request.path}"


def default_origin(request) -> str:
    """``X-Sentinel-Origin`` → ``S-User`` → peer IP (adapters/origin.py)."""
    from sentinel_tpu.adapters.origin import from_headers

    return from_headers(request.headers, request.remote or "")


def sentinel_middleware(
    resource_extractor: Callable = default_resource,
    origin_parser: Callable = default_origin,
    block_status: int = 429,
    block_handler: Optional[Callable] = None,
):
    """Build an ``@web.middleware``-conformant guard. ``block_handler``
    (request, error) → response overrides the default 429 JSON body."""
    from aiohttp import web

    @web.middleware
    async def middleware(request, handler):
        resource = resource_extractor(request)
        if not resource:
            return await handler(request)
        _ctx.enter(
            name=f"aiohttp_context:{resource}", origin=origin_parser(request)
        )
        try:
            try:
                with _entry(resource, EntryType.IN) as e:
                    try:
                        return await handler(request)
                    except web.HTTPException:
                        raise  # normal control flow, not a business error
                    except BaseException as err:
                        e.trace(err)
                        raise
            except BlockException as blocked:
                if block_handler is not None:
                    resp = block_handler(request, blocked)
                    if inspect.isawaitable(resp):  # async handlers welcome
                        resp = await resp
                    return resp
                return web.json_response(
                    DEFAULT_BLOCK_BODY, status=block_status
                )
        finally:
            _ctx.exit()

    return middleware
