"""Sketch variants for the hot-param plane, behind one interface.

``ParamConfig.sketch`` selects the fat (update) sketch — ``"cms"`` (the
seed's plain int32 count-min) or ``"salsa"`` (:mod:`sentinel_tpu.sketch.salsa`,
int16 self-adjusting counters at the same HBM bytes) — and
``ParamConfig.impl`` independently selects the kernel ("jax" | "pallas" |
"auto", probed by ``engine.param.resolve_param_impl``). The SF slim twin
(:mod:`sentinel_tpu.sketch.slim`) composes around either variant; the
accuracy harness (:mod:`sentinel_tpu.sketch.parity`) proves every
combination keeps the one-sided (never-undercount) guarantee.

This module holds the variant-dispatch helpers the cluster service needs
outside the decide kernels: post-update current-bucket estimate gathers
(slim maintenance), MOVE-import folds, host-side decoding for exports, and
the metrics snapshot.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

VARIANTS = ("cms", "salsa")


def gather_current_estimate(config, counts, rule_slot, idx, cur_idx):
    """``[N] int32`` per-request fat estimate over the CURRENT bucket only
    (min over depth lanes), decoding in-flight for SALSA. Traced inside the
    slim post-step jit."""
    from sentinel_tpu.sketch.salsa import CAP

    safe_slot = jnp.where(rule_slot >= 0, rule_slot, 0)
    d_ar = jnp.arange(config.depth)[None, :]
    if config.sketch == "salsa":
        pair = (idx // 2) * 2
        lo = counts[safe_slot[:, None], cur_idx, d_ar, pair].astype(jnp.int32)
        hi = counts[safe_slot[:, None], cur_idx, d_ar, pair + 1].astype(
            jnp.int32
        )
        merged = hi < 0
        mval = lo + CAP * (-hi - 1)
        own = jnp.where(idx % 2 == 0, lo, hi)
        per_d = jnp.where(merged, mval, own)
    else:
        per_d = counts[safe_slot[:, None], cur_idx, d_ar, idx]
    return jnp.min(per_d, axis=1)


def decoded_counts_np(config, counts: np.ndarray) -> np.ndarray:
    """Host view of the fat cells as per-cell *query* values: identity for
    cms, pairwise decode for SALSA (both cells of a merged pair read the
    merged value). Exports sum these — the wire document stays plain int
    sums whatever the in-memory encoding is."""
    if config.sketch == "salsa":
        from sentinel_tpu.sketch.salsa import decode_cells_np

        return decode_cells_np(np.asarray(counts))
    return np.asarray(counts)


def fold_param_sums(config, state, now: int, rows, sums):
    """Sketch-aware analog of ``token_service._fold_into_current`` for the
    param plane: pre-rotate a stale current bucket (fat column, slim column,
    and the bucket's slim-authority flag), then add the imported per-cell
    window sums into the current bucket. For SALSA the add happens in
    decoded space — merged pairs absorb both cells' sums into the joint
    counter (conservative: the union bound) — and re-encoding applies the
    usual merge-on-saturation, counted into ``state.merges``."""
    from sentinel_tpu.sketch.salsa import CAP, MERGE_CEIL, SAT

    B = config.n_buckets
    idx = int((now // config.bucket_ms) % B)
    aligned = int(now - now % config.bucket_ms)
    starts = np.asarray(state.starts)
    counts, slim = state.counts, state.slim
    slim_auth, merges = state.slim_auth, state.merges
    if int(starts[idx]) != aligned:
        counts = counts.at[:, idx].set(0)
        if config.slim_enabled:
            slim = slim.at[:, idx].set(0)
        slim_auth = slim_auth.at[idx].set(False)
        starts = np.array(starts)
        starts[idx] = aligned
    if rows is not None and len(rows):
        rows = np.asarray(rows, np.int32)
        sums = np.asarray(sums)
        if config.sketch == "salsa":
            plane = np.asarray(counts)[:, idx]  # [P, D, 2W] int16
            c = plane.astype(np.int64)
            lo, hi = c[..., 0::2], c[..., 1::2]
            merged = hi < 0
            mval = lo + CAP * (-hi - 1)
            ev = np.where(merged, mval, lo)
            od = np.where(merged, 0, hi)
            add = sums.astype(np.int64)
            add_ev, add_od = add[..., 0::2], add[..., 1::2]
            mrow = merged[rows]
            ev_r = ev[rows] + np.where(mrow, add_ev + add_od, add_ev)
            od_r = od[rows] + np.where(mrow, 0, add_od)
            newly = (~mrow) & ((ev_r > SAT) | (od_r > SAT))
            m2 = mrow | newly
            val = np.where(newly, np.maximum(ev_r, od_r), ev_r)
            val = np.minimum(val, MERGE_CEIL)
            new_rows = np.empty_like(plane[rows])
            new_rows[..., 0::2] = np.where(m2, val % CAP, ev_r).astype(
                np.int16
            )
            new_rows[..., 1::2] = np.where(m2, -(val // CAP) - 1,
                                           od_r).astype(np.int16)
            out = np.array(plane)
            out[rows] = new_rows
            counts = counts.at[:, idx].set(jnp.asarray(out))
            mdelta = np.zeros(config.max_param_rules, np.int32)
            np.add.at(mdelta, rows, newly.sum(axis=(1, 2)).astype(np.int32))
            merges = merges + jnp.asarray(mdelta)
        else:
            counts = counts.at[rows, idx].add(
                jnp.asarray(sums.astype(np.int32))
            )
    return state._replace(
        starts=jnp.asarray(starts),
        counts=counts,
        slim=slim,
        slim_auth=slim_auth,
        merges=merges,
    )


def sketch_stats(config, state) -> Dict[str, object]:
    """Host snapshot for the ``sketch`` observability block
    (``clusterServerStats`` / the Prometheus exporter)."""
    merges = np.asarray(state.merges)
    nz = np.nonzero(merges)[0]
    return {
        "variant": config.sketch,
        "fatBytes": int(np.asarray(state.counts).nbytes),
        "slimBytes": (
            int(np.asarray(state.slim).nbytes) if config.slim_enabled else 0
        ),
        "slimEnabled": bool(config.slim_enabled),
        "mergesTotal": int(merges.sum()),
        "mergesBySlot": {int(s): int(merges[s]) for s in nz},
    }
