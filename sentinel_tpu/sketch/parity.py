"""Accuracy-vs-reference harness for the sketch variants.

Drives fixed-seed Zipf value streams through the real decide path
(``engine.param.param_decide`` — the same jitted kernels production runs,
for any ``sketch`` × ``impl`` combination) against an exact host-side dict
counter, and reports the per-key overestimate distribution. Used by
``tests/test_sketch_parity.py`` and ``benchmarks/sketch_bench.py``; the CI
``sketch-parity`` job gates on **zero undercounts** (the one-sided CMS
guarantee every variant must keep — see docs/SKETCHES.md) and on the slim
twin's error staying within 2× of the fat sketch on a stream both can hold.

Queries go through :func:`query_np`, a host-side mirror of the device
estimate math (decoded live-bucket sums, min over lanes) so measuring
accuracy never perturbs the state under test.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from sentinel_tpu.engine.param import hash_indices, make_param_state, param_decide
from sentinel_tpu.sketch import decoded_counts_np
from sentinel_tpu.sketch.slim import slim_indices, slim_query_np

DEFAULT_SEED = 0x5A15A  # fixed-seed streams: CI runs are reproducible


def key_hashes(n_keys: int, seed: int = DEFAULT_SEED) -> np.ndarray:
    """``n_keys`` stable, distinct 64-bit value hashes."""
    rng = np.random.default_rng(seed)
    h = rng.integers(-(2 ** 63), 2 ** 63 - 1, size=2 * n_keys, dtype=np.int64)
    h = np.unique(h)[:n_keys]
    if h.shape[0] < n_keys:  # astronomically unlikely; keep deterministic
        extra = np.arange(n_keys - h.shape[0], dtype=np.int64) + 7
        h = np.concatenate([h, extra])
    return h


def zipf_stream(
    n_keys: int,
    n_events: int,
    alpha: float = 1.1,
    seed: int = DEFAULT_SEED,
) -> Tuple[np.ndarray, np.ndarray]:
    """``-> (hashes [n_events] int64, key_ids [n_events] int32)`` — a
    Zipf(alpha)-weighted stream over ``n_keys`` distinct values."""
    rng = np.random.default_rng(seed)
    w = 1.0 / np.power(np.arange(1, n_keys + 1, dtype=np.float64), alpha)
    w /= w.sum()
    ids = rng.choice(n_keys, size=n_events, p=w).astype(np.int32)
    return key_hashes(n_keys, seed)[ids], ids


def exact_counts(key_ids: np.ndarray, n_keys: int,
                 acquire: int = 1) -> np.ndarray:
    """The reference: an exact per-key counter of the same stream."""
    return np.bincount(key_ids, minlength=n_keys).astype(np.int64) * acquire


def run_stream(
    config,
    stream_hashes: np.ndarray,
    *,
    slot: int = 0,
    acquire: int = 1,
    threshold: float = 1e9,
    batch: int = 512,
    now: int = 1_000,
    maintain_slim: bool = True,
):
    """Feed a value-hash stream through ``param_decide`` in fixed-size
    batches (one jit signature) at a fixed ``now`` (single live bucket — no
    decay between feed and query) and return the final ``ParamState``."""
    import jax.numpy as jnp

    state = make_param_state(config)
    n = stream_hashes.shape[0]
    slim_on = maintain_slim and config.slim_enabled
    for off in range(0, n, batch):
        chunk = stream_hashes[off:off + batch]
        pad = batch - chunk.shape[0]
        idx = np.pad(
            hash_indices(chunk, config.depth, config.cell_width),
            ((0, pad), (0, 0)),
        )
        idx_slim = (
            np.pad(slim_indices(config, chunk), ((0, pad), (0, 0)))
            if slim_on else None
        )
        valid = np.zeros(batch, bool)
        valid[:chunk.shape[0]] = True
        state, _admit, _est = param_decide(
            config,
            state,
            jnp.full((batch,), slot, jnp.int32),
            jnp.asarray(idx),
            jnp.full((batch,), acquire, jnp.int32),
            jnp.full((batch,), threshold, jnp.float32),
            jnp.asarray(valid),
            jnp.int32(now),
            idx_slim=None if idx_slim is None else jnp.asarray(idx_slim),
        )
    return state


def query_np(config, state, slot: int, hashes: np.ndarray,
             now: int) -> np.ndarray:
    """``[N] int64`` fat-sketch estimates — host mirror of the device math
    (decoded cells, live-bucket sums, min over depth lanes)."""
    idx = hash_indices(hashes, config.depth, config.cell_width)
    dec = decoded_counts_np(config, state.counts)[int(slot)]  # [B, D, C]
    starts = np.asarray(state.starts)
    age = int(now) - starts
    live = (age >= 0) & (age < config.interval_ms)
    winsum = (dec.astype(np.int64) * live[:, None, None]).sum(axis=0)
    per_d = winsum[np.arange(config.depth)[None, :], idx]
    return per_d.min(axis=1)


def stream_report(
    config,
    *,
    n_keys: int,
    n_events: int,
    alpha: float = 1.1,
    seed: int = DEFAULT_SEED,
    acquire: int = 1,
    batch: int = 512,
    with_slim: bool = True,
) -> Dict[str, object]:
    """One full parity run: feed the stream, query every distinct key, and
    report the overestimate distribution vs the exact reference (plus the
    slim twin's, when enabled). ``undercounts`` MUST be zero for every
    variant — that's the safety gate."""
    now = 1_000
    hashes, ids = zipf_stream(n_keys, n_events, alpha, seed)
    state = run_stream(
        config, hashes, acquire=acquire, batch=batch, now=now,
        maintain_slim=with_slim,
    )
    keys = key_hashes(n_keys, seed)
    true = exact_counts(ids, n_keys, acquire)
    est = query_np(config, state, 0, keys, now)
    err = est - true
    report: Dict[str, object] = {
        "sketch": config.sketch,
        "impl": config.impl,
        "nKeys": int(n_keys),
        "nEvents": int(n_events),
        "alpha": float(alpha),
        "seed": int(seed),
        "undercounts": int((err < 0).sum()),
        "errCdf": _cdf(err),
        "meanRelErr": float(
            (err / np.maximum(true, 1)).mean()
        ),
    }
    if with_slim and config.slim_enabled:
        est_slim = slim_query_np(config, state, 0, keys, now)
        serr = est_slim - true
        report["slim"] = {
            "undercounts": int((serr < 0).sum()),
            "errCdf": _cdf(serr),
            "meanRelErr": float((serr / np.maximum(true, 1)).mean()),
        }
    return report


def _cdf(err: np.ndarray) -> Dict[str, float]:
    return {
        "p50": float(np.percentile(err, 50)),
        "p90": float(np.percentile(err, 90)),
        "p99": float(np.percentile(err, 99)),
        "max": float(err.max()) if err.size else 0.0,
        "mean": float(err.mean()) if err.size else 0.0,
    }


def effective_cardinality(
    config,
    *,
    err_budget: float = 0.25,
    k_grid=(32, 48, 64, 96, 128, 192, 256, 384, 512),
    events_per_key: int = 12,
    alpha: float = 1.05,
    seed: int = DEFAULT_SEED,
    batch: int = 512,
) -> float:
    """Largest key cardinality the sketch holds with p90 overestimate
    within ``err_budget`` of the mean per-key count, on the fixed-seed Zipf
    stream, log-interpolated past the last grid point that meets the
    budget. This is the "effective key cardinality at equal HBM bytes"
    metric from the SALSA evaluation: plain int32 width-W vs SALSA int16
    width-2W are byte-identical, so the ratio of their effective
    cardinalities is the memory win. The p90 (not mean-relative) statistic
    keeps the sweep monotone — mean relative error is dominated by a
    handful of tail keys catching heavy-hitter collision mass.
    """
    import math

    budget = err_budget * events_per_key  # absolute p90 error budget
    errs = []
    for k in k_grid:
        rep = stream_report(
            config,
            n_keys=int(k),
            n_events=int(k) * events_per_key,
            alpha=alpha,
            seed=seed,
            batch=batch,
            with_slim=False,
        )
        errs.append(float(rep["errCdf"]["p90"]))
    # last grid point within budget, then log-interpolate into the first
    # failing point after it
    last_ok = None
    for i, e in enumerate(errs):
        if e <= budget:
            last_ok = i
    if last_ok is None:
        return float(k_grid[0])
    if last_ok == len(k_grid) - 1:
        return float(k_grid[-1])
    k0, k1 = float(k_grid[last_ok]), float(k_grid[last_ok + 1])
    e0, e1 = max(errs[last_ok], 1e-3), max(errs[last_ok + 1], 1e-3)
    t = (math.log(budget + 1e-9) - math.log(e0)) / (
        math.log(e1) - math.log(e0)
    )
    t = min(max(t, 0.0), 1.0)
    return float(math.exp(math.log(k0) + t * (math.log(k1) - math.log(k0))))
