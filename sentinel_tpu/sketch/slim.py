"""SF-sketch slim twin of the fat param sketch (arXiv:1701.04148).

The fat sketch (plain CMS or SALSA, ``engine.param`` / ``sketch.salsa``)
takes every update; this module maintains a much smaller *query* twin —
``slim[P, B, slim_depth, slim_width]`` int32 — that replication deltas ship
instead of the fat rows (``token_service.export_delta``). The twin is built
incrementally: whenever a value is touched, the fat sketch's post-update
current-bucket estimate for that value is scatter-**max**'d into the
value's slim cells. Because a value's true count only grows when it is
touched, and the fat estimate at touch time is already an upper bound, every
slim cell holds ``max`` over its colliding values of an upper bound — the
windowed slim estimate (min over slim lanes of the live-bucket sums) never
undercounts. See docs/SKETCHES.md for the full argument.

A standby applies slim rows from deltas and flags those buckets
*slim-authoritative* (``ParamState.slim_auth``). Its decide path then serves
``fat_estimate + slim_estimate(auth buckets)``: the fat part covers its own
(bootstrap-snapshot) history, the slim part covers what the primary admitted
since — double-counting the overlap of one snapshot-to-delta gap at most,
which errs in the safe (over-estimate) direction and washes out as the
flagged buckets rotate off the ring within one window.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Lane-constant offset for the twin's host-side hash derivation: slim lanes
# come from a disjoint part of the splitmix sequence than any plausible fat
# depth, so a fat-lane collision does not imply a slim-lane collision.
SLIM_SALT = 64


def slim_indices(config, value_hashes: np.ndarray) -> np.ndarray:
    """``[N] int64 -> [N, slim_depth] int32`` twin cell indices (host)."""
    from sentinel_tpu.engine.param import hash_indices

    return hash_indices(
        value_hashes, config.slim_depth, config.slim_width, salt=SLIM_SALT
    )


@partial(jax.jit, static_argnames=("config",))
def slim_prestep(
    config, state, rule_slot, idx_slim, now
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Roll the slim ring for the current bucket and return the per-request
    slim estimate over delta-authoritative live buckets.

    ``-> (slim', slim_auth', est_slim[N] int32)``. On a primary every
    ``slim_auth`` flag is False and ``est_slim`` is all zeros — the decide
    outcome is bit-identical to a slim-less build.
    """
    now = jnp.asarray(now, jnp.int32)
    B = config.n_buckets
    cur_idx = (now // config.bucket_ms) % B
    cur_start = now - now % config.bucket_ms
    stale = state.starts[cur_idx] != cur_start
    # mirror the fat roll: a stale current bucket is a NEW window bucket —
    # zero its slim column and drop its authority flag
    slim = jnp.where(
        (jnp.arange(B)[None, :, None, None] == cur_idx) & stale,
        0,
        state.slim,
    )
    slim_auth = jnp.where(
        (jnp.arange(B) == cur_idx) & stale, False, state.slim_auth
    )

    starts = state.starts.at[cur_idx].set(cur_start)
    age = now - starts
    bucket_ok = (age >= 0) & (age < config.interval_ms)  # [B]
    use = bucket_ok & slim_auth  # [B]

    safe_slot = jnp.where(rule_slot >= 0, rule_slot, 0)
    ds_ar = jnp.arange(config.slim_depth)[None, :]  # [1, Ds]

    def gather_sum(b):
        per_d = slim[safe_slot[:, None], b, ds_ar, idx_slim]  # [N, Ds]
        return per_d * use[b].astype(jnp.int32)

    sums = sum(gather_sum(b) for b in range(B))  # [N, Ds]
    est_slim = jnp.min(sums, axis=1)  # [N]
    est_slim = jnp.where(rule_slot >= 0, est_slim, 0)
    return slim, slim_auth, est_slim


@partial(jax.jit, static_argnames=("config",))
def slim_poststep(config, state, rule_slot, idx, idx_slim, valid, now):
    """Scatter-max the fat sketch's post-update current-bucket estimate of
    each touched value into the value's slim cells. ``state`` is the
    post-core state (fat already updated, starts already rolled)."""
    from sentinel_tpu.sketch import gather_current_estimate

    now = jnp.asarray(now, jnp.int32)
    cur_idx = (now // config.bucket_ms) % config.n_buckets
    est_cur = gather_current_estimate(config, state.counts, rule_slot, idx,
                                      cur_idx)  # [N] int32
    live = valid & (rule_slot >= 0)
    safe_slot = jnp.where(rule_slot >= 0, rule_slot, 0)
    ds_ar = jnp.arange(config.slim_depth)[None, :]
    vals = jnp.where(live, est_cur, 0)[:, None].repeat(config.slim_depth, 1)
    return state.slim.at[
        safe_slot[:, None], cur_idx, ds_ar, idx_slim
    ].max(vals, mode="drop")


def slim_estimate_np(config, state, value_hashes: np.ndarray,
                     now: int) -> np.ndarray:
    """Host-side windowed slim estimate (parity harness / drills): min over
    slim lanes of the live-bucket sums, ignoring authority flags — this
    queries the twin as a standalone sketch."""
    idx = slim_indices(config, value_hashes)  # [N, Ds]
    starts = np.asarray(state.starts)
    slim = np.asarray(state.slim)  # [P, B, Ds, Ws]
    age = int(now) - starts
    live = (age >= 0) & (age < config.interval_ms)  # [B]
    # windowed per-lane sums for slot 0 ... caller picks the slot
    return idx, starts, slim, live


def slim_query_np(config, state, slot: int, value_hashes: np.ndarray,
                  now: int) -> np.ndarray:
    """``[N] int64 -> [N] int64`` standalone slim estimates for one slot."""
    idx, _starts, slim, live = slim_estimate_np(
        config, state, value_hashes, now
    )
    row = slim[int(slot)]  # [B, Ds, Ws]
    winsum = (row * live[:, None, None]).sum(axis=0)  # [Ds, Ws]
    per_d = winsum[np.arange(config.slim_depth)[None, :], idx]  # [N, Ds]
    return per_d.min(axis=1)
