"""SALSA self-adjusting counters for the param sketch (arXiv:2102.12531).

Same HBM bytes as the plain int32 CMS, twice the cells: ``counts`` becomes
``[P, B, depth, 2*width]`` **int16**. Cold traffic enjoys 2× the key
cardinality; when a cell saturates, it merges with its pair neighbor into
one double-width logical counter, degrading resolution only where the
counts are hot enough not to need it.

The merge state is encoded **in-band** — no side bitmaps to allocate, ship,
or keep in sync with serialization:

- unmerged pair ``(2p, 2p+1)``: two independent int16 counters, each held
  below ``SAT`` (merge threshold) by the merge-after-batch discipline;
- merged pair: the logical value ``v`` is split as ``cells[2p] = v % CAP``
  and ``cells[2p+1] = -(v // CAP) - 1`` — the negative high half *is* the
  merge flag (live counters are never negative), giving ``CAP * 32767``
  (~134M) of headroom per merged pair.

Updates and queries stay pure gather/scatter plus elementwise fixups over
the current-bucket plane, so the XLA core below and the Pallas kernel in
``ops/salsa_pallas.py`` share the exact same decide/update semantics as the
plain CMS paths. One-sidedness: a merge stores ``max`` of the two cells
(each an upper bound of its own key set, so the max upper-bounds the
union), the bucket roll zeroes int16 cells exactly like int32 ones, and
saturating arithmetic only ever clamps at the ~134M ceiling — far above any
admissible window threshold (docs/SKETCHES.md).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

LOGCAP = 12
CAP = 1 << LOGCAP  # low-half radix of a merged pair
SAT = 1 << 14  # merge threshold: cell > SAT after a batch ⇒ merge its pair
MERGE_CEIL = CAP * 32767 - 1  # merged-pair clamp (~134M)


def _interleave(even, odd):
    """[..., W], [..., W] -> [..., 2W] with even/odd lanes restored."""
    return jnp.stack([even, odd], axis=-1).reshape(
        even.shape[:-1] + (even.shape[-1] * 2,)
    )


def decode_plane(cells: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """``[..., 2W] int16 -> (dec [..., 2W] int32, merged [..., W] bool)``.

    Scatter-accumulation form: a merged pair carries its full logical value
    at the EVEN cell (odd cell decodes to 0), so routed adds accumulate in
    one place and re-encoding is a pure elementwise split.
    """
    c = cells.astype(jnp.int32)
    lo, hi = c[..., 0::2], c[..., 1::2]
    merged = hi < 0
    mval = lo + CAP * (-hi - 1)
    even = jnp.where(merged, mval, lo)
    odd = jnp.where(merged, 0, hi)
    return _interleave(even, odd), merged


def encode_plane(dec: jax.Array,
                 merged: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Inverse of :func:`decode_plane` plus merge-on-saturation.

    ``-> (cells int16, newly_merged [..., W] bool)``. An unmerged pair with
    either side above ``SAT`` merges, taking ``max`` of the two (both are
    upper bounds of their own key sets; max upper-bounds the union, so no
    key ever undercounts).
    """
    ev, od = dec[..., 0::2], dec[..., 1::2]
    newly = (~merged) & ((ev > SAT) | (od > SAT))
    m2 = merged | newly
    val = jnp.where(newly, jnp.maximum(ev, od), ev)
    val = jnp.minimum(val, MERGE_CEIL)
    lo16 = jnp.where(m2, val % CAP, ev).astype(jnp.int16)
    hi16 = jnp.where(m2, -(val // CAP) - 1, od).astype(jnp.int16)
    return _interleave(lo16, hi16), newly


def decode_cells_np(cells: np.ndarray) -> np.ndarray:
    """Host mirror for export paths: ``[..., 2W] int16 -> [..., 2W] int32``
    per-cell *query* values — both cells of a merged pair read the merged
    value, exactly what a gather at either index would see."""
    c = cells.astype(np.int64)
    lo, hi = c[..., 0::2], c[..., 1::2]
    merged = hi < 0
    mval = lo + CAP * (-hi - 1)
    even = np.where(merged, mval, lo)
    odd = np.where(merged, mval, hi)
    out = np.empty(c.shape, np.int32)
    out[..., 0::2] = even
    out[..., 1::2] = odd
    return out


@partial(jax.jit, static_argnames=("config",))
def salsa_decide_jax(
    config, state, rule_slot, idx, acquire, threshold, valid, now
):
    """Same contract as ``engine.param._param_decide_jax`` over the SALSA
    encoding: gathers decode pairwise in-flight; the update decodes the
    current-bucket plane, scatter-adds with merged pairs routed to their
    even cell, and re-encodes with merge-on-saturation. ``state.merges``
    accumulates newly merged pairs per slot."""
    from sentinel_tpu.engine.prefix import segment_prefix_builder

    now = jnp.asarray(now, jnp.int32)
    B = config.n_buckets
    cur_idx = (now // config.bucket_ms) % B
    cur_start = now - now % config.bucket_ms

    stale = state.starts[cur_idx] != cur_start
    counts = jnp.where(
        (jnp.arange(B)[None, :, None, None] == cur_idx) & stale,
        0,
        state.counts,
    )  # zeroed int16 cells are unmerged zeros — the roll clears merge state
    starts = state.starts.at[cur_idx].set(cur_start)

    age = now - starts
    bucket_ok = (age >= 0) & (age < config.interval_ms)  # [B]

    safe_slot = jnp.where(rule_slot >= 0, rule_slot, 0)
    live = valid & (rule_slot >= 0)
    d_ar = jnp.arange(config.depth)[None, :]  # [1, D]
    pair = (idx // 2) * 2  # [N, D] even cell of each index's pair

    def gather_dec(b):
        # decode only the gathered pairs: two int16 gathers per lane
        lo = counts[safe_slot[:, None], b, d_ar, pair].astype(jnp.int32)
        hi = counts[safe_slot[:, None], b, d_ar, pair + 1].astype(jnp.int32)
        merged = hi < 0
        mval = lo + CAP * (-hi - 1)
        own = jnp.where(idx % 2 == 0, lo, hi)
        return jnp.where(merged, mval, own) * bucket_ok[b].astype(jnp.int32)

    sums = sum(gather_dec(b) for b in range(B))  # [N, D]
    estimate = jnp.min(sums, axis=1)  # [N]

    # in-batch prefix admission — identical discipline to the cms core
    key = safe_slot
    for d in range(config.depth):
        key = key * jnp.int32(-1640531527) + idx[:, d]
    seg_prefix = segment_prefix_builder(key, "sort")
    acq = acquire.astype(jnp.int32)
    admit = live
    for _ in range(3):  # odd refinement ⇒ never overshoot (see decide.py)
        contrib = jnp.where(admit, acq, 0)
        prefix = seg_prefix(contrib)
        admit = live & (
            estimate.astype(jnp.float32) + prefix + acq.astype(jnp.float32)
            <= threshold
        )

    # update: decode current plane → routed scatter → re-encode (merges)
    cur_plane = jnp.take(counts, cur_idx, axis=1)  # [P, D, 2W] int16
    dec_cur, merged_cur = decode_plane(cur_plane)  # int32 / [P, D, W] bool
    m_req = merged_cur[safe_slot[:, None], d_ar, idx // 2]  # [N, D]
    idx_eff = jnp.where(m_req, pair, idx)
    upd_vals = jnp.where(admit, acq, 0)[:, None].repeat(config.depth, 1)
    dec_cur = dec_cur.at[
        safe_slot[:, None], d_ar, idx_eff
    ].add(upd_vals, mode="drop")
    new_plane, newly = encode_plane(dec_cur, merged_cur)
    counts = counts.at[:, cur_idx].set(new_plane)
    merges = state.merges + newly.sum(axis=(1, 2)).astype(jnp.int32)

    return (
        state._replace(starts=starts, counts=counts, merges=merges),
        admit,
        estimate,
    )


@partial(jax.jit, static_argnames=("config",))
def salsa_decide_pallas(
    config, state, rule_slot, idx, acquire, threshold, valid, now
):
    """SALSA via the VMEM-resident one-hot-matmul kernel
    (``ops/salsa_pallas.py``); plane-major ``[B*D, P, 2W]`` at the
    boundary, exactly like the cms pallas wrapper."""
    from sentinel_tpu.ops.salsa_pallas import salsa_decide_update_pallas

    P, B, D = config.max_param_rules, config.n_buckets, config.depth
    C = config.cell_width  # 2W int16 cells
    planes = jnp.transpose(state.counts, (1, 2, 0, 3)).reshape(B * D, P, C)
    planes, starts, admit, est, merge_delta = salsa_decide_update_pallas(
        planes,
        state.starts,
        rule_slot,
        idx,
        acquire,
        threshold,
        valid,
        now,
        P=P,
        B=B,
        D=D,
        C=C,
        bucket_ms=config.bucket_ms,
        interpret=jax.default_backend() != "tpu",
    )
    counts = jnp.transpose(planes.reshape(B, D, P, C), (2, 0, 1, 3))
    return (
        state._replace(
            starts=starts, counts=counts, merges=state.merges + merge_delta
        ),
        admit,
        est,
    )
