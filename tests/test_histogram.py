"""LatencyHistogram: bucket layout, quantiles, concurrency, exposition."""

import threading

import pytest

from sentinel_tpu.metrics.histogram import LatencyHistogram, log_buckets


class TestLogBuckets:
    def test_boundaries_geometric_and_rounded(self):
        bounds = log_buckets(0.01, 100.0, per_decade=2)
        assert bounds[0] == 0.01
        assert bounds[-1] == 100.0
        assert len(bounds) == 9  # 4 decades × 2 + the closing bound
        assert all(b2 > b1 for b1, b2 in zip(bounds, bounds[1:]))
        # intermediate bounds are rounded to 4 significant digits so the
        # rendered `le` labels stay stable and readable
        assert 0.03162 in bounds
        assert 31.62 in bounds

    def test_bad_specs_raise(self):
        with pytest.raises(ValueError):
            log_buckets(0, 10)
        with pytest.raises(ValueError):
            log_buckets(10, 10)
        with pytest.raises(ValueError):
            log_buckets(1, 10, per_decade=0)

    def test_bad_explicit_bounds_raise(self):
        with pytest.raises(ValueError):
            LatencyHistogram(bounds=[])
        with pytest.raises(ValueError):
            LatencyHistogram(bounds=[2.0, 1.0])
        with pytest.raises(ValueError):
            LatencyHistogram(bounds=[0.0, 1.0])


class TestRecording:
    def test_le_inclusive_bucketing(self):
        h = LatencyHistogram(bounds=[1.0, 10.0, 100.0])
        h.record(1.0)  # value == bound lands in that bucket (le semantics)
        h.record(1.5)
        h.record(100.0)
        h.record(1000.0)  # above the last bound → +Inf overflow
        text = h.render_prometheus("x_ms", "t")
        assert 'x_ms_bucket{le="1"} 1' in text
        assert 'x_ms_bucket{le="10"} 2' in text
        assert 'x_ms_bucket{le="100"} 3' in text
        assert 'x_ms_bucket{le="+Inf"} 4' in text
        assert "x_ms_count 4" in text

    def test_rejects_negative_nan_and_nonpositive_n(self):
        h = LatencyHistogram(bounds=[1.0])
        h.record(-0.5)
        h.record(float("nan"))
        h.record(1.0, n=0)
        h.record(1.0, n=-3)
        assert h.count == 0
        assert h.snapshot()["p50"] is None

    def test_weighted_record_and_reset(self):
        h = LatencyHistogram(bounds=[1.0, 2.0])
        h.record(0.5, n=10)
        assert h.count == 10
        assert h.sum == pytest.approx(5.0)
        h.reset()
        assert h.count == 0
        assert h.snapshot()["count"] == 0


class TestQuantiles:
    def test_empty_snapshot(self):
        snap = LatencyHistogram().snapshot()
        assert snap["count"] == 0
        assert snap["avg"] is None
        assert snap["p50"] is None
        assert snap["max"] is None

    def test_interpolation_stays_inside_bucket(self):
        h = LatencyHistogram(bounds=[1.0, 2.0, 4.0, 8.0])
        for _ in range(100):
            h.record(1.5)
        snap = h.snapshot()
        assert snap["count"] == 100
        assert snap["avg"] == pytest.approx(1.5)
        assert snap["max"] == 1.5
        # all mass in (1, 2]; interpolation is clamped to the observed max
        assert 1.0 <= snap["p50"] <= 1.5
        assert 1.0 <= snap["p99"] <= 1.5

    def test_quantiles_order_across_buckets(self):
        h = LatencyHistogram(bounds=[1.0, 2.0, 4.0, 8.0, 16.0])
        for v in (0.5, 1.5, 3.0, 6.0, 12.0):
            h.record(v, n=20)
        p50, p90, p99 = h.quantile(0.5), h.quantile(0.9), h.quantile(0.99)
        assert p50 <= p90 <= p99 <= 12.0

    def test_overflow_bucket_clamps_to_observed_max(self):
        h = LatencyHistogram(bounds=[1.0, 10.0])
        h.record(5_000.0)
        assert h.snapshot()["max"] == 5_000.0
        # an outlier reports its real magnitude, not "somewhere above 10"
        assert 10.0 <= h.quantile(0.5) <= 5_000.0
        assert h.quantile(0.99) <= 5_000.0


class TestConcurrentRecording:
    def test_no_lost_counts_under_contention(self):
        h = LatencyHistogram(bounds=[1.0, 2.0, 4.0])
        n_threads, per_thread = 8, 5_000

        def pump(k: int) -> None:
            v = 0.5 * (k % 4 + 1)  # 0.5 / 1.0 / 1.5 / 2.0 — spread buckets
            for _ in range(per_thread):
                h.record(v)

        threads = [
            threading.Thread(target=pump, args=(k,)) for k in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert h.count == n_threads * per_thread
        # two threads per value → Σ = 2 × per_thread × (0.5+1+1.5+2)
        assert h.sum == pytest.approx(2 * per_thread * 5.0)
        text = h.render_prometheus("c_ms", "t")
        assert f'c_ms_bucket{{le="+Inf"}} {n_threads * per_thread}' in text


class TestRenderPrometheus:
    def test_labels_merge_with_le(self):
        h = LatencyHistogram(bounds=[1.0, 2.0])
        h.record(1.5)
        text = h.render_prometheus("y_ms", "help here", labels='stage="decide"')
        assert "# HELP y_ms help here" in text
        assert "# TYPE y_ms histogram" in text
        assert 'y_ms_bucket{stage="decide",le="1"} 0' in text
        assert 'y_ms_bucket{stage="decide",le="2"} 1' in text
        assert 'y_ms_bucket{stage="decide",le="+Inf"} 1' in text
        assert 'y_ms_sum{stage="decide"} 1.5' in text
        assert 'y_ms_count{stage="decide"} 1' in text

    def test_buckets_are_cumulative(self):
        h = LatencyHistogram(bounds=[1.0, 2.0, 4.0])
        for v in (0.5, 1.5, 3.0, 9.0):
            h.record(v)
        text = h.render_prometheus("z_ms", "t")
        counts = [
            int(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith("z_ms_bucket")
        ]
        assert counts == [1, 2, 3, 4]
        assert all(b >= a for a, b in zip(counts, counts[1:]))
