"""Detached (async) entries: out-of-order and cross-thread completion.

reference: ``SphU.asyncEntry`` / ``AsyncEntry.java`` — the guard verdict is
taken synchronously, completion happens elsewhere.
"""

import threading

import pytest

from sentinel_tpu.local import context as ctx_mod
from sentinel_tpu.local.chain import get_cluster_node, reset_cluster_nodes_for_tests
from sentinel_tpu.local.flow import FlowRuleManager
from sentinel_tpu.local.sph import async_entry, entry, sph


@pytest.fixture(autouse=True)
def clean(manual_clock):
    manual_clock.set_ms(10_000)
    yield
    FlowRuleManager.reset_for_tests()
    reset_cluster_nodes_for_tests()
    sph().reset_for_tests()
    ctx_mod.reset_for_tests()


class TestAsyncEntry:
    def test_out_of_order_completion_keeps_stats_straight(self, manual_clock):
        a = async_entry("rpc-a")
        b = async_entry("rpc-b")
        # caller's stack is clean: a plain sync entry nests normally
        with entry("sync-work"):
            pass
        node_a = get_cluster_node("rpc-a")
        node_b = get_cluster_node("rpc-b")
        assert node_a.cur_thread_num == 1 and node_b.cur_thread_num == 1
        manual_clock.sleep(50)
        a.exit()  # A completes first — B must stay live
        assert node_a.cur_thread_num == 0
        assert node_b.cur_thread_num == 1
        assert not b._exited
        manual_clock.sleep(100)
        b.exit()
        assert node_b.cur_thread_num == 0
        # RT covers each call's real duration
        assert node_a.avg_rt() == pytest.approx(50.0)
        assert node_b.avg_rt() == pytest.approx(150.0)

    def test_foreign_thread_completion_preserves_caller_context(self):
        ctx_mod.enter("caller_ctx")
        e = async_entry("bg-op")
        marker = {}

        def completer():
            ctx_mod.enter("worker_ctx")
            e.exit()
            # the worker's own context must survive the foreign exit
            marker["worker_ctx"] = ctx_mod.get_context().name
            ctx_mod.exit()

        t = threading.Thread(target=completer)
        t.start()
        t.join()
        assert marker["worker_ctx"] == "worker_ctx"
        assert ctx_mod.get_context().name == "caller_ctx"
        ctx_mod.exit()

    def test_error_traced_on_late_completion(self):
        e = async_entry("failing-rpc")
        e.trace(RuntimeError("downstream died"))
        e.exit()
        node = get_cluster_node("failing-rpc")
        assert node.exception_qps() > 0
