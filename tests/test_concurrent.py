"""Cluster concurrency (semaphore) mode tests.

Mirrors the reference's ``ConcurrentClusterFlowCheckerTest`` /
``CurrentConcurrencyManagerTest`` / ``TokenCacheNodeManagerTest`` strategy:
checker semantics with an explicit clock, expiry without real sleeps, and
(beyond the reference) one wire-level round-trip test.
"""

import pytest

from sentinel_tpu.cluster.concurrent import (
    ConcurrencyManager,
    ConcurrentFlowRule,
    ExpiryTask,
)
from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.server import TokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.engine import EngineConfig, TokenStatus
from sentinel_tpu.engine.rules import ThresholdMode

T0 = 1_700_000_000_000


@pytest.fixture
def mgr():
    m = ConcurrencyManager()
    m.load_rules(
        [
            ConcurrentFlowRule(flow_id=1, concurrency_level=3),
            ConcurrentFlowRule(
                flow_id=2, concurrency_level=2, mode=ThresholdMode.AVG_LOCAL
            ),
            ConcurrentFlowRule(flow_id=3, concurrency_level=5, resource_timeout_ms=100),
        ]
    )
    return m


class TestAcquireRelease:
    def test_admit_up_to_level_then_block(self, mgr):
        results = [mgr.acquire(1, now_ms=T0) for _ in range(4)]
        assert [r.status for r in results[:3]] == [TokenStatus.OK] * 3
        assert results[3].status == TokenStatus.BLOCKED
        assert mgr.now_calls(1) == 3
        assert results[0].remaining == 2 and results[2].remaining == 0

    def test_release_frees_permit(self, mgr):
        r1 = mgr.acquire(1, now_ms=T0)
        assert mgr.release(r1.token_id) == TokenStatus.RELEASE_OK
        assert mgr.now_calls(1) == 0
        assert mgr.acquire(1, now_ms=T0).status == TokenStatus.OK

    def test_double_release_is_idempotent(self, mgr):
        r = mgr.acquire(1, now_ms=T0)
        assert mgr.release(r.token_id) == TokenStatus.RELEASE_OK
        assert mgr.release(r.token_id) == TokenStatus.ALREADY_RELEASE
        assert mgr.now_calls(1) == 0  # no double decrement

    def test_weighted_acquire(self, mgr):
        assert mgr.acquire(1, acquire=2, now_ms=T0).status == TokenStatus.OK
        assert mgr.acquire(1, acquire=2, now_ms=T0).status == TokenStatus.BLOCKED
        assert mgr.acquire(1, acquire=1, now_ms=T0).status == TokenStatus.OK

    def test_no_rule(self, mgr):
        assert mgr.acquire(99, now_ms=T0).status == TokenStatus.NO_RULE_EXISTS

    def test_avg_local_scales_with_connected_count(self, mgr):
        # level 2 × 3 clients = 6 permits
        mgr.set_connected_count(3)
        results = [mgr.acquire(2, now_ms=T0) for _ in range(7)]
        assert sum(r.status == TokenStatus.OK for r in results) == 6
        assert results[6].status == TokenStatus.BLOCKED


class TestExpiry:
    def test_expired_tokens_reclaimed(self, mgr):
        for _ in range(5):
            assert mgr.acquire(3, now_ms=T0).status == TokenStatus.OK
        assert mgr.acquire(3, now_ms=T0).status == TokenStatus.BLOCKED
        # resource_timeout_ms=100: all expire by T0+101
        reclaimed = mgr.expire(now_ms=T0 + 101)
        assert reclaimed == 5
        assert mgr.now_calls(3) == 0
        assert mgr.acquire(3, now_ms=T0 + 101).status == TokenStatus.OK

    def test_release_after_expiry_reports_already_release(self, mgr):
        r = mgr.acquire(3, now_ms=T0)
        mgr.expire(now_ms=T0 + 200)
        assert mgr.release(r.token_id) == TokenStatus.ALREADY_RELEASE
        assert mgr.now_calls(3) == 0

    def test_acquire_sweeps_amortized(self, mgr):
        # a crashed client's stale permits are reclaimed by the next acquire
        for _ in range(5):
            mgr.acquire(3, now_ms=T0)
        r = mgr.acquire(3, now_ms=T0 + 150)  # after TTL: sweep frees all 5
        assert r.status == TokenStatus.OK
        assert mgr.now_calls(3) == 1

    def test_mixed_ttls_sweep_all_expired(self):
        m = ConcurrencyManager()
        m.load_rules(
            [
                ConcurrentFlowRule(1, 10, resource_timeout_ms=1000),
                ConcurrentFlowRule(2, 10, resource_timeout_ms=50),
            ]
        )
        m.acquire(1, now_ms=T0)  # long TTL issued first
        m.acquire(2, now_ms=T0)  # short TTL second
        assert m.expire(now_ms=T0 + 100) == 1  # only flow 2's token expired
        assert m.now_calls(1) == 1 and m.now_calls(2) == 0

    def test_full_scan_reclaims_behind_long_ttl_wall(self):
        # expired short-TTL tokens sitting behind >limit live long-TTL tokens
        # must still be reclaimed by the unbounded background sweep
        m = ConcurrencyManager()
        m.load_rules(
            [
                ConcurrentFlowRule(1, 500, resource_timeout_ms=3_600_000),
                ConcurrentFlowRule(2, 5, resource_timeout_ms=100),
            ]
        )
        for _ in range(200):  # long-TTL wall issued first
            m.acquire(1, now_ms=T0)
        for _ in range(5):
            m.acquire(2, now_ms=T0)
        assert m.expire(now_ms=T0 + 200, limit=64) == 0  # bounded scan misses
        assert m.expire(now_ms=T0 + 200) == 5  # full scan reclaims
        assert m.now_calls(2) == 0 and m.now_calls(1) == 200

    def test_expiry_task_lifecycle(self, mgr):
        task = ExpiryTask(mgr, interval_s=0.01)
        task.start()
        task.stop()  # no deadlock / thread leak


class TestWire:
    def test_acquire_release_over_socket(self):
        svc = DefaultTokenService(EngineConfig(max_flows=8, max_namespaces=2, batch_size=8))
        svc.load_concurrent_rules([ConcurrentFlowRule(flow_id=7, concurrency_level=2)])
        server = TokenServer(svc, port=0, batch_window_ms=0.5)
        server.start()
        client = TokenClient("127.0.0.1", server.port, timeout_ms=2000)
        try:
            r1 = client.request_concurrent_token(7)
            r2 = client.request_concurrent_token(7)
            r3 = client.request_concurrent_token(7)
            assert r1.ok and r2.ok
            assert r1.token_id > 0 and r1.token_id != r2.token_id
            assert r3.status == TokenStatus.BLOCKED
            assert client.release_concurrent_token(r1.token_id).status == TokenStatus.RELEASE_OK
            assert client.request_concurrent_token(7).ok
            assert client.release_concurrent_token(r1.token_id).status == TokenStatus.ALREADY_RELEASE
        finally:
            client.close()
            server.stop()
