"""Window kernel tests — the analog of the reference's LeapArray test suite
(``sentinel-core/src/test/.../slots/statistic/base/LeapArrayTest.java``,
``BucketLeapArrayTest``), with explicit time instead of a mocked clock."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentinel_tpu.stats import window as W
from sentinel_tpu.stats.events import Event, N_EVENTS

SPEC = W.WindowSpec(bucket_ms=500, n_buckets=2)  # second-level default: 1000ms/2
R = 8


def add_pass(ws, now, res, n=1):
    return W.add_events(
        SPEC,
        ws,
        jnp.int32(now),
        jnp.array([res], jnp.int32),
        jnp.array([Event.PASS], jnp.int32),
        jnp.array([n], jnp.int32),
    )


def pass_sum(ws, now):
    return np.asarray(W.window_sum(SPEC, ws, jnp.int32(now), Event.PASS))


class TestBucketIndex:
    def test_ring_math(self):
        # mirrors LeapArrayTest.testCalculateTimeIdx / windowStart math
        idx, start = W.bucket_index(SPEC, jnp.int32(1_234))
        assert int(idx) == (1_234 // 500) % 2 == 0
        assert int(start) == 1_000

    def test_wraps(self):
        idx0, _ = W.bucket_index(SPEC, jnp.int32(0))
        idx1, _ = W.bucket_index(SPEC, jnp.int32(500))
        idx2, _ = W.bucket_index(SPEC, jnp.int32(1_000))
        assert int(idx0) == int(idx2) != int(idx1)


class TestAddAndSum:
    def test_counts_within_interval(self):
        ws = W.make_window(SPEC, R, N_EVENTS)
        now = 10_000
        ws = add_pass(ws, now, res=3, n=2)
        ws = add_pass(ws, now + 100, res=3)
        assert pass_sum(ws, now + 100)[3] == 3
        assert pass_sum(ws, now + 100)[0] == 0

    def test_window_slides_off(self):
        ws = W.make_window(SPEC, R, N_EVENTS)
        ws = add_pass(ws, 10_000, res=1, n=5)
        # still visible within the 1s interval
        assert pass_sum(ws, 10_900)[1] == 5
        # gone once the bucket's window start leaves (now - interval, now]
        assert pass_sum(ws, 11_500)[1] == 0

    def test_two_buckets_both_count(self):
        ws = W.make_window(SPEC, R, N_EVENTS)
        ws = add_pass(ws, 10_000, res=0, n=1)  # bucket A
        ws = add_pass(ws, 10_600, res=0, n=2)  # bucket B
        assert pass_sum(ws, 10_999)[0] == 3

    def test_stale_slot_reset_on_reuse(self):
        # After a full ring revolution the old slot must be zeroed when rewritten
        # (LeapArray.java:147-155 reset arm).
        ws = W.make_window(SPEC, R, N_EVENTS)
        ws = add_pass(ws, 10_000, res=2, n=7)
        ws = add_pass(ws, 11_000, res=2, n=1)  # same ring slot, one interval later
        assert pass_sum(ws, 11_000)[2] == 1

    def test_idle_gap_masked_on_read(self):
        # Counts written long ago must not reappear even without intervening writes.
        ws = W.make_window(SPEC, R, N_EVENTS)
        ws = add_pass(ws, 10_000, res=2, n=7)
        assert pass_sum(ws, 60_000)[2] == 0

    def test_batched_duplicate_accumulation(self):
        ws = W.make_window(SPEC, R, N_EVENTS)
        res = jnp.array([5, 5, 5, 1], jnp.int32)
        chan = jnp.array([Event.PASS, Event.PASS, Event.BLOCK, Event.PASS], jnp.int32)
        val = jnp.array([1, 2, 4, 8], jnp.int32)
        ws = W.add_events(SPEC, ws, jnp.int32(20_000), res, chan, val)
        assert pass_sum(ws, 20_000)[5] == 3
        assert np.asarray(W.window_sum(SPEC, ws, jnp.int32(20_000), Event.BLOCK))[5] == 4
        assert pass_sum(ws, 20_000)[1] == 8

    def test_valid_mask_respects_padding(self):
        ws = W.make_window(SPEC, R, N_EVENTS)
        res = jnp.array([5, 5], jnp.int32)
        chan = jnp.array([Event.PASS, Event.PASS], jnp.int32)
        val = jnp.array([1, 100], jnp.int32)
        ws = W.add_events(
            SPEC, ws, jnp.int32(20_000), res, chan, val,
            valid=jnp.array([True, False]),
        )
        assert pass_sum(ws, 20_000)[5] == 1

    def test_jit_compatible(self):
        fn = jax.jit(
            lambda ws, now, r, c, v: W.add_events(SPEC, ws, now, r, c, v)
        )
        ws = W.make_window(SPEC, R, N_EVENTS)
        ws = fn(
            ws,
            jnp.int32(10_000),
            jnp.array([0], jnp.int32),
            jnp.array([0], jnp.int32),
            jnp.array([3], jnp.int32),
        )
        assert pass_sum(ws, 10_000)[0] == 3


class TestReferenceParityWindowing:
    """Property test: tensor windows match a straightforward per-event replay
    (the oracle mirrors LeapArray read semantics)."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_replay(self, seed):
        rng = np.random.default_rng(seed)
        ws = W.make_window(SPEC, R, N_EVENTS)
        events = []  # (t, res, n)
        t = 5_000
        for _ in range(200):
            t += int(rng.integers(0, 180))
            res = int(rng.integers(0, R))
            n = int(rng.integers(1, 4))
            events.append((t, res, n))
            ws = add_pass(ws, t, res, n)
        now = t
        got = pass_sum(ws, now)
        # oracle: event counts whose *bucket window start* is within (now-interval, now]
        want = np.zeros(R, np.int64)
        for (et, res, n) in events:
            bstart = et - et % SPEC.bucket_ms
            if 0 <= now - bstart < SPEC.interval_ms:
                want[res] += n
        assert (got == want).all()


class TestFutureWindows:
    def test_add_future_and_sum(self):
        ws = W.make_window(SPEC, R, N_EVENTS)
        now = jnp.int32(10_000)
        ws = W.add_future(
            SPEC, ws, now,
            wait_ms=jnp.array([500], jnp.int32),
            resource_ids=jnp.array([4], jnp.int32),
            channel_ids=jnp.array([Event.OCCUPIED_PASS], jnp.int32),
            values=jnp.array([2], jnp.int32),
        )
        waiting = np.asarray(W.future_sum(SPEC, ws, now, Event.OCCUPIED_PASS))
        assert waiting[4] == 2
        # once time reaches the future bucket it is no longer "waiting"
        waiting_later = np.asarray(
            W.future_sum(SPEC, ws, jnp.int32(10_500), Event.OCCUPIED_PASS)
        )
        assert waiting_later[4] == 0
        # ...but it IS a valid current bucket now (borrowed tokens count as passed)
        cur = np.asarray(W.window_sum(SPEC, ws, jnp.int32(10_500), Event.OCCUPIED_PASS))
        assert cur[4] == 2

    def test_invalid_rows_do_not_reset_live_buckets(self):
        # regression: a valid=False (padded) row must not drive the slot-reset
        # union — previously it could wipe live current-window counts.
        ws = W.make_window(SPEC, R, N_EVENTS)
        ws = add_pass(ws, 10_000, res=1, n=5)
        ws = W.add_future(
            SPEC, ws, jnp.int32(10_000),
            wait_ms=jnp.array([SPEC.interval_ms], jnp.int32),  # maps onto current slot pre-clamp
            resource_ids=jnp.array([1], jnp.int32),
            channel_ids=jnp.array([Event.OCCUPIED_PASS], jnp.int32),
            values=jnp.array([3], jnp.int32),
            valid=jnp.array([False]),
        )
        assert pass_sum(ws, 10_000)[1] == 5

    def test_wait_clamped_to_ring_capacity(self):
        # regression: wait_ms large enough to wrap the ring must be clamped to
        # at most n_buckets-1 windows ahead, never colliding with the current slot.
        ws = W.make_window(SPEC, R, N_EVENTS)
        ws = add_pass(ws, 10_000, res=1, n=5)
        ws = W.add_future(
            SPEC, ws, jnp.int32(10_000),
            wait_ms=jnp.array([10 * SPEC.interval_ms], jnp.int32),
            resource_ids=jnp.array([1], jnp.int32),
            channel_ids=jnp.array([Event.OCCUPIED_PASS], jnp.int32),
            values=jnp.array([3], jnp.int32),
        )
        assert pass_sum(ws, 10_000)[1] == 5  # current bucket untouched
        waiting = np.asarray(W.future_sum(SPEC, ws, jnp.int32(10_000), Event.OCCUPIED_PASS))
        assert waiting[1] == 3  # landed in the farthest future slot instead

    def test_zero_wait_rows_masked(self):
        ws = W.make_window(SPEC, R, N_EVENTS)
        ws = W.add_future(
            SPEC, ws, jnp.int32(10_000),
            wait_ms=jnp.array([0, 500], jnp.int32),
            resource_ids=jnp.array([4, 4], jnp.int32),
            channel_ids=jnp.array([Event.OCCUPIED_PASS] * 2, jnp.int32),
            values=jnp.array([1, 10], jnp.int32),
        )
        waiting = np.asarray(W.future_sum(SPEC, ws, jnp.int32(10_000), Event.OCCUPIED_PASS))
        assert waiting[4] == 10

    def test_rebase(self):
        ws = W.make_window(SPEC, R, N_EVENTS)
        ws = add_pass(ws, 10_000, res=0, n=5)
        ws2 = W.rebase(ws, 4_000)
        assert pass_sum(ws2, 6_000)[0] == 5
