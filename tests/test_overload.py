"""Server-side overload protection: deadline-aware admission + shedding.

Covers the wire-codec deadline rev, the BBR admission controller in
isolation, the brownout ladder wired through both front doors (forced
levels via a fake controller), the queue-full OVERLOAD answer, the
deadline shed, failover's OVERLOAD-is-alive contract, the shed metrics
surface, and stop() under sustained load with full queues.
"""

import socket
import threading
import time

import numpy as np
import pytest

from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.server import TokenServer
from sentinel_tpu.cluster.server_native import (
    NativeTokenServer,
    native_available,
)
from sentinel_tpu.cluster.token_service import DefaultTokenService, TokenResult
from sentinel_tpu.engine import ClusterFlowRule, EngineConfig, TokenStatus
from sentinel_tpu.engine.rules import ThresholdMode
from sentinel_tpu.ha.failover import FailoverTokenClient
from sentinel_tpu.metrics.ha import ha_metrics
from sentinel_tpu.metrics.server import ServerMetrics, server_metrics
from sentinel_tpu.overload import (
    AdmissionController,
    BrownoutLevel,
    OverloadConfig,
)

G = ThresholdMode.GLOBAL
CFG = EngineConfig(max_flows=64, max_namespaces=4, batch_size=256)
_OVL = int(TokenStatus.OVERLOAD)


def _service(count=1e9):
    svc = DefaultTokenService(CFG)
    svc.load_rules([ClusterFlowRule(flow_id=1, count=count, mode=G)])
    return svc


@pytest.fixture(scope="module")
def module_svc():
    # one service (= one decide-kernel compile) for every server test in
    # this module; each test builds its own front door around it
    return _service()


@pytest.fixture
def svc(module_svc):
    """The shared service, with dispatch wrapper + rules restored after
    each test (tests stop their servers before teardown runs)."""
    orig = module_svc.dispatch_batch_arrays
    yield module_svc
    module_svc.dispatch_batch_arrays = orig
    module_svc.load_rules([ClusterFlowRule(flow_id=1, count=1e9, mode=G)])


def _slow_materialize(svc, delay_s):
    """Wrap the service's dispatch so materialization (the device wait,
    which the asyncio loop offloads to a thread) takes ``delay_s``."""
    orig = svc.dispatch_batch_arrays

    def slow_dispatch(ids, counts, prios):
        mat = orig(ids, counts, prios)

        def slow_mat():
            time.sleep(delay_s)
            return mat()

        return slow_mat

    svc.dispatch_batch_arrays = slow_dispatch


class _FakeController(AdmissionController):
    """Pinned brownout level — tests the wiring, not the estimator."""

    def __init__(self, lvl, admit_frac=1.0):
        super().__init__(config=OverloadConfig(), metrics=ServerMetrics())
        self._forced = lvl
        self._admit_frac = admit_frac

    def level(self, now=None):
        return self._forced


# -- codec rev: optional deadline trailer -----------------------------------
class TestDeadlineCodec:
    def test_deadline_roundtrip(self):
        ids = np.array([1, 2, 3], np.int64)
        payload = P.encode_batch_request(7, ids, deadline_ms=1234)[2:]
        xid, got_ids, counts, prios = P.decode_batch_request(payload)
        assert xid == 7 and got_ids.tolist() == [1, 2, 3]
        assert P.decode_batch_deadline(payload) == 1234

    def test_legacy_frame_reads_zero(self):
        payload = P.encode_batch_request(9, np.array([5], np.int64))[2:]
        assert P.decode_batch_deadline(payload) == 0

    def test_deadline_saturates_at_uint32(self):
        payload = P.encode_batch_request(
            1, np.array([1], np.int64), deadline_ms=2**40
        )[2:]
        assert P.decode_batch_deadline(payload) == 0xFFFFFFFF

    def test_trailer_invisible_to_row_decode(self):
        # rev-1 decoders read n rows and ignore trailing bytes — the
        # back-compat contract the rev relies on
        ids = np.arange(10, dtype=np.int64)
        with_dl = P.encode_batch_request(3, ids, deadline_ms=500)[2:]
        without = P.encode_batch_request(3, ids)[2:]
        a = P.decode_batch_request(with_dl)
        b = P.decode_batch_request(without)
        assert a[0] == b[0]
        for x, y in zip(a[1:], b[1:]):
            assert np.array_equal(x, y)


# -- the admission controller in isolation ----------------------------------
class TestAdmissionController:
    def test_inflight_accounting_clamps(self):
        ctl = AdmissionController(
            config=OverloadConfig(), metrics=ServerMetrics()
        )
        ctl.note_enqueued(5)
        assert ctl.inflight == 5
        ctl.note_done(3)
        assert ctl.inflight == 2
        ctl.note_done(10)  # lost accounting must not go negative
        assert ctl.inflight == 0

    def test_level_ladder(self):
        cfg = OverloadConfig(
            headroom_shed=2.0, headroom_degrade=4.0, min_bdp=10.0,
            recheck_ms=0.0, sustain_ms=0.0,
        )
        ctl = AdmissionController(config=cfg, metrics=ServerMetrics())
        # idle metrics → BDP == min_bdp == 10
        assert ctl.level() == BrownoutLevel.NORMAL
        ctl.note_enqueued(21)  # > 2 × 10
        assert ctl.level() == BrownoutLevel.SHED_LOW
        ctl.note_enqueued(20)  # 41 > 4 × 10
        assert ctl.level() == BrownoutLevel.DEGRADE
        ctl.note_done(41)
        assert ctl.level() == BrownoutLevel.NORMAL

    def test_escalation_requires_sustained_pressure(self):
        cfg = OverloadConfig(
            headroom_shed=2.0, headroom_degrade=4.0, min_bdp=10.0,
            recheck_ms=0.0, sustain_ms=40.0,
        )
        ctl = AdmissionController(config=cfg, metrics=ServerMetrics())
        ctl.note_enqueued(100)
        # a fresh spike is NOT overload — a draining burst looks identical
        assert ctl.level() == BrownoutLevel.NORMAL
        time.sleep(0.06)
        assert ctl.level() == BrownoutLevel.DEGRADE
        # a dip below threshold resets the sustain clock
        ctl.note_done(100)
        assert ctl.level() == BrownoutLevel.NORMAL
        ctl.note_enqueued(100)
        assert ctl.level() == BrownoutLevel.NORMAL

    def test_disabled_never_sheds(self):
        cfg = OverloadConfig(enabled=False, min_bdp=1.0)
        ctl = AdmissionController(config=cfg, metrics=ServerMetrics())
        ctl.note_enqueued(10**6)
        assert ctl.level() == BrownoutLevel.NORMAL

    def test_shed_mask_shed_low_spares_prioritized(self):
        ctl = AdmissionController(
            config=OverloadConfig(), metrics=ServerMetrics()
        )
        prios = np.array([True, False, True, False])
        mask = ctl.shed_mask(prios, BrownoutLevel.SHED_LOW)
        assert mask.tolist() == [False, True, False, True]

    def test_degrade_verdicts_split(self):
        ctl = AdmissionController(
            config=OverloadConfig(retry_hint_ms=7), metrics=ServerMetrics()
        )
        shed = np.array([True, False, True])
        status, remaining, wait = ctl.degrade_verdicts(shed)
        assert status.tolist() == [_OVL, int(TokenStatus.OK), _OVL]
        assert wait.tolist() == [7, 0, 7]
        assert remaining.tolist() == [0, 0, 0]

    def test_degrade_mask_seeded_fraction(self):
        ctl = AdmissionController(
            config=OverloadConfig(), metrics=ServerMetrics(), seed=42
        )
        ctl._admit_frac = 0.5
        mask = ctl.shed_mask(np.zeros(2000, bool), BrownoutLevel.DEGRADE)
        frac_shed = mask.mean()
        assert 0.4 < frac_shed < 0.6  # sheds ~1 - admit_frac

    def test_snapshot_surface(self):
        ctl = AdmissionController(
            config=OverloadConfig(), metrics=ServerMetrics()
        )
        snap = ctl.snapshot()
        assert snap["levelName"] == "NORMAL"
        assert snap["inflight"] == 0 and snap["enabled"] is True


class TestWeightedShed:
    """Per-namespace share-weighted SHED_LOW: each tenant keeps a
    guaranteed ceil(share x N) rows of the batch; only its newest
    non-prioritized rows beyond that are shed."""

    @staticmethod
    def _ctl(shares, default=0.0):
        return AdmissionController(
            config=OverloadConfig(ns_shares=shares,
                                  ns_default_share=default),
            metrics=ServerMetrics(),
        )

    def test_flooding_tenant_sheds_beyond_share(self):
        ctl = self._ctl({"a": 0.25, "b": 0.25})
        # batch of 8: a floods with 6 rows, b sends 2
        ns_idx = np.array([0, 0, 0, 0, 0, 0, 1, 1], np.int32)
        prios = np.zeros(8, bool)
        mask = ctl.shed_mask(prios, BrownoutLevel.SHED_LOW,
                             ns_idx=ns_idx, ns_names=("a", "b"))
        # a's guarantee is ceil(0.25*8)=2: its 4 NEWEST rows are shed
        assert mask.tolist() == [False, False, True, True,
                                 True, True, False, False]

    def test_in_share_tenant_is_untouched(self):
        ctl = self._ctl({"a": 0.5, "b": 0.5})
        ns_idx = np.array([0, 0, 1, 1], np.int32)
        mask = ctl.shed_mask(np.zeros(4, bool), BrownoutLevel.SHED_LOW,
                             ns_idx=ns_idx, ns_names=("a", "b"))
        assert not mask.any()

    def test_prioritized_rows_never_shed_at_shed_low(self):
        ctl = self._ctl({"a": 0.0})
        ns_idx = np.zeros(4, np.int32)
        prios = np.array([True, True, True, False])
        mask = ctl.shed_mask(prios, BrownoutLevel.SHED_LOW,
                             ns_idx=ns_idx, ns_names=("a",))
        # only the single non-prioritized row is sheddable
        assert mask.tolist() == [False, False, False, True]

    def test_unattributed_rows_get_default_share(self):
        ctl = self._ctl({"a": 1.0}, default=0.0)
        # ns_idx -1 = no rule matched: with default share 0, all shed
        ns_idx = np.array([-1, -1, 0, 0], np.int32)
        mask = ctl.shed_mask(np.zeros(4, bool), BrownoutLevel.SHED_LOW,
                             ns_idx=ns_idx, ns_names=("a",))
        assert mask.tolist() == [True, True, False, False]

    def test_no_shares_falls_back_to_legacy(self):
        ctl = AdmissionController(
            config=OverloadConfig(), metrics=ServerMetrics())
        prios = np.array([True, False])
        mask = ctl.shed_mask(prios, BrownoutLevel.SHED_LOW,
                             ns_idx=np.zeros(2, np.int32), ns_names=("a",))
        assert mask.tolist() == [False, True]  # ~prios, as before

    def test_no_attribution_falls_back_to_legacy(self):
        ctl = self._ctl({"a": 1.0})
        prios = np.array([True, False])
        assert ctl.shed_mask(prios, BrownoutLevel.SHED_LOW).tolist() == [
            False, True]

    def test_set_shares_installs_and_clears(self):
        ctl = AdmissionController(
            config=OverloadConfig(), metrics=ServerMetrics())
        ctl.set_shares({"a": 0.5})
        assert ctl.snapshot()["nsShares"] == {"a": 0.5}
        ctl.set_shares(None)
        assert ctl.snapshot()["nsShares"] == {}

    def test_parse_shares(self):
        from sentinel_tpu.overload import parse_shares

        assert parse_shares("a=0.25, b=0.5") == {"a": 0.25, "b": 0.5}
        assert parse_shares("") == {}
        # malformed entries are dropped, negatives clamped to 0
        assert parse_shares("a=x,b=-1,=0.2,c=0.1") == {"b": 0.0, "c": 0.1}


# -- shed metrics surface ----------------------------------------------------
class TestShedMetrics:
    def test_count_and_render(self):
        m = ServerMetrics()
        m.count_shed("queue_full", 3)
        m.count_shed("deadline", 2)
        m.count_shed("deadline", -5)  # ignored
        assert m.shed_totals() == {"queue_full": 3, "deadline": 2}
        assert m.shed_total == 5
        text = m.render()
        assert 'sentinel_server_shed_total{reason="queue_full"} 3' in text
        assert 'sentinel_server_shed_total{reason="deadline"} 2' in text
        snap = m.snapshot()
        assert snap["shedTotal"] == 5
        assert snap["shedByReason"]["queue_full"] == 3

    def test_zero_sample_always_rendered(self):
        m = ServerMetrics()
        assert 'sentinel_server_shed_total{reason="queue_full"} 0' in m.render()


# -- asyncio front door: queue-full OVERLOAD + deadline shed ----------------
class TestAsyncioOverload:
    def test_queue_full_answers_overload(self, svc):
        _slow_materialize(svc, 0.15)
        server = TokenServer(
            svc, port=0, max_queue=1, max_inflight=1, max_batch=8,
            inline_below=0, batch_window_ms=0.0,
        )
        server.start()
        shed0 = server_metrics().shed_totals().get("queue_full", 0)
        results = [None] * 6
        try:
            def worker(i):
                c = TokenClient("127.0.0.1", server.port, timeout_ms=4000)
                try:
                    results[i] = c.request_batch_arrays(
                        np.full(8, 1, np.int64)
                    )
                finally:
                    c.close()

            threads = [
                threading.Thread(target=worker, args=(i,)) for i in range(6)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
        finally:
            server.stop()
        assert all(r is not None for r in results), "every request answered"
        all_status = np.concatenate([r[0] for r in results])
        assert (all_status == _OVL).sum() > 0, "some rows refused"
        assert server_metrics().shed_totals().get("queue_full", 0) > shed0
        # refused rows carry the retry hint
        hinted = np.concatenate([r[2] for r in results])[all_status == _OVL]
        assert (hinted == server.overload.retry_hint_ms).all()

    def test_expired_deadline_is_dropped_not_served(self, svc):
        _slow_materialize(svc, 0.25)
        server = TokenServer(
            svc, port=0, max_inflight=1, max_batch=8, inline_below=0,
            batch_window_ms=0.0,
        )
        server.start()
        shed0 = server_metrics().shed_totals().get("deadline", 0)
        try:
            s = socket.create_connection(("127.0.0.1", server.port), 3)
            s.settimeout(3.0)
            # frame A occupies the device for 300ms…
            s.sendall(P.encode_batch_request(1, np.array([1], np.int64)))
            time.sleep(0.1)  # let A get picked up
            # …frame B's 50ms budget expires while it waits in the queue
            s.sendall(
                P.encode_batch_request(
                    2, np.full(8, 1, np.int64), deadline_ms=50
                )
            )
            buf = b""
            xids = set()
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline and len(xids) < 1:
                try:
                    chunk = s.recv(65536)
                except socket.timeout:
                    break
                if not chunk:
                    break
                buf += chunk
                fr = P.FrameReader()
                for payload in fr.feed(buf):
                    xids.add(P.decode_batch_response(payload)[0])
            assert 1 in xids, "the live frame is answered"
            # B was shed: counted, and no response frame for xid 2
            assert 2 not in xids
            s.close()
        finally:
            server.stop()
        assert server_metrics().shed_totals().get("deadline", 0) >= shed0 + 8

    def test_shed_low_spares_prioritized_rows(self, svc):
        server = TokenServer(
            svc, port=0, overload=_FakeController(BrownoutLevel.SHED_LOW),
            inline_below=1024,
        )
        server.start()
        try:
            c = TokenClient("127.0.0.1", server.port, timeout_ms=3000)
            prios = np.array([True, False] * 8)
            out = c.request_batch_arrays(
                np.full(16, 1, np.int64), prios=prios
            )
            c.close()
        finally:
            server.stop()
        assert out is not None
        status = out[0]
        assert (status[~prios] == _OVL).all(), "non-prio rows refused"
        assert (status[prios] == int(TokenStatus.OK)).all(), "prio rows served"

    def test_degrade_answers_locally_without_device(self, svc):
        svc.load_rules(  # budget of ONE: device would block most
            [ClusterFlowRule(flow_id=1, count=1.0, mode=G)]
        )
        server = TokenServer(
            svc, port=0,
            overload=_FakeController(BrownoutLevel.DEGRADE, admit_frac=1.0),
        )
        server.start()
        try:
            c = TokenClient("127.0.0.1", server.port, timeout_ms=3000)
            out = c.request_batch_arrays(np.full(10, 1, np.int64))
            c.close()
        finally:
            server.stop()
        assert out is not None
        # every row passed locally — impossible via the device (budget 1),
        # so DEGRADE provably never consulted it
        assert (out[0] == int(TokenStatus.OK)).all()

    def test_stop_under_sustained_load_returns_promptly(self, svc):
        _slow_materialize(svc, 0.15)
        server = TokenServer(
            svc, port=0, max_queue=2, max_inflight=1, max_batch=8,
            inline_below=0,
        )
        server.start()
        stop_evt = threading.Event()

        def hammer():
            c = TokenClient("127.0.0.1", server.port, timeout_ms=300)
            while not stop_evt.is_set():
                c.request_batch_arrays(np.full(8, 1, np.int64))
            c.close()

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)  # queues full, device busy
        t0 = time.monotonic()
        server.stop()
        elapsed = time.monotonic() - t0
        stop_evt.set()
        for t in threads:
            t.join(timeout=5)
        assert elapsed < 10.0, f"stop() hung for {elapsed:.1f}s"


# -- native front door -------------------------------------------------------
@pytest.mark.skipif(not native_available(), reason="native library not built")
class TestNativeOverload:
    def test_intake_gives_up_and_answers_overload(self, svc):
        orig = svc.dispatch_batch_arrays

        def slow_dispatch(ids, counts, prios):
            time.sleep(0.15)  # stall the device lane (a thread, not a loop)
            return orig(ids, counts, prios)

        svc.dispatch_batch_arrays = slow_dispatch
        server = NativeTokenServer(
            svc, port=0, fuse_depth=1, n_dispatchers=1, shed_age_ms=100.0,
            idle_ttl_s=None,
        )
        server.start()
        shed0 = server_metrics().shed_totals()
        results = [None] * 6
        try:
            def worker(i):
                c = TokenClient("127.0.0.1", server.port, timeout_ms=6000)
                try:
                    results[i] = c.request_batch_arrays(
                        np.full(16, 1, np.int64)
                    )
                finally:
                    c.close()

            threads = [
                threading.Thread(target=worker, args=(i,)) for i in range(6)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
        finally:
            server.stop()
        assert all(r is not None for r in results), "every request answered"
        all_status = np.concatenate([r[0] for r in results])
        assert (all_status == _OVL).sum() > 0
        shed1 = server_metrics().shed_totals()
        sheds = sum(
            shed1.get(k, 0) - shed0.get(k, 0)
            for k in ("queue_full", "deadline")
        )
        assert sheds > 0

    def test_degrade_wiring(self, svc):
        svc.load_rules([ClusterFlowRule(flow_id=1, count=1.0, mode=G)])
        server = NativeTokenServer(
            svc, port=0,
            overload=_FakeController(BrownoutLevel.DEGRADE, admit_frac=1.0),
            idle_ttl_s=None,
        )
        server.start()
        try:
            c = TokenClient("127.0.0.1", server.port, timeout_ms=3000)
            out = c.request_batch_arrays(np.full(10, 1, np.int64))
            c.close()
        finally:
            server.stop()
        assert out is not None
        assert (out[0] == int(TokenStatus.OK)).all()

    def test_stop_under_sustained_load_respects_drain_timeout(self, svc):
        orig = svc.dispatch_batch_arrays

        def slow_dispatch(ids, counts, prios):
            time.sleep(0.12)
            return orig(ids, counts, prios)

        svc.dispatch_batch_arrays = slow_dispatch
        server = NativeTokenServer(
            svc, port=0, fuse_depth=1, n_dispatchers=1, shed_age_ms=100.0,
            drain_timeout_s=2.0, idle_ttl_s=None,
        )
        server.start()
        stop_evt = threading.Event()

        def hammer():
            c = TokenClient("127.0.0.1", server.port, timeout_ms=300)
            while not stop_evt.is_set():
                c.request_batch_arrays(np.full(8, 1, np.int64))
            c.close()

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        t0 = time.monotonic()
        server.stop()
        elapsed = time.monotonic() - t0
        stop_evt.set()
        for t in threads:
            t.join(timeout=5)
        # lanes get drain_timeout_s each + abandon grace; 4 lanes × 2s
        # bounds well under the hard ceiling
        assert elapsed < 15.0, f"stop() hung for {elapsed:.1f}s"


# -- failover: OVERLOAD is proof of life ------------------------------------
class _StubClient:
    """Per-port scripted endpoint client (failover walk tests)."""

    scripts = {}

    def __init__(self, host, port, timeout_ms=20, namespace="default"):
        self.port = port

    def _answer(self):
        r = self.scripts[self.port]
        return r() if callable(r) else r

    def request_token(self, flow_id, acquire=1, prioritized=False):
        return self._answer()

    def request_batch_arrays(self, flow_ids, acquires=None, prios=None,
                             timeout_ms=None):
        return self._answer()

    def close(self):
        pass


class TestFailoverOverload:
    def _fc(self, scripts):
        _StubClient.scripts = scripts
        return FailoverTokenClient(
            [("a", 1), ("b", 2)], client_factory=_StubClient,
            failure_threshold=3,
        )

    def test_overload_backs_off_to_standby_without_breaker_charge(self):
        fb0 = ha_metrics().fallback_totals().get("overload_backoff", 0)
        fc = self._fc({
            1: TokenResult(TokenStatus.OVERLOAD, wait_ms=5),
            2: TokenResult(TokenStatus.OK, remaining=9),
        })
        for _ in range(10):
            r = fc.request_token(1)
            assert r.status == TokenStatus.OK
        # the overloaded-but-alive primary was never evicted
        snap = fc.health_snapshot()
        assert snap[0]["state"] == "CLOSED"
        assert (
            ha_metrics().fallback_totals().get("overload_backoff", 0)
            >= fb0 + 10
        )

    def test_all_overloaded_returns_overload_not_fallback(self):
        fc = self._fc({
            1: TokenResult(TokenStatus.OVERLOAD, wait_ms=5),
            2: TokenResult(TokenStatus.OVERLOAD, wait_ms=7),
        })
        r = fc.request_token(1)
        # the explicit refusal (with its retry hint) surfaces to the caller
        assert r.status == TokenStatus.OVERLOAD
        assert r.wait_ms == 5
        snap = fc.health_snapshot()
        assert all(e["state"] == "CLOSED" for e in snap)

    def test_fully_overloaded_batch_walks_partial_returns(self):
        ovl = (
            np.full(4, _OVL, np.int8),
            np.zeros(4, np.int32),
            np.full(4, 5, np.int32),
        )
        ok = (
            np.zeros(4, np.int8),
            np.zeros(4, np.int32),
            np.zeros(4, np.int32),
        )
        fc = self._fc({1: ovl, 2: ok})
        st, _, _ = fc.request_batch_arrays(np.full(4, 1, np.int64))
        assert (st == 0).all(), "all-OVERLOAD batch walks to the standby"
        # partial overload is an ANSWER: returned as-is from the primary
        mixed = (
            np.array([0, _OVL, 0, _OVL], np.int8),
            np.zeros(4, np.int32),
            np.zeros(4, np.int32),
        )
        fc2 = self._fc({1: mixed, 2: ok})
        st2, _, _ = fc2.request_batch_arrays(np.full(4, 1, np.int64))
        assert st2.tolist() == [0, _OVL, 0, _OVL]
