"""Blocked (matmul/reduce) scan ops vs numpy oracles."""

import jax.numpy as jnp
import numpy as np
import pytest

from sentinel_tpu.ops.scan_mm import blocked_cummax, blocked_cumsum


class TestBlockedCumsum:
    @pytest.mark.parametrize("n", [1, 5, 128, 129, 1000, 4096])
    def test_1d(self, n):
        rng = np.random.default_rng(n)
        x = rng.integers(0, 100, n).astype(np.float32)
        got = np.asarray(blocked_cumsum(jnp.asarray(x)))
        np.testing.assert_allclose(got, np.cumsum(x), rtol=0, atol=0)

    @pytest.mark.parametrize("n,k", [(7, 3), (128, 64), (300, 5)])
    def test_2d(self, n, k):
        rng = np.random.default_rng(n * k)
        x = rng.integers(0, 50, (n, k)).astype(np.float32)
        got = np.asarray(blocked_cumsum(jnp.asarray(x)))
        np.testing.assert_allclose(got, np.cumsum(x, axis=0), rtol=0, atol=0)

    def test_small_block(self):
        x = np.arange(20, dtype=np.float32)
        got = np.asarray(blocked_cumsum(jnp.asarray(x), block=8))
        np.testing.assert_allclose(got, np.cumsum(x))


class TestBlockedCummax:
    @pytest.mark.parametrize("n", [1, 5, 128, 129, 1000, 4096])
    def test_1d(self, n):
        rng = np.random.default_rng(n)
        x = rng.normal(size=n).astype(np.float32) * 100
        got = np.asarray(blocked_cummax(jnp.asarray(x)))
        np.testing.assert_allclose(got, np.maximum.accumulate(x))

    def test_negative_heads(self):
        # the segment-rebase caller feeds -1 for non-head rows
        x = np.array([-1, 3, -1, -1, 7, -1, 2], dtype=np.float32)
        got = np.asarray(blocked_cummax(jnp.asarray(x), block=4))
        np.testing.assert_allclose(got, np.maximum.accumulate(x))
