"""Namespace partitioning (tier 2) tests.

Reference model: a namespace is served by exactly one token server
(``ClusterFlowRuleManager.java:67`` namespace→flowId sets,
``ConnectionManager.java:35`` namespace→connection groups, client assignment
config per namespace). These tests cover the ownership map, rule
partitioning, the routing client, connection groups fed by the PING
handshake, per-namespace isolation under shard movement, and the DCN-tier
metric aggregation.
"""

import threading

import pytest

from sentinel_tpu.cluster.connection import ConnectionManager
from sentinel_tpu.cluster.namespaces import (
    NamespaceAssignment,
    aggregate_snapshots,
    flow_namespaces,
    partition_rules,
)
from sentinel_tpu.cluster.routing import RoutingTokenClient
from sentinel_tpu.cluster.token_service import TokenResult, TokenService
from sentinel_tpu.engine import ClusterFlowRule, TokenStatus
from sentinel_tpu.engine.rules import ThresholdMode


class TestNamespaceAssignment:
    def test_assign_move_generation(self):
        a = NamespaceAssignment({"ns1": "pod0"})
        assert a.pod_of("ns1") == "pod0"
        assert a.generation == 0
        a.assign("ns2", "pod1")
        assert a.generation == 1
        a.move("ns2", "pod0")
        assert a.generation == 2
        assert a.namespaces_of("pod0") == ["ns1", "ns2"]
        a.assign("ns2", "pod0")  # no-op: same owner
        assert a.generation == 2
        a.unassign("ns1")
        assert a.pod_of("ns1") is None
        assert a.generation == 3

    def test_partition_rules_and_unassigned(self):
        a = NamespaceAssignment({"a": "pod0", "b": "pod1"})
        rules = [
            ClusterFlowRule(flow_id=1, count=1, namespace="a"),
            ClusterFlowRule(flow_id=2, count=1, namespace="b"),
            ClusterFlowRule(flow_id=3, count=1, namespace="a"),
            ClusterFlowRule(flow_id=4, count=1, namespace="orphan"),
        ]
        parts = partition_rules(rules, a)
        assert [r.flow_id for r in parts["pod0"]] == [1, 3]
        assert [r.flow_id for r in parts["pod1"]] == [2]
        # unassigned namespaces surface under None instead of vanishing
        assert [r.flow_id for r in parts[None]] == [4]
        assert flow_namespaces(rules)[4] == "orphan"


class TestConnectionManager:
    def test_groups_counts_and_callbacks(self):
        seen = []
        cm = ConnectionManager(on_count_changed=lambda ns, n: seen.append((ns, n)))
        assert cm.add("a", "1.1.1.1:1") == 1
        assert cm.add("a", "1.1.1.1:2") == 2
        assert cm.add("b", "1.1.1.1:1") == 1  # one conn, two namespaces
        assert cm.connected_count("a") == 2
        cm.remove_address("1.1.1.1:1")  # drops both registrations
        assert cm.connected_count("a") == 1
        assert cm.connected_count("b") == 0
        assert cm.namespaces() == ["a"]
        assert ("a", 2) in seen and ("b", 0) in seen

    def test_duplicate_add_is_idempotent(self):
        cm = ConnectionManager()
        cm.add("a", "x:1")
        assert cm.add("a", "x:1") == 1
        assert cm.snapshot() == {"a": ["x:1"]}


class _StubClient(TokenService):
    """Records which pod answered; stands in for a real TokenClient."""

    def __init__(self, host, port, timeout_ms=20, namespace="default"):
        self.endpoint = (host, port)
        self.namespace = namespace
        self.calls = []
        self.closed = False

    def request_token(self, flow_id, acquire=1, prioritized=False):
        self.calls.append(flow_id)
        return TokenResult(TokenStatus.OK, remaining=self.endpoint[1])

    def ping(self, namespace=None):
        self.pinged = getattr(self, "pinged", []) + [namespace or self.namespace]
        return True

    def close(self):
        self.closed = True


class TestRoutingTokenClient:
    def _router(self):
        return RoutingTokenClient(
            namespace_of={1: "a", 2: "b"},
            pod_of={"a": "pod0", "b": "pod1"},
            endpoints={"pod0": ("h0", 10), "pod1": ("h1", 11)},
            client_factory=_StubClient,
        )

    def test_routes_by_namespace(self):
        r = self._router()
        assert r.request_token(1).remaining == 10  # pod0's port marker
        assert r.request_token(2).remaining == 11
        # unknown flow → NO_RULE (caller falls back locally)
        assert r.request_token(99).status == TokenStatus.NO_RULE_EXISTS

    def test_client_carries_namespace_handshake(self):
        r = self._router()
        r.request_token(1)
        client = r._clients["pod0"]
        assert client.namespace == "a"

    def test_pod_serving_multiple_namespaces_declares_each(self):
        # AVG_LOCAL counts are per namespace group — a pod client must
        # declare EVERY namespace it routes, not just its first
        r = RoutingTokenClient(
            namespace_of={1: "a", 2: "b"},
            pod_of={"a": "pod0", "b": "pod0"},
            endpoints={"pod0": ("h0", 10)},
            client_factory=_StubClient,
        )
        r.request_token(1)
        r.request_token(2)
        r.request_token(2)  # already declared — no extra ping
        client = r._clients["pod0"]
        assert client.namespace == "a"  # ctor namespace (auto-handshake)
        assert getattr(client, "pinged", []) == ["b"]

    def test_update_moves_namespace_and_closes_dead_pods(self):
        r = self._router()
        r.request_token(2)
        old = r._clients["pod1"]
        # move namespace b to pod0 and retire pod1 entirely
        r.update(pod_of={"a": "pod0", "b": "pod0"},
                 endpoints={"pod0": ("h0", 10)})
        assert r.request_token(2).remaining == 10
        assert old.closed

    def test_close_closes_all(self):
        r = self._router()
        r.request_token(1)
        r.request_token(2)
        clients = list(r._clients.values())
        r.close()
        assert all(c.closed for c in clients)


class TestAggregation:
    def test_sums_disjoint_and_overlapping(self):
        total = aggregate_snapshots([
            {1: {"pass_qps": 5.0}, 2: {"pass_qps": 1.0}},
            {3: {"pass_qps": 2.0}, 2: {"pass_qps": 0.5}},  # mid-move overlap
        ])
        assert total[1]["pass_qps"] == 5.0
        assert total[2]["pass_qps"] == 1.5
        assert total[3]["pass_qps"] == 2.0


class TestPartitionIsolationE2E:
    """Two in-process pods; namespace movement repoints routing and the new
    owner enforces with fresh windows (the documented ephemeral stance)."""

    def test_isolation_and_movement(self):
        from sentinel_tpu.cluster.token_service import DefaultTokenService
        from sentinel_tpu.engine import EngineConfig

        rules = [
            ClusterFlowRule(flow_id=1, count=1e9, namespace="a",
                            mode=ThresholdMode.GLOBAL),
            ClusterFlowRule(flow_id=2, count=1e9, namespace="b",
                            mode=ThresholdMode.GLOBAL),
        ]
        assignment = NamespaceAssignment({"a": "pod0", "b": "pod1"})
        pods = {
            p: DefaultTokenService(
                EngineConfig(max_flows=8, max_namespaces=4, batch_size=8)
            )
            for p in ("pod0", "pod1")
        }
        parts = partition_rules(rules, assignment)
        for pod_id, pod_rules in parts.items():
            pods[pod_id].load_rules(pod_rules)

        # ownership: each pod only answers its own namespace's flows
        assert pods["pod0"].request_token(1).status == TokenStatus.OK
        assert pods["pod0"].request_token(2).status == TokenStatus.NO_RULE_EXISTS
        assert pods["pod1"].request_token(2).status == TokenStatus.OK

        # move namespace b → pod0 (rules follow ownership; counters don't)
        assignment.move("b", "pod0")
        parts = partition_rules(rules, assignment)
        pods["pod0"].load_rules(parts["pod0"])
        pods["pod1"].load_rules(parts.get("pod1", []))
        assert pods["pod0"].request_token(2).status == TokenStatus.OK
        # the old owner no longer recognizes the moved flow
        assert pods["pod1"].request_token(2).status == TokenStatus.NO_RULE_EXISTS

        for svc in pods.values():
            svc.close()

    def test_avg_local_scales_with_handshaked_clients(self):
        """Connection-group counts from the PING handshake scale AVG_LOCAL
        thresholds (ClusterFlowChecker.java:43-47)."""
        from sentinel_tpu.cluster.client import TokenClient
        from sentinel_tpu.cluster.server import TokenServer
        from sentinel_tpu.cluster.token_service import DefaultTokenService
        from sentinel_tpu.engine import EngineConfig

        svc = DefaultTokenService(
            EngineConfig(max_flows=8, max_namespaces=4, batch_size=8)
        )
        svc.load_rules([
            ClusterFlowRule(flow_id=5, count=2.0, namespace="grp",
                            mode=ThresholdMode.AVG_LOCAL),
        ])
        server = TokenServer(svc, host="127.0.0.1", port=0)
        server.start()
        try:
            c1 = TokenClient("127.0.0.1", server.port, timeout_ms=2000,
                             namespace="grp")
            c2 = TokenClient("127.0.0.1", server.port, timeout_ms=2000,
                             namespace="grp")
            assert c1.ping() and c2.ping()
            assert server.connections.connected_count("grp") == 2
            # threshold = 2.0/client × 2 clients = 4 global
            statuses = [c1.request_token(5).status for _ in range(6)]
            assert statuses.count(TokenStatus.OK) == 4, statuses
            assert statuses.count(TokenStatus.BLOCKED) == 2, statuses
            c1.close()
            c2.close()
        finally:
            server.stop()
            svc.close()


class _ConcurrentStubClient(_StubClient):
    """Pod-local concurrent-token semantics: ids count from 1 PER POD, so
    cross-pod collisions are real (the advisor's round-2 finding)."""

    def __init__(self, host, port, timeout_ms=20, namespace="default"):
        super().__init__(host, port, timeout_ms, namespace)
        self._next = 1
        self.held = {}

    def request_concurrent_token(self, flow_id, acquire=1, prioritized=False):
        tid = self._next
        self._next += 1
        self.held[tid] = flow_id
        return TokenResult(TokenStatus.OK, remaining=5, token_id=tid)

    def release_concurrent_token(self, token_id):
        if int(token_id) in self.held:
            del self.held[int(token_id)]
            return TokenResult(TokenStatus.RELEASE_OK)
        return TokenResult(TokenStatus.ALREADY_RELEASE)


class TestConcurrentTokenRouting:
    def _router(self):
        return RoutingTokenClient(
            namespace_of={1: "a", 2: "b"},
            pod_of={"a": "pod0", "b": "pod1"},
            endpoints={"pod0": ("h0", 10), "pod1": ("h1", 11)},
            client_factory=_ConcurrentStubClient,
        )

    def test_release_targets_issuing_pod_only(self):
        router = self._router()
        ra = router.request_concurrent_token(1)  # pod0 issues local id 1
        rb = router.request_concurrent_token(2)  # pod1 ALSO issues local id 1
        assert ra.ok and rb.ok
        # caller-visible ids are pod-namespaced → no collision
        assert ra.token_id != rb.token_id
        pod0 = router._clients["pod0"]
        pod1 = router._clients["pod1"]
        assert pod0.held and pod1.held
        out = router.release_concurrent_token(ra.token_id)
        assert out.ok
        # ONLY pod0's token released; pod1's same-local-id token survives
        assert not pod0.held
        assert pod1.held == {1: 2}

    def test_release_unprefixed_id_falls_back_to_fanout(self):
        router = self._router()
        r = router.request_concurrent_token(1)
        raw_local = r.token_id & ((1 << 48) - 1)
        # a raw pod-local id (issued before the router, or by another path)
        out = router.release_concurrent_token(raw_local)
        assert out.ok  # found via first-success fan-out

    def test_release_after_pod_removed_fails_fast(self):
        # a prefixed token whose issuing pod left the routing table can only
        # have been held by that pod — the release must NOT fan out with the
        # masked local id (it could release another pod's same-local-id
        # token) and must answer already-released (round-3 advisor finding)
        router = self._router()
        ra = router.request_concurrent_token(1)  # pod0 local id 1
        rb = router.request_concurrent_token(2)  # pod1 ALSO local id 1
        assert ra.ok and rb.ok
        pod1 = router._clients["pod1"]
        router.update(
            pod_of={"b": "pod1"},
            endpoints={"pod1": ("h1", 11)},  # pod0 removed
        )
        out = router.release_concurrent_token(ra.token_id)
        assert out.status == TokenStatus.ALREADY_RELEASE
        # pod1's same-local-id token is untouched
        assert pod1.held == {1: 2}

    def test_release_result_is_release_ok(self):
        # round-2 code compared against OK and always reported FAIL
        router = self._router()
        r = router.request_concurrent_token(1)
        out = router.release_concurrent_token(r.token_id)
        assert out.status == TokenStatus.RELEASE_OK
