"""Serve-bench harness units + one live end-to-end round.

The serve bench is round-5's primary evidence instrument (served rate,
load-latency curve, operating point), so its selection logic is tested
like product code; one live closed-loop round through a real front door
keeps the client protocol honest.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import serve_bench  # noqa: E402


class TestOperatingPoint:
    def _pt(self, rate, p99, sent=1000, dropped=0, lost=0):
        return {
            "offered_rate": rate, "achieved_rate": rate,
            "frames_sent": sent, "frames_dropped": dropped,
            "frames_lost": lost, "p99_ms": p99,
        }

    def test_highest_rate_meeting_slo_wins(self):
        pts = [self._pt(100, 0.5), self._pt(200, 1.0), self._pt(400, 1.9),
               self._pt(800, 5.0)]
        assert serve_bench.operating_point(pts)["achieved_rate"] == 400

    def test_shedding_point_excluded(self):
        # fast p99 but >1% frames shed: the latency is survivorship bias
        pts = [self._pt(100, 0.5),
               self._pt(800, 0.9, sent=900, dropped=100)]
        assert serve_bench.operating_point(pts)["achieved_rate"] == 100

    def test_no_point_meets_slo(self):
        pts = [self._pt(100, 3.0), self._pt(200, 8.0)]
        assert serve_bench.operating_point(pts) is None

    def test_missing_p99_skipped(self):
        pts = [{"offered_rate": 1, "error": "clients failed"},
               self._pt(50, 1.0)]
        assert serve_bench.operating_point(pts)["achieved_rate"] == 50


class TestPercentiles:
    def test_pcts_empty(self):
        out = serve_bench._pcts(np.empty(0))
        assert out["p99_ms"] is None and out["max_ms"] is None

    def test_pcts_values(self):
        out = serve_bench._pcts(np.asarray([1.0, 2.0, 3.0, 100.0]))
        assert out["p50_ms"] == 2.5 and out["max_ms"] == 100.0


class TestClientPacing:
    """Open-loop sender math from serve_client (absolute schedule)."""

    def test_open_loop_offered_rate_is_absolute_schedule(self):
        import serve_client

        dt, n_frames = serve_client.open_loop_schedule(512, 100_000.0, 2.0)
        assert dt == pytest.approx(0.00512)
        assert n_frames == 390
        # the realized offered load over the window matches the nominal
        # rate (the schedule spans `seconds` exactly, jitter-independent)
        assert n_frames * 512 / 2.0 == pytest.approx(100_000.0, rel=0.01)
        # degenerate input still sends at least one frame
        assert serve_client.open_loop_schedule(1024, 10.0, 0.1)[1] == 1


class TestServeLive:
    def test_closed_loop_round_through_native_door(self):
        """One real client subprocess against a real front door: the
        served count, error count, and RTT samples must be coherent."""
        from sentinel_tpu.cluster.server_native import native_available

        service, server, front_door = serve_bench.build_server(
            n_flows=256, max_batch=1024, serve_buckets=(256, 1024),
            native=native_available(),
        )
        try:
            out = serve_bench.run_closed(
                server.port, clients=1, batch=128, pipeline=2,
                seconds=1.0, n_flows=256,
            )
            assert out["errors"] == 0
            assert out["verdicts_ok"] > 0
            assert out["verdicts_ok"] % 128 == 0  # whole frames only
            assert out["p99_ms"] is not None and out["p99_ms"] > 0
        finally:
            server.stop()
            service.close()

    def test_warmup_latency_excluded_from_closed_loop_window(self):
        """A slow FIRST response (server-side compile) must not consume
        the measurement window: each pump thread's clock starts after its
        warmup round trip. Regression: a remote-compile warmup once ate
        the whole window and produced a 0-verdict, 0-error artifact."""
        import socket
        import threading
        import time as _time

        from sentinel_tpu.cluster import protocol as P

        delay_s = 1.2
        seconds = 0.8
        srv = socket.create_server(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        stop = threading.Event()

        def serve():
            srv.settimeout(0.2)
            conns = []
            while not stop.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                t = threading.Thread(
                    target=handle, args=(conn,), daemon=True
                )
                t.start()
                conns.append(t)

        def handle(conn):
            frames = P.FrameReader()
            first = True
            try:
                while not stop.is_set():
                    data = conn.recv(65536)
                    if not data:
                        return
                    for payload in frames.feed(data):
                        xid, ids, _c, _p = P.decode_batch_request(payload)
                        if first:
                            _time.sleep(delay_s)  # simulated cold compile
                            first = False
                        n = len(ids)
                        conn.sendall(P.encode_batch_response(
                            xid, np.zeros(n, np.int8),
                            np.zeros(n, np.int32), np.zeros(n, np.int32),
                        ))
            except OSError:
                return

        st = threading.Thread(target=serve, daemon=True)
        st.start()
        try:
            out = serve_bench.run_closed(
                port, clients=1, batch=64, pipeline=2,
                seconds=seconds, n_flows=64,
            )
            # the old clock placement yielded 0 verdicts here (delay_s >
            # seconds); the fixed clock measures a full post-warmup window
            assert out["verdicts_ok"] > 0
            assert out["errors"] == 0
            assert out["p99_ms"] is not None
        finally:
            stop.set()
            srv.close()

    def test_client_subprocess_never_claims_accelerator(self):
        """The client pins jax to CPU before anything else imports it —
        the env var alone is too late under the axon sitecustomize."""
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        # defense-in-depth: with no pool address the accelerator plugin
        # never registers, so if the CPU pin under test ever regresses the
        # subprocess fails fast instead of making a real tunnel claim that
        # this test's timeout-kill would leave wedged. JAX_PLATFORMS is
        # deliberately NOT overridden — the assertion below reads the
        # config value the module itself must have pinned.
        env["PALLAS_AXON_POOL_IPS"] = ""
        src = (
            "import sys; sys.argv=['x']; "
            "import importlib.util as u; "
            f"spec=u.spec_from_file_location('sc', r'{serve_bench.CLIENT}'); "
            "m=u.module_from_spec(spec); spec.loader.exec_module(m); "
            "import jax; print(jax.config.jax_platforms)"
        )
        proc = subprocess.run(
            [sys.executable, "-c", src], capture_output=True, text=True,
            timeout=120, env=env,
        )
        # module-level code must have pinned the platform config to cpu
        # (main() isn't run: argv has no --port, __name__ != '__main__');
        # reading jax.config initializes no backend
        assert proc.returncode == 0, proc.stderr[-500:]
        assert proc.stdout.strip().splitlines()[-1] == "cpu"
