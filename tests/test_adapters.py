"""Adapter tests (L7): decorator, WSGI, ASGI, gateway, gRPC, HTTP clients.

Mirrors the reference's per-adapter strategy (SURVEY.md §4): each adapter is
driven through its framework's own test harness idiom — raw WSGI callables,
an asyncio-driven ASGI app, grpc's in-process server — with rules loaded via
the ordinary managers and verdicts asserted at the framework boundary.
"""

import asyncio

import pytest

import sentinel_tpu.local as sentinel
from sentinel_tpu.adapters import (
    GatewayFlowRule,
    GatewayParamFlowItem,
    GatewayRuleManager,
    MatchStrategy,
    ParseStrategy,
    SentinelAsgiMiddleware,
    SentinelWsgiMiddleware,
    sentinel_resource,
)
from sentinel_tpu.adapters.gateway import (
    ABSENT,
    NOT_MATCH,
    DictRequestAdapter,
    ResourceMode,
)
from sentinel_tpu.local import BlockException, FlowRule, FlowRuleManager


@pytest.fixture(autouse=True)
def clean(manual_clock):
    sentinel.reset_for_tests()
    GatewayRuleManager.reset_for_tests()
    yield manual_clock
    GatewayRuleManager.reset_for_tests()
    sentinel.reset_for_tests()


class TestDecorator:
    def test_guards_and_blocks(self, manual_clock):
        calls = []

        @sentinel_resource("deco_res")
        def fn(x):
            calls.append(x)
            return x * 2

        FlowRuleManager.load_rules([FlowRule(resource="deco_res", count=2)])
        assert fn(1) == 2 and fn(2) == 4
        with pytest.raises(BlockException):
            fn(3)
        assert calls == [1, 2]

    def test_block_handler(self, manual_clock):
        @sentinel_resource("deco_bh", block_handler=lambda x, ex: f"blocked:{x}")
        def fn(x):
            return x

        FlowRuleManager.load_rules([FlowRule(resource="deco_bh", count=1)])
        assert fn("a") == "a"
        assert fn("b") == "blocked:b"

    def test_fallback_on_error_and_trace(self, manual_clock):
        @sentinel_resource("deco_fb", fallback=lambda ex: "fell back")
        def fn():
            raise ValueError("boom")

        assert fn() == "fell back"
        from sentinel_tpu.local.chain import cluster_node_map

        node = cluster_node_map()["deco_fb"]
        assert node.exception_qps(manual_clock.now_ms()) > 0

    def test_fallback_used_for_block_when_no_block_handler(self, manual_clock):
        @sentinel_resource("deco_fb2", fallback=lambda ex: "fb")
        def fn():
            return "ok"

        FlowRuleManager.load_rules([FlowRule(resource="deco_fb2", count=1)])
        assert fn() == "ok"
        assert fn() == "fb"

    def test_ignored_exceptions_not_traced(self, manual_clock):
        @sentinel_resource("deco_ig", exceptions_to_ignore=(KeyError,))
        def fn():
            raise KeyError("skip")

        with pytest.raises(KeyError):
            fn()
        from sentinel_tpu.local.chain import cluster_node_map

        node = cluster_node_map()["deco_ig"]
        assert node.exception_qps(manual_clock.now_ms()) == 0

    def test_default_resource_name(self, manual_clock):
        @sentinel_resource()
        def some_fn():
            return 1

        some_fn()
        from sentinel_tpu.local.chain import cluster_node_map

        assert any("some_fn" in name for name in cluster_node_map())

    def test_async_function(self, manual_clock):
        @sentinel_resource("deco_async", block_handler=lambda ex: "blocked")
        async def fn():
            return "ok"

        FlowRuleManager.load_rules([FlowRule(resource="deco_async", count=1)])
        assert asyncio.run(fn()) == "ok"
        assert asyncio.run(fn()) == "blocked"

    def test_async_handlers_are_awaited(self, manual_clock):
        async def on_block(ex):
            return "async-blocked"

        @sentinel_resource("deco_async_bh", block_handler=on_block)
        async def fn():
            return "ok"

        FlowRuleManager.load_rules([FlowRule(resource="deco_async_bh", count=1)])
        assert asyncio.run(fn()) == "ok"
        assert asyncio.run(fn()) == "async-blocked"  # result, not a coroutine

    def test_args_as_params_feed_hot_param_rules(self, manual_clock):
        from sentinel_tpu.local import ParamFlowRule, ParamFlowRuleManager

        @sentinel_resource("deco_param", args_as_params=True,
                           block_handler=lambda uid, ex: "limited")
        def fn(uid):
            return "ok"

        ParamFlowRuleManager.load_rules(
            [ParamFlowRule(resource="deco_param", param_idx=0, count=1)]
        )
        assert fn("alice") == "ok"
        assert fn("alice") == "limited"  # per-value limit hit
        assert fn("bob") == "ok"  # other value unaffected


def _wsgi_app(environ, start_response):
    start_response("200 OK", [("Content-Type", "text/plain")])
    return [b"hello"]


def _call_wsgi(app, path="/", method="GET", remote="1.2.3.4"):
    status_headers = {}

    def start_response(status, headers):
        status_headers["status"] = status
        status_headers["headers"] = headers

    environ = {"REQUEST_METHOD": method, "PATH_INFO": path, "REMOTE_ADDR": remote}
    body = b"".join(app(environ, start_response))
    return status_headers["status"], body


class TestWsgi:
    def test_pass_and_block(self, manual_clock):
        app = SentinelWsgiMiddleware(_wsgi_app)
        FlowRuleManager.load_rules([FlowRule(resource="GET:/api", count=2)])
        for _ in range(2):
            status, body = _call_wsgi(app, "/api")
            assert status.startswith("200") and body == b"hello"
        status, body = _call_wsgi(app, "/api")
        assert status.startswith("429") and b"Sentinel" in body
        # other path unaffected
        status, _ = _call_wsgi(app, "/other")
        assert status.startswith("200")

    def test_custom_block_handler(self, manual_clock):
        def on_block(environ, start_response, e):
            start_response("503 Service Unavailable", [])
            return [b"custom"]

        app = SentinelWsgiMiddleware(_wsgi_app, block_handler=on_block)
        FlowRuleManager.load_rules([FlowRule(resource="GET:/x", count=0)])
        status, body = _call_wsgi(app, "/x")
        assert status.startswith("503") and body == b"custom"

    def test_skip_unnamed_resources(self, manual_clock):
        app = SentinelWsgiMiddleware(
            _wsgi_app, resource_extractor=lambda env: ""
        )
        FlowRuleManager.load_rules([FlowRule(resource="GET:/", count=0)])
        status, _ = _call_wsgi(app, "/")
        assert status.startswith("200")  # unguarded

    def test_total_entry(self, manual_clock):
        from sentinel_tpu.adapters.wsgi import TOTAL_RESOURCE

        app = SentinelWsgiMiddleware(_wsgi_app, with_total=True)
        FlowRuleManager.load_rules([FlowRule(resource=TOTAL_RESOURCE, count=1)])
        assert _call_wsgi(app, "/a")[0].startswith("200")
        assert _call_wsgi(app, "/b")[0].startswith("429")  # umbrella cap

    def test_error_traced(self, manual_clock):
        def bad_app(environ, start_response):
            raise RuntimeError("boom")

        app = SentinelWsgiMiddleware(bad_app)
        with pytest.raises(RuntimeError):
            _call_wsgi(app, "/err")
        from sentinel_tpu.local.chain import cluster_node_map

        node = cluster_node_map()["GET:/err"]
        assert node.exception_qps(manual_clock.now_ms()) > 0

    def test_streaming_body_holds_entry_open(self, manual_clock):
        """THREAD concurrency and RT must span body iteration, not just the
        app call (streaming responses)."""
        from sentinel_tpu.local.chain import cluster_node_map

        observed = []

        def streaming_app(environ, start_response):
            start_response("200 OK", [])

            def gen():
                observed.append(cluster_node_map()["GET:/stream"].cur_thread_num)
                yield b"chunk"

            return gen()

        app = SentinelWsgiMiddleware(streaming_app)
        status_headers = {}
        body = app(
            {"REQUEST_METHOD": "GET", "PATH_INFO": "/stream", "REMOTE_ADDR": ""},
            lambda s, h: status_headers.update(status=s),
        )
        chunks = list(body)  # consume — entry held open during iteration
        assert chunks == [b"chunk"]
        assert observed == [1]  # concurrency visible mid-stream
        assert cluster_node_map()["GET:/stream"].cur_thread_num == 0  # released

    def test_streaming_iteration_error_traced(self, manual_clock):
        from sentinel_tpu.local.chain import cluster_node_map

        def streaming_app(environ, start_response):
            start_response("200 OK", [])

            def gen():
                yield b"x"
                raise RuntimeError("mid-stream")

            return gen()

        app = SentinelWsgiMiddleware(streaming_app)
        body = app(
            {"REQUEST_METHOD": "GET", "PATH_INFO": "/stream2", "REMOTE_ADDR": ""},
            lambda s, h: None,
        )
        with pytest.raises(RuntimeError):
            list(body)
        node = cluster_node_map()["GET:/stream2"]
        assert node.exception_qps(manual_clock.now_ms()) > 0
        assert node.cur_thread_num == 0


async def _asgi_app(scope, receive, send):
    await send({"type": "http.response.start", "status": 200, "headers": []})
    await send({"type": "http.response.body", "body": b"hello"})


def _call_asgi(app, path="/", method="GET"):
    sent = []

    async def run():
        scope = {"type": "http", "method": method, "path": path,
                 "client": ("9.9.9.9", 1234)}

        async def receive():
            return {"type": "http.request"}

        async def send(msg):
            sent.append(msg)

        await app(scope, receive, send)

    asyncio.run(run())
    status = next(m["status"] for m in sent if m["type"] == "http.response.start")
    body = b"".join(m.get("body", b"") for m in sent if m["type"] == "http.response.body")
    return status, body


class TestAsgi:
    def test_pass_and_block(self, manual_clock):
        app = SentinelAsgiMiddleware(_asgi_app)
        FlowRuleManager.load_rules([FlowRule(resource="GET:/api", count=1)])
        assert _call_asgi(app, "/api") == (200, b"hello")
        status, body = _call_asgi(app, "/api")
        assert status == 429 and b"Sentinel" in body

    def test_non_http_passthrough(self, manual_clock):
        ran = []

        async def ws_app(scope, receive, send):
            ran.append(scope["type"])

        app = SentinelAsgiMiddleware(ws_app)

        async def run():
            await app({"type": "websocket"}, None, None)

        asyncio.run(run())
        assert ran == ["websocket"]

    def test_concurrent_tasks_have_isolated_contexts(self, manual_clock):
        """Two interleaving tasks must not corrupt each other's entry stack
        (the reference needs AsyncEntry for this; contextvars gives it)."""
        app = SentinelAsgiMiddleware(_asgi_app)
        order = []

        async def slow_app(scope, receive, send):
            order.append(f"in:{scope['path']}")
            await asyncio.sleep(0.01)
            order.append(f"out:{scope['path']}")
            await send({"type": "http.response.start", "status": 200, "headers": []})
            await send({"type": "http.response.body", "body": b"x"})

        app = SentinelAsgiMiddleware(slow_app)

        async def call(path):
            sent = []

            async def send(msg):
                sent.append(msg)

            await app({"type": "http", "method": "GET", "path": path,
                       "client": None}, None, send)
            return sent

        async def run():
            return await asyncio.gather(call("/a"), call("/b"))

        r = asyncio.run(run())
        assert all(any(m.get("status") == 200 for m in sent) for sent in r)
        assert order == ["in:/a", "in:/b", "out:/a", "out:/b"]  # interleaved


class TestGateway:
    def test_route_limit_per_client_ip(self, manual_clock):
        GatewayRuleManager.load_rules(
            [
                GatewayFlowRule(
                    resource="route_a", count=2,
                    param_item=GatewayParamFlowItem(ParseStrategy.CLIENT_IP),
                )
            ]
        )
        req1 = DictRequestAdapter(ip="10.0.0.1")
        req2 = DictRequestAdapter(ip="10.0.0.2")
        for _ in range(2):
            with GatewayRuleManager.entry("route_a", req1):
                pass
        with pytest.raises(BlockException):
            with GatewayRuleManager.entry("route_a", req1):
                pass
        # different IP gets its own bucket
        with GatewayRuleManager.entry("route_a", req2):
            pass

    def test_rule_without_param_item_acts_as_plain_limit(self, manual_clock):
        GatewayRuleManager.load_rules(
            [GatewayFlowRule(resource="route_b", count=1)]
        )
        with GatewayRuleManager.entry("route_b", DictRequestAdapter()):
            pass
        with pytest.raises(BlockException):
            with GatewayRuleManager.entry("route_b", DictRequestAdapter()):
                pass

    def test_header_with_pattern_matching(self, manual_clock):
        GatewayRuleManager.load_rules(
            [
                GatewayFlowRule(
                    resource="route_c", count=1,
                    param_item=GatewayParamFlowItem(
                        ParseStrategy.HEADER, field_name="X-Tier",
                        pattern="gold", match_strategy=MatchStrategy.EXACT,
                    ),
                )
            ]
        )
        gold = DictRequestAdapter(headers={"X-Tier": "gold"})
        bronze = DictRequestAdapter(headers={"X-Tier": "bronze"})
        args = GatewayRuleManager.parse("route_c", gold)
        assert args == ("gold",)
        assert GatewayRuleManager.parse("route_c", bronze) == (NOT_MATCH,)
        assert GatewayRuleManager.parse(
            "route_c", DictRequestAdapter()
        ) == (ABSENT,)

    def test_multiple_rules_align_param_indexes(self, manual_clock):
        GatewayRuleManager.load_rules(
            [
                GatewayFlowRule(
                    resource="route_d", count=10,
                    param_item=GatewayParamFlowItem(ParseStrategy.CLIENT_IP),
                ),
                GatewayFlowRule(
                    resource="route_d", count=5,
                    param_item=GatewayParamFlowItem(
                        ParseStrategy.URL_PARAM, field_name="user"
                    ),
                ),
            ]
        )
        req = DictRequestAdapter(ip="1.1.1.1", params={"user": "u7"})
        assert GatewayRuleManager.parse("route_d", req) == ("1.1.1.1", "u7")

    def test_removed_gateway_rules_are_unloaded(self, manual_clock):
        from sentinel_tpu.local import ParamFlowRuleManager

        GatewayRuleManager.load_rules(
            [GatewayFlowRule(resource="route_gone", count=1)]
        )
        assert "route_gone" in ParamFlowRuleManager.all_rules()
        GatewayRuleManager.load_rules([])
        assert "route_gone" not in ParamFlowRuleManager.all_rules()

    def test_gateway_load_preserves_foreign_param_rules(self, manual_clock):
        from sentinel_tpu.local import ParamFlowRule, ParamFlowRuleManager

        ParamFlowRuleManager.load_rules(
            [ParamFlowRule(resource="user_res", param_idx=0, count=3)]
        )
        GatewayRuleManager.load_rules(
            [GatewayFlowRule(resource="route_e", count=1)]
        )
        assert "user_res" in ParamFlowRuleManager.all_rules()
        assert "route_e" in ParamFlowRuleManager.all_rules()


class TestGrpc:
    def test_server_interceptor_blocks(self, manual_clock):
        grpc = pytest.importorskip("grpc")
        from concurrent import futures

        from sentinel_tpu.adapters.grpc_interceptor import (
            SentinelServerInterceptor,
        )

        method = "/test.Svc/Do"

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method == method:
                    return grpc.unary_unary_rpc_method_handler(
                        lambda req, ctx: req + b"!"
                    )
                return None

        server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=2),
            interceptors=[SentinelServerInterceptor()],
        )
        server.add_generic_rpc_handlers([Handler()])
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        try:
            FlowRuleManager.load_rules([FlowRule(resource=method, count=1)])
            channel = grpc.insecure_channel(f"127.0.0.1:{port}")
            stub = channel.unary_unary(method)
            assert stub(b"hi", timeout=5) == b"hi!"
            with pytest.raises(grpc.RpcError) as exc_info:
                stub(b"hi", timeout=5)
            assert exc_info.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
            channel.close()
        finally:
            server.stop(0)


class TestHttpClient:
    def test_httpx_transport_guard(self, manual_clock):
        httpx = pytest.importorskip("httpx")
        from sentinel_tpu.adapters.http_client import SentinelHttpxTransport

        calls = []

        def app(request):
            calls.append(str(request.url))
            return httpx.Response(200, text="ok")

        transport = SentinelHttpxTransport(inner=httpx.MockTransport(app))
        client = httpx.Client(transport=transport)
        FlowRuleManager.load_rules(
            [FlowRule(resource="GET:http://svc/api", count=1)]
        )
        assert client.get("http://svc/api").status_code == 200
        with pytest.raises(BlockException):
            client.get("http://svc/api")
        assert len(calls) == 1  # second call never reached the network

    def test_requests_session_guard(self, manual_clock):
        pytest.importorskip("requests")
        from sentinel_tpu.adapters.http_client import guarded_requests_session

        session = guarded_requests_session()
        FlowRuleManager.load_rules(
            [FlowRule(resource="GET:http://127.0.0.1:1/x", count=0)]
        )
        with pytest.raises(BlockException):
            session.request("GET", "http://127.0.0.1:1/x")


class TestOriginPropagation:
    """Cross-service origin convention (adapters/origin.py): the outbound
    wrappers attach ``X-Sentinel-Origin: <app name>`` and the inbound
    adapters parse it into the context origin, so authority rules gate
    callers by *application* across an HTTP hop — the dubbo
    consumer→provider attachment idiom (``SentinelDubboProviderFilter``)."""

    @pytest.fixture()
    def app_name(self):
        from sentinel_tpu.core.config import SentinelConfig

        SentinelConfig.set("csp.sentinel.app.name", "svc-a")
        yield "svc-a"
        SentinelConfig.reset_for_tests()

    def test_authority_rule_across_http_hop(self, manual_clock, app_name):
        # real wire hop: requests session → wsgiref server → wsgi middleware
        pytest.importorskip("requests")
        import threading
        from wsgiref.simple_server import WSGIServer, make_server

        import requests

        from sentinel_tpu.adapters.http_client import guarded_requests_session
        from sentinel_tpu.local.authority import (
            AuthorityRule,
            AuthorityRuleManager,
        )

        AuthorityRuleManager.load_rules(
            [AuthorityRule(resource="GET:/api", limit_app="svc-a")]
        )
        app = SentinelWsgiMiddleware(_wsgi_app)
        httpd = make_server("127.0.0.1", 0, app, server_class=WSGIServer)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            session = guarded_requests_session()
            ok = session.request("GET", f"http://127.0.0.1:{port}/api")
            assert ok.status_code == 200  # origin svc-a is whitelisted
            bare = requests.get(f"http://127.0.0.1:{port}/api")
            assert bare.status_code == 429  # peer-IP origin is not
        finally:
            httpd.shutdown()
            httpd.server_close()
            t.join(timeout=5)

    def test_httpx_transport_attaches_origin(self, manual_clock, app_name):
        httpx = pytest.importorskip("httpx")
        from sentinel_tpu.adapters.http_client import SentinelHttpxTransport

        seen = {}

        def app(request):
            seen.update(request.headers)
            return httpx.Response(200, text="ok")

        client = httpx.Client(
            transport=SentinelHttpxTransport(inner=httpx.MockTransport(app))
        )
        assert client.get("http://svc/api").status_code == 200
        assert seen.get("x-sentinel-origin") == "svc-a"

    def test_asgi_scope_prefers_origin_header(self):
        from sentinel_tpu.adapters.asgi import default_origin

        scope = {
            "client": ("10.1.2.3", 1234),
            "headers": [
                (b"host", b"svc"),
                (b"x-sentinel-origin", b"svc-a"),
                (b"s-user", b"alice"),
            ],
        }
        assert default_origin(scope) == "svc-a"
        scope["headers"] = [(b"s-user", b"alice")]
        assert default_origin(scope) == "alice"
        scope["headers"] = []
        assert default_origin(scope) == "10.1.2.3"


class TestGatewayApiDefinitions:
    """ApiDefinition / matcher semantics (ApiDefinition.java,
    ApiPathPredicateItem.java, GatewayApiMatcherManager.java)."""

    @pytest.fixture(autouse=True)
    def clean_api(self):
        from sentinel_tpu.adapters.gateway_api import (
            GatewayApiDefinitionManager, GatewayApiMatcherManager,
        )

        GatewayApiDefinitionManager.reset_for_tests()
        GatewayApiMatcherManager.reset_for_tests()
        yield
        GatewayApiMatcherManager.reset_for_tests()
        GatewayApiDefinitionManager.reset_for_tests()

    def _defs(self):
        from sentinel_tpu.adapters.gateway_api import (
            ApiDefinition, ApiPathPredicateItem, ApiPredicateGroupItem,
            UrlMatchStrategy,
        )

        return [
            ApiDefinition("orders_api", (
                ApiPathPredicateItem("/orders", UrlMatchStrategy.EXACT),
                ApiPathPredicateItem("/orders/", UrlMatchStrategy.PREFIX),
            )),
            ApiDefinition("catalog_api", (
                ApiPredicateGroupItem((
                    ApiPathPredicateItem(r"^/catalog/\d+$",
                                         UrlMatchStrategy.REGEX),
                    ApiPathPredicateItem("/sku", UrlMatchStrategy.EXACT),
                )),
            )),
        ]

    def test_match_strategies(self):
        from sentinel_tpu.adapters.gateway_api import (
            GatewayApiDefinitionManager, GatewayApiMatcherManager,
        )

        GatewayApiDefinitionManager.load_api_definitions(self._defs())
        pick = GatewayApiMatcherManager.pick_matching_api_names
        assert pick("/orders") == ["orders_api"]
        assert pick("/orders/42/items") == ["orders_api"]
        assert pick("/catalog/17") == ["catalog_api"]
        assert pick("/sku") == ["catalog_api"]
        assert pick("/other") == []

    def test_invalid_definitions_rejected(self):
        from sentinel_tpu.adapters.gateway_api import (
            ApiDefinition, GatewayApiDefinitionManager,
        )

        GatewayApiDefinitionManager.load_api_definitions(
            [ApiDefinition("", ()), ApiDefinition("empty", ())]
        )
        assert GatewayApiDefinitionManager.get_api_definitions() == []

    def test_json_roundtrip(self):
        from sentinel_tpu.adapters.gateway_api import (
            GatewayApiDefinitionManager, api_definition_to_dict,
            parse_api_definition,
        )

        for d in self._defs():
            assert parse_api_definition(api_definition_to_dict(d)) == d

    def test_property_driven_updates(self):
        from sentinel_tpu.adapters.gateway_api import (
            GatewayApiDefinitionManager, GatewayApiMatcherManager,
        )
        from sentinel_tpu.core.property import DynamicProperty

        prop = DynamicProperty()
        GatewayApiDefinitionManager.register_property(prop)
        prop.update_value(
            [{"apiName": "v_api",
              "predicateItems": [{"pattern": "/v/", "matchStrategy": 1}]}]
        )
        assert GatewayApiMatcherManager.pick_matching_api_names(
            "/v/x") == ["v_api"]
        prop.update_value([])
        assert GatewayApiMatcherManager.pick_matching_api_names("/v/x") == []

    def test_guard_enters_route_and_matching_apis(self, manual_clock):
        from sentinel_tpu.adapters.gateway import GatewayGuard
        from sentinel_tpu.adapters.gateway_api import (
            GatewayApiDefinitionManager,
        )

        GatewayApiDefinitionManager.load_api_definitions(self._defs())
        # rule on the CUSTOM API, not the route: only reachable through the
        # API-matching layer
        GatewayRuleManager.load_rules(
            [GatewayFlowRule(resource="orders_api", count=1,
                             resource_mode=ResourceMode.CUSTOM_API_NAME)]
        )
        req = DictRequestAdapter(ip="9.9.9.9")
        with GatewayGuard("route_orders", req, path="/orders/1"):
            pass
        with pytest.raises(BlockException):
            with GatewayGuard("route_orders", req, path="/orders/2"):
                pass
        # a path outside the API is not limited
        with GatewayGuard("route_orders", req, path="/other"):
            pass

    def test_gateway_wsgi_middleware_maps_path_to_api(self, manual_clock):
        from sentinel_tpu.adapters.gateway import SentinelGatewayWsgiMiddleware
        from sentinel_tpu.adapters.gateway_api import (
            GatewayApiDefinitionManager,
        )

        GatewayApiDefinitionManager.load_api_definitions(self._defs())
        GatewayRuleManager.load_rules(
            [GatewayFlowRule(resource="catalog_api", count=1,
                             resource_mode=ResourceMode.CUSTOM_API_NAME)]
        )

        def app(environ, start_response):
            start_response("200 OK", [("Content-Type", "text/plain")])
            return [b"ok"]

        mw = SentinelGatewayWsgiMiddleware(app)
        statuses = []

        def capture(status, headers):
            statuses.append(status)

        env = {"PATH_INFO": "/catalog/5", "REMOTE_ADDR": "1.2.3.4",
               "QUERY_STRING": ""}
        assert list(mw(dict(env), capture)) == [b"ok"]
        body = list(mw(dict(env), capture))
        assert statuses[-1].startswith("429")
        assert b"Blocked" in body[0]
        # non-matching path sails through
        env2 = {"PATH_INFO": "/healthz", "REMOTE_ADDR": "1.2.3.4",
                "QUERY_STRING": ""}
        assert list(mw(dict(env2), capture)) == [b"ok"]

    def test_regex_predicate_is_full_match(self):
        from sentinel_tpu.adapters.gateway_api import (
            ApiDefinition, ApiPathPredicateItem, GatewayApiDefinitionManager,
            GatewayApiMatcherManager, UrlMatchStrategy,
        )

        GatewayApiDefinitionManager.load_api_definitions(
            [ApiDefinition("v1_api", (
                ApiPathPredicateItem(r"/v1/(orders|users)",
                                     UrlMatchStrategy.REGEX),
            ))]
        )
        pick = GatewayApiMatcherManager.pick_matching_api_names
        assert pick("/v1/orders") == ["v1_api"]
        # unanchored fragment must NOT over-match containing paths
        assert pick("/internal/v1/orders-export") == []
        assert pick("/v1/orders/extra") == []

    def test_header_rule_matches_canonical_case_behind_adapter(self, manual_clock):
        from sentinel_tpu.adapters.gateway import _wsgi_request_adapter

        GatewayRuleManager.load_rules(
            [
                GatewayFlowRule(
                    resource="r_hdr", count=5,
                    param_item=GatewayParamFlowItem(
                        ParseStrategy.HEADER, field_name="X-Api-Key",
                    ),
                )
            ]
        )
        env = {"PATH_INFO": "/x", "HTTP_X_API_KEY": "k123"}
        req = _wsgi_request_adapter(env)
        # adapter lowercases; canonical-cased rule must still see the value
        assert GatewayRuleManager.parse("r_hdr", req) == ("k123",)

    def test_gateway_wsgi_streaming_holds_entries_open(self, manual_clock):
        from sentinel_tpu.adapters.gateway import SentinelGatewayWsgiMiddleware
        from sentinel_tpu.local.flow import FlowGrade

        GatewayRuleManager.load_rules(
            [GatewayFlowRule(resource="/stream", count=1,
                             grade=FlowGrade.THREAD)]
        )

        def app(environ, start_response):
            start_response("200 OK", [("Content-Type", "text/plain")])
            return iter([b"a", b"b"])

        mw = SentinelGatewayWsgiMiddleware(app)
        statuses = []

        def capture(status, headers):
            statuses.append(status)

        env = {"PATH_INFO": "/stream", "REMOTE_ADDR": "1.1.1.1",
               "QUERY_STRING": ""}
        body1 = mw(dict(env), capture)
        assert statuses[-1].startswith("200")
        # first body still streaming → concurrency slot held → second blocks
        body2 = mw(dict(env), capture)
        assert statuses[-1].startswith("429")
        body1.close()  # releases the entries
        body3 = mw(dict(env), capture)
        assert statuses[-1].startswith("200")
        list(body3)  # consume to completion also releases
        body4 = mw(dict(env), capture)
        assert statuses[-1].startswith("200")


class TestClassInterceptor:
    """sentinel_intercept — the CDI interceptor-binding analog
    (SentinelResourceInterceptor.java:35-70)."""

    def test_public_methods_guarded_with_formatted_names(self, manual_clock):
        from sentinel_tpu.adapters import sentinel_intercept

        @sentinel_intercept()
        class Svc:
            def checkout(self, x):
                return x * 2

            def _internal(self, x):  # private: untouched
                return x

        FlowRuleManager.load_rules(
            [FlowRule(resource="Svc.checkout", count=1)]
        )
        s = Svc()
        assert s.checkout(3) == 6
        with pytest.raises(BlockException):
            s.checkout(4)
        assert s._internal(5) == 5  # never enters the slot chain
        assert not hasattr(Svc._internal, "__sentinel_resource__")

    def test_method_level_binding_wins(self, manual_clock):
        from sentinel_tpu.adapters import sentinel_intercept

        @sentinel_intercept()
        class Svc:
            @sentinel_resource("custom_name")
            def pay(self, x):
                return x

        assert Svc.pay.__sentinel_resource__ == "custom_name"
        FlowRuleManager.load_rules(
            [FlowRule(resource="custom_name", count=1)]
        )
        s = Svc()
        assert s.pay(1) == 1
        with pytest.raises(BlockException):
            s.pay(2)

    def test_binding_level_fallback_and_static_methods(self, manual_clock):
        from sentinel_tpu.adapters import sentinel_intercept

        def fb(*args, ex=None, **kwargs):
            return "fallback"

        @sentinel_intercept(fallback=fb)
        class Svc:
            def boom(self):
                raise ValueError("business error")

            @staticmethod
            def tally(x):
                return x + 1

        s = Svc()
        assert s.boom() == "fallback"  # traced, then binding fallback
        assert Svc.tally(1) == 2  # staticmethod rebound and callable
        assert Svc.__dict__["tally"].__func__.__sentinel_resource__ == (
            "Svc.tally"
        )

    def test_include_exclude_narrow_the_binding(self, manual_clock):
        from sentinel_tpu.adapters import sentinel_intercept

        @sentinel_intercept(exclude=("skip_me",))
        class Svc:
            def a(self):
                return 1

            def skip_me(self):
                return 2

        assert hasattr(Svc.a, "__sentinel_resource__")
        assert not hasattr(Svc.skip_me, "__sentinel_resource__")

    def test_nested_classes_and_callable_instances_untouched(
        self, manual_clock
    ):
        import functools

        from sentinel_tpu.adapters import sentinel_intercept

        @sentinel_intercept()
        class Svc:
            class Config:  # nested class: callable, must not be wrapped
                pass

            handler = functools.partial(int, "7")  # callable instance

            def work(self):
                return 1

        assert isinstance(Svc.Config, type)
        assert isinstance(Svc().Config(), Svc.Config)
        assert Svc().handler() == 7  # no self injected
        assert hasattr(Svc.work, "__sentinel_resource__")
