"""Hot-parameter flow control tests.

Local token-bucket semantics mirror ``ParamFlowThrottleRateLimitingTest`` /
``ParamFlowDefaultCheckerTest``; the CMS engine is property-tested for its
one-sided error guarantee (estimate >= true count — the safe direction)."""

import jax.numpy as jnp
import numpy as np
import pytest

import sentinel_tpu.local as sentinel
from sentinel_tpu.core.hashing import stable_param_hash
from sentinel_tpu.engine.param import (
    ParamConfig,
    hash_indices,
    make_param_state,
    param_decide,
)
from sentinel_tpu.local import (
    BlockException,
    FlowGrade,
    ParamFlowItem,
    ParamFlowRule,
    ParamFlowRuleManager,
)


@pytest.fixture(autouse=True)
def clean_engine(manual_clock):
    sentinel.reset_for_tests()
    yield manual_clock
    sentinel.reset_for_tests()


def hit(resource, value, n=1):
    ok = blocked = 0
    for _ in range(n):
        try:
            with sentinel.entry(resource, args=(value,)):
                ok += 1
        except BlockException:
            blocked += 1
    return ok, blocked


class TestLocalParamQps:
    def test_per_value_budgets_independent(self, manual_clock):
        ParamFlowRuleManager.load_rules(
            [ParamFlowRule(resource="hot", param_idx=0, count=5)]
        )
        assert hit("hot", "alice", 8) == (5, 3)
        assert hit("hot", "bob", 8) == (5, 3)  # separate bucket

    def test_token_refill_over_time(self, manual_clock):
        ParamFlowRuleManager.load_rules(
            [ParamFlowRule(resource="hot2", param_idx=0, count=10)]
        )
        assert hit("hot2", "k", 10) == (10, 0)
        assert hit("hot2", "k", 1) == (0, 1)  # drained
        manual_clock.sleep(500)  # half the duration → ~5 tokens back
        ok, _ = hit("hot2", "k", 10)
        assert 4 <= ok <= 6

    def test_burst_headroom(self, manual_clock):
        ParamFlowRuleManager.load_rules(
            [ParamFlowRule(resource="hot3", param_idx=0, count=2, burst_count=3)]
        )
        ok, blocked = hit("hot3", "k", 8)
        assert ok == 5  # count + burst on first window

    def test_item_override(self, manual_clock):
        ParamFlowRuleManager.load_rules(
            [
                ParamFlowRule(
                    resource="hot4", param_idx=0, count=1,
                    items=[ParamFlowItem("vip", 10)],
                )
            ]
        )
        assert hit("hot4", "vip", 12) == (10, 2)
        assert hit("hot4", "pleb", 3) == (1, 2)

    def test_missing_arg_passes(self, manual_clock):
        ParamFlowRuleManager.load_rules(
            [ParamFlowRule(resource="hot5", param_idx=2, count=1)]
        )
        ok, blocked = hit("hot5", "x", 5)  # args has only idx 0
        assert (ok, blocked) == (5, 0)

    def test_thread_grade_releases_on_exit(self, manual_clock):
        ParamFlowRuleManager.load_rules(
            [
                ParamFlowRule(
                    resource="hot6", param_idx=0, count=1,
                    grade=FlowGrade.THREAD,
                )
            ]
        )
        e1 = sentinel.entry("hot6", args=("k",))
        with pytest.raises(BlockException):
            sentinel.entry("hot6", args=("k",))
        # another value unaffected
        e2 = sentinel.entry("hot6", args=("other",))
        e2.exit()
        e1.exit()
        e3 = sentinel.entry("hot6", args=("k",))  # released
        e3.exit()

    def test_rate_limiter_mode_paces(self, manual_clock):
        from sentinel_tpu.local import ControlBehavior

        ParamFlowRuleManager.load_rules(
            [
                ParamFlowRule(
                    resource="hot7", param_idx=0, count=10,
                    control_behavior=ControlBehavior.RATE_LIMITER,
                    max_queueing_time_ms=2000,
                )
            ]
        )
        t0 = manual_clock.now_ms()
        ok, blocked = hit("hot7", "k", 5)
        assert ok == 5
        assert manual_clock.now_ms() - t0 == pytest.approx(400, abs=1)


class TestReloadAndHashing:
    def test_republish_preserves_value_buckets(self, manual_clock):
        # regression: reloading an identical rule set must not refill buckets
        rules = [ParamFlowRule(resource="rp", param_idx=0, count=3)]
        ParamFlowRuleManager.load_rules(rules)
        assert hit("rp", "k", 3) == (3, 0)
        ParamFlowRuleManager.load_rules(
            [ParamFlowRule(resource="rp", param_idx=0, count=3)]
        )
        assert hit("rp", "k", 1) == (0, 1)  # still drained

    def test_republish_preserves_thread_holds(self, manual_clock):
        ParamFlowRuleManager.load_rules(
            [ParamFlowRule(resource="rp2", param_idx=0, count=1,
                           grade=FlowGrade.THREAD)]
        )
        e1 = sentinel.entry("rp2", args=("k",))
        ParamFlowRuleManager.load_rules(
            [ParamFlowRule(resource="rp2", param_idx=0, count=1,
                           grade=FlowGrade.THREAD)]
        )
        with pytest.raises(BlockException):
            sentinel.entry("rp2", args=("k",))  # hold survives the reload
        e1.exit()
        e2 = sentinel.entry("rp2", args=("k",))
        e2.exit()

    def test_changed_rule_gets_fresh_state(self, manual_clock):
        ParamFlowRuleManager.load_rules(
            [ParamFlowRule(resource="rp3", param_idx=0, count=2)]
        )
        assert hit("rp3", "k", 2) == (2, 0)
        ParamFlowRuleManager.load_rules(
            [ParamFlowRule(resource="rp3", param_idx=0, count=5)]  # changed
        )
        ok, _ = hit("rp3", "k", 6)
        assert ok == 5  # fresh bucket at the new threshold

    def test_hash_type_tagged(self):
        assert stable_param_hash(1) != stable_param_hash("1")
        assert stable_param_hash("1") != stable_param_hash(b"1")
        assert stable_param_hash(True) != stable_param_hash(1)
        assert stable_param_hash(None) != stable_param_hash("None")
        assert stable_param_hash("x") == stable_param_hash("x")


class TestCmsEngine:
    CFG = ParamConfig(max_param_rules=8, depth=2, width=512)

    def _decide(self, state, slots, hashes, thresholds, now, acquire=1):
        idx = hash_indices(np.asarray(hashes, np.int64), self.CFG.depth, self.CFG.width)
        n = len(slots)
        return param_decide(
            self.CFG,
            state,
            jnp.asarray(np.asarray(slots, np.int32)),
            jnp.asarray(idx),
            jnp.full((n,), acquire, jnp.int32),
            jnp.asarray(np.asarray(thresholds, np.float32)),
            jnp.ones((n,), bool),
            jnp.int32(now),
        )

    def test_threshold_enforced_per_value(self):
        state = make_param_state(self.CFG)
        h = stable_param_hash("user-1")
        state, admit, est = self._decide(
            state, [0] * 10, [h] * 10, [4.0] * 10, now=10_000
        )
        assert np.asarray(admit).sum() == 4

    def test_values_independent(self):
        state = make_param_state(self.CFG)
        hashes = [stable_param_hash(f"u{i}") for i in range(50)]
        state, admit, _ = self._decide(
            state, [0] * 50, hashes, [1.0] * 50, now=10_000
        )
        assert np.asarray(admit).all()  # one token each, all distinct values

    def test_window_slides(self):
        state = make_param_state(self.CFG)
        h = stable_param_hash("k")
        state, admit, _ = self._decide(state, [0], [h], [1.0], now=10_000)
        assert np.asarray(admit)[0]
        state, admit, _ = self._decide(state, [0], [h], [1.0], now=10_400)
        assert not np.asarray(admit)[0]
        state, admit, _ = self._decide(state, [0], [h], [1.0], now=11_100)
        assert np.asarray(admit)[0]  # old bucket expired

    def test_rules_isolated_by_slot(self):
        state = make_param_state(self.CFG)
        h = stable_param_hash("shared-key")
        state, admit, _ = self._decide(state, [0, 1], [h, h], [1.0, 1.0], 10_000)
        assert np.asarray(admit).all()  # same value, different rules

    @pytest.mark.parametrize("seed", range(3))
    def test_estimate_never_undercounts(self, seed):
        # CMS guarantee: estimate >= true windowed count per value
        rng = np.random.default_rng(seed)
        state = make_param_state(self.CFG)
        true_counts = {}
        now = 10_000
        for _ in range(8):
            vals = rng.integers(0, 30, size=16)
            hashes = [stable_param_hash(int(v)) for v in vals]
            # estimates reflect PRE-batch state (in-batch coupling is the
            # prefix term's job) → compare against the pre-batch snapshot
            snapshot = dict(true_counts)
            state, admit, est = self._decide(
                state, [0] * 16, hashes, [1e9] * 16, now
            )
            adm = np.asarray(admit)
            est = np.asarray(est)
            for i, v in enumerate(vals):
                assert est[i] >= snapshot.get(int(v), 0)
                if adm[i]:
                    true_counts[int(v)] = true_counts.get(int(v), 0) + 1
        assert sum(true_counts.values()) == 128


class TestClusterParamPath:
    def test_end_to_end_via_service(self, manual_clock):
        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.cluster.token_service import (
            ClusterParamFlowRule,
            DefaultTokenService,
        )
        from sentinel_tpu.engine import EngineConfig

        svc = DefaultTokenService(EngineConfig(max_flows=16, max_namespaces=4,
                                               batch_size=16))
        svc.load_param_rules(
            [
                ClusterParamFlowRule(
                    flow_id=500, count=2.0,
                    item_thresholds=((stable_param_hash("vip"), 10.0),),
                )
            ]
        )
        cluster_api.set_embedded_server(svc)
        try:
            ParamFlowRuleManager.load_rules(
                [
                    ParamFlowRule(
                        resource="chot", param_idx=0, count=1e9,
                        cluster_mode=True, cluster_config={"flow_id": 500},
                    )
                ]
            )
            assert hit("chot", "norm", 4) == (2, 2)
            assert hit("chot", "vip", 12) == (10, 2)
        finally:
            cluster_api.reset_for_tests()

    def test_param_state_survives_epoch_rebase(self, manual_clock):
        from sentinel_tpu.cluster.token_service import (
            ClusterParamFlowRule,
            DefaultTokenService,
        )
        from sentinel_tpu.engine import EngineConfig, TokenStatus

        svc = DefaultTokenService(EngineConfig(max_flows=16, max_namespaces=4,
                                               batch_size=16))
        svc.load_param_rules([ClusterParamFlowRule(flow_id=3, count=2.0)])
        h = stable_param_hash("k")
        assert svc.request_params_token(3, 1, [h]).status == TokenStatus.OK
        manual_clock.sleep(13 * 24 * 3600 * 1000)  # force a rebase
        svc.request_token(999)  # trigger _engine_now via the flow path
        # after the rebase the window machinery must still work end-to-end
        assert svc.request_params_token(3, 1, [h]).status == TokenStatus.OK
        assert svc.request_params_token(3, 1, [h]).status == TokenStatus.OK
        assert svc.request_params_token(3, 1, [h]).status == TokenStatus.BLOCKED

    def test_partial_load_rejected_atomically(self, manual_clock):
        from sentinel_tpu.cluster.token_service import (
            ClusterParamFlowRule,
            DefaultTokenService,
        )
        from sentinel_tpu.engine import EngineConfig
        from sentinel_tpu.engine.param import ParamConfig

        svc = DefaultTokenService(
            EngineConfig(max_flows=16, max_namespaces=4, batch_size=16),
            ParamConfig(max_param_rules=2),
        )
        svc.load_param_rules([ClusterParamFlowRule(flow_id=1, count=1.0),
                              ClusterParamFlowRule(flow_id=2, count=1.0)])
        with pytest.raises(ValueError, match="capacity"):
            svc.load_param_rules(
                [ClusterParamFlowRule(flow_id=i, count=1.0) for i in (3, 4, 5)]
            )
        # original rule set untouched
        assert set(svc._param_rules) == {1, 2}

    def test_wire_protocol_param_request(self, manual_clock):
        from sentinel_tpu.cluster.client import TokenClient
        from sentinel_tpu.cluster.server import TokenServer
        from sentinel_tpu.cluster.token_service import (
            ClusterParamFlowRule,
            DefaultTokenService,
        )
        from sentinel_tpu.engine import EngineConfig, TokenStatus

        svc = DefaultTokenService(EngineConfig(max_flows=16, max_namespaces=4,
                                               batch_size=16))
        svc.load_param_rules([ClusterParamFlowRule(flow_id=7, count=1.0)])
        server = TokenServer(svc, port=0)
        server.start()
        client = TokenClient("127.0.0.1", server.port, timeout_ms=2000)
        try:
            h = stable_param_hash("x")
            r1 = client.request_params_token(7, 1, [h])
            r2 = client.request_params_token(7, 1, [h])
            assert r1.status == TokenStatus.OK
            assert r2.status == TokenStatus.BLOCKED
        finally:
            client.close()
            server.stop()
