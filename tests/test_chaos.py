"""Chaos fault-injection harness: registry semantics + serving invariants.

The invariant suite drives a real server/client pair under each injector
and asserts the robustness contract: every request RESOLVES (a verdict,
an OVERLOAD refusal, or a client-side timeout — never a hang), no serving
thread dies, and stop() drains cleanly afterwards. Fixed seeds make a
failing run reproducible.
"""

import threading
import time

import numpy as np
import pytest

from sentinel_tpu import chaos
from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.server import TokenServer
from sentinel_tpu.cluster.server_native import (
    NativeTokenServer,
    native_available,
)
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.engine import ClusterFlowRule, EngineConfig
from sentinel_tpu.engine.rules import ThresholdMode

G = ThresholdMode.GLOBAL
CFG = EngineConfig(max_flows=64, max_namespaces=4, batch_size=256)


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    chaos.disarm()


def _service():
    svc = DefaultTokenService(CFG)
    svc.load_rules([ClusterFlowRule(flow_id=1, count=1e9, mode=G)])
    return svc


@pytest.fixture(scope="module")
def svc():
    # one service (= one decide-kernel compile) for the whole module; the
    # shared-server invariant tests below are sequential users of it
    return _service()


@pytest.fixture(scope="module")
def asyncio_server(svc):
    server = TokenServer(svc, port=0)
    server.start()
    yield server
    chaos.disarm()
    t0 = time.monotonic()
    server.stop()
    assert time.monotonic() - t0 < 10, "stop() hung after chaos"


@pytest.fixture(scope="module")
def native_server(svc):
    if not native_available():
        pytest.skip("native library not built")
    server = NativeTokenServer(svc, port=0, idle_ttl_s=None, drain_timeout_s=3.0)
    server.start()
    yield server
    chaos.disarm()
    t0 = time.monotonic()
    server.stop()
    assert time.monotonic() - t0 < 20, "stop() hung after chaos"


# -- registry ---------------------------------------------------------------
class TestRegistry:
    def test_parse_spec_grammar(self):
        inj = chaos.parse_spec("lane_delay:p=0.2,ms=5;frame_drop;clock_skew:ms=100,n=3")
        assert inj["lane_delay"].p == 0.2 and inj["lane_delay"].ms == 5.0
        assert inj["frame_drop"].p == 1.0
        assert inj["clock_skew"].n == 3

    def test_parse_rejects_unknown_point_and_arg(self):
        with pytest.raises(ValueError):
            chaos.parse_spec("warp_core_breach")
        with pytest.raises(ValueError):
            chaos.parse_spec("lane_delay:q=1")

    def test_armed_flag_is_zero_overhead_gate(self):
        assert chaos.ARMED is False
        chaos.arm("frame_drop:p=0.5", seed=1)
        assert chaos.ARMED is True
        chaos.disarm()
        assert chaos.ARMED is False

    def test_seeded_decisions_are_reproducible(self):
        decisions = []
        for _ in range(2):
            chaos.arm("frame_drop:p=0.5", seed=1234)
            decisions.append([chaos.should("frame_drop") for _ in range(50)])
            chaos.disarm()
        assert decisions[0] == decisions[1]
        assert any(decisions[0]) and not all(decisions[0])

    def test_firing_budget_n(self):
        chaos.arm("frame_drop:n=3", seed=7)
        fires = sum(chaos.should("frame_drop") for _ in range(10))
        assert fires == 3
        assert chaos.fired()["frame_drop"] == 3

    def test_unarmed_point_never_fires(self):
        chaos.arm("frame_drop", seed=7)
        assert not chaos.should("device_stall")
        assert chaos.delay_s("lane_delay") == 0.0

    def test_mangle_flips_exactly_one_byte(self):
        chaos.arm("frame_corrupt", seed=7)
        data = bytes(range(32))
        out = chaos.mangle("frame_corrupt", data)
        diff = [i for i in range(32) if out[i] != data[i]]
        assert len(diff) == 1
        assert out[diff[0]] == data[diff[0]] ^ 0xFF

    def test_skew_is_constant_not_probabilistic(self):
        chaos.arm("clock_skew:ms=250,p=0.0", seed=7)
        assert chaos.skew_ms() == 250.0 == chaos.skew_ms()

    def test_arm_from_env(self):
        reg = chaos.ChaosRegistry()
        assert not reg.arm_from_env({})
        assert reg.arm_from_env(
            {chaos.ENV_SPEC: "frame_drop:p=0.1", chaos.ENV_SEED: "9"}
        )
        assert reg.injectors()["frame_drop"].p == 0.1
        chaos.disarm()  # arm() flipped the module flag

    def test_clock_skew_shifts_now_ms(self):
        from sentinel_tpu.core import clock

        base = clock.now_ms()
        chaos.arm("clock_skew:ms=60000", seed=1)
        skewed = clock.now_ms()
        chaos.disarm()
        assert skewed - base >= 60000 - 5


# -- serving invariants under injection -------------------------------------
SPECS = [
    pytest.param("lane_delay:ms=10", id="lane_delay"),
    pytest.param("frame_drop:p=0.3", id="frame_drop"),
    pytest.param("frame_corrupt:p=0.1", id="frame_corrupt"),
    pytest.param("device_stall:ms=40,p=0.5", id="device_stall"),
    pytest.param("clock_skew:ms=5000", id="clock_skew"),
    pytest.param("conn_reset:p=0.2", id="conn_reset"),
]


def _run_fleet(port, n_threads=4, n_requests=6, timeout_ms=300):
    """Closed-loop client fleet; returns per-call outcomes. TokenClient
    never raises — a timeout/degrade surfaces as None/FAIL — so a missing
    outcome means a HANG, the invariant violation under test."""
    outcomes = [[] for _ in range(n_threads)]

    def worker(i):
        c = TokenClient("127.0.0.1", port, timeout_ms=timeout_ms)
        try:
            for _ in range(n_requests):
                outcomes[i].append(
                    c.request_batch_arrays(np.full(4, 1, np.int64))
                )
        finally:
            c.close()

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        # generous bound: n_requests × timeout + reconnect slack
        t.join(timeout=n_requests * (timeout_ms / 1000.0) + 10)
    hung = [t for t in threads if t.is_alive()]
    return outcomes, hung


class TestInvariantsAsyncio:
    # the server fixture is module-scoped ON PURPOSE: surviving every
    # injector in sequence (and the shared stop() at teardown) IS the
    # invariant; each test re-proves clean service after its own disarm
    @pytest.mark.parametrize("spec", SPECS)
    def test_every_request_resolves_and_server_survives(self, asyncio_server, spec):
        chaos.arm(spec, seed=20260804)
        outcomes, hung = _run_fleet(asyncio_server.port)
        assert not hung, "client threads hung — a request never resolved"
        assert all(len(o) == 6 for o in outcomes)
        point = spec.split(":")[0]
        if point != "clock_skew":  # skew is passive, not a firing probe
            assert chaos.fired().get(point, 0) > 0, "fault never fired"
        chaos.disarm()
        # the server survived: a fresh client gets clean verdicts
        c = TokenClient("127.0.0.1", asyncio_server.port, timeout_ms=3000)
        out = c.request_batch_arrays(np.full(4, 1, np.int64))
        c.close()
        assert out is not None and (out[0] == 0).all()


class TestInvariantsNative:
    @pytest.mark.parametrize(
        "spec",
        [
            pytest.param("lane_delay:ms=10;frame_drop:p=0.2", id="lanes"),
            pytest.param(
                "device_stall:ms=40,p=0.5;frame_corrupt:p=0.1",
                id="device+corrupt",
            ),
        ],
    )
    def test_every_request_resolves_and_server_survives(self, native_server, spec):
        chaos.arm(spec, seed=20260804)
        outcomes, hung = _run_fleet(native_server.port)
        assert not hung, "client threads hung — a request never resolved"
        assert all(len(o) == 6 for o in outcomes)
        assert sum(chaos.fired().values()) > 0
        chaos.disarm()
        c = TokenClient("127.0.0.1", native_server.port, timeout_ms=3000)
        out = c.request_batch_arrays(np.full(4, 1, np.int64))
        c.close()
        assert out is not None and (out[0] == 0).all()
