"""Smoke-run the deterministic (manual-clock) examples as subprocesses —
they are user-facing documentation and must keep working."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST_EXAMPLES = [
    "warm_up.py",
    "circuit_breaker.py",
    "param_flow.py",
    "system_guard.py",
    "async_entry_demo.py",
    "namespace_partition_demo.py",
    "envoy_rls_scale_demo.py",
    "decorator_degrade_demo.py",
    "interceptor_service_demo.py",
    "datasource_cluster_demo.py",
    "gateway_demo.py",
    "http_origin_demo.py",
    "prometheus_exporter_demo.py",
    "asgi_app_demo.py",
    "multi_pod_demo.py",
    "mesh_sharded_server.py",
    "warmup_demo.py",
    "pacing_demo.py",
    "outcome_demo.py",
]


@pytest.mark.parametrize("script", FAST_EXAMPLES)
def test_example_runs(script):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", script)],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.strip(), "example produced no output"


def test_outcome_demo_moves_the_rt_gauge():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "outcome_demo.py")],
        capture_output=True, text=True, timeout=300, env=env,
    ).stdout
    assert "outcome loop closed" in out
    assert "'negative': 1" in out  # the bogus report was validated away
    assert "extra RPCs: 0" in out


def test_pacing_demo_spreads_the_burst():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "pacing_demo.py")],
        capture_output=True, text=True, timeout=300, env=env,
    ).stdout
    assert "SHOULD_WAIT" in out
    assert "zero rejects" in out


def test_namespace_partition_demo_shows_movement():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "examples", "namespace_partition_demo.py")],
        capture_output=True, text=True, timeout=300, env=env,
    ).stdout
    assert "independent budgets" in out
    assert "after moving 'search' to pod0" in out


def test_envoy_rls_scale_demo_enforces_at_10k():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "examples", "envoy_rls_scale_demo.py")],
        capture_output=True, text=True, timeout=300, env=env,
    ).stdout
    assert "loaded 10000 RLS descriptors" in out
    assert "100 of 150 allowed" in out


def test_warm_up_shows_ramp():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "warm_up.py")],
        capture_output=True, text=True, timeout=120, env=env,
    ).stdout
    rates = [
        int(line.split("admissible=")[1].split("/")[0])
        for line in out.splitlines()
        if "admissible=" in line
    ]
    assert rates[0] < 40 and rates[-1] == 100  # cold → warm
    assert rates == sorted(rates)  # monotone ramp
