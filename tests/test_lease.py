"""Wire-rev-5 token leasing (perf tentpole): conservation end to end.

Server side: a grant pre-pays the slice into the LEASED window column (so
decide-path occupancy and every psum'd replica already account delegated
tokens), return/renew credit the EXACT grant bucket only while its start
stamp still matches, TTL expiry revokes, snapshot/restore and live MOVE
carry the charge while recalling the registry. Client side: hot flows
admit locally from the cached slice, every refusal falls back to the
per-request wire path, and close() returns unused tokens early.
"""

import time

import numpy as np
import pytest

from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.server import TokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.engine import ClusterFlowRule, EngineConfig, TokenStatus
from sentinel_tpu.engine.rules import ThresholdMode
from sentinel_tpu.engine.state import flow_spec

G = ThresholdMode.GLOBAL
# default window: 10 x 100ms buckets -> threshold == rule count per window
CFG = EngineConfig(max_flows=64, max_namespaces=8, batch_size=64)
FLOW = 101


def _svc(count=50.0, ns="default", **kw):
    svc = DefaultTokenService(CFG, **kw)
    svc.load_rules([ClusterFlowRule(FLOW, count, G, ns)])
    return svc


def _drain(svc, flow=FLOW):
    """Admit until BLOCKED; returns how many decisions passed — the flow's
    remaining window headroom as the decide kernel sees it."""
    passed = 0
    while svc.request_token(flow).ok:
        passed += 1
        assert passed <= 1000, "window never closed"
    return passed


# -- server conservation ------------------------------------------------------
class TestServerLease:
    def test_grant_charges_window_and_decide_sees_it(self, manual_clock):
        svc = _svc()
        r = svc.lease_grant(FLOW, want=20)
        assert r.ok and r.tokens == 20 and r.lease_id > 0 and r.ttl_ms > 0
        # the 20 delegated tokens occupy the window NOW (charge-at-grant):
        # only 30 of the 50 window tokens remain for the decision path
        assert _drain(svc) == 30
        assert svc.outstanding_leases() == 20

    def test_return_credits_unused_tokens_back(self, manual_clock):
        svc = _svc()
        r = svc.lease_grant(FLOW, want=20)
        assert svc.lease_return(r.lease_id, used=5).ok
        # 15 unused came back; only the 5 actually spent stay charged
        assert _drain(svc) == 45
        assert svc.outstanding_leases() == 0
        assert svc.lease_stats()["returned"] == 1

    def test_return_is_idempotent_for_unknown_lease(self, manual_clock):
        svc = _svc()
        assert svc.lease_return(424242, used=7).ok
        assert _drain(svc) == 50

    def test_renew_credits_then_regrants_atomically(self, manual_clock):
        svc = _svc()
        a = svc.lease_grant(FLOW, want=20)
        b = svc.lease_renew(a.lease_id, FLOW, used=5, want=20)
        assert b.ok and b.lease_id != a.lease_id
        # credit first (LEASED 20 -> 5), then grant against the freed
        # headroom: min(20, 0.5 * (50 - 5)) = 20
        assert b.tokens == 20
        assert _drain(svc) == 50 - 5 - 20
        assert svc.outstanding_leases() == 20

    def test_credit_requires_the_exact_grant_bucket(self, manual_clock):
        # TTL far beyond the window so rotation (not expiry) is what's
        # being exercised
        svc = _svc(lease_ttl_ms=600_000)
        spec = flow_spec(CFG)
        r = svc.lease_grant(FLOW, want=20)
        manual_clock.advance(spec.bucket_ms * spec.n_buckets + 1)
        assert svc.lease_return(r.lease_id, used=5).ok
        # the grant bucket rotated out, taking the charge with it; the
        # credit MUST be dropped (not applied to some newer bucket), or the
        # window sum would go net negative and over-admit
        assert _drain(svc) == 50

    def test_fraction_caps_grant_and_headroom_refuses(self, manual_clock):
        svc = _svc()
        a = svc.lease_grant(FLOW, want=1000)
        assert a.tokens == 25  # lease_fraction 0.5 of the 50-token window
        b = svc.lease_grant(FLOW, want=1000)
        assert b.tokens == 12  # half of what the first grant left
        got, last = a.tokens + b.tokens, b
        while True:
            last = svc.lease_grant(FLOW, want=1000)
            if not last.ok:
                break
            got += last.tokens
        assert last.status == int(TokenStatus.NOT_LEASABLE)
        assert got + _drain(svc) == 50  # delegated + direct == the window

    def test_zero_want_and_unknown_flow_refused(self, manual_clock):
        svc = _svc()
        assert svc.lease_grant(FLOW, 0).status == int(
            TokenStatus.NOT_LEASABLE)
        assert svc.lease_grant(777, 8).status == int(
            TokenStatus.NO_RULE_EXISTS)

    def test_disabled_by_fraction_zero(self, manual_clock):
        svc = _svc(lease_fraction=0.0)
        assert svc.lease_grant(FLOW, 8).status == int(
            TokenStatus.NOT_LEASABLE)

    def test_ttl_expiry_revokes_and_renew_degrades_to_grant(
        self, manual_clock
    ):
        svc = _svc(lease_ttl_ms=500)
        a = svc.lease_grant(FLOW, want=20)
        manual_clock.advance(600)
        assert svc.outstanding_leases() == 0
        assert svc.lease_stats()["revoked"] == 1
        # renewing the dead lease is a credit-less grant: the old charge
        # stays in the window (a dead client may have spent all of it —
        # the conservative assumption) and a fresh slice is cut
        b = svc.lease_renew(a.lease_id, FLOW, used=20, want=10)
        assert b.ok and b.tokens == 10
        assert _drain(svc) == 50 - 20 - 10

    def test_stats_and_outstanding_gauges(self, manual_clock):
        svc = _svc()
        a = svc.lease_grant(FLOW, want=10)
        svc.lease_renew(a.lease_id, FLOW, used=10, want=10)
        s = svc.lease_stats()
        assert s["granted"] == 1 and s["renewed"] == 1
        assert s["outstanding"] == 1 and s["outstanding_tokens"] == 10


# -- failover + rebalance conservation ----------------------------------------
class TestLeaseStateMotion:
    def test_snapshot_restore_carries_charge_not_registry(
        self, manual_clock
    ):
        donor = _svc()
        donor.lease_grant(FLOW, want=20)
        heir = DefaultTokenService(CFG)
        heir.import_state(donor.export_state())
        # the LEASED charge replicated bit-equal with the window state...
        d = np.asarray(donor.export_state()["flow"]["counts"])
        h = np.asarray(heir.export_state()["flow"]["counts"])
        assert np.array_equal(d, h)
        # ...so the heir admits exactly what the donor would have
        assert _drain(heir) == 30
        # but the lease registry is host state and deliberately NOT
        # replicated: a promoted standby starts with zero outstanding and
        # serves renews as credit-less grants (see lease_renew)
        assert heir.outstanding_leases() == 0

    def test_move_transfers_charge_and_recalls_leases(self, manual_clock):
        ns = "mv-lease"
        src = DefaultTokenService(CFG)
        src.load_namespace_rules(ns, [ClusterFlowRule(11, 50.0, G, ns)])
        a = src.lease_grant(11, want=20)
        assert a.ok
        src.begin_move(ns, "10.0.0.9:1234", 3)
        # recall: the registry entry dies with the move; renew and grant
        # answer MOVED so clients re-grant at the destination
        assert src.outstanding_leases() == 0
        assert src.lease_stats()["revoked"] == 1
        r = src.lease_renew(a.lease_id, 11, used=5, want=20)
        assert r.status == int(TokenStatus.MOVED)
        assert r.endpoint == "10.0.0.9:1234" and r.tokens == 3
        assert src.lease_grant(11, 8).status == int(TokenStatus.MOVED)
        # transfer: the LEASED charge rides the namespace export — the
        # destination's window already owes the delegated 20 tokens
        doc = src.export_namespace_state(ns)
        dst = DefaultTokenService(CFG)
        dst.import_namespace_state(doc)
        assert _drain(dst, 11) == 30
        # and the same doc folds back losslessly on abort at the source
        src.abort_move(ns)
        assert _drain(src, 11) == 30


# -- client-local admission over a live front door ----------------------------
class TestClientLease:
    @pytest.fixture()
    def served(self):
        # real wall clock: the client's lease cache runs on time.monotonic.
        # TTL sized far beyond the test so only explicit paths end a lease.
        svc = DefaultTokenService(
            EngineConfig(max_flows=16, max_namespaces=4, batch_size=64),
            lease_ttl_ms=60_000,
        )
        svc.load_rules([ClusterFlowRule(1, 1e9, G)])
        server = TokenServer(svc, port=0)
        server.start()
        yield svc, server
        server.stop()
        svc.close()

    def test_local_admission_amortizes_rpcs(self, served):
        svc, server = served
        c = TokenClient("127.0.0.1", server.port, timeout_ms=3000,
                        lease=True, lease_want=64)
        try:
            for _ in range(40):
                assert c.request_token(1).ok
            s = c.lease_stats()
            # one synchronous grant; renew-ahead runs in the background;
            # everything else never touched the wire
            assert s["granted"] == 1
            assert s["local_admits"] >= 39
            assert s["wire_rows"] == 0
            assert s["rpcs"] <= 5  # handshake + grant + background renews
        finally:
            c.close()

    def test_refusal_falls_back_to_wire_decisions(self):
        svc = DefaultTokenService(
            EngineConfig(max_flows=16, max_namespaces=4, batch_size=64),
            lease_fraction=0.0,  # leasing disabled server-side
        )
        svc.load_rules([ClusterFlowRule(1, 1e9, G)])
        server = TokenServer(svc, port=0)
        server.start()
        c = TokenClient("127.0.0.1", server.port, timeout_ms=3000,
                        lease=True, lease_want=64)
        try:
            for _ in range(10):
                assert c.request_token(1).ok  # NOT_LEASABLE never loses a verdict
            s = c.lease_stats()
            assert s["refused"] >= 1
            assert s["local_admits"] == 0
            assert s["wire_rows"] == 10
        finally:
            c.close()
            server.stop()
            svc.close()

    def test_close_returns_the_unused_slice(self, served):
        svc, server = served
        c = TokenClient("127.0.0.1", server.port, timeout_ms=3000,
                        lease=True, lease_want=64)
        for _ in range(5):
            assert c.request_token(1).ok
        time.sleep(0.2)  # let any renew-ahead thread settle
        c.close()
        assert c.lease_stats()["returned"] >= 1
        s = svc.lease_stats()
        assert s["outstanding"] == 0 and s["outstanding_tokens"] == 0
        assert s["returned"] >= 1
