"""Flight recorder, spans, black-box dumps, and the per-tenant SLO plane."""

import json
import threading

import numpy as np
import pytest

from sentinel_tpu.trace import blackbox
from sentinel_tpu.trace import ring
from sentinel_tpu.trace import spans
from sentinel_tpu.trace.slo import (
    BUDGET_FRACTION,
    SloPlane,
    merge_fleet,
    reset_slo_plane_for_tests,
    slo_plane,
)


@pytest.fixture(autouse=True)
def clean_trace():
    ring.reset_for_tests()
    blackbox.reset_for_tests()
    reset_slo_plane_for_tests()
    yield
    ring.reset_for_tests()
    blackbox.reset_for_tests()
    reset_slo_plane_for_tests()


class TestRing:
    def test_disarmed_is_default(self):
        assert ring.ARMED is False

    def test_record_and_read(self):
        ring.arm(sample=1.0)
        ring.record(ring.CLIENT_IN, xid=42, shard=1, aux=8)
        ring.record(ring.REPLY_OUT, xid=42, shard=1)
        evs = ring.events(xid=42)
        assert [e["stage"] for e in evs] == ["client_in", "reply_out"]
        assert evs[0]["shard"] == 1 and evs[0]["aux"] == 8
        # time-sorted across the (single) ring
        assert evs[0]["t_ns"] <= evs[1]["t_ns"]

    def test_control_events_ignore_sampling(self):
        ring.arm(sample=0.0)  # sample nothing from the data plane
        ring.record(ring.CLIENT_IN, xid=7)
        ring.record(ring.BROWNOUT, aux=1)  # xid=0: control plane
        assert ring.events(xid=7) == []
        assert [e["stage"] for e in ring.events()] == ["brownout"]

    def test_record_many_honors_sample(self):
        ring.arm(sample=0.0)
        ring.record_many(ring.DISPATCH, [1, 2, 3])
        assert ring.events() == []
        ring.arm(sample=1.0)
        ring.record_many(ring.DISPATCH, np.array([1, 2, 3]), aux=3)
        assert sorted(e["xid"] for e in ring.events()) == [1, 2, 3]

    def test_wrap_evicts_oldest(self):
        ring.arm(sample=1.0)
        cap = ring.DEFAULT_RING_EVENTS
        for i in range(cap + 10):
            ring.record(ring.ENQUEUE, xid=i + 1)
        st = ring.status()
        assert st["threads"][0]["events"] == cap
        assert st["threads"][0]["dropped"] == 10
        evs = ring.events()
        assert len(evs) == cap
        # the 10 oldest xids were overwritten, the newest survive
        xids = {e["xid"] for e in evs}
        assert 1 not in xids and 10 not in xids
        assert cap + 10 in xids
        # rows() preserved oldest→newest order through the wrap
        assert evs[0]["xid"] == 11 and evs[-1]["xid"] == cap + 10

    def test_torn_tail_rows_dropped(self):
        # a thread that died mid-record leaves a zeroed/torn row; readers
        # must treat the ring as advisory and drop t_ns==0 rows
        ring.arm(sample=1.0)
        ring.record(ring.CLIENT_IN, xid=5)
        ring.record(ring.REPLY_OUT, xid=5)
        r = ring._TLS.ring
        r.buf[1]["t_ns"] = 0  # tear the second row
        evs = ring.events()
        assert [e["stage"] for e in evs] == ["client_in"]

    def test_dead_thread_ring_still_readable(self):
        ring.arm(sample=1.0)

        def worker():
            ring.record(ring.DISPATCH, xid=99)

        t = threading.Thread(target=worker, name="dead-lane")
        t.start()
        t.join()
        evs = ring.events(xid=99)
        assert len(evs) == 1 and evs[0]["thread"] == "dead-lane"

    def test_sampled_xids_newest_first(self):
        ring.arm(sample=1.0)
        for x in (10, 20, 30):
            ring.record(ring.CLIENT_IN, xid=x)
        ring.record(ring.CLIENT_IN, xid=20)  # re-seen: now the newest
        assert ring.sampled_xids() == [20, 30, 10]
        assert ring.sampled_xids(limit=1) == [20]

    def test_status_shape(self):
        ring.arm(sample=0.25)
        ring.record(ring.HIER)
        st = ring.status()
        assert st["armed"] is True
        assert st["sample"] == 0.25
        assert st["totalEvents"] == 1
        ring.disarm()
        assert ring.status()["armed"] is False


class TestSpans:
    def _request(self, xid):
        ring.record(ring.CLIENT_IN, xid=xid)
        ring.record(ring.ENQUEUE, xid=xid)
        ring.record(ring.DISPATCH, xid=xid)
        ring.record(ring.REPLY_OUT, xid=xid)

    def test_complete_span(self):
        ring.arm(sample=1.0)
        self._request(101)
        sp = spans.assemble(101)
        assert sp["complete"] is True
        assert sp["stages"] == ["client_in", "enqueue", "dispatch",
                                "reply_out"]
        assert sp["durationUs"] >= 0

    def test_shed_is_a_complete_exit(self):
        ring.arm(sample=1.0)
        ring.record(ring.CLIENT_IN, xid=102)
        ring.record(ring.SHED, xid=102)
        assert spans.assemble(102)["complete"] is True

    def test_incomplete_span(self):
        ring.arm(sample=1.0)
        ring.record(ring.CLIENT_IN, xid=103)
        ring.record(ring.DISPATCH, xid=103)  # reply never recorded
        sp = spans.assemble(103)
        assert sp["complete"] is False

    def test_unsampled_xid_returns_none(self):
        ring.arm(sample=1.0)
        self._request(104)
        assert spans.assemble(9999) is None

    def test_wrapped_ring_yields_incomplete_not_crash(self):
        # the entry hop was evicted by ring wrap → the span is honest
        # about the missing stage instead of raising
        ring.arm(sample=1.0)
        ring.record(ring.CLIENT_IN, xid=105)
        for i in range(ring.DEFAULT_RING_EVENTS):
            ring.record(ring.ENQUEUE, xid=1_000_000 + i)
        ring.record(ring.REPLY_OUT, xid=105)
        sp = spans.assemble(105)
        assert sp is not None and sp["complete"] is False
        assert "client_in" not in sp["stages"]

    def test_assemble_recent_and_completeness(self):
        ring.arm(sample=1.0)
        self._request(201)
        self._request(202)
        ring.record(ring.CLIENT_IN, xid=203)  # torn: no exit
        assembled = spans.assemble_recent()
        assert len(assembled) == 3
        comp = spans.completeness(assembled)
        assert comp == {"spans": 3, "complete": 2, "fraction": 2 / 3}
        assert spans.completeness([])["fraction"] is None

    def test_write_artifact(self, tmp_path):
        ring.arm(sample=1.0)
        self._request(301)
        path = spans.write_artifact(str(tmp_path / "spans.json"))
        with open(path) as f:
            doc = json.load(f)
        assert doc["schema"] == "sentinel-trace-spans/1"
        assert doc["completeness"]["complete"] == 1
        assert doc["build"]["version"]
        assert doc["spans"][0]["xid"] == 301


class TestBlackbox:
    def test_dump_parses_with_full_payload(self, tmp_path):
        ring.arm(sample=1.0)
        ring.record(ring.CLIENT_IN, xid=11)
        slo_plane().record("ns-a", 1.0, n=4)
        path = blackbox.dump("unit_test", directory=str(tmp_path))
        with open(path) as f:
            doc = json.load(f)
        assert doc["schema"] == "sentinel-blackbox/1"
        assert doc["reason"] == "unit_test"
        assert len(doc["configFingerprint"]) == 16
        assert doc["trace"]["armed"] is True
        assert any(e["xid"] == 11 for e in doc["events"])
        assert "ns-a" in doc["slo"]["tenants"]
        assert "verdicts" in doc["metrics"]
        assert doc["build"]["wire_rev"]

    def test_dump_requires_a_directory(self):
        with pytest.raises(ValueError):
            blackbox.dump("no_dir")

    def test_maybe_dump_noop_unconfigured(self):
        assert blackbox.maybe_dump("brownout:shed_low") is None
        assert blackbox.dumps_written == 0

    def test_maybe_dump_rate_limited(self, tmp_path):
        blackbox.configure(str(tmp_path), min_interval_s=3600.0)
        first = blackbox.maybe_dump("brownout:shed_low")
        assert first is not None
        assert blackbox.maybe_dump("brownout:degrade") is None
        assert blackbox.dumps_written == 1
        assert blackbox.last_path == first

    def test_config_fingerprint_tracks_config(self):
        from sentinel_tpu.core.config import SentinelConfig

        a = blackbox.config_fingerprint()
        SentinelConfig.set("sentinel.tpu.test.fingerprint", "x")
        try:
            assert blackbox.config_fingerprint() != a
        finally:
            with SentinelConfig._lock:
                SentinelConfig._props.pop("sentinel.tpu.test.fingerprint",
                                          None)
        assert blackbox.config_fingerprint() == a


class TestSloPlane:
    def test_record_and_snapshot(self):
        p = SloPlane(objective_ms=2.0)
        p.record("ns-a", 1.0, n=90)
        p.record("ns-a", 5.0, n=10)
        snap = p.snapshot()
        t = snap["tenants"]["ns-a"]
        assert snap["objectiveMs"] == 2.0
        assert t["count"] == 100
        assert t["windows"]["1m"] == {"total": 100, "over": 10}
        # 10% over a 1% budget → burn 10 on both windows
        assert t["burnRate"]["1m"] == pytest.approx(10.0)
        assert t["burnRate"]["1h"] == pytest.approx(10.0)
        assert t["p99Ms"] >= 1.0

    def test_burn_window_expiry(self):
        p = SloPlane(objective_ms=2.0)
        p.record("ns-a", 5.0, n=10, now_s=1000)
        total, over = p._tenants["ns-a"].windows["1m"].totals(now_s=1030)
        assert (total, over) == (10, 10)
        total, over = p._tenants["ns-a"].windows["1m"].totals(now_s=1061)
        assert (total, over) == (0, 0)  # aged out of the 1m window
        total, over = p._tenants["ns-a"].windows["1h"].totals(now_s=1061)
        assert (total, over) == (10, 10)  # still inside the 1h window

    def test_shed_burns_whole_budget(self):
        p = SloPlane(objective_ms=2.0)
        p.record_shed("ns-b", "overload", n=5)
        snap = p.snapshot()["tenants"]["ns-b"]
        assert snap["shed"] == {"overload": 5}
        assert snap["windows"]["1m"] == {"total": 5, "over": 5}
        assert p.burn_rates("ns-b")["1m"] == pytest.approx(1 / BUDGET_FRACTION)
        assert p.burn_rates("missing")["1m"] is None

    def test_record_shed_indexed(self):
        p = SloPlane(objective_ms=2.0)
        ns_idx = np.array([0, 0, 1, -1], dtype=np.int32)
        p.record_shed_indexed(ns_idx, ("flood", "steady"), "queue_full")
        snap = p.snapshot()["tenants"]
        assert snap["flood"]["shed"] == {"queue_full": 2}
        assert snap["steady"]["shed"] == {"queue_full": 1}
        assert snap["(no-rule)"]["shed"] == {"queue_full": 1}

    def test_render_series(self):
        p = SloPlane(objective_ms=2.0)
        p.record("ns-a", 5.0, n=10)
        p.record_shed("ns-a", "brownout", n=3)
        text = p.render()
        assert "sentinel_slo_objective_ms 2" in text
        assert 'sentinel_slo_latency_ms_bucket{namespace="ns-a"' in text
        assert 'sentinel_slo_burn_rate{namespace="ns-a",window="1m"}' in text
        assert 'sentinel_slo_shed_total{namespace="ns-a",reason="brownout"} 3' \
            in text

    def test_singleton_reads_configured_objective(self):
        from sentinel_tpu.core.config import SentinelConfig
        from sentinel_tpu.trace.slo import KEY_OBJECTIVE_MS

        SentinelConfig.set(KEY_OBJECTIVE_MS, "50")
        try:
            reset_slo_plane_for_tests()
            assert slo_plane().objective_ms == 50.0
        finally:
            with SentinelConfig._lock:
                SentinelConfig._props.pop(KEY_OBJECTIVE_MS, None)


class TestMergeFleet:
    def _pod(self, total, over, count=None, p99=1.0, shed=None):
        return {"objectiveMs": 2.0, "tenants": {"ns-a": {
            "count": count if count is not None else total,
            "p99Ms": p99,
            "windows": {"1m": {"total": total, "over": over},
                        "1h": {"total": total, "over": over}},
            "shed": shed or {},
        }}}

    def test_sums_windows_and_recomputes_burn(self):
        # pod A: 100 rows none over; pod B: 100 rows all over.
        # a mean of per-pod burns would say 50× regardless of load split;
        # the merged burn must come from the SUMMED windows
        merged = merge_fleet([self._pod(100, 0), self._pod(100, 100)])
        t = merged["tenants"]["ns-a"]
        assert t["windows"]["1m"] == {"total": 200, "over": 100}
        assert t["burnRate"]["1m"] == pytest.approx(50.0)
        assert t["count"] == 200

    def test_keeps_worst_p99_and_sums_shed(self):
        merged = merge_fleet([
            self._pod(10, 0, p99=1.5, shed={"overload": 3}),
            self._pod(10, 0, p99=8.0, shed={"overload": 4, "brownout": 1}),
        ])
        t = merged["tenants"]["ns-a"]
        assert t["p99Ms"] == 8.0
        assert t["shed"] == {"overload": 7, "brownout": 1}

    def test_malformed_pod_contributes_nothing(self):
        merged = merge_fleet([
            self._pod(10, 5), "not-a-snapshot", {"tenants": None}, None,
        ])
        t = merged["tenants"]["ns-a"]
        assert t["windows"]["1m"] == {"total": 10, "over": 5}

    def test_live_snapshot_roundtrip(self):
        a = SloPlane(objective_ms=2.0)
        b = SloPlane(objective_ms=2.0)
        a.record("ns-x", 1.0, n=50)
        b.record("ns-x", 9.0, n=50)
        merged = merge_fleet([a.snapshot(), b.snapshot()])
        t = merged["tenants"]["ns-x"]
        assert t["count"] == 100
        assert t["burnRate"]["1m"] == pytest.approx(50.0)


class TestTransportCommands:
    def _route(self, path, params, body=""):
        import sentinel_tpu.transport.handlers  # noqa: F401
        from sentinel_tpu.transport.command import _route

        code, payload, ctype = _route("GET", path, params, body)
        assert code == 200
        return json.loads(payload)

    def test_trace_arm_status_disarm(self):
        out = self._route("cluster/server/trace",
                          {"action": "arm", "sample": "0.5"})
        assert out["armed"] is True and out["sample"] == 0.5
        assert ring.ARMED is True
        out = self._route("cluster/server/trace", {"action": "disarm"})
        assert out["armed"] is False
        assert ring.ARMED is False

    def test_trace_spans_and_blackbox(self, tmp_path):
        ring.arm(sample=1.0)
        ring.record(ring.CLIENT_IN, xid=77)
        ring.record(ring.REPLY_OUT, xid=77)
        out = self._route("cluster/server/trace",
                          {"action": "spans", "xid": "77"})
        assert out["complete"] is True
        out = self._route("cluster/server/trace",
                          {"action": "spans", "xid": "0x4D"})  # hex = 77
        assert out["xid"] == 77
        out = self._route("cluster/server/trace", {"action": "spans"})
        assert out["completeness"]["spans"] == 1
        out = self._route("cluster/server/trace",
                          {"action": "spans", "dir": str(tmp_path)})
        assert json.load(open(out["path"]))["schema"] == \
            "sentinel-trace-spans/1"
        out = self._route("cluster/server/trace",
                          {"action": "blackbox", "dir": str(tmp_path)})
        assert json.load(open(out["path"]))["schema"] == "sentinel-blackbox/1"
        # no dir configured and none passed → clean error, not a 500
        blackbox.reset_for_tests()
        out = self._route("cluster/server/trace", {"action": "blackbox"})
        assert "error" in out

    def test_slo_local_and_fleet(self):
        slo_plane().record("ns-a", 5.0, n=10)
        out = self._route("cluster/server/slo", {"action": "local"})
        assert "ns-a" in out["tenants"]
        pods = json.dumps([out, {"slo": out}, "garbage"])
        merged = self._route("cluster/server/slo", {"action": "fleet"},
                             body=pods)
        assert merged["pods"] == 3
        assert merged["tenants"]["ns-a"]["count"] == 20

    def test_cluster_server_stats_carries_trace_slo_build(self):
        out = self._route("clusterServerStats", {})
        assert "armed" in out["trace"]
        assert "tenants" in out["slo"]
        assert out["buildInfo"]["version"]
