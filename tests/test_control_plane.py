"""Control plane tests: datasources, command center, metric log, heartbeat."""

import json
import threading
import urllib.request

import pytest

import sentinel_tpu.local as sentinel
from sentinel_tpu.datasource import (
    FileRefreshableDataSource,
    FileWritableDataSource,
    WritableDataSourceRegistry,
    flow_rules_from_json,
    flow_rules_to_json,
)
from sentinel_tpu.local import BlockException, FlowRule, FlowRuleManager
from sentinel_tpu.metrics.log import MetricNode, MetricSearcher, MetricTimer, MetricWriter
from sentinel_tpu.transport.command import CommandCenter


@pytest.fixture(autouse=True)
def clean_engine(manual_clock):
    sentinel.reset_for_tests()
    WritableDataSourceRegistry.reset_for_tests()
    yield manual_clock
    WritableDataSourceRegistry.reset_for_tests()
    sentinel.reset_for_tests()


RULES_JSON = json.dumps(
    [{"resource": "ds_res", "count": 2, "grade": 1, "limitApp": "default"}]
)


class TestDatasources:
    def test_file_datasource_loads_and_follows_changes(self, tmp_path, manual_clock):
        path = tmp_path / "flow.json"
        path.write_text(RULES_JSON)
        ds = FileRefreshableDataSource(str(path), flow_rules_from_json,
                                       refresh_interval_s=0.05)
        FlowRuleManager.register_property(ds.property)
        ds.start()
        try:
            assert len(FlowRuleManager.get_rules("ds_res")) == 1
            assert FlowRuleManager.get_rules("ds_res")[0].count == 2
            # change the file → rules follow
            path.write_text(json.dumps([{"resource": "ds_res", "count": 9}]))
            deadline = threading.Event()
            for _ in range(100):
                if FlowRuleManager.get_rules("ds_res") and \
                        FlowRuleManager.get_rules("ds_res")[0].count == 9:
                    break
                deadline.wait(0.05)
            assert FlowRuleManager.get_rules("ds_res")[0].count == 9
        finally:
            ds.close()

    def test_sentinel_json_schema_roundtrip(self):
        rules = flow_rules_from_json(RULES_JSON)
        text = flow_rules_to_json(rules)
        again = flow_rules_from_json(text)
        assert again == rules

    def test_malformed_file_keeps_last_good_rules(self, tmp_path, manual_clock):
        path = tmp_path / "flow.json"
        path.write_text(RULES_JSON)
        ds = FileRefreshableDataSource(str(path), flow_rules_from_json)
        FlowRuleManager.register_property(ds.property)
        ds.refresh()
        assert len(FlowRuleManager.get_rules("ds_res")) == 1
        path.write_text("{not json")
        ds.refresh()  # swallowed, logged
        assert len(FlowRuleManager.get_rules("ds_res")) == 1


@pytest.fixture
def command_center():
    cc = CommandCenter(host="127.0.0.1", port=0).start()
    yield cc
    cc.stop()


def http_get(cc, path, timeout=5):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{cc.port}/{path}", timeout=timeout
    ) as r:
        return r.status, r.read().decode()


def http_post(cc, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{cc.port}/{path}", data=body.encode(), method="POST"
    )
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, r.read().decode()


class TestCommandCenter:
    def test_api_lists_commands(self, command_center):
        status, body = http_get(command_center, "api")
        urls = {item["url"] for item in json.loads(body)}
        for expected in ("version", "getRules", "setRules", "metric",
                         "clusterNode", "basicInfo", "systemStatus"):
            assert f"/{expected}" in urls

    def test_version_and_basic_info(self, command_center):
        status, body = http_get(command_center, "version")
        assert "sentinel-tpu/" in body
        status, body = http_get(command_center, "basicInfo")
        info = json.loads(body)
        assert info["pid"] > 0

    def test_rule_crud_roundtrip(self, command_center):
        status, body = http_post(
            command_center, "setRules?type=flow",
            json.dumps([{"resource": "cmd_res", "count": 1}]),
        )
        assert body == "success"
        # rule actually enforced
        ok = blocked = 0
        for _ in range(3):
            try:
                with sentinel.entry("cmd_res"):
                    ok += 1
            except BlockException:
                blocked += 1
        assert (ok, blocked) == (1, 2)
        status, body = http_get(command_center, "getRules?type=flow")
        rules = json.loads(body)
        assert rules[0]["resource"] == "cmd_res"

    def test_gateway_api_definitions_roundtrip(self, command_center):
        from sentinel_tpu.adapters.gateway_api import (
            GatewayApiDefinitionManager,
        )

        try:
            defs = [{"apiName": "prod-api", "predicateItems": [
                {"pattern": "/product/", "matchStrategy": 1}]}]
            status, body = http_post(
                command_center, "gateway/updateApiDefinitions",
                json.dumps(defs),
            )
            assert body == "success"
            status, body = http_get(
                command_center, "gateway/getApiDefinitions"
            )
            got = json.loads(body)
            assert got == defs
            # the matcher actually picks the group up
            from sentinel_tpu.adapters.gateway_api import (
                GatewayApiMatcherManager,
            )

            assert GatewayApiMatcherManager.pick_matching_api_names(
                "/product/7"
            ) == ["prod-api"]
        finally:
            GatewayApiDefinitionManager.reset_for_tests()

    def test_set_rules_writes_through_datasource(self, command_center, tmp_path):
        from sentinel_tpu.datasource import converters as conv

        path = tmp_path / "flow_out.json"
        # the natural pairing: the handler hands *parsed rules* to the
        # registered serializer (ModifyRulesCommandHandler.java:58)
        WritableDataSourceRegistry.register(
            "flow", FileWritableDataSource(str(path), conv.flow_rules_to_json)
        )
        http_post(
            command_center, "setRules?type=flow",
            json.dumps([{"resource": "w_res", "count": 5}]),
        )
        saved = json.loads(path.read_text())
        assert saved[0]["resource"] == "w_res"
        assert saved[0]["count"] == 5

    def test_cluster_node_stats(self, command_center):
        with sentinel.entry("stat_cmd_res"):
            pass
        status, body = http_get(command_center, "clusterNode")
        nodes = json.loads(body)
        names = [n["resourceName"] for n in nodes]
        assert "stat_cmd_res" in names

    def test_unknown_command_404(self, command_center):
        try:
            http_get(command_center, "nonsense")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert "api" in e.read().decode()

    def test_cluster_mode_commands(self, command_center):
        status, body = http_get(command_center, "getClusterMode")
        assert json.loads(body)["mode"] == -1
        http_get(command_center, "setClusterMode?mode=0")
        status, body = http_get(command_center, "getClusterMode")
        assert json.loads(body)["mode"] == 0
        from sentinel_tpu.cluster import api as cluster_api

        cluster_api.reset_for_tests()

    def test_demotion_clears_embedded_service(self, command_center):
        # promote to SERVER (ephemeral port), then demote: the stopped
        # server's service must not keep answering cluster/server/* commands
        from sentinel_tpu.cluster import api as cluster_api

        try:
            # promotion warms up every serve-bucket kernel variant — allow
            # for the compiles
            status, body = http_get(
                command_center, "setClusterMode?mode=1&tokenPort=0", timeout=120
            )
            assert "success" in body
            assert cluster_api.get_embedded_server() is not None
            status, body = http_get(command_center, "cluster/server/info")
            assert status == 200 and "error" not in body
            http_get(command_center, "setClusterMode?mode=-1")
            assert cluster_api.get_embedded_server() is None
            status, body = http_get(command_center, "cluster/server/info")
            assert "error" in body  # 'not a token server'
        finally:
            cluster_api.reset_for_tests()


class TestAsgiCommandCenter:
    """ASGI-embedded command transport (netty-http/spring-mvc variant
    analog): same handler registry, served by the app's own server."""

    @staticmethod
    def _call(app, path, method="GET", query="", body=b""):
        import asyncio

        sent = []

        async def run():
            scope = {"type": "http", "method": method, "path": path,
                     "query_string": query.encode()}
            chunks = [{"type": "http.request", "body": body}]

            async def receive():
                return chunks.pop(0)

            async def send(msg):
                sent.append(msg)

            await app(scope, receive, send)

        asyncio.run(run())
        status = next(
            m["status"] for m in sent if m["type"] == "http.response.start"
        )
        out = b"".join(
            m.get("body", b"") for m in sent
            if m["type"] == "http.response.body"
        )
        return status, out

    def test_api_version_and_unknown(self):
        from sentinel_tpu.transport.command_asgi import command_asgi_app

        app = command_asgi_app()
        status, body = self._call(app, "/api")
        assert status == 200 and b"getRules" in body
        status, body = self._call(app, "/version")
        assert status == 200 and b"sentinel-tpu" in body
        status, _ = self._call(app, "/definitely-not-a-command")
        assert status == 404

    def test_rule_crud_matches_thread_server(self):
        from sentinel_tpu.transport.command_asgi import command_asgi_app

        app = command_asgi_app()
        rules = [{"resource": "asgi_res", "count": 7, "grade": 1}]
        status, body = self._call(
            app, "/setRules", method="POST", query="type=flow",
            body=json.dumps(rules).encode(),
        )
        assert status == 200 and b"success" in body
        status, body = self._call(app, "/getRules", query="type=flow")
        assert status == 200
        got = json.loads(body)
        assert any(r["resource"] == "asgi_res" for r in got)

    def test_body_size_cap(self):
        from sentinel_tpu.transport.command_asgi import command_asgi_app

        app = command_asgi_app(max_body_bytes=64)
        status, _ = self._call(
            app, "/setRules", method="POST", query="type=flow",
            body=b"x" * 128,
        )
        assert status == 413

    def test_lifespan_protocol(self):
        import asyncio

        from sentinel_tpu.transport.command_asgi import command_asgi_app

        app = command_asgi_app()
        sent = []

        async def run():
            msgs = [{"type": "lifespan.startup"}, {"type": "lifespan.shutdown"}]

            async def receive():
                return msgs.pop(0)

            async def send(msg):
                sent.append(msg["type"])

            await app({"type": "lifespan"}, receive, send)

        asyncio.run(run())
        assert sent == ["lifespan.startup.complete",
                        "lifespan.shutdown.complete"]


class TestMetricLog:
    def test_writer_searcher_roundtrip(self, tmp_path):
        w = MetricWriter(base_dir=str(tmp_path), single_file_size=10_000)
        nodes = [
            MetricNode(timestamp_ms=1_700_000_000_000, resource="res|pipe",
                       pass_qps=10, block_qps=2, rt=1.5),
            MetricNode(timestamp_ms=1_700_000_000_000, resource="other",
                       pass_qps=3),
        ]
        w.write(nodes)
        w.close()
        s = MetricSearcher(str(tmp_path), w.app)
        found = s.find(1_699_999_999_000, 1_700_000_001_000)
        assert len(found) == 2
        assert found[0].resource == "res_pipe"  # pipe escaped
        assert found[0].pass_qps == 10
        only = s.find(0, 2**61, identity="other")
        assert len(only) == 1 and only[0].pass_qps == 3

    def test_searcher_seeks_via_index(self, tmp_path):
        # many seconds of data; a narrow window must come back complete even
        # though the seek skips everything before it
        w = MetricWriter(base_dir=str(tmp_path), single_file_size=10_000_000)
        t0 = 1_700_000_000_000
        for i in range(200):
            w.write([MetricNode(timestamp_ms=t0 + i * 1000, resource="r",
                                pass_qps=i)])
        w.close()
        s = MetricSearcher(str(tmp_path), w.app)
        found = s.find(t0 + 150_000, t0 + 152_000)
        assert [n.pass_qps for n in found] == [150, 151, 152]
        # the seek really skipped: offset for a late window is deep in the file
        idx = str(tmp_path / f"{w.app}-metrics.log.0.idx")
        assert s._seek_offset(idx, t0 + 150_000) > 0

    def test_rolling_keeps_bounded_files(self, tmp_path):
        w = MetricWriter(base_dir=str(tmp_path), single_file_size=200,
                         total_file_count=3)
        for i in range(40):
            w.write([MetricNode(timestamp_ms=1_700_000_000_000 + i * 1000,
                                resource=f"r{i}", pass_qps=1)])
        w.close()
        import os

        files = [f for f in os.listdir(tmp_path) if not f.endswith(".idx")]
        assert 1 <= len(files) <= 3

    def test_metric_timer_collects_from_engine(self, manual_clock):
        with sentinel.entry("timer_res"):
            pass
        manual_clock.sleep(1000)  # move into the next second so prev is complete
        timer = MetricTimer.__new__(MetricTimer)  # no writer needed
        nodes = MetricTimer.collect_once(timer)
        names = [n.resource for n in nodes]
        assert "timer_res" in names


class TestHeartbeat:
    def test_heartbeat_posts_registration(self, command_center):
        # a tiny dashboard stub: reuse the command center HTTP machinery
        received = {}
        from sentinel_tpu.transport.command import command_mapping

        @command_mapping("registry/machine", "test stub")
        def stub(params, body):
            received.update(json.loads(body))
            return "ok"

        from sentinel_tpu.transport.heartbeat import HeartbeatSender

        hb = HeartbeatSender(
            dashboard_addrs=[f"127.0.0.1:{command_center.port}"],
            command_port=1234,
        )
        assert hb.send_once() is True
        assert received["port"] == 1234
        assert received["app"]

class TestSwitchCommands:
    """Regression: sentinel_tpu.local.sph must resolve to the *module*, not the
    re-exported ``sph`` function (round-2 shadowing bug broke these commands
    and reset_for_tests)."""

    def test_get_and_set_switch_roundtrip(self, command_center):
        status, body = http_get(command_center, "getSwitch")
        assert status == 200
        assert json.loads(body)["enabled"] is True

        status, body = http_get(command_center, "setSwitch?value=false")
        assert status == 200 and "success" in body
        status, body = http_get(command_center, "getSwitch")
        assert json.loads(body)["enabled"] is False

        http_get(command_center, "setSwitch?value=true")
        status, body = http_get(command_center, "getSwitch")
        assert json.loads(body)["enabled"] is True

    def test_set_switch_rejects_bad_value(self, command_center):
        status, body = http_get(command_center, "setSwitch?value=banana")
        assert "error" in body

    def test_local_reset_for_tests_direct(self):
        import sentinel_tpu.local as local_pkg

        local_pkg.reset_for_tests()  # must not raise
        from sentinel_tpu.local.sph import is_enabled

        assert is_enabled() is True


class TestDatasourceClusterAssignment:
    """Property/datasource-driven cluster reconfiguration
    (ClusterClientConfigManager / ClusterStateManager property path)."""

    @pytest.fixture(autouse=True)
    def clean(self):
        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.cluster import assign
        from sentinel_tpu.transport import handlers as H

        yield
        assign.reset_for_tests()
        H.apply_cluster_mode(-1)  # stop any promoted server
        H._CLUSTER_CLIENT_CONFIG.clear()
        cluster_api.reset_for_tests()

    def test_file_assignment_repoints_client(self, tmp_path):
        import jax  # noqa: F401  (conftest pinned CPU)

        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.cluster import assign
        from sentinel_tpu.cluster.server import TokenServer
        from sentinel_tpu.cluster.token_service import DefaultTokenService
        from sentinel_tpu.datasource.file import FileRefreshableDataSource
        from sentinel_tpu.engine import ClusterFlowRule, EngineConfig
        from sentinel_tpu.engine.rules import ThresholdMode
        from sentinel_tpu.transport import handlers as H

        cfg = EngineConfig(max_flows=16, max_namespaces=4, batch_size=64)
        svc = DefaultTokenService(cfg)
        svc.load_rules(
            [ClusterFlowRule(flow_id=1, count=3.0,
                             mode=ThresholdMode.GLOBAL)]
        )
        server = TokenServer(svc, port=0)
        server.start()
        try:
            path = tmp_path / "assign.json"
            path.write_text(json.dumps(
                {"serverHost": "127.0.0.1", "serverPort": server.port,
                 "requestTimeout": 2000, "namespace": "nsX"}
            ))
            ds = FileRefreshableDataSource(str(path), converter=json.loads)
            assign.register_client_assign_property(ds.property)
            ds.refresh()
            assert H._CLUSTER_CLIENT_CONFIG["serverPort"] == server.port
            assert H._CLUSTER_CLIENT_CONFIG["namespace"] == "nsX"
            assert cluster_api.get_mode() == cluster_api.ClusterMode.CLIENT
            # the installed client really serves verdicts from that server
            oks = sum(
                cluster_api._pick_service().request_token(1).ok
                for _ in range(5)
            )
            assert oks == 3
            # flip the file → client re-points (new port recorded)
            path.write_text(json.dumps(
                {"serverHost": "127.0.0.1", "serverPort": server.port,
                 "requestTimeout": 50, "namespace": "nsY"}
            ))
            ds.refresh()
            assert H._CLUSTER_CLIENT_CONFIG["namespace"] == "nsY"
        finally:
            server.stop()

    def test_mode_property_promotes_and_demotes(self):
        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.cluster import assign
        from sentinel_tpu.core.property import DynamicProperty

        prop = DynamicProperty()
        assign.register_cluster_mode_property(prop)
        prop.update_value({"mode": 1, "tokenPort": 0})
        assert cluster_api.get_embedded_server() is not None
        assert cluster_api.get_mode() == cluster_api.ClusterMode.SERVER
        prop.update_value(-1)
        assert cluster_api.get_embedded_server() is None

    def test_identical_assignment_does_not_churn_connection(self, tmp_path):
        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.cluster import assign
        from sentinel_tpu.core.property import DynamicProperty

        prop = DynamicProperty()
        assign.register_client_assign_property(prop)
        payload = {"serverHost": "127.0.0.1", "serverPort": 19999}
        prop.update_value(dict(payload))
        first = cluster_api._client
        assert first is not None
        # same assignment again (datasource poll) → same client object
        prop.update_value({**payload, "_noise": 1})  # dict differs, config same
        assert cluster_api._client is first

    def test_mode_property_port_change_moves_server(self):
        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.cluster import assign
        from sentinel_tpu.core.property import DynamicProperty
        from sentinel_tpu.transport import handlers as H

        prop = DynamicProperty()
        assign.register_cluster_mode_property(prop)
        prop.update_value({"mode": 1, "tokenPort": 0})
        first = H._EMBEDDED_SERVER["server"]
        port1 = first.port
        # pick a different concrete port and push it
        import socket as s

        sock = s.socket()
        sock.bind(("127.0.0.1", 0))
        port2 = sock.getsockname()[1]
        sock.close()
        prop.update_value({"mode": 1, "tokenPort": port2})
        second = H._EMBEDDED_SERVER["server"]
        assert second.port == port2
        assert second.service is first.service  # rules/counters preserved

    def test_reassignment_after_demotion_restores_client_mode(self):
        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.transport import handlers as H

        payload = {"serverHost": "127.0.0.1", "serverPort": 19998}
        assert H.apply_client_assignment(payload) is None
        assert cluster_api.get_mode() == cluster_api.ClusterMode.CLIENT
        H.apply_cluster_mode(-1)  # fleet ops switch the agent off
        assert cluster_api.get_mode() == cluster_api.ClusterMode.NOT_STARTED
        # identical re-assignment must restore CLIENT mode, not no-op
        assert H.apply_client_assignment(payload) is None
        assert cluster_api.get_mode() == cluster_api.ClusterMode.CLIENT
        assert cluster_api._pick_service() is not None

    def test_mode_port_move_rolls_back_on_bind_failure(self):
        import socket as s

        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.engine import ClusterFlowRule
        from sentinel_tpu.engine.rules import ThresholdMode
        from sentinel_tpu.transport import handlers as H

        H.apply_cluster_mode(1, 0)
        server = H._EMBEDDED_SERVER["server"]
        old_port = server.port
        service = server.service
        service.load_rules(
            [ClusterFlowRule(flow_id=9, count=5.0, mode=ThresholdMode.GLOBAL)]
        )
        # a port that is already bound → the move must fail...
        blocker = s.socket()
        blocker.bind(("0.0.0.0", 0))
        blocker.listen(1)
        busy_port = blocker.getsockname()[1]
        try:
            with pytest.raises(Exception):
                H.apply_cluster_mode(1, busy_port)
            # ...and roll back: a server still runs on the old port with the
            # SAME service (rules preserved)
            rolled = H._EMBEDDED_SERVER["server"]
            assert rolled is not None
            assert rolled.port == old_port
            assert rolled.service is service
            assert [r.flow_id for r in rolled.service.current_rules()] == [9]
        finally:
            blocker.close()

    def test_native_transport_selected_by_config(self):
        # csp.sentinel.cluster.server.native=true promotes through the
        # native epoll front door, and a port move preserves the class
        from sentinel_tpu.cluster.server_native import (
            NativeTokenServer,
            native_available,
        )
        from sentinel_tpu.core.config import SentinelConfig
        from sentinel_tpu.transport import handlers as H

        if not native_available():
            pytest.skip("native library not built")
        SentinelConfig.set("csp.sentinel.cluster.server.native", "true")
        try:
            H.apply_cluster_mode(1, 0)
            server = H._EMBEDDED_SERVER["server"]
            assert isinstance(server, NativeTokenServer)
            import socket as s

            sock = s.socket()
            sock.bind(("0.0.0.0", 0))
            new_port = sock.getsockname()[1]
            sock.close()
            H.apply_cluster_mode(1, new_port)
            moved = H._EMBEDDED_SERVER["server"]
            assert isinstance(moved, NativeTokenServer)
            assert moved.port == new_port
        finally:
            H.apply_cluster_mode(-1)
            SentinelConfig.reset_for_tests()

    def test_port_move_preserves_server_tuning(self):
        # a datasource-driven port change rebuilds the TokenServer; operator
        # tuning (batch window, loop count, …) must survive the move instead
        # of resetting to constructor defaults (round-3 advisor finding)
        import socket as s

        from sentinel_tpu.transport import handlers as H

        H.apply_cluster_mode(1, 0)
        server = H._EMBEDDED_SERVER["server"]
        server.batch_window_ms = 0.7
        server.max_batch = 512
        server.inline_below = 16
        server.idle_ttl_s = 123.0
        sock = s.socket()
        sock.bind(("0.0.0.0", 0))
        new_port = sock.getsockname()[1]
        sock.close()
        H.apply_cluster_mode(1, new_port)
        moved = H._EMBEDDED_SERVER["server"]
        assert moved is not server and moved.port == new_port
        assert moved.batch_window_ms == 0.7
        assert moved.max_batch == 512
        assert moved.inline_below == 16
        assert moved.idle_ttl_s == 123.0

    def test_port_move_rearms_concurrent_expiry(self):
        import socket as s

        from sentinel_tpu.cluster.concurrent import ConcurrentFlowRule
        from sentinel_tpu.transport import handlers as H

        H.apply_cluster_mode(1, 0)
        service = H._EMBEDDED_SERVER["server"].service
        service.load_concurrent_rules(
            [ConcurrentFlowRule(flow_id=4, concurrency_level=2)]
        )
        assert service._expiry is not None
        sock = s.socket()
        sock.bind(("0.0.0.0", 0))
        new_port = sock.getsockname()[1]
        sock.close()
        H.apply_cluster_mode(1, new_port)
        moved = H._EMBEDDED_SERVER["server"]
        assert moved.port == new_port
        assert moved.service is service
        # stop() closed the expiry sweeper; the restart must re-arm it
        assert service._expiry is not None
