"""Backend datasources against in-process fake servers.

Mirrors the reference's per-backend submodule tests (which mock the vendor
clients): here each backend is driven against a local fake speaking the
real wire protocol — HTTP for consul/etcd/nacos/apollo/eureka/config-server,
RESP over a socket for redis, an injected fake client for zookeeper.
"""

import base64
import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from sentinel_tpu.datasource import (
    ApolloDataSource,
    ConsulDataSource,
    EtcdDataSource,
    EurekaDataSource,
    NacosDataSource,
    RedisDataSource,
    SpringCloudConfigDataSource,
    ZookeeperDataSource,
    flow_rules_from_json,
)

RULES_V1 = json.dumps([{"resource": "r", "count": 5}])
RULES_V2 = json.dumps([{"resource": "r", "count": 9}])


def wait_for(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class FakeHttp:
    """Configurable fake HTTP server; route -> callable(handler) or
    (status, headers, body) tuple."""

    def __init__(self):
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def _serve(self):
                path = self.path.split("?")[0]
                route = fake.routes.get((self.command, path))
                if route is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                if callable(route):
                    route(self)
                    return
                status, headers, body = route
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            do_GET = do_POST = _serve

            def log_message(self, *a):  # quiet
                pass

        self.routes = {}
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture
def http_server():
    srv = FakeHttp()
    yield srv
    srv.close()


def counts(ds):
    rules = ds.property.value or []
    return [r.count for r in rules]


class TestConsul:
    def test_initial_read_and_push(self, http_server):
        state = {"value": RULES_V1, "index": 7}
        changed = threading.Event()

        def kv(h):
            qs = h.path.split("?", 1)[1] if "?" in h.path else ""
            if "index=" in qs:  # blocking query: wait for a change signal
                changed.wait(2)
            h.send_response(200)
            h.send_header("X-Consul-Index", str(state["index"]))
            h.end_headers()
            payload = [{"Value": base64.b64encode(
                state["value"].encode()).decode()}]
            h.wfile.write(json.dumps(payload).encode())

        http_server.routes[("GET", "/v1/kv/sentinel/rules")] = kv
        ds = ConsulDataSource(
            flow_rules_from_json, port=http_server.port, wait_s=1
        ).start()
        try:
            assert counts(ds) == [5]
            state.update(value=RULES_V2, index=8)
            changed.set()
            assert wait_for(lambda: counts(ds) == [9])
        finally:
            ds.close()


class TestEtcd:
    def test_watch_stream_triggers_refresh(self, http_server):
        # true watch (jetcd Watch analog): the /v3/watch chunked stream
        # delivers an events message and the value updates WITHOUT waiting
        # for a mod-revision poll (poll interval here is far beyond the
        # wait_for window)
        state = {"value": RULES_V1, "rev": 1}
        changed = threading.Event()

        def rng(h):
            length = int(h.headers.get("Content-Length", 0))
            h.rfile.read(length)
            h.send_response(200)
            h.end_headers()
            body = {"kvs": [{
                "value": base64.b64encode(state["value"].encode()).decode(),
                "mod_revision": str(state["rev"]),
            }]}
            h.wfile.write(json.dumps(body).encode())

        def watch(h):
            length = int(h.headers.get("Content-Length", 0))
            req = json.loads(h.rfile.read(length))
            assert "create_request" in req
            # real chunked transfer needs HTTP/1.1 on the status line —
            # under the handler's default HTTP/1.0 the client ignores
            # Transfer-Encoding and this test wouldn't exercise dechunking
            h.protocol_version = "HTTP/1.1"
            h.send_response(200)
            h.send_header("Transfer-Encoding", "chunked")
            h.end_headers()

            def chunk(obj):
                data = json.dumps(obj).encode() + b"\n"
                h.wfile.write(f"{len(data):x}\r\n".encode())
                h.wfile.write(data + b"\r\n")
                h.wfile.flush()

            chunk({"result": {"created": True}})
            if changed.wait(5):
                chunk({"result": {"events": [{"type": "PUT"}]}})
            # hold the stream open briefly so the client reads the event,
            # then end the chunked body properly
            changed.wait(0.2)
            h.wfile.write(b"0\r\n\r\n")
            h.close_connection = True

        http_server.routes[("POST", "/v3/kv/range")] = rng
        http_server.routes[("POST", "/v3/watch")] = watch
        ds = EtcdDataSource(
            flow_rules_from_json,
            endpoint=f"http://127.0.0.1:{http_server.port}",
            refresh_interval_s=30.0,  # poll can't be what picks this up
        ).start()
        try:
            assert counts(ds) == [5]
            state.update(value=RULES_V2, rev=2)
            changed.set()
            assert wait_for(lambda: counts(ds) == [9])
        finally:
            ds.close()

    def test_poll_on_mod_revision(self, http_server):
        state = {"value": RULES_V1, "rev": 1}

        def rng(h):
            length = int(h.headers.get("Content-Length", 0))
            h.rfile.read(length)
            h.send_response(200)
            h.end_headers()
            body = {"kvs": [{
                "value": base64.b64encode(state["value"].encode()).decode(),
                "mod_revision": str(state["rev"]),
            }]}
            h.wfile.write(json.dumps(body).encode())

        http_server.routes[("POST", "/v3/kv/range")] = rng
        ds = EtcdDataSource(
            flow_rules_from_json,
            endpoint=f"http://127.0.0.1:{http_server.port}",
            refresh_interval_s=0.05,
            watch=False,  # this test exercises the poll backstop alone
        ).start()
        try:
            assert counts(ds) == [5]
            state.update(value=RULES_V2, rev=2)
            assert wait_for(lambda: counts(ds) == [9])
        finally:
            ds.close()


class TestNacos:
    def test_long_poll_change(self, http_server):
        state = {"value": RULES_V1}
        changed = threading.Event()

        def get_cfg(h):
            h.send_response(200)
            h.end_headers()
            h.wfile.write(state["value"].encode())

        def listener(h):
            length = int(h.headers.get("Content-Length", 0))
            h.rfile.read(length)
            fired = changed.wait(1)
            h.send_response(200)
            h.end_headers()
            if fired:
                changed.clear()
                h.wfile.write(b"sentinel-rules%02DEFAULT_GROUP%01")

        http_server.routes[("GET", "/nacos/v1/cs/configs")] = get_cfg
        http_server.routes[("POST", "/nacos/v1/cs/configs/listener")] = listener
        ds = NacosDataSource(
            flow_rules_from_json,
            server_addr=f"127.0.0.1:{http_server.port}",
            data_id="sentinel-rules",
            long_poll_timeout_ms=1000,
        ).start()
        try:
            assert counts(ds) == [5]
            state["value"] = RULES_V2
            changed.set()
            assert wait_for(lambda: counts(ds) == [9])
        finally:
            ds.close()


class TestApollo:
    def test_notification_long_poll(self, http_server):
        state = {"value": RULES_V1, "nid": 3}
        changed = threading.Event()

        def configs(h):
            h.send_response(200)
            h.end_headers()
            h.wfile.write(json.dumps({
                "configurations": {"sentinel.rules": state["value"]}
            }).encode())

        def notifications(h):
            fired = changed.wait(1)
            if not fired:
                h.send_response(304)
                h.end_headers()
                return
            changed.clear()
            h.send_response(200)
            h.end_headers()
            h.wfile.write(json.dumps([{
                "namespaceName": "application",
                "notificationId": state["nid"],
            }]).encode())

        http_server.routes[
            ("GET", "/configs/sentinel/default/application")] = configs
        http_server.routes[("GET", "/notifications/v2")] = notifications
        ds = ApolloDataSource(
            flow_rules_from_json,
            server_url=f"http://127.0.0.1:{http_server.port}",
            long_poll_timeout_s=1,
        ).start()
        try:
            assert counts(ds) == [5]
            state.update(value=RULES_V2, nid=4)
            changed.set()
            assert wait_for(lambda: counts(ds) == [9])
            assert ds._notification_id == 4
        finally:
            ds.close()


class TestEureka:
    def test_reads_instance_metadata_with_fallback(self, http_server):
        body = json.dumps({"application": {"instance": [
            {"instanceId": "other", "metadata": {}},
            {"instanceId": "i-1",
             "metadata": {"sentinel.rules": RULES_V1}},
        ]}}).encode()
        http_server.routes[("GET", "/eureka/apps/svc")] = (
            200, {"Content-Type": "application/json"}, body)
        ds = EurekaDataSource(
            flow_rules_from_json,
            app_id="svc",
            instance_id="i-1",
            service_urls=(
                "http://127.0.0.1:1/eureka",  # dead replica → fallback
                f"http://127.0.0.1:{http_server.port}/eureka",
            ),
            refresh_interval_s=60,
        ).start()
        try:
            assert counts(ds) == [5]
        finally:
            ds.close()


class TestSpringCloudConfig:
    def test_property_source_precedence(self, http_server):
        body = json.dumps({"propertySources": [
            {"source": {"sentinel.rules": RULES_V2}},  # wins (front = highest)
            {"source": {"sentinel.rules": RULES_V1}},
        ]}).encode()
        http_server.routes[("GET", "/sentinel/default/main")] = (
            200, {}, body)
        ds = SpringCloudConfigDataSource(
            flow_rules_from_json,
            uri=f"http://127.0.0.1:{http_server.port}",
            label="main",
            refresh_interval_s=60,
        ).start()
        try:
            assert counts(ds) == [9]
        finally:
            ds.close()


class FakeRedis:
    """Minimal RESP2 server: GET of one key + SUBSCRIBE with later publishes."""

    def __init__(self, rule_key, value):
        self.rule_key = rule_key
        self.value = value
        self.subscribers = []
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.port = self.sock.getsockname()[1]
        self.sock.listen()
        self._stop = False
        threading.Thread(target=self._accept, daemon=True).start()

    @staticmethod
    def _bulk(b):
        return b"$%d\r\n%s\r\n" % (len(b), b)

    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn):
        f = conn.makefile("rb")
        while True:
            head = f.readline()
            if not head or not head.startswith(b"*"):
                return
            n = int(head[1:])
            parts = []
            for _ in range(n):
                f.readline()  # $len
                parts.append(f.readline().strip())
            cmd = parts[0].upper()
            if cmd == b"GET":
                conn.sendall(self._bulk(self.value.encode()))
            elif cmd == b"SUBSCRIBE":
                chan = parts[1]
                conn.sendall(b"*3\r\n" + self._bulk(b"subscribe")
                             + self._bulk(chan) + b":1\r\n")
                self.subscribers.append((conn, chan))
            else:
                conn.sendall(b"+OK\r\n")

    def publish(self, payload: str):
        for conn, chan in self.subscribers:
            conn.sendall(b"*3\r\n" + self._bulk(b"message")
                         + self._bulk(chan) + self._bulk(payload.encode()))

    def close(self):
        self._stop = True
        self.sock.close()


class TestRedis:
    def test_reconnects_after_subscription_drop(self):
        srv = FakeRedis("sentinel.rules", RULES_V1)
        ds = RedisDataSource(
            flow_rules_from_json, port=srv.port,
            rule_key="sentinel.rules", channel="chan",
        )
        ds._RECONNECT_DELAY_S = 0.05
        ds.start()
        try:
            assert wait_for(lambda: srv.subscribers)
            # kill the subscription socket server-side; the value changes
            # while the channel is down — the resync GET must pick it up
            conn, _ = srv.subscribers.pop()
            srv.value = RULES_V2
            # shutdown (not just close): the server's makefile still holds
            # the fd, so close() alone would never send the FIN
            conn.shutdown(socket.SHUT_RDWR)
            conn.close()
            assert wait_for(lambda: srv.subscribers)  # resubscribed
            assert wait_for(lambda: counts(ds) == [9])
            srv.publish(json.dumps([{"resource": "r", "count": 3}]))
            assert wait_for(lambda: counts(ds) == [3])
        finally:
            ds.close()
            srv.close()

    def test_get_then_pubsub_update(self):
        srv = FakeRedis("sentinel.rules", RULES_V1)
        ds = RedisDataSource(
            flow_rules_from_json, port=srv.port,
            rule_key="sentinel.rules", channel="chan",
        ).start()
        try:
            assert counts(ds) == [5]
            assert wait_for(lambda: srv.subscribers)
            srv.publish(RULES_V2)
            assert wait_for(lambda: counts(ds) == [9])
        finally:
            ds.close()
            srv.close()


class FakeZkClient:
    def __init__(self, data):
        self.data = data
        self.watchers = []
        self.started = False

    def start(self):
        self.started = True

    def stop(self):
        self.started = False

    def ensure_path(self, path):
        pass

    def get(self, path):
        return self.data, object()

    def DataWatch(self, path, func):  # noqa: N802 (kazoo's API name)
        self.watchers.append(func)
        func(self.data, object())

    def set(self, data):
        self.data = data
        for func in self.watchers:
            func(data, object())


class TestZookeeper:
    def test_watch_fires_initial_and_updates(self):
        client = FakeZkClient(RULES_V1.encode())
        ds = ZookeeperDataSource(
            flow_rules_from_json, client=client
        ).start()
        assert counts(ds) == [5]
        client.set(RULES_V2.encode())
        assert counts(ds) == [9]
        assert ds.read_source() == RULES_V2
        ds.close()
