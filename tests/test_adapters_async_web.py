"""aiohttp middleware and Tornado mixin adapters (real framework servers)."""

import asyncio
import json

import pytest

from sentinel_tpu.local.chain import (
    cluster_node_map,
    reset_cluster_nodes_for_tests,
)
from sentinel_tpu.local.flow import FlowRule, FlowRuleManager


@pytest.fixture(autouse=True)
def clean(manual_clock):
    reset_cluster_nodes_for_tests()
    FlowRuleManager.load_rules([])
    yield
    FlowRuleManager.load_rules([])
    reset_cluster_nodes_for_tests()


class TestAiohttp:
    def _app(self):
        from aiohttp import web

        from sentinel_tpu.adapters.aiohttp_middleware import sentinel_middleware

        async def hello(request):
            return web.json_response({"ok": True})

        async def boom(request):
            raise RuntimeError("kaput")

        app = web.Application(middlewares=[sentinel_middleware()])
        app.router.add_get("/hello", hello)
        app.router.add_get("/boom", boom)
        return app

    def _drive(self, paths):
        from aiohttp.test_utils import TestClient, TestServer

        async def run():
            client = TestClient(TestServer(self._app()))
            await client.start_server()
            try:
                out = []
                for p in paths:
                    resp = await client.get(p)
                    out.append((resp.status, await resp.text()))
                return out
            finally:
                await client.close()

        return asyncio.new_event_loop().run_until_complete(run())

    def test_pass_block_and_trace(self):
        FlowRuleManager.load_rules([FlowRule(resource="GET:/hello", count=2.0)])
        results = self._drive(["/hello"] * 4 + ["/boom"])
        statuses = [s for s, _ in results[:4]]
        assert statuses == [200, 200, 429, 429]
        assert json.loads(results[2][1])["error"].startswith("Blocked")
        assert results[4][0] == 500  # handler error propagates
        node = cluster_node_map()["GET:/hello"]
        assert node.pass_qps() == 2
        assert node.block_qps() == 2
        boom = cluster_node_map()["GET:/boom"]
        assert boom.exception_qps() == 1


class TestTornado:
    def _fetch(self, app, paths):
        from tornado.httpserver import HTTPServer
        from tornado.httpclient import AsyncHTTPClient
        from tornado.testing import bind_unused_port

        async def run():
            sock, port = bind_unused_port()
            server = HTTPServer(app)
            server.add_sockets([sock])
            client = AsyncHTTPClient()
            out = []
            try:
                for p in paths:
                    resp = await client.fetch(
                        f"http://127.0.0.1:{port}{p}", raise_error=False
                    )
                    out.append((resp.code, resp.body.decode()))
            finally:
                server.stop()
            return out

        return asyncio.new_event_loop().run_until_complete(run())

    def _app(self):
        from tornado import web

        from sentinel_tpu.adapters.tornado_handler import (
            SentinelRequestHandlerMixin,
        )

        class Hello(SentinelRequestHandlerMixin, web.RequestHandler):
            def get(self):
                self.write("hi")

        class Boom(SentinelRequestHandlerMixin, web.RequestHandler):
            def get(self):
                raise RuntimeError("kaput")

        return web.Application([("/hello", Hello), ("/boom", Boom)])

    def test_pass_block_and_trace(self):
        FlowRuleManager.load_rules([FlowRule(resource="GET:/hello", count=2.0)])
        results = self._fetch(self._app(), ["/hello"] * 4 + ["/boom"])
        assert [s for s, _ in results[:4]] == [200, 200, 429, 429]
        assert "Blocked" in results[2][1]
        assert results[4][0] == 500
        node = cluster_node_map()["GET:/hello"]
        assert node.pass_qps() == 2
        assert node.block_qps() == 2
        assert cluster_node_map()["GET:/boom"].exception_qps() == 1
