"""Warm-standby replication: codecs, delta apply, promotion under chaos.

Tentpole suite for the lossless-failover PR. Layers under test, bottom up:

- rev-3 frame codecs (hello/ack/chunked blobs) round-trip and reject torn
  or fuzzed input at the parse boundary;
- ``export_delta``/``apply_replication_delta`` converge a standby's
  counters bit-for-bit with the primary's, including ring rotation and the
  generation fence;
- the full sender→applier stack over real servers: a standby refuses with
  STANDBY while replicating, survives ``conn_reset``/``lane_delay`` chaos
  on the repl channel, and after promotion serves with counters inside the
  staleness budget (one delta-ship interval).

Satellite regressions ride along: torn snapshot artifacts, datasource
refresh backoff + last-known-good, heartbeat backoff jitter.
"""

import json
import os
import random
import time

import numpy as np
import pytest

from sentinel_tpu import chaos
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.server import TokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.engine import ClusterFlowRule, EngineConfig
from sentinel_tpu.engine.decide import TokenStatus
from sentinel_tpu.engine.rules import ThresholdMode
from sentinel_tpu.ha import FailoverTokenClient
from sentinel_tpu.ha import replication as R
from sentinel_tpu.metrics.ha import ha_metrics, reset_ha_metrics_for_tests

G = ThresholdMode.GLOBAL
CFG = EngineConfig(max_flows=64, max_namespaces=4, batch_size=64)
SEED = 0xB10B

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    chaos.disarm()


def _service(count=1e9):
    svc = DefaultTokenService(CFG)
    svc.load_rules([ClusterFlowRule(flow_id=1, count=count, mode=G)])
    return svc


def _payload(frame: bytes) -> bytes:
    """Strip the 2-byte length prefix (the codecs emit wire frames; the
    decoders take what a reader hands them after de-framing)."""
    assert int.from_bytes(frame[:2], "big") == len(frame) - 2
    return frame[2:]


# -- rev-3 frame codecs ------------------------------------------------------
class TestReplCodec:
    def test_hello_roundtrip(self):
        pay = _payload(
            P.encode_repl_hello(7, 3, 1234, 56, sender_id="10.0.0.1:9000")
        )
        xid, gen, epoch, seq, sender = P.decode_repl_hello(pay)
        assert (xid, gen, epoch, seq) == (7, 3, 1234, 56)
        assert sender == "10.0.0.1:9000"
        assert P.peek_type(pay) == P.MsgType.REPL_HELLO

    def test_ack_roundtrip(self):
        pay = _payload(P.encode_repl_ack(9, P.ReplAck.NEED_SNAPSHOT, 4, 100))
        xid, code, gen, seq = P.decode_repl_ack(pay)
        assert (xid, code, gen, seq) == (9, P.ReplAck.NEED_SNAPSHOT, 4, 100)
        assert isinstance(code, P.ReplAck)

    @pytest.mark.parametrize("size", [0, 1, 1000, 200_000])
    def test_blob_chunk_roundtrip(self, size):
        blob = bytes(random.Random(SEED + size).randrange(256)
                     for _ in range(size))
        frames = P.encode_repl_blob(5, P.MsgType.REPL_DELTA, 2, 11, blob)
        # every frame's payload fits the 16-bit length prefix
        assert all(len(f) - 2 <= P.MAX_FRAME for f in frames)
        asm = P.ReplBlobAssembler()
        out = None
        for f in frames:
            assert out is None  # incomplete until the last chunk
            pay = _payload(f)
            out = asm.feed(P.peek_type(pay), pay)
        mtype, gen, seq, got = out
        assert (mtype, gen, seq) == (P.MsgType.REPL_DELTA, 2, 11)
        assert got == blob

    def test_blob_fuzz_roundtrip(self):
        rng = random.Random(SEED)
        asm = P.ReplBlobAssembler()
        for trial in range(25):
            blob = os.urandom(rng.randrange(0, 150_000))
            frames = P.encode_repl_blob(
                trial, P.MsgType.REPL_SNAPSHOT, 1, trial, blob
            )
            out = None
            for f in frames:
                pay = _payload(f)
                out = asm.feed(P.peek_type(pay), pay)
            assert out is not None and out[3] == blob

    def test_assembler_rejects_torn_stream(self):
        blob = bytes(200_000)
        frames = P.encode_repl_blob(1, P.MsgType.REPL_DELTA, 1, 1, blob)
        assert len(frames) >= 3
        asm = P.ReplBlobAssembler()
        p0, p2 = _payload(frames[0]), _payload(frames[2])
        asm.feed(P.peek_type(p0), p0)
        with pytest.raises(ValueError):
            asm.feed(P.peek_type(p2), p2)  # gap: skipped idx 1
        # the torn stream cleared assembler state; a fresh blob still lands
        out = None
        for f in P.encode_repl_blob(2, P.MsgType.REPL_DELTA, 1, 2, b"ok"):
            pay = _payload(f)
            out = asm.feed(P.peek_type(pay), pay)
        assert out is not None and out[3] == b"ok"

    def test_chunk_decode_rejects_runt(self):
        with pytest.raises(ValueError):
            P.decode_repl_chunk(b"\x00\x00\x00\x01\x07")

    def test_delta_blob_rejects_garbage(self):
        rng = random.Random(SEED)
        for _ in range(20):
            with pytest.raises(ValueError):
                R.decode_delta_blob(os.urandom(rng.randrange(1, 4096)))
        with pytest.raises(ValueError):
            R.decode_delta_blob(b"")


# -- delta export/apply ------------------------------------------------------
class TestDeltaApply:
    def test_counters_converge_bit_for_bit(self, manual_clock):
        # frozen clock: on a loaded host the wall between the two
        # metrics_snapshot reads below can cross a 100ms bucket boundary,
        # expiring one admission from the second read but not the first
        primary = _service()
        standby = _service()
        primary.replication_enable()
        # bootstrap: standby restores the primary's full state once
        standby.import_state(
            R.decode_snapshot_blob(
                R.encode_snapshot_blob(primary.export_state())
            )
        )
        for _ in range(17):
            primary.request_token(1)
        delta = R.decode_delta_blob(
            R.encode_delta_blob(primary.export_delta())
        )
        standby.apply_replication_delta(delta)
        p = primary.metrics_snapshot()
        s = standby.metrics_snapshot()
        assert p[1]["pass_qps"] == s[1]["pass_qps"] > 0

    def _param_service(self, slim_width=256):
        from sentinel_tpu.cluster.token_service import ClusterParamFlowRule
        from sentinel_tpu.engine.param import ParamConfig

        svc = DefaultTokenService(
            CFG,
            param_config=ParamConfig(
                max_param_rules=8, impl="jax", slim_width=slim_width
            ),
        )
        svc.load_param_rules([ClusterParamFlowRule(flow_id=9, count=5.0)])
        return svc

    def test_param_slim_delta_carries_enforcement(self, manual_clock):
        """Deltas ship the SF slim twin, not fat rows — and the slim rows
        alone must carry enforcement: a value the primary exhausted AFTER
        the bootstrap snapshot must be blocked on the promoted standby."""
        from sentinel_tpu.engine.param import ParamConfig  # noqa: F401

        primary = self._param_service()
        standby = self._param_service()
        primary.replication_enable()
        standby.import_state(
            R.decode_snapshot_blob(
                R.encode_snapshot_blob(primary.export_state())
            )
        )
        hot = 0x7E57_C0DE
        blocked = False
        for _ in range(30):
            if primary.request_params_token(9, 1, [hot]).status \
                    == TokenStatus.BLOCKED:
                blocked = True
        assert blocked, "primary never exhausted the param threshold"
        delta = R.decode_delta_blob(R.encode_delta_blob(primary.export_delta()))
        assert "param_slim" in delta and "param_counts" not in delta
        standby.apply_replication_delta(delta)
        # fat counters on the standby are still snapshot-stale (all zero);
        # the slim rows shipped in the delta must block on their own
        r = standby.request_params_token(9, 1, [hot])
        assert r.status == TokenStatus.BLOCKED

    def test_param_slim_delta_bytes_4x_under_fat(self, manual_clock):
        """Identical traffic, identical dirty slots: the slim-twin delta
        blob must come in ≥4× under the fat-row delta blob (the per-tick
        replication cost the SF split exists to cut)."""
        import numpy as np

        rng = np.random.default_rng(SEED)
        vals = rng.integers(-2 ** 63, 2 ** 63 - 1, size=1500, dtype=np.int64)
        sizes = {}
        for label, slim_width in (("slim", 256), ("fat", 0)):
            svc = self._param_service(slim_width=slim_width)
            svc.replication_enable()
            for off in range(0, len(vals), 60):
                svc.request_params_token(
                    9, 1, [int(h) for h in vals[off:off + 60]]
                )
            sizes[label] = len(R.encode_delta_blob(svc.export_delta()))
        assert sizes["fat"] >= 4 * sizes["slim"], sizes

    @pytest.mark.parametrize("standby_devices", [1, 4])
    def test_mesh_primary_delta_converges(self, standby_devices):
        """PR-7 sharded replication: a mesh-backed primary's export_delta
        (shard-aware host row gather) lands bit-for-bit on a standby with
        a DIFFERENT mesh shape — through the real rev-3 blob codecs."""
        import jax

        from sentinel_tpu.parallel import make_flow_mesh

        mesh = make_flow_mesh()
        primary = DefaultTokenService(CFG, mesh=mesh)
        primary.load_rules(
            [ClusterFlowRule(flow_id=i, count=1e9, mode=G) for i in range(16)]
        )
        primary.replication_enable()
        standby_mesh = (
            None if standby_devices == 1
            else make_flow_mesh(jax.devices()[:standby_devices])
        )
        standby = DefaultTokenService(CFG, mesh=standby_mesh)
        standby.import_state(
            R.decode_snapshot_blob(
                R.encode_snapshot_blob(primary.export_state())
            )
        )
        ids = np.tile(np.arange(16, dtype=np.int64), 8)
        primary.request_batch_arrays(ids)
        delta = R.decode_delta_blob(
            R.encode_delta_blob(primary.export_delta())
        )
        assert delta.get("flow_ids"), "dirty rows expected"
        standby.apply_replication_delta(delta)
        np.testing.assert_array_equal(
            np.asarray(standby._state.flow.counts),
            np.asarray(primary._state.flow.counts),
        )
        np.testing.assert_array_equal(
            np.asarray(standby._state.ns.counts),
            np.asarray(primary._state.ns.counts),
        )
        if standby_mesh is not None:
            assert (
                len(standby._state.flow.counts.addressable_shards)
                == standby_devices
            )
        primary.close()
        standby.close()

    def test_idle_tick_ships_heartbeat_delta(self):
        primary = _service()
        standby = _service()
        primary.replication_enable()
        standby.import_state(primary.export_state())
        delta = primary.export_delta()
        assert "flow_ids" not in delta  # nothing dirty
        standby.apply_replication_delta(
            R.decode_delta_blob(R.encode_delta_blob(delta))
        )  # starts-only delta applies cleanly

    def test_generation_fences_slot_reuse(self):
        primary = _service()
        primary.replication_enable()
        gen0 = primary.state_generation()
        primary.load_rules([ClusterFlowRule(flow_id=2, count=10, mode=G)])
        assert primary.state_generation() == gen0 + 1
        assert primary.export_delta()["gen"] == gen0 + 1

    def test_epoch_mismatch_rejected(self):
        primary = _service()
        standby = _service()
        primary.replication_enable()
        standby.import_state(primary.export_state())
        delta = primary.export_delta()
        delta["epoch_ms"] = delta["epoch_ms"] + 1
        with pytest.raises(ValueError):
            standby.apply_replication_delta(delta)

    def test_unknown_flow_rejected(self):
        primary = DefaultTokenService(CFG)
        primary.load_rules([
            ClusterFlowRule(flow_id=1, count=10, mode=G),
            ClusterFlowRule(flow_id=9, count=10, mode=G),
        ])
        primary.replication_enable()
        primary.request_token(9)
        delta = primary.export_delta()
        standby = _service()  # only knows flow 1
        # align the epoch fence so the test reaches the flow-id remap
        standby._epoch_ms = int(delta["epoch_ms"])
        with pytest.raises(ValueError):
            standby.apply_replication_delta(delta)


# -- circuit-breaker columns across the HA planes ----------------------------
class TestBreakerColumnsAcrossHA:
    """The breaker state machine must survive every serialization plane: an
    OPEN breaker that a standby or MOVE destination silently restores as
    CLOSED would re-admit a failing dependency exactly when the primary had
    fenced it off. Deltas ship the three columns under their own dirty set;
    snapshots restore them bit-exact (and tolerate their absence in
    pre-breaker artifacts); MOVE blobs carry RELATIVE clocks so the
    retry-after countdown is frozen in transit and re-anchors on import."""

    def _breaker_service(self, recovery_ms=2000):
        from sentinel_tpu.engine import DegradeRule, DegradeStrategy

        svc = DefaultTokenService(CFG)
        svc.load_rules([
            ClusterFlowRule(flow_id=1, count=1e9, mode=G, namespace="brns")
        ])
        svc.load_degrade_rules([
            DegradeRule(1, DegradeStrategy.ERROR_RATIO, threshold=0.2,
                        min_request_amount=5, stat_interval_ms=1000,
                        recovery_timeout_ms=recovery_ms, namespace="brns"),
        ])
        return svc

    def _trip(self, svc, mc):
        """Report an error burst, then decide once: CLOSED→OPEN. Returns
        the DEGRADED verdict's retry-after-ms."""
        svc.report_outcomes(
            np.full(8, 1, np.int64), np.full(8, 5, np.int64),
            np.ones(8, np.int64),
        )
        mc.advance(50)
        st, rem, _ = svc.request_batch_arrays(np.array([1], np.int64))
        assert int(np.asarray(st)[0]) == int(TokenStatus.DEGRADED)
        return int(np.asarray(rem)[0])

    def _assert_breaker_equal(self, a, b):
        for leaf_a, leaf_b in zip(a._state.breaker, b._state.breaker):
            np.testing.assert_array_equal(
                np.asarray(leaf_a), np.asarray(leaf_b)
            )

    def test_breaker_rows_ship_in_delta_and_dirty_set_drains(
        self, manual_clock
    ):
        manual_clock.advance(1_000)
        primary = self._breaker_service()
        standby = self._breaker_service()
        primary.replication_enable()
        standby.import_state(
            R.decode_snapshot_blob(
                R.encode_snapshot_blob(primary.export_state())
            )
        )
        self._trip(primary, manual_clock)
        delta = R.decode_delta_blob(
            R.encode_delta_blob(primary.export_delta())
        )
        assert delta.get("breaker_fids") == [1]
        assert int(np.asarray(delta["breaker_state"])[0]) != 0  # OPEN ships
        standby.apply_replication_delta(delta)
        self._assert_breaker_equal(standby, primary)
        # collect-and-clear: with no new breaker activity the next delta
        # carries no breaker rows (heartbeat-sized, not O(breakers))
        assert "breaker_fids" not in primary.export_delta()

    def test_snapshot_roundtrip_bit_exact_and_tolerant_absent(
        self, manual_clock
    ):
        manual_clock.advance(1_000)
        donor = self._breaker_service()
        self._trip(donor, manual_clock)
        doc = R.decode_snapshot_blob(
            R.encode_snapshot_blob(donor.export_state())
        )
        twin = DefaultTokenService(CFG)
        twin.import_state(doc)
        self._assert_breaker_equal(twin, donor)
        assert int(np.asarray(twin._state.breaker.state)[
            twin._index.slot_of[1]]) != 0
        # pre-breaker artifact: no "breaker" key → restore CLOSED/cold,
        # which under-protects briefly but never wrongly rejects
        doc2 = R.decode_snapshot_blob(
            R.encode_snapshot_blob(donor.export_state())
        )
        doc2.pop("breaker")
        cold = DefaultTokenService(CFG)
        cold.import_state(doc2)
        assert (np.asarray(cold._state.breaker.state) == 0).all()
        # the restored outcome telemetry still shows the error burst, so
        # the cold breaker legitimately RE-trips on its first decide …
        st, _, _ = cold.request_batch_arrays(np.array([1], np.int64))
        assert int(np.asarray(st)[0]) == int(TokenStatus.DEGRADED)
        # … but the donor's OPEN countdown was forgotten: once the stat
        # window drains past the re-trip fence, the flow serves again
        manual_clock.advance(2_100)
        st, _, _ = cold.request_batch_arrays(np.array([1], np.int64))
        assert int(np.asarray(st)[0]) == int(TokenStatus.OK)

    def test_move_blob_freezes_retry_countdown_in_transit(self, manual_clock):
        from sentinel_tpu.cluster.rebalance import (
            decode_move_state_blob,
            encode_move_state_blob,
        )

        manual_clock.advance(1_000)
        src = self._breaker_service(recovery_ms=2000)
        self._trip(src, manual_clock)
        manual_clock.advance(300)  # burn 300ms of the 2000ms recovery
        st, rem, _ = src.request_batch_arrays(np.array([1], np.int64))
        assert int(np.asarray(st)[0]) == int(TokenStatus.DEGRADED)
        rem_at_export = int(np.asarray(rem)[0])
        blob = encode_move_state_blob(src.export_namespace_state("brns"))
        # 450ms of transit: the blob carries clocks RELATIVE to export
        # time, so the countdown must NOT tick while the bytes are in
        # flight — the destination owes the dependency the full remaining
        # quiet period, however long the MOVE took
        manual_clock.advance(450)
        dest = DefaultTokenService(CFG)
        dest.import_namespace_state(decode_move_state_blob(blob))
        st_d, rem_d, _ = dest.request_batch_arrays(np.array([1], np.int64))
        assert int(np.asarray(st_d)[0]) == int(TokenStatus.DEGRADED)
        assert int(np.asarray(rem_d)[0]) == rem_at_export
        assert (
            dest.breaker_stats()["flows"][1]["state_code"]
            == src.breaker_stats()["flows"][1]["state_code"]
        )


# -- sender → applier over real servers, chaos on the channel ----------------
class TestPromotionUnderChaos:
    def test_standby_promotion_with_chaotic_repl_channel(self):
        reset_ha_metrics_for_tests()
        standby = TokenServer(_service(), port=0, standby_of="primary")
        standby.start()
        primary = TokenServer(
            _service(), port=0,
            replicate_to=[("127.0.0.1", standby.port)],
            repl_interval_ms=50,
        )
        primary.start()
        fc = FailoverTokenClient(
            [("127.0.0.1", primary.port), ("127.0.0.1", standby.port)],
            failure_threshold=3, deadline_ms=2000,
        )
        try:
            # chaos on the wire: resets + delay hit the repl channel (and
            # everything else). Invariant: every client request RESOLVES.
            chaos.arm("conn_reset:p=0.02;lane_delay:p=0.2,ms=2", seed=SEED)
            served = 0
            for _ in range(40):
                r = fc.request_token(1)
                assert r is not None
                assert r.status in (
                    TokenStatus.OK, TokenStatus.BLOCKED,
                    TokenStatus.SHOULD_WAIT,
                )
                served += 1
            assert served == 40
            # deterministic settle: disarm, then let the final delta ship
            chaos.disarm()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                p = primary.service.metrics_snapshot()
                s = standby.service.metrics_snapshot()
                if s and p and s[1]["pass_qps"] == p[1]["pass_qps"]:
                    break
                time.sleep(0.05)
            else:
                pytest.fail(f"standby never converged: {p} vs {s}")
            # repl channel survived the chaos: deltas ship and apply (a
            # snapshot may have subsumed the traffic; heartbeat deltas tick
            # every interval regardless, so one lands within the deadline)
            deadline = time.monotonic() + 5.0
            repl = ha_metrics().snapshot()["replication"]
            while (repl["events"].get("shipped", 0) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
                repl = ha_metrics().snapshot()["replication"]
            assert repl["events"].get("shipped", 0) >= 1
            # primary dies; promotion opens the door; the client walks over
            primary.stop()
            assert standby.promote(reason="test")
            assert not standby.is_standby
            for _ in range(10):
                r = fc.request_token(1)
                assert r is not None and r.status in (
                    TokenStatus.OK, TokenStatus.BLOCKED,
                    TokenStatus.SHOULD_WAIT,
                )
        finally:
            chaos.disarm()
            fc.close()
            primary.stop()
            standby.stop()

    def test_unpromoted_standby_refuses_with_standby_status(self):
        standby = TokenServer(_service(), port=0, standby_of="primary")
        standby.start()
        try:
            client = TokenClient("127.0.0.1", standby.port)
            r = client.request_token(1)
            assert r.status == TokenStatus.STANDBY
            assert client.ping()  # standbys stay pingable
            client.close()
            standby.promote(reason="test")
            client = TokenClient("127.0.0.1", standby.port)
            assert client.request_token(1).status == TokenStatus.OK
            client.close()
        finally:
            standby.stop()

    def test_watchdog_auto_promotes_on_primary_silence(self):
        standby = TokenServer(
            _service(), port=0, standby_of="primary",
            promote_after_ms=200,
        )
        standby.start()
        try:
            # no contact yet → death undetectable → no premature promotion
            # even after the timer would have elapsed (slow-booting primary)
            time.sleep(0.5)
            assert standby.is_standby
            # one HELLO-equivalent contact arms the silence timer
            standby.applier._touch()
            deadline = time.monotonic() + 5.0
            while standby.is_standby and time.monotonic() < deadline:
                time.sleep(0.05)
            assert not standby.is_standby, "watchdog never promoted"
        finally:
            standby.stop()


# -- satellite: torn snapshot artifacts --------------------------------------
class TestSnapshotTornWrite:
    def test_torn_newest_artifact_falls_back(self, tmp_path):
        from sentinel_tpu.core import clock as _clock
        from sentinel_tpu.ha.snapshot import load_latest, save_snapshot

        donor = _service()
        donor.request_token(1)
        p1 = save_snapshot(donor, str(tmp_path))
        time.sleep(0.002)  # distinct saved_at_ms artifact names
        p2 = save_snapshot(donor, str(tmp_path))
        assert p1 != p2
        good = json.load(open(p1))
        # simulate a torn write surviving a crash under the final name
        with open(p2, "w") as f:
            f.write(open(p1).read()[: 40])
        doc = load_latest(str(tmp_path))
        assert doc is not None and doc == good

    def test_all_torn_restores_nothing(self, tmp_path):
        from sentinel_tpu.ha.snapshot import load_latest, save_snapshot

        donor = _service()
        path = save_snapshot(donor, str(tmp_path))
        with open(path, "w") as f:
            f.write("{\"truncated\": ")
        assert load_latest(str(tmp_path)) is None


# -- satellite: datasource refresh backoff -----------------------------------
class TestDatasourceBackoff:
    def test_failed_parse_retains_last_known_good(self):
        from sentinel_tpu.datasource.base import (
            ReadableDataSource,
            refresh_failure_totals,
            reset_refresh_failures_for_tests,
        )

        reset_refresh_failures_for_tests()

        class Src(ReadableDataSource):
            def __init__(self):
                super().__init__(converter=lambda s: json.loads(s))
                self.raw = '["rule-a"]'

            def read_source(self):
                return self.raw

        src = Src()
        assert src.refresh() is True
        assert src.property.value == ["rule-a"]
        src.raw = '{"truncated'  # torn mid-write
        assert src.refresh() is False
        assert src.property.value == ["rule-a"], "stale beats none"
        src.raw = "null"  # parses, but to nothing
        assert src.refresh() is False
        assert src.property.value == ["rule-a"]
        assert refresh_failure_totals().get("Src", 0) == 2

    def test_poll_interval_backs_off_and_caps(self):
        from sentinel_tpu.datasource.base import AutoRefreshDataSource

        src = AutoRefreshDataSource(converter=lambda s: s,
                                    refresh_interval_s=1.0)
        assert src._poll_interval_s() == 1.0
        src._consecutive_failures = 2
        assert src._poll_interval_s() == 4.0
        src._consecutive_failures = 30
        assert src._poll_interval_s() == 10.0  # capped at 10×
        src._consecutive_failures = 0
        assert src._poll_interval_s() == 1.0

    def test_loop_counts_consecutive_failures(self):
        from sentinel_tpu.datasource.base import AutoRefreshDataSource

        boom = AutoRefreshDataSource(
            converter=lambda s: s, refresh_interval_s=0.01
        )
        boom.read_source = lambda: (_ for _ in ()).throw(IOError("down"))
        boom.start()
        try:
            deadline = time.monotonic() + 2.0
            while (boom._consecutive_failures < 2
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert boom._consecutive_failures >= 2
        finally:
            boom.close()


# -- satellite: heartbeat backoff --------------------------------------------
class TestHeartbeatBackoff:
    def test_interval_backs_off_with_jitter_and_resets(self):
        from sentinel_tpu.transport.heartbeat import HeartbeatSender

        hb = HeartbeatSender(
            dashboard_addrs=["127.0.0.1:1"], interval_ms=1000
        )
        assert hb._interval_s() == 1.0  # healthy: exact cadence
        hb._consecutive_failures = 1
        for _ in range(20):
            assert 2.0 * 0.75 <= hb._interval_s() <= 2.0 * 1.25
        hb._consecutive_failures = 50
        for _ in range(20):
            assert 10.0 * 0.75 <= hb._interval_s() <= 10.0 * 1.25  # capped
        hb._consecutive_failures = 0
        assert hb._interval_s() == 1.0

    def test_loop_resets_on_success(self):
        from sentinel_tpu.transport.heartbeat import HeartbeatSender

        hb = HeartbeatSender(dashboard_addrs=["x"], interval_ms=10)
        hb.send_once = lambda: True
        hb._consecutive_failures = 5
        hb._stop.clear()
        import threading

        t = threading.Thread(target=hb._loop, daemon=True)
        t.start()
        deadline = time.monotonic() + 2.0
        while hb._consecutive_failures and time.monotonic() < deadline:
            time.sleep(0.01)
        hb.stop()
        assert hb._consecutive_failures == 0


class TestMegakernelStateArtifactParity:
    """Fused-kernel state contract, host-serialization layer: the decide
    megakernel (``decide_impl="pallas"``) must leave the service's state
    tensors byte-identical to the XLA pipeline's after the same request
    stream, so every serialized artifact — snapshot blob, replication
    delta blob, MOVE namespace doc — is bit-identical across impls. A
    single diverging bit here would poison standbys and MOVE targets
    with an impl-dependent state stream."""

    def _twin(self, impl):
        svc = DefaultTokenService(CFG._replace(decide_impl=impl))
        svc.load_rules([
            ClusterFlowRule(flow_id=1, count=7.0, mode=G, namespace="mv"),
            ClusterFlowRule(flow_id=2, count=3.0, mode=G, namespace="mv"),
            ClusterFlowRule(flow_id=3, count=1e9, mode=G),
        ])
        svc.replication_enable()
        rng = np.random.default_rng(SEED)
        for _ in range(6):
            ids = np.sort(rng.integers(1, 4, size=24)).astype(np.int64)
            svc.request_batch_arrays(ids)
        return svc

    def test_snapshot_delta_and_move_blobs_bit_identical(self, manual_clock):
        svcs = {impl: self._twin(impl) for impl in ("xla", "pallas")}
        snaps = {
            impl: R.encode_snapshot_blob(svc.export_state())
            for impl, svc in svcs.items()
        }
        assert snaps["xla"] == snaps["pallas"]
        deltas = {
            impl: R.encode_delta_blob(svc.export_delta())
            for impl, svc in svcs.items()
        }
        assert deltas["xla"] == deltas["pallas"]
        from sentinel_tpu.cluster.rebalance import encode_move_state_blob

        moves = {
            impl: encode_move_state_blob(svc.export_namespace_state("mv"))
            for impl, svc in svcs.items()
        }
        assert moves["xla"] == moves["pallas"]

    def test_pallas_primary_converges_xla_standby(self, manual_clock):
        """Cross-impl replication: a megakernel primary's delta stream
        must land bit-for-bit on an XLA-pipeline standby — mixed-impl
        pods (e.g. a TPU primary with a CPU warm standby) replicate
        through the same bytes."""
        primary = self._twin("pallas")
        standby = DefaultTokenService(CFG._replace(decide_impl="xla"))
        standby.import_state(
            R.decode_snapshot_blob(
                R.encode_snapshot_blob(primary.export_state())
            )
        )
        rng = np.random.default_rng(SEED + 1)
        for _ in range(3):
            ids = np.sort(rng.integers(1, 4, size=16)).astype(np.int64)
            primary.request_batch_arrays(ids)
        delta = R.decode_delta_blob(
            R.encode_delta_blob(primary.export_delta())
        )
        standby.apply_replication_delta(delta)
        p = primary.metrics_snapshot()
        s = standby.metrics_snapshot()
        assert p[1]["pass_qps"] == s[1]["pass_qps"] > 0
